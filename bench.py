"""Headline benchmark + the reference's full single-chip table.

Default (driver contract): runs the headline row — Qwen3-0.6B, seq 8192,
micro-batch 1, gradient checkpointing, bf16 (reference README.md:31,
9,834 tok/s / 39.0% MFU on one Ascend 910B) — and prints exactly ONE
JSON line:
    {"metric": ..., "value": N, "unit": "...", "vs_baseline": N}

Other modes:
    python bench.py --table          # all 8 single-chip rows (BASELINE.md
                                     # §Single-NPU); per-row JSON to stderr,
                                     # full results to bench_table.json,
                                     # headline row still the stdout line
    BENCH_ROW=<label> python bench.py   # one specific row
MFU is the hardware-normalised comparison: our MFU on whatever single
TPU chip the driver provides vs the reference's MFU at the identical
model/sequence configuration.
"""

from __future__ import annotations

import json
import os
import sys
import time

# Benchmark wants the real chip; nothing here should touch the test env.
os.environ.setdefault("XLA_PYTHON_CLIENT_MEM_FRACTION", "0.92")

HEADLINE = "qwen3-0.6b_seq8192_bs1_gc"

# The reference's published single-chip table (BASELINE.md §Single-NPU;
# reference README.md:30-36 + scripts/run_npu.sh:20-24 sweep rows).
# label -> (model, run-shape kwargs, baseline MFU %, baseline tok/s)
SINGLE_CHIP_ROWS = {
    "qwen3-0.6b_seq2048_bs2": ("qwen3-0.6b", dict(seq=2048, micro_bs=2), 22.5, 9731),
    HEADLINE: ("qwen3-0.6b", dict(seq=8192, gc=True), 39.0, 9834),
    "qwen3-0.6b_seq16384_bs1_gc": ("qwen3-0.6b", dict(seq=16384, gc=True), 56.0, 9079),
    # 1.7B/4B rows store master weights + adam moments in bf16 — exactly
    # what the reference's torch bf16 AdamW stores (tensor.to(bf16) model,
    # exp_avg/exp_avg_sq in param dtype). fp32 master state for 1.7B is
    # 19.2 GB before activations (tools/aot_memory.py) — it only exists on
    # the reference's 64 GB chips, not a 16 GB v5e.
    "qwen3-1.7b_seq2048_bs1": (
        "qwen3-1.7b", dict(seq=2048, extra={"param_dtype": "bfloat16"}),
        24.9, 4685),
    "qwen3-1.7b_seq8192_bs1_gc": (
        "qwen3-1.7b", dict(seq=8192, gc=True, extra={"param_dtype": "bfloat16"}),
        51.5, 7396),
    # 4B AdamW state alone is 22.5 GB even in bf16 — beyond ANY single
    # 16 GB chip. Adafactor (sharding-aware, trainer/factored.py) is the
    # idiomatic TPU answer: same model FLOPs, factored second moments.
    "qwen3-4b_seq2048_bs1_gc": (
        "qwen3-4b", dict(seq=2048, gc=True, extra={
            "param_dtype": "bfloat16", "optimizer_name": "adafactor"}),
        28.4, 2415),
    # 910-sweep rows (scripts/run_npu.sh:20-24)
    "qwen3-0.6b_seq16384_sweep": ("qwen3-0.6b", dict(seq=16384, gc=True), 60.1, 9700),
    "qwen3-0.6b_seq2048_bs4_ga2": (
        "qwen3-0.6b", dict(seq=2048, micro_bs=4, grad_accum=2), 43.9, 19000,
    ),
}


_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory", "OOM")


def _pallas_active() -> bool:
    """Was the Pallas flash kernel actually the attention path for this
    run? (Guards the fallback retry: non-kernel failures — config errors,
    CPU runs, FLASH_ATTEN=0 — would just reproduce identically.)"""
    from scaletorch_tpu.env import get_env
    from scaletorch_tpu.ops.flash_attention import _pallas_available

    if get_env("SCALETORCH_TPU_DISABLE_PALLAS") or not get_env("FLASH_ATTEN"):
        return False
    try:
        return _pallas_available()
    except Exception:  # noqa: BLE001 — backend not even initialisable
        return False


def run_row(label: str, warmup: int, steps: int) -> dict:
    from scaletorch_tpu.benchmark import benchmark_config, make_bench_args

    model, shape, base_mfu, base_tok_s = SINGLE_CHIP_ROWS[label]
    shape = dict(shape)
    shape.setdefault("remat_policy", os.environ.get(
        "BENCH_REMAT_POLICY", "nothing_saveable"))
    gc_fallback = False
    pallas_fallback = False
    first_error = None
    try:
        cfg = make_bench_args(model, **shape)
        r = benchmark_config(cfg, warmup=warmup, steps=steps)
    except Exception as e:  # noqa: BLE001
        err = repr(e)
        # VMEM RESOURCE_EXHAUSTED is a kernel-tile overflow (a Pallas
        # problem), NOT an HBM capacity problem — classify it as a kernel
        # failure so the pallas fallback, not the gc fallback, engages.
        is_hbm_oom = (any(m in err for m in _OOM_MARKERS)
                      and "vmem" not in err.lower())
        if is_hbm_oom and not shape.get("gc"):
            # The reference measured its no-GC rows on 64 GB 910Bs; on a
            # smaller-HBM chip rerun them with gradient checkpointing and
            # say so, rather than reporting nothing.
            gc_fallback = True
        elif not is_hbm_oom and _pallas_active():
            # Kernel-runtime regression on this chip/toolchain should
            # degrade the row to the XLA SDPA path, not erase it.
            pallas_fallback = True
        else:
            raise
        first_error = err[:300]
        print(json.dumps({"event": "row_fallback", "metric": label,
                          "error": first_error}), file=sys.stderr, flush=True)
    if gc_fallback or pallas_fallback:
        # Retry outside the except block: the exception's traceback pins
        # the failed attempt's device buffers until it is cleared.
        import gc

        gc.collect()
        if pallas_fallback:
            os.environ["SCALETORCH_TPU_DISABLE_PALLAS"] = "1"
            if not shape.get("gc"):
                # the SDPA fallback materialises full score matrices; a
                # no-GC shape would trade a kernel failure for an HBM OOM
                gc_fallback = True
        cfg = make_bench_args(model, **(dict(shape, gc=True)
                                        if gc_fallback else shape))
        r = benchmark_config(cfg, warmup=warmup, steps=steps)
        # peak_bytes_in_use still reflects the failed first attempt (no
        # reset API), so the fallback row's memory reading is meaningless.
        r["memory_gb"] = None
    import jax

    if r["mfu"] > 100.0:
        # A >100% MFU means the timing barrier was violated (e.g. a
        # degraded remote-execution tunnel acking work early) — report an
        # error rather than a fantasy number.
        raise RuntimeError(
            f"implausible MFU {r['mfu']}% for {label}: timing barrier violated"
        )
    return {
        "metric": f"{label}_single_chip_mfu",
        "value": r["mfu"],
        "unit": "% MFU",
        "vs_baseline": round(r["mfu"] / base_mfu, 3),
        "tokens_per_second": r["tokens_per_second"],
        "baseline_mfu": base_mfu,
        "baseline_tokens_per_second": base_tok_s,
        "memory_gb": r["memory_gb"],
        "device": jax.local_devices()[0].device_kind,
        **({"gc_fallback": True} if gc_fallback else {}),
        **({"pallas_fallback": True} if pallas_fallback else {}),
        **({"fallback_error": first_error} if first_error else {}),
        # Echo every training-recipe deviation so cross-commit bench JSON
        # diffs show WHAT changed, not just that the number moved.
        **{k: v for k, v in shape.get("extra", {}).items()
           if k in ("param_dtype", "optimizer_name")},
    }


def main() -> None:
    # stdout must carry ONLY the result JSON line (driver contract): move
    # the framework logger's stream handlers to stderr.
    import logging

    from scaletorch_tpu.utils.logger import get_logger

    for h in get_logger().handlers:
        if isinstance(h, logging.StreamHandler):
            h.setStream(sys.stderr)

    warmup = int(os.environ.get("BENCH_WARMUP_STEPS", 3))
    steps = int(os.environ.get("BENCH_STEPS", 10))

    unknown = [a for a in sys.argv[1:] if a != "--table"]
    if unknown:
        raise SystemExit(f"unknown arguments {unknown}; supported: --table "
                         "(other knobs via BENCH_* env vars)")

    if "--table" in sys.argv:
        # One subprocess per row: isolates OOMs and keeps per-row device
        # memory peaks meaningful (peak_bytes_in_use is a process-lifetime
        # high-water mark with no reset API).
        import subprocess

        results = {}
        for label in SINGLE_CHIP_ROWS:
            t0 = time.perf_counter()
            env = dict(os.environ, BENCH_ROW=label)
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                capture_output=True, text=True, env=env,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            try:
                results[label] = json.loads(proc.stdout.strip().splitlines()[-1])
            except Exception:  # noqa: BLE001 — per-row isolation
                results[label] = {
                    "metric": label,
                    "error": (proc.stderr.strip().splitlines() or ["no output"])[-1][:300],
                }
            results[label]["wall_s"] = round(time.perf_counter() - t0, 1)
            print(json.dumps(results[label]), file=sys.stderr, flush=True)
            with open("bench_table.json", "w") as f:
                json.dump(results, f, indent=1)
        head = results.get(HEADLINE, {})
        if "error" in head:
            print(json.dumps({"metric": "error", "value": 0, "unit": "",
                              "vs_baseline": 0, "error": head["error"]}))
            sys.exit(1)
        print(json.dumps(head))
        return

    label = os.environ.get("BENCH_ROW", HEADLINE)
    if label not in SINGLE_CHIP_ROWS:
        raise KeyError(
            f"BENCH_ROW {label!r} unknown; rows: {', '.join(SINGLE_CHIP_ROWS)}"
        )
    # Back-compat: BENCH_SEQ_LEN overrides the headline row's sequence.
    if label == HEADLINE and os.environ.get("BENCH_SEQ_LEN"):
        SINGLE_CHIP_ROWS[label][1]["seq"] = int(os.environ["BENCH_SEQ_LEN"])
    print(json.dumps(run_row(label, warmup, steps)))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — the driver needs a JSON line either way
        print(json.dumps({"metric": "error", "value": 0, "unit": "",
                          "vs_baseline": 0, "error": repr(e)}))
        sys.exit(1)
