"""Headline benchmark + the reference's full single-chip table.

Default (driver contract): measures the headline row — Qwen3-0.6B,
seq 8192, micro-batch 1, gradient checkpointing, bf16 (reference
README.md:31, 9,834 tok/s / 39.0% MFU on one Ascend 910B) — and prints
exactly ONE JSON line:
    {"metric": ..., "value": N, "unit": "...", "vs_baseline": N}

Hang-proofing (the round-2 postmortem): every piece of device work runs
in a SUBPROCESS with a hard wall-clock budget, because the two failure
modes that produce *nothing* — a PJRT backend init that never returns
(dead remote-execution tunnel) and a kernel that wedges mid-step — raise
no exception and defeat any in-process fallback ladder. The parent
process never touches JAX. Orchestration:

  1. "banked" row: the headline config on the XLA-SDPA attention path
     (``SCALETORCH_TPU_DISABLE_PALLAS=1``) — the path that measured
     45.41% MFU in round 1. Budgeted; its result is banked.
  2. Pallas experiment (only if 1 succeeded and budget remains): a tiny
     flash-attention fwd+bwd pre-flight subprocess, then the headline
     row with the Pallas kernel. Either timing out only costs the
     experiment — the banked row is still reported.
  3. The better row (by MFU) is the stdout JSON line, annotated with
     ``attention_path`` and the losing candidate's number.
  4. Remaining budget measures extra single-chip table rows (seq-16384
     first — the reference's 56.0%-MFU best) on the winning attention
     path, streamed into bench_table.json; a timeout ends the phase but
     never the stdout line.

Timeouts use a SIGINT-only stop ladder: SIGKILL/SIGTERM on a process
holding the TPU can wedge the remote-execution tunnel for every later
process (observed round 2), so a child that ignores two SIGINTs is left
to the driver's cleanup and the chip is treated as held ("wedged") —
no further device subprocesses are attempted.

Children emit ``{"event": "progress", "stage": ...}`` lines to stderr
("backend_up" → "trainer_built" → "compiled" → "done"); on timeout the
last stage classifies the wedge (before "backend_up" = tunnel dead;
after = kernel/step wedge) in the error JSON.

Other modes:
    python bench.py --table          # all 8 single-chip rows (BASELINE.md
                                     # §Single-NPU); per-row JSON to stderr,
                                     # full results to bench_table.json,
                                     # headline row still the stdout line
    BENCH_ROW=<label> python bench.py     # one row, in-process (child mode)
    BENCH_PREFLIGHT=1 python bench.py     # kernel pre-flight (child mode)

MFU is the hardware-normalised comparison: our MFU on whatever single
TPU chip the driver provides vs the reference's MFU at the identical
model/sequence configuration.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

# Benchmark wants the real chip; nothing here should touch the test env.
os.environ.setdefault("XLA_PYTHON_CLIENT_MEM_FRACTION", "0.92")

HEADLINE = "qwen3-0.6b_seq8192_bs1_gc"

# The reference's published single-chip table (BASELINE.md §Single-NPU;
# reference README.md:30-36 + scripts/run_npu.sh:20-24 sweep rows).
# label -> (model, run-shape kwargs, baseline MFU %, baseline tok/s)
SINGLE_CHIP_ROWS = {
    "qwen3-0.6b_seq2048_bs2": ("qwen3-0.6b", dict(seq=2048, micro_bs=2), 22.5, 9731),
    HEADLINE: ("qwen3-0.6b", dict(seq=8192, gc=True), 39.0, 9834),
    "qwen3-0.6b_seq16384_bs1_gc": ("qwen3-0.6b", dict(seq=16384, gc=True), 56.0, 9079),
    # Same reference row, the AOT-planned recipe (AOT_SEQ16K.json
    # on_chip_plan): bf16 master + save_attn keeps the flash kernel's
    # (out, lse) so GC backward skips the flash-forward recompute — the
    # likely MFU winner at this length. Giving the driver BOTH recipes
    # maximises the odds of landing the 56.0% target in one invocation.
    "qwen3-0.6b_seq16384_bf16_save_attn": (
        "qwen3-0.6b",
        dict(seq=16384, gc=True, remat_policy="save_attn",
             extra={"param_dtype": "bfloat16"}),
        56.0, 9079),
    # 1.7B/4B rows store master weights + adam moments in bf16 — exactly
    # what the reference's torch bf16 AdamW stores (tensor.to(bf16) model,
    # exp_avg/exp_avg_sq in param dtype). fp32 master state for 1.7B is
    # 19.2 GB before activations (tools/aot_memory.py) — it only exists on
    # the reference's 64 GB chips, not a 16 GB v5e.
    "qwen3-1.7b_seq2048_bs1": (
        "qwen3-1.7b", dict(seq=2048, extra={"param_dtype": "bfloat16"}),
        24.9, 4685),
    "qwen3-1.7b_seq8192_bs1_gc": (
        "qwen3-1.7b", dict(seq=8192, gc=True, extra={"param_dtype": "bfloat16"}),
        51.5, 7396),
    # 4B AdamW state alone is 22.5 GB even in bf16 — beyond ANY single
    # 16 GB chip. Adafactor (sharding-aware, trainer/factored.py) is the
    # idiomatic TPU answer: same model FLOPs, factored second moments.
    "qwen3-4b_seq2048_bs1_gc": (
        "qwen3-4b", dict(seq=2048, gc=True, extra={
            "param_dtype": "bfloat16", "optimizer_name": "adafactor"}),
        28.4, 2415),
    # 910-sweep rows (scripts/run_npu.sh:20-24)
    "qwen3-0.6b_seq16384_sweep": ("qwen3-0.6b", dict(seq=16384, gc=True), 60.1, 9700),
    "qwen3-0.6b_seq2048_bs4_ga2": (
        "qwen3-0.6b", dict(seq=2048, micro_bs=4, grad_accum=2), 43.9, 19000,
    ),
}


_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory", "OOM")

# MoE dispatch wall-clock A/B (VERDICT r4 #3): the einsum-vs-index token
# movement, measured at a config AOT-verified to fit one v5e (moe-mid,
# 9.4 GB upper bound — tools/bench_moe_dispatch.py). One driver
# invocation settles whether the 2.65x compiled-FLOPs win
# (AOT_30B_A3B.json) survives contact with silicon.
MOE_AB_MODEL = os.environ.get("BENCH_MOE_AB_MODEL", "moe-mid")
MOE_AB_SHAPE = dict(seq=int(os.environ.get("BENCH_MOE_AB_SEQ", 4096)),
                    gc=True)

# CPU fallback (the r03-r05 un-wedger): when no healthy TPU is reachable
# — dead axon relay, cpu-only env — the bench must still produce a
# number instead of burning its whole budget against a backend init that
# never returns. The row is a scaled-down config a 2-core CPU finishes
# in minutes; tok/s (not MFU) is the metric, compared against the first
# CPU measurement below so the trajectory stays attested across rounds.
CPU_FALLBACK_MODEL = "dense-tiny"
CPU_FALLBACK_SHAPE = dict(seq=512, micro_bs=1)
# measured on this container's 2-core CPU (round 6, median of 3 runs);
# future rounds' vs_baseline is relative to this
CPU_FALLBACK_BASELINE_TOK_S = 660.0

# Tests monkeypatch this to substitute a fake child.
CHILD_ARGV = [sys.executable, os.path.abspath(__file__)]


def _budget(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def _mark(stage: str) -> None:
    """Child-side progress marker (stderr) the parent uses to classify
    where a timed-out child wedged."""
    print(json.dumps({"event": "progress", "stage": stage,
                      "t": round(time.time(), 1)}),
          file=sys.stderr, flush=True)


def _last_stage(stderr_text: str) -> str | None:
    stage = None
    for line in stderr_text.splitlines():
        if '"event": "progress"' in line or '"event":"progress"' in line:
            try:
                stage = json.loads(line).get("stage", stage)
            except ValueError:
                pass
    return stage


class ChildResult:
    """Outcome of one budgeted device subprocess."""

    def __init__(self, *, payload=None, error=None, timed_out=False,
                 wedged=False, stage=None, wall_s=0.0, stderr_tail=""):
        self.payload = payload          # parsed stdout JSON (or None)
        self.error = error              # short error string (or None)
        self.timed_out = timed_out      # budget exceeded
        self.wedged = wedged            # still alive after the stop ladder
        self.stage = stage              # last progress marker seen
        self.wall_s = wall_s
        self.stderr_tail = stderr_tail

    @property
    def ok(self) -> bool:
        return self.payload is not None and "error" not in self.payload


def _stop_gently(proc: subprocess.Popen) -> bool:
    """SIGINT-only stop ladder. Returns True if the child exited.

    Never escalates to SIGTERM/SIGKILL: abruptly killing a process with
    in-flight TPU work has wedged the remote-execution tunnel for the
    whole session before (round 2); a stuck child is instead left to the
    driver's own cleanup and reported as ``wedged``.
    """
    waits = [int(w) for w in
             os.environ.get("BENCH_SIGINT_WAITS", "45,20").split(",")]
    for wait_s in waits:
        try:
            proc.send_signal(signal.SIGINT)
        except OSError:
            return True
        try:
            proc.wait(timeout=wait_s)
            return True
        except subprocess.TimeoutExpired:
            continue
    return proc.poll() is not None


def _run_child(env_overrides: dict, budget_s: int, label: str) -> ChildResult:
    """Run bench.py as a child with a hard wall-clock budget.

    stdout/stderr go to temp files (no pipe-buffer deadlock); the last
    stdout line is the child's JSON result.
    """
    t0 = time.perf_counter()
    env = dict(os.environ)
    # Pin the child-mode selectors: a stale exported BENCH_PREFLIGHT /
    # BENCH_ROW from a manual debugging run must not hijack the child's
    # dispatch (a preflight payload recorded as the banked measurement
    # would break the driver contract downstream).
    env["BENCH_PREFLIGHT"] = "0"
    env["BENCH_ROW"] = ""
    env["BENCH_MOE_AB"] = ""
    env["BENCH_PROBE"] = "0"
    env["BENCH_CPU_FALLBACK"] = "0"
    env.update({k: str(v) for k, v in env_overrides.items()})
    with tempfile.TemporaryFile(mode="w+") as out, \
            tempfile.TemporaryFile(mode="w+") as err:
        proc = subprocess.Popen(
            CHILD_ARGV, stdout=out, stderr=err, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        timed_out = wedged = False
        try:
            proc.wait(timeout=budget_s)
        except subprocess.TimeoutExpired:
            timed_out = True
            wedged = not _stop_gently(proc)
        wall = time.perf_counter() - t0
        out.seek(0)
        err.seek(0)
        out_text = out.read()
        err_text = err.read()

    stage = _last_stage(err_text)
    tail = "\n".join(err_text.strip().splitlines()[-4:])
    payload = None
    error = None
    # Parse stdout even after a timeout: a child that printed its result
    # and then stalled in PJRT-client teardown (slow on a degraded
    # tunnel) still produced a valid measurement.
    lines = [ln for ln in out_text.strip().splitlines() if ln.strip()]
    if lines:
        try:
            payload = json.loads(lines[-1])
        except ValueError:
            error = f"{label}: unparseable child output: {lines[-1][:200]}"
    if timed_out and payload is not None and "error" not in payload:
        payload["late_exit"] = True
    elif timed_out:
        error = (f"{label}: exceeded {budget_s}s budget "
                 f"(last stage: {stage or 'none — backend never came up'})")
        payload = None
    elif payload is None and error is None:
        error = (f"{label}: no output (rc={proc.returncode}): "
                 f"{tail[-300:] or 'empty stderr'}")
    if payload is not None and "error" in payload:
        error = f"{label}: {str(payload.get('error'))[:300]}"
    res = ChildResult(payload=payload, error=error, timed_out=timed_out,
                      wedged=wedged, stage=stage, wall_s=round(wall, 1),
                      stderr_tail=tail)
    print(json.dumps({"event": "child_done", "label": label,
                      "ok": res.ok, "error": error, "wall_s": res.wall_s,
                      "stage": stage, "wedged": wedged}),
          file=sys.stderr, flush=True)
    return res


# --------------------------------------------------------------------------
# Child modes (these DO touch the device)
# --------------------------------------------------------------------------

def _pallas_active() -> bool:
    """Was the Pallas flash kernel actually the attention path for this
    run? (Guards the fallback retry: non-kernel failures — config errors,
    CPU runs, FLASH_ATTEN=0 — would just reproduce identically.)"""
    from scaletorch_tpu.env import get_env
    from scaletorch_tpu.ops.flash_attention import _pallas_available

    if get_env("SCALETORCH_TPU_DISABLE_PALLAS") or not get_env("FLASH_ATTEN"):
        return False
    try:
        return _pallas_available()
    except Exception:  # noqa: BLE001 — backend not even initialisable
        return False


def run_preflight() -> dict:
    """Tiny flash-attention fwd+bwd on the real chip: proves the Pallas
    kernel compiles AND executes on this chip/toolchain before the full
    row bets its budget on it. Exercises the GQA index maps and the
    custom VJP at the headline row's head geometry."""
    _mark("start")
    import jax
    import jax.numpy as jnp
    import numpy as np

    jax.local_devices()  # force backend init
    _mark("backend_up")
    from scaletorch_tpu.ops.flash_attention import _pallas_available, flash_attention

    if not _pallas_available():
        return {"preflight": "skip", "reason": "pallas unavailable on this platform"}

    rng = np.random.default_rng(0)
    B, Hq, Hkv, S, D = 1, 16, 8, 4096, 128  # qwen3-0.6b head geometry
    q = jnp.asarray(rng.standard_normal((B, Hq, S, D)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((B, Hkv, S, D)), jnp.bfloat16)

    def loss(q, k, v):
        return flash_attention(q, k, v, causal=True).astype(jnp.float32).sum()

    step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    t0 = time.perf_counter()
    g = step(q, k, v)
    jax.block_until_ready(g)
    compile_s = time.perf_counter() - t0
    _mark("compiled")
    t0 = time.perf_counter()
    for _ in range(3):
        g = step(q, k, v)
    jax.block_until_ready(g)
    step_ms = (time.perf_counter() - t0) / 3 * 1e3
    _mark("done")
    return {"preflight": "ok", "compile_s": round(compile_s, 1),
            "step_ms": round(step_ms, 2),
            "device": jax.local_devices()[0].device_kind}


def run_row(label: str, warmup: int, steps: int) -> dict:
    _mark("start")
    import jax

    jax.local_devices()  # force backend init before any heavy work
    _mark("backend_up")
    from scaletorch_tpu.benchmark import benchmark_config, make_bench_args

    model, shape, base_mfu, base_tok_s = SINGLE_CHIP_ROWS[label]
    shape = dict(shape)
    shape.setdefault("remat_policy", os.environ.get(
        "BENCH_REMAT_POLICY", "nothing_saveable"))
    gc_fallback = False
    pallas_fallback = False
    first_error = None
    pallas_was_active = _pallas_active()
    try:
        cfg = make_bench_args(model, **shape)
        r = benchmark_config(cfg, warmup=warmup, steps=steps, progress=_mark)
    except Exception as e:  # noqa: BLE001
        err = repr(e)
        # VMEM RESOURCE_EXHAUSTED is a kernel-tile overflow (a Pallas
        # problem), NOT an HBM capacity problem — classify it as a kernel
        # failure so the pallas fallback, not the gc fallback, engages.
        is_hbm_oom = (any(m in err for m in _OOM_MARKERS)
                      and "vmem" not in err.lower())
        if is_hbm_oom and not shape.get("gc"):
            # The reference measured its no-GC rows on 64 GB 910Bs; on a
            # smaller-HBM chip rerun them with gradient checkpointing and
            # say so, rather than reporting nothing.
            gc_fallback = True
        elif not is_hbm_oom and pallas_was_active:
            # Kernel-runtime regression on this chip/toolchain should
            # degrade the row to the XLA SDPA path, not erase it.
            pallas_fallback = True
        else:
            raise
        first_error = err[:300]
        print(json.dumps({"event": "row_fallback", "metric": label,
                          "error": first_error}), file=sys.stderr, flush=True)
    if gc_fallback or pallas_fallback:
        # Retry outside the except block: the exception's traceback pins
        # the failed attempt's device buffers until it is cleared.
        import gc

        gc.collect()
        if pallas_fallback:
            os.environ["SCALETORCH_TPU_DISABLE_PALLAS"] = "1"
            pallas_was_active = False
            if not shape.get("gc"):
                # the SDPA fallback materialises full score matrices; a
                # no-GC shape would trade a kernel failure for an HBM OOM
                gc_fallback = True
        cfg = make_bench_args(model, **(dict(shape, gc=True)
                                        if gc_fallback else shape))
        r = benchmark_config(cfg, warmup=warmup, steps=steps, progress=_mark)
        # peak_bytes_in_use still reflects the failed first attempt (no
        # reset API), so the fallback row's memory reading is meaningless.
        r["memory_gb"] = None
    _mark("done")

    if r["mfu"] > 100.0:
        # A >100% MFU means the timing barrier was violated (e.g. a
        # degraded remote-execution tunnel acking work early) — report an
        # error rather than a fantasy number.
        raise RuntimeError(
            f"implausible MFU {r['mfu']}% for {label}: timing barrier violated"
        )
    return {
        "metric": f"{label}_single_chip_mfu",
        "value": r["mfu"],
        "unit": "% MFU",
        "vs_baseline": round(r["mfu"] / base_mfu, 3),
        "tokens_per_second": r["tokens_per_second"],
        "baseline_mfu": base_mfu,
        "baseline_tokens_per_second": base_tok_s,
        "memory_gb": r["memory_gb"],
        "device": jax.local_devices()[0].device_kind,
        "attention_path": "pallas" if pallas_was_active else "sdpa",
        **({"gc_fallback": True} if gc_fallback else {}),
        **({"pallas_fallback": True} if pallas_fallback else {}),
        **({"fallback_error": first_error} if first_error else {}),
        # Echo every training-recipe deviation so cross-commit bench JSON
        # diffs show WHAT changed, not just that the number moved.
        **{k: v for k, v in shape.get("extra", {}).items()
           if k in ("param_dtype", "optimizer_name")},
        **({"remat_policy": shape["remat_policy"]}
           if shape.get("remat_policy", "nothing_saveable")
           != "nothing_saveable" else {}),
    }


def run_probe() -> dict:
    """Bounded backend probe: init the backend, report platform/device,
    exit. The parent uses this (with a small budget) to decide whether a
    real chip is reachable BEFORE betting a 600s row budget on it — the
    r03-r05 wedge spent every budget re-discovering the same dead
    tunnel."""
    _mark("start")
    import jax

    devs = jax.local_devices()
    _mark("backend_up")
    return {
        "probe": "ok",
        "platform": jax.default_backend(),
        "device": devs[0].device_kind,
        "count": len(devs),
    }


def run_cpu_fallback_row(warmup: int, steps: int) -> dict:
    """Child-side CPU measurement: the scaled-down row on the CPU
    backend. tok/s is the metric — MFU against a TPU peak would be
    meaningless here; vs_baseline compares against the first CPU
    measurement so round-over-round drift stays visible."""
    _mark("start")
    import jax

    jax.local_devices()
    _mark("backend_up")
    from scaletorch_tpu.benchmark import benchmark_config, make_bench_args

    cfg = make_bench_args(CPU_FALLBACK_MODEL, **CPU_FALLBACK_SHAPE)
    r = benchmark_config(cfg, warmup=warmup, steps=steps, progress=_mark)
    _mark("done")
    tok_s = r["tokens_per_second"]
    base = CPU_FALLBACK_BASELINE_TOK_S
    return {
        "metric": (f"{CPU_FALLBACK_MODEL}_seq{CPU_FALLBACK_SHAPE['seq']}"
                   "_cpu_fallback_tok_s"),
        "value": tok_s,
        "unit": "tok/s (cpu)",
        "vs_baseline": round(tok_s / base, 3) if base else 1.0,
        "cpu_fallback": True,
        "baseline_tokens_per_second": base,
        "step_time_s": r["step_time_s"],
        "loss": r["loss"],
        "num_params": r["num_params"],
        "device": jax.local_devices()[0].device_kind,
    }


def _cpu_fallback_reason() -> str | None:
    """Why (or whether) device benching is hopeless in this environment.
    Returns None when a TPU may be reachable — the bounded probe child
    then has the final word. BENCH_FORCE_CPU=1 forces the fallback,
    =0 forbids it (operator/test override)."""
    force = os.environ.get("BENCH_FORCE_CPU", "")
    if force == "1":
        return "BENCH_FORCE_CPU=1"
    if force == "0":
        return None
    if _tunnel_probe() is False:
        return ("axon relay tunnel unreachable (connection refused) — "
                "skipping backend init entirely")
    # Only a platform list that PINS cpu (no tpu entry) is a static
    # verdict; "tpu,cpu"-style priority lists leave the decision to the
    # bounded probe child.
    plats = [p.strip() for p in
             os.environ.get("JAX_PLATFORMS", "").lower().split(",")
             if p.strip()]
    if plats and "cpu" in plats and "tpu" not in plats:
        return (f"JAX_PLATFORMS={','.join(plats)} pins the cpu backend "
                "(no accelerator in this environment)")
    return None


def _probe_says_no_tpu() -> str | None:
    """Run the bounded backend probe; a reason string when no healthy
    TPU answered, None when one did."""
    pre = _run_child({"BENCH_PROBE": "1"}, _budget("BENCH_PROBE_BUDGET", 150),
                     "backend_probe")
    if not pre.ok:
        return (f"backend probe failed within its budget: "
                f"{pre.error or 'no output'}")
    platform = str(pre.payload.get("platform", "")).lower()
    if platform not in ("tpu",):
        return f"backend probe found platform {platform!r}, not tpu"
    return None


def run_cpu_fallback(reason: str) -> int:
    """Parent-side CPU fallback: one budgeted CPU child, one JSON line.
    The child env pins JAX_PLATFORMS=cpu and clears the relay pool so
    nothing in it can touch the dead tunnel."""
    print(json.dumps({"event": "cpu_fallback", "reason": reason}),
          file=sys.stderr, flush=True)
    res = _run_child(
        {"BENCH_CPU_FALLBACK": "1", "JAX_PLATFORMS": "cpu",
         "PALLAS_AXON_POOL_IPS": "", "SCALETORCH_TPU_DISABLE_PALLAS": "1"},
        _budget("BENCH_CPU_BUDGET", 480), "cpu_fallback")
    if res.ok:
        payload = dict(res.payload)
        payload["cpu_fallback_reason"] = reason
        _dump_table({"cpu_fallback": payload})
        print(json.dumps(payload))
        return 0
    _error_line(res.error or "cpu fallback row produced nothing",
                cpu_fallback_attempted=True, cpu_fallback_reason=reason)
    return 1


def _ab_summary(table: dict) -> dict | None:
    """Ratio of the two A/B legs' step times, or None when either leg is
    missing/errored (a failed leg must never fabricate a speedup). The
    2.65x compiled-FLOPs prediction is attached only for the config it
    was computed at (moe-mid, AOT_30B_A3B.json) — an overridden A/B model
    measures against no prediction."""
    ab_e = table.get("moe_dispatch_einsum", {})
    ab_i = table.get("moe_dispatch_index", {})
    if not ab_e or not ab_i or "error" in ab_e or "error" in ab_i:
        return None
    return {
        "index_speedup_wallclock": round(
            ab_e["step_time_s"] / ab_i["step_time_s"], 3),
        "config": f"{MOE_AB_MODEL} seq{MOE_AB_SHAPE['seq']} gc",
        **({"compiled_flops_prediction": 2.65}
           if MOE_AB_MODEL == "moe-mid" and MOE_AB_SHAPE["seq"] == 4096
           else {}),
    }


def run_moe_dispatch(mode: str, warmup: int, steps: int) -> dict:
    """One leg of the dispatch A/B: moe-mid with the given token-movement
    form. The parent computes the ratio of the two legs' step times."""
    _mark("start")
    import jax

    jax.local_devices()
    _mark("backend_up")
    from scaletorch_tpu.benchmark import benchmark_config, make_bench_args

    cfg = make_bench_args(MOE_AB_MODEL, **MOE_AB_SHAPE,
                          extra={"moe_dispatch": mode})
    r = benchmark_config(cfg, warmup=warmup, steps=steps, progress=_mark)
    _mark("done")
    return {
        "metric": f"moe_dispatch_{mode}",
        "step_time_s": r["step_time_s"],
        "tokens_per_second": r["tokens_per_second"],
        "mfu": r["mfu"],
        "memory_gb": r["memory_gb"],
        "device": jax.local_devices()[0].device_kind,
    }


# --------------------------------------------------------------------------
# Parent orchestration (never touches JAX)
# --------------------------------------------------------------------------

def _error_line(reason: str, **extra) -> None:
    print(json.dumps({"metric": "error", "value": 0, "unit": "",
                      "vs_baseline": 0, "error": reason[:400], **extra}))


def _tunnel_probe() -> bool | None:
    """Is anything listening on the remote-execution relay's first port?
    Diagnostic only (None when the env doesn't look like the tunnel
    setup): a refused connect distinguishes 'relay process is gone'
    from 'relay up but the far side is stuck'."""
    import socket

    host = os.environ.get("PALLAS_AXON_POOL_IPS", "")
    if host != "127.0.0.1":
        return None
    try:
        with socket.create_connection((host, 8082), timeout=2):
            return True
    except OSError:
        return False


def _dump_table(results: dict) -> None:
    with open("bench_table.json", "w") as f:
        json.dump(results, f, indent=1)


def run_headline() -> int:
    """Default driver mode. Returns the exit code; ALWAYS prints exactly
    one JSON line to stdout."""
    t_start = time.perf_counter()
    deadline = t_start + _budget("BENCH_TOTAL_BUDGET", 1260)
    results: dict = {}

    # Phase 0 — is there a chip at all? Static signals first (dead relay,
    # cpu-only env), then a bounded probe child; either verdict routes to
    # the CPU fallback row instead of wedging every later budget against
    # a backend init that never returns (the r03-r05 failure mode).
    reason = _cpu_fallback_reason()
    if reason is None and os.environ.get("BENCH_FORCE_CPU", "") != "0":
        reason = _probe_says_no_tpu()
    if reason is not None:
        return run_cpu_fallback(reason)

    # Phase 1 — banked row on the XLA SDPA path (round 1's measured-good
    # configuration: 45.41% MFU / 1.164x baseline).
    banked = _run_child(
        {"BENCH_ROW": HEADLINE, "SCALETORCH_TPU_DISABLE_PALLAS": "1"},
        min(_budget("BENCH_ROW_BUDGET", 600),
            int(deadline - time.perf_counter())), "sdpa_row")
    if banked.ok:
        results["sdpa"] = banked.payload
        _dump_table({HEADLINE + "_sdpa": banked.payload})
    else:
        tunnel_dead = banked.timed_out and banked.stage in (None, "start")
        probe = _tunnel_probe()
        _error_line(
            banked.error or "sdpa row produced nothing",
            wedge_stage=banked.stage,
            **({"relay_listening": probe} if probe is not None else {}),
            **({"tunnel": "backend init never completed — axon relay "
                          "tunnel suspected dead"} if tunnel_dead else {}),
        )
        return 1

    # Phase 2 — Pallas experiment, only with a healthy chip and budget.
    remaining = deadline - time.perf_counter()
    skip_reason = None
    if banked.wedged:
        # the banked child printed its result but never exited (stuck in
        # teardown ignoring SIGINT) — the chip is held; launching more
        # device children would just burn their budgets against it
        skip_reason = "chip held by the wedged sdpa child"
    elif os.environ.get("BENCH_SKIP_PALLAS_EXPERIMENT") == "1":
        skip_reason = "BENCH_SKIP_PALLAS_EXPERIMENT=1"
    elif remaining < 360:
        skip_reason = f"only {int(remaining)}s budget left"
    if skip_reason is None:
        # FLASH_ATTEN=1 explicitly: the experiment must measure the
        # Pallas path even if the outer env turned flash off (otherwise
        # the row silently re-measures SDPA and wastes its budget).
        pre = _run_child({"BENCH_PREFLIGHT": "1", "FLASH_ATTEN": "1",
                          "SCALETORCH_TPU_DISABLE_PALLAS": "0"},
                         min(_budget("BENCH_PREFLIGHT_BUDGET", 240),
                             int(remaining - 120)), "pallas_preflight")
        chip_wedged = pre.wedged
        if pre.ok and pre.payload.get("preflight") == "ok":
            remaining = deadline - time.perf_counter()
            if remaining > 180:
                pal = _run_child(
                    {"BENCH_ROW": HEADLINE, "FLASH_ATTEN": "1",
                     "SCALETORCH_TPU_DISABLE_PALLAS": "0"},
                    min(_budget("BENCH_PALLAS_ROW_BUDGET", 480),
                        # keep headroom for the SIGINT stop ladder so a
                        # hung row can't push the parent past its budget
                        int(remaining) - 90), "pallas_row")
                chip_wedged = pal.wedged
                if pal.ok:
                    results["pallas"] = pal.payload
                else:
                    results["pallas_error"] = pal.error
            else:
                results["pallas_error"] = "no budget left for the pallas row"
        elif pre.ok:  # preflight ran but reported skip
            results["pallas_error"] = str(pre.payload.get("reason", "preflight skip"))
        else:
            results["pallas_error"] = pre.error
    else:
        chip_wedged = banked.wedged
        results["pallas_error"] = f"experiment skipped: {skip_reason}"

    # Pick the better headline row; annotate the losing candidate.
    best = results["sdpa"]
    pallas_won = ("pallas" in results
                  and results["pallas"]["value"] > best["value"])
    if pallas_won:
        best = dict(results["pallas"])
        best["sdpa_mfu"] = results["sdpa"]["value"]
    else:
        best = dict(best)
        if "pallas" in results:
            best["pallas_mfu"] = results["pallas"]["value"]
        elif results.get("pallas_error"):
            best["pallas_skipped"] = str(results["pallas_error"])[:200]
    table = {HEADLINE + "_" + k: v for k, v in results.items()
             if isinstance(v, dict)}
    _dump_table(table)

    # Phase 3 — opportunistic extra table rows with whatever budget is
    # left (the reference publishes a full measured table; one driver
    # invocation should bank as much of it as the window allows). The
    # winning attention path is reused; the seq-16384 row leads (the
    # reference's best single-chip MFU, 56.0%).
    # pin the winning path explicitly — extra rows must not drift to the
    # other path under a stale outer FLASH_ATTEN/DISABLE_PALLAS export
    extra_env = ({"FLASH_ATTEN": "1", "SCALETORCH_TPU_DISABLE_PALLAS": "0"}
                 if pallas_won
                 else {"SCALETORCH_TPU_DISABLE_PALLAS": "1"})

    def _measure(label: str, env: dict, budget_key: str) -> bool:
        """One budgeted phase-3 child into the table. Returns False when
        the phase should END (wedge, no budget, or a timeout — even a
        late_exit row means every further child pays budget + stop ladder
        on a degraded chip)."""
        nonlocal chip_wedged
        remaining = deadline - time.perf_counter()
        if chip_wedged or remaining < 400:
            return False
        res = _run_child(env, min(_budget(budget_key, 420),
                                  int(remaining) - 90), label)
        chip_wedged = res.wedged
        if res.payload is not None:
            table[label] = res.payload
        else:
            table[label] = {"metric": label, "error": res.error}
        _dump_table(table)
        return not res.timed_out

    # priority order (VERDICT): the seq-16384 rows (reference's 56.0%
    # best — standard recipe, then the AOT-planned bf16+save_attn
    # recipe), then the MoE dispatch wall-clock A/B, then the rest of
    # the single-chip table.
    # the bf16+save_attn recipe only makes sense on the flash path (its
    # whole point is keeping the kernel's (out, lse) residuals); when
    # SDPA won, skip it so the dispatch A/B stays reachable in-budget
    seq16k_rows = ["qwen3-0.6b_seq16384_bs1_gc"]
    if pallas_won:
        seq16k_rows.append("qwen3-0.6b_seq16384_bf16_save_attn")
    go = True
    for label in seq16k_rows:
        go = _measure(label, dict(extra_env, BENCH_ROW=label),
                      "BENCH_EXTRA_ROW_BUDGET")
        if not go:
            break
    if go:
        for mode in ("einsum", "index"):
            go = _measure(f"moe_dispatch_{mode}",
                          dict(extra_env, BENCH_MOE_AB=mode),
                          "BENCH_MOE_AB_BUDGET")
            if not go:
                break
        ab = _ab_summary(table)
        if ab is not None:
            table["moe_dispatch_ab"] = ab
            best["moe_dispatch_index_speedup"] = ab["index_speedup_wallclock"]
            _dump_table(table)
    if go:
        for label in ("qwen3-0.6b_seq2048_bs4_ga2", "qwen3-0.6b_seq2048_bs2",
                      "qwen3-1.7b_seq8192_bs1_gc", "qwen3-1.7b_seq2048_bs1",
                      "qwen3-4b_seq2048_bs1_gc"):
            if not _measure(label, dict(extra_env, BENCH_ROW=label),
                            "BENCH_EXTRA_ROW_BUDGET"):
                break
    best["bench_wall_s"] = round(time.perf_counter() - t_start, 1)
    best["rows_measured"] = sum(1 for v in table.values() if "error" not in v)
    print(json.dumps(best))
    return 0


def run_table() -> int:
    """--table: every single-chip row + the MoE dispatch A/B, one
    budgeted subprocess each."""
    results = {}
    wedged = False
    row_budget = _budget("BENCH_TABLE_ROW_BUDGET", 780)
    ab_children = {f"moe_dispatch_{m}": {"BENCH_MOE_AB": m}
                   for m in ("einsum", "index")}
    for label in list(SINGLE_CHIP_ROWS) + list(ab_children):
        if wedged:
            results[label] = {"metric": label,
                              "error": "skipped: chip wedged by an earlier row"}
        else:
            env = ab_children.get(label, {"BENCH_ROW": label})
            res = _run_child(env, row_budget, label)
            if res.payload is not None:
                results[label] = res.payload
            else:
                results[label] = {"metric": label, "error": res.error,
                                  **({"wedge_stage": res.stage}
                                     if res.timed_out else {})}
            results[label]["wall_s"] = res.wall_s
            wedged = res.wedged
        print(json.dumps(results[label]), file=sys.stderr, flush=True)
        _dump_table(results)
    ab = _ab_summary(results)
    if ab is not None:
        results["moe_dispatch_ab"] = ab
        _dump_table(results)
    head = results.get(HEADLINE, {})
    if "error" in head:
        _error_line(str(head["error"]))
        return 1
    print(json.dumps(head))
    return 0


def main() -> int:
    unknown = [a for a in sys.argv[1:] if a != "--table"]
    if unknown:
        raise SystemExit(f"unknown arguments {unknown}; supported: --table "
                         "(other knobs via BENCH_* env vars)")

    # An explicit --table wins over any (possibly stale) child-mode env:
    # table children are spawned WITHOUT --table, so there is no recursion.
    if "--table" in sys.argv:
        return run_table()

    # Child modes next: they are the only paths that import JAX.
    if (os.environ.get("BENCH_PREFLIGHT") == "1" or os.environ.get("BENCH_ROW")
            or os.environ.get("BENCH_MOE_AB")
            or os.environ.get("BENCH_PROBE") == "1"
            or os.environ.get("BENCH_CPU_FALLBACK") == "1"):
        # stdout must carry ONLY the result JSON (parent parses the last
        # line): move the framework logger's streams to stderr.
        import logging

        from scaletorch_tpu.utils.logger import get_logger

        for h in get_logger().handlers:
            if isinstance(h, logging.StreamHandler):
                h.setStream(sys.stderr)
    if os.environ.get("BENCH_PROBE") == "1":
        print(json.dumps(run_probe()))
        return 0
    if os.environ.get("BENCH_CPU_FALLBACK") == "1":
        print(json.dumps(run_cpu_fallback_row(
            int(os.environ.get("BENCH_WARMUP_STEPS", 1)),
            int(os.environ.get("BENCH_STEPS", 3)))))
        return 0
    if os.environ.get("BENCH_PREFLIGHT") == "1":
        print(json.dumps(run_preflight()))
        return 0
    if os.environ.get("BENCH_MOE_AB"):
        mode = os.environ["BENCH_MOE_AB"]
        if mode not in ("einsum", "index"):
            raise KeyError(f"BENCH_MOE_AB {mode!r} must be einsum|index")
        print(json.dumps(run_moe_dispatch(
            mode,
            int(os.environ.get("BENCH_WARMUP_STEPS", 2)),
            int(os.environ.get("BENCH_STEPS", 8)))))
        return 0
    if os.environ.get("BENCH_ROW"):
        warmup = int(os.environ.get("BENCH_WARMUP_STEPS", 3))
        steps = int(os.environ.get("BENCH_STEPS", 10))
        label = os.environ["BENCH_ROW"]
        if label not in SINGLE_CHIP_ROWS:
            raise KeyError(
                f"BENCH_ROW {label!r} unknown; rows: {', '.join(SINGLE_CHIP_ROWS)}"
            )
        # Back-compat: BENCH_SEQ_LEN overrides the headline row's sequence.
        if label == HEADLINE and os.environ.get("BENCH_SEQ_LEN"):
            SINGLE_CHIP_ROWS[label][1]["seq"] = int(os.environ["BENCH_SEQ_LEN"])
        print(json.dumps(run_row(label, warmup, steps)))
        return 0

    return run_headline()


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as e:  # noqa: BLE001 — the driver needs a JSON line either way
        _error_line(repr(e))
        sys.exit(1)
