"""Headline benchmark: Qwen3-0.6B single-chip pretraining throughput.

Mirrors the reference's headline single-device row — Qwen3-0.6B,
seq 8192, micro-batch 1, gradient checkpointing, bf16 — which achieved
9,834 tok/s at 39.0% MFU on one Ascend 910B (BASELINE.md, reference
README.md:31). MFU is the hardware-normalised comparison: we report our
MFU on whatever single TPU chip the driver provides and compare against
the reference's 39.0% at the identical model/sequence configuration.

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": "...", "vs_baseline": N}
"""

from __future__ import annotations

import json
import os
import sys
import time

# Benchmark wants the real chip; nothing here should touch the test env.
os.environ.setdefault("XLA_PYTHON_CLIENT_MEM_FRACTION", "0.92")

BASELINE_MFU = 39.0  # reference Qwen3-0.6B seq8192 BS1 GC on 910B (README.md:31)

# Qwen3-0.6B architecture (HF Qwen/Qwen3-0.6B config).
QWEN3_0_6B = dict(
    model_type="qwen3",
    vocab_size=151936,
    hidden_size=1024,
    intermediate_size=3072,
    num_hidden_layers=28,
    num_attention_heads=16,
    num_key_value_heads=8,
    head_dim=128,
    tie_word_embeddings=True,
    rope_theta=1e6,
)


def main() -> None:
    import jax

    from scaletorch_tpu.config import ScaleTorchTPUArguments
    from scaletorch_tpu.trainer.trainer import Trainer

    seq_len = int(os.environ.get("BENCH_SEQ_LEN", 8192))
    warmup = int(os.environ.get("BENCH_WARMUP_STEPS", 3))
    steps = int(os.environ.get("BENCH_STEPS", 10))

    cfg = ScaleTorchTPUArguments(
        **QWEN3_0_6B,
        sequence_length=seq_len,
        micro_batch_size=1,
        gradient_accumulation_steps=1,
        gradient_checkpointing=True,
        synthetic_data=True,
        dtype="bfloat16",
        total_train_steps=warmup + steps,
        log_frequency=10_000,  # silence per-step logging during timing
        max_grad_norm=1.0,
    )

    trainer = Trainer(cfg)
    trainer.train(num_steps=warmup)  # compile + stabilise
    jax.block_until_ready(trainer.params)

    t0 = time.perf_counter()
    trainer.train(num_steps=steps)
    jax.block_until_ready(trainer.params)
    elapsed = time.perf_counter() - t0

    tok_s = trainer.loader.tokens_per_step * steps / elapsed

    from scaletorch_tpu.utils.misc import get_mfu, get_num_params

    mfu = get_mfu(
        tok_s,
        get_num_params(trainer.params),
        trainer.model_cfg.num_hidden_layers,
        trainer.model_cfg.num_attention_heads,
        trainer.model_cfg.actual_head_dim,
        seq_len,
        num_chips=len(jax.devices()),
    )
    result = {
        "metric": "qwen3-0.6b_seq8192_bs1_gc_single_chip_mfu",
        "value": round(mfu, 2),
        "unit": "% MFU",
        "vs_baseline": round(mfu / BASELINE_MFU, 3),
        "tokens_per_second": round(tok_s, 1),
        "device": jax.devices()[0].device_kind,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — the driver needs a JSON line either way
        print(json.dumps({"metric": "error", "value": 0, "unit": "",
                          "vs_baseline": 0, "error": repr(e)}))
        sys.exit(1)
