#!/usr/bin/env python
"""Device-mesh parallelism walkthrough — the TPU/JAX rendition of the
reference's examples/device_mesh tier (device_mesh_api.py, dtensor_demo,
tensor_parallel_demo, sequence_parallel_demo, fsdp_dp_demo, fsdp_tp_demo,
manual_process_group).

Where torch builds each strategy from process groups + DTensor placements
+ module wrappers, JAX has exactly two primitives and everything below is
a composition of them:

  * ``NamedSharding(mesh, PartitionSpec(...))`` — declarative placement;
    the XLA SPMD partitioner inserts the collectives (DTensor's role).
  * ``jax.shard_map`` — per-device programs with explicit collectives
    (the manual process-group role).

Run (8 virtual devices):
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/device_mesh/mesh_demos.py
"""

from __future__ import annotations

import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)


def demo_mesh_api():
    """2-D mesh construction (reference device_mesh_api.py:1-30 and
    manual_process_group.py roles — axis names replace group handles)."""
    import jax
    from jax.sharding import Mesh

    devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("replicate", "shard"))
    print(f"[mesh-api] mesh axes {dict(mesh.shape)} "
          f"(2 replicate x 4 shard, no process groups needed)")
    return mesh


def demo_dtensor_placements(mesh):
    """Shard / Replicate / partial placements (reference dtensor_demo):
    in JAX each is a PartitionSpec, conversions are device_put."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = jnp.arange(32.0).reshape(8, 4)
    sharded = jax.device_put(x, NamedSharding(mesh, P("shard", None)))
    replicated = jax.device_put(x, NamedSharding(mesh, P()))
    print(f"[dtensor] Shard(0): {sharded.sharding.spec}, per-device "
          f"{sharded.addressable_shards[0].data.shape}; Replicate(): "
          f"{replicated.sharding.spec}, per-device "
          f"{replicated.addressable_shards[0].data.shape}")
    # 'partial' (pending-reduction) values live inside shard_map as
    # un-psummed accumulators — see demo_tensor_parallel's local matmuls.
    resharded = jax.device_put(replicated, NamedSharding(mesh, P(None, "shard")))
    print(f"[dtensor] redistribute -> {resharded.sharding.spec}, per-device "
          f"{resharded.addressable_shards[0].data.shape}")


def demo_tensor_parallel():
    """Megatron TP MLP: column-shard W1, row-shard W2, ONE all-reduce
    (reference tensor_parallel_demo.py) — via the framework's own ops."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from scaletorch_tpu.parallel.tensor_parallel import (
        column_parallel_linear,
        pvary_missing,
        row_parallel_linear,
    )

    mesh = Mesh(np.asarray(jax.devices()[:4]), ("tp",))
    h, f = 32, 64
    x = jnp.ones((2, 8, h))
    w1 = 0.02 * jnp.arange(h * f, dtype=jnp.float32).reshape(h, f) / (h * f)
    w2 = w1.T / 10.0

    def tp_mlp(x, w1, w2):
        x = pvary_missing(x, ("tp",))
        hidden = column_parallel_linear(x, w1, axis="tp")     # no comm
        return row_parallel_linear(hidden, w2, axis="tp")     # one psum

    out = jax.shard_map(
        tp_mlp, mesh=mesh,
        in_specs=(P(), P(None, "tp"), P("tp", None)), out_specs=P(),
    )(x, w1, w2)
    ref = (x @ w1) @ w2
    ok = bool(jnp.allclose(out, ref, atol=1e-5))
    assert ok, "tensor-parallel MLP diverged from single-device reference"
    print(f"[tp] col+row parallel MLP matches single-device: "
          f"{ok} (one all-reduce total)")


def demo_sequence_parallel():
    """SP: ranks hold different sequence shards; all-gather in, reduce-
    scatter out (reference sequence_parallel_demo.py)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from scaletorch_tpu.parallel.sequence_parallel import (
        all_gather_sequence,
        reduce_scatter_sequence,
    )

    mesh = Mesh(np.asarray(jax.devices()[:4]), ("tp",))
    x = jnp.arange(4 * 16 * 8, dtype=jnp.float32).reshape(1, 64, 8)

    def sp_block(x_shard, w):
        full = all_gather_sequence(x_shard, axis="tp")        # [1, 64, 8]
        # In real SP this matmul is row-parallel, so each rank holds a
        # PARTIAL result; the reduce-scatter both sums the partials and
        # re-shards the sequence. Emulate the partial with w/4.
        y = full @ (w / 4.0)
        return reduce_scatter_sequence(y, axis="tp")          # [1, 16, 8]

    w = jnp.eye(8) * 2.0
    w_v = jax.shard_map(
        lambda x, w: sp_block(x, jax.lax.pvary(w, ("tp",))),
        mesh=mesh, in_specs=(P(None, "tp", None), P()),
        out_specs=P(None, "tp", None),
    )(x, w)
    ok = bool(jnp.allclose(w_v, x * 2.0, atol=1e-5))
    assert ok, "sequence-parallel round-trip diverged"
    print(f"[sp] gather->compute->reduce-scatter round-trips the sequence: "
          f"{ok} (per-rank seq {x.shape[1] // 4})")


def demo_fsdp_dp():
    """HSDP: FSDP sharding inside fast-link groups, DP replication across
    them (reference fsdp_dp_demo.py) — one PartitionSpec, zero wrappers."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("dp_replicate", "dp_shard"))
    w = jnp.zeros((1024, 64))
    placed = jax.device_put(w, NamedSharding(mesh, P("dp_shard", None)))
    shard = placed.addressable_shards[0].data.shape
    print(f"[hsdp] param {w.shape} -> per-device {shard}: sharded 4-way "
          f"inside each replica group, replicated across the 2 groups")


def demo_fsdp_tp():
    """FSDP x TP 2-D parallelism (reference fsdp_tp_demo.py): shard
    storage over 'fsdp', shard computation over 'tp'."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("fsdp", "tp"))
    w_col = jnp.zeros((64, 512))   # column-parallel weight
    placed = jax.device_put(w_col, NamedSharding(mesh, P("fsdp", "tp")))
    print(f"[fsdp+tp] weight {w_col.shape} -> per-device "
          f"{placed.addressable_shards[0].data.shape}: tp splits the "
          f"compute dim, fsdp splits storage of each tp shard; XLA "
          f"all-gathers over 'fsdp' just-in-time")


def main():
    import jax

    if len(jax.devices()) < 8:
        raise SystemExit(
            f"these demos need >= 8 devices, have {len(jax.devices())}. "
            "Run with: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
    mesh = demo_mesh_api()
    demo_dtensor_placements(mesh)
    demo_tensor_parallel()
    demo_sequence_parallel()
    demo_fsdp_dp()
    demo_fsdp_tp()
    print("all device-mesh demos passed")


if __name__ == "__main__":
    main()
