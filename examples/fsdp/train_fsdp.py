#!/usr/bin/env python
"""FSDP training demo — parameter + optimizer-state sharding on a mesh.

Counterpart of reference examples/FSDP2/fsdp2_main.py (toy Transformer,
``fully_shard`` over a 1-D device mesh, mixed precision, checkpoint
save/resume): the TPU version places each parameter sharded over the
``fsdp`` axis (parallel/fsdp.py) and lets the XLA SPMD partitioner issue
the just-in-time all-gathers and gradient reduce-scatters that FSDP2
performs with imperative hooks. Run on any mesh:

    # 8 virtual CPU devices
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/fsdp/train_fsdp.py --steps 10

    python examples/fsdp/train_fsdp.py --mixed-precision   # bf16 params
    python examples/fsdp/train_fsdp.py --checkpoint-dir /tmp/fsdp_ckpt
    # second run with the same --checkpoint-dir resumes (reference
    # fsdp2_main.py's save-then-load flow)
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)


def main(argv=None) -> float:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--rows", type=int, default=8,
                    help="global batch rows (sharded over the fsdp axis)")
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--mixed-precision", action="store_true",
                    help="bf16 params + bf16 compute "
                         "(reference fsdp2_main.py --mixed-precision)")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="save/resume dir; a second run resumes from it")
    ap.add_argument("--log_interval", type=int, default=1)
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from scaletorch_tpu.config import ScaleTorchTPUArguments
    from scaletorch_tpu.models.llama import LlamaConfig, forward, init_params
    from scaletorch_tpu.parallel.fsdp import setup_fsdp
    from scaletorch_tpu.trainer.optimizer import create_optimizer

    dtype = jnp.bfloat16 if args.mixed_precision else jnp.float32
    cfg = LlamaConfig(
        vocab_size=512, hidden_size=128, intermediate_size=256,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, max_position_embeddings=max(64, args.seq),
        dtype=dtype, param_dtype=dtype,
    )

    # Peek at the checkpoint BEFORE building the optimizer: the restored
    # adam count is cumulative, so the LR schedule's horizon must cover
    # resumed + new steps or resumed training runs at the decayed floor.
    start_step = 0
    ckpt = None
    if args.checkpoint_dir:
        import orbax.checkpoint as ocp

        ckpt = ocp.CheckpointManager(os.path.abspath(args.checkpoint_dir))
        start_step = ckpt.latest_step() or 0

    targs = ScaleTorchTPUArguments(
        total_train_steps=start_step + args.steps,
        learning_rate=args.lr, warmup_steps=2, max_grad_norm=1.0,
    )
    tx, _ = create_optimizer(targs, include_clip=True)

    params_host = init_params(jax.random.key(0), cfg)
    step_fn, params, opt_state, mesh = setup_fsdp(forward, cfg, params_host, tx)
    n_dev = mesh.shape["fsdp"]
    if args.rows % n_dev:
        raise SystemExit(f"--rows {args.rows} must divide over {n_dev} devices")

    if ckpt is not None and start_step:
        import orbax.checkpoint as ocp

        # Restore INTO the current mesh's shardings (abstract template):
        # resuming on a different topology re-shards instead of replaying
        # the saved placement from the sharding file.
        template = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
            {"params": params, "opt_state": opt_state},
        )
        restored = ckpt.restore(
            start_step, args=ocp.args.StandardRestore(template)
        )
        # Belt and braces: orbax honours the template for arrays but can
        # leave rank-0 leaves on a single device — re-place everything.
        restored = jax.tree.map(
            lambda x, t: jax.device_put(x, t.sharding), restored, template
        )
        params, opt_state = restored["params"], restored["opt_state"]
        print(f"resumed from step {start_step} in {args.checkpoint_dir}")

    # parameter memory actually sharded: report per-device bytes
    total = sum(p.size * p.dtype.itemsize for p in jax.tree.leaves(params))
    local = sum(
        p.addressable_shards[0].data.size * p.dtype.itemsize
        for p in jax.tree.leaves(params)
    )
    print(f"devices={n_dev} param_bytes total={total/1e6:.1f}MB "
          f"per-device={local/1e6:.1f}MB (x{total/max(local,1):.1f} saving)")

    rng = np.random.default_rng(start_step)
    loss = float("nan")
    for step in range(start_step, start_step + args.steps):
        ids = rng.integers(0, cfg.vocab_size, (1, args.rows, args.seq + 1))
        batch = {
            "input_ids": jnp.asarray(ids[:, :, :-1], jnp.int32),
            "target_ids": jnp.asarray(ids[:, :, 1:], jnp.int32),
        }
        params, opt_state, m = step_fn(params, opt_state, batch)
        loss = float(m["loss"])
        if (step + 1) % args.log_interval == 0:
            print(f"step {step + 1:>4} | loss {loss:.4f} "
                  f"| gnorm {float(m['grad_norm']):.3f}")

    if ckpt is not None:
        import orbax.checkpoint as ocp

        ckpt.save(
            start_step + args.steps,
            args=ocp.args.StandardSave({"params": params,
                                        "opt_state": opt_state}),
        )
        ckpt.wait_until_finished()
        print(f"saved step {start_step + args.steps} to {args.checkpoint_dir}")
    return loss


if __name__ == "__main__":
    main()
