#!/usr/bin/env python
"""Distributed ImageNet-style training — ResNet over a data-parallel mesh.

Counterpart of reference examples/torch_examples/imagenet/dist_train.py
(the classic DDP script: resnet18 default, SGD+momentum, StepLR decay
x0.1 every 30 epochs, top-1/top-5 accuracy, best-checkpoint save,
resume). TPU rendition: the batch is sharded over a 1-D `dp` mesh with
NamedSharding and XLA handles the gradient all-reduce; BatchNorm
statistics reduce over the GLOBAL batch (sync-BN — torch's
SyncBatchNorm rather than DDP's local default, models/resnet.py), so
training dynamics are independent of the device count.

Data: an ImageFolder-style directory of per-class .npy arrays if --data
is given, else a deterministic synthetic stand-in (fixed class
prototypes + noise) so the example is hermetic offline.

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/imagenet/dist_train.py --arch resnet18 \
        --image-size 64 --num-classes 10 --epochs 2
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)


def synthetic_images(n, num_classes, size, seed=0):
    """Fixed per-class prototypes + noise (learnable, hermetic)."""
    protos = np.random.default_rng(4321).uniform(
        0, 1, (num_classes, size, size, 3)).astype(np.float32)
    rng = np.random.default_rng(seed)
    y = rng.integers(0, num_classes, n).astype(np.int32)
    x = protos[y] + rng.normal(0, 0.35, (n, size, size, 3)).astype(np.float32)
    return np.clip(x, 0, 1), y


def load_folder(data_dir, size):
    """Minimal ImageFolder: data_dir/<class>/*.npy arrays [H, W, 3]."""
    classes = sorted(
        d for d in os.listdir(data_dir)
        if os.path.isdir(os.path.join(data_dir, d))
    )
    xs, ys = [], []
    for ci, cname in enumerate(classes):
        cdir = os.path.join(data_dir, cname)
        for f in sorted(os.listdir(cdir)):
            if f.endswith(".npy"):
                arr = np.load(os.path.join(cdir, f)).astype(np.float32)
                if arr.shape[:2] != (size, size):
                    raise SystemExit(
                        f"{f}: expected {size}x{size}, got {arr.shape[:2]}; "
                        "resize offline (no image libs in this example)")
                xs.append(arr)
                ys.append(ci)
    if not xs:
        raise SystemExit(f"no .npy files under {data_dir}")
    x, y = np.stack(xs), np.asarray(ys, np.int32)
    # deterministic shuffle BEFORE the train/val split: the folder walk is
    # class-ordered, so an unshuffled tail split would make the val set a
    # single class that training never saw
    perm = np.random.default_rng(0).permutation(len(x))
    return x[perm], y[perm], classes


def topk_correct(logits, labels, ks=(1, 5)):
    import jax.numpy as jnp

    order = jnp.argsort(logits, axis=-1)[:, ::-1]
    out = []
    for k in ks:
        kk = min(k, logits.shape[-1])
        out.append(jnp.any(order[:, :kk] == labels[:, None], axis=-1).sum())
    return out


def main(argv=None) -> float:
    ap = argparse.ArgumentParser(description="ResNet ImageNet-style training")
    ap.add_argument("--data", default=None, help="ImageFolder-style dir of "
                    "per-class .npy arrays; synthetic when omitted")
    ap.add_argument("-a", "--arch", default="resnet18",
                    choices=["resnet18", "resnet34"])
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("-b", "--batch-size", type=int, default=64,
                    help="GLOBAL batch (sharded over the dp mesh)")
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--wd", type=float, default=1e-4)
    ap.add_argument("--lr-step-epochs", type=int, default=30,
                    help="StepLR: decay x0.1 every N epochs (reference)")
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--num-classes", type=int, default=1000)
    ap.add_argument("--train-samples", type=int, default=2048)
    ap.add_argument("--val-samples", type=int, default=512)
    ap.add_argument("--width", type=int, default=64)
    ap.add_argument("--bn-momentum", type=float, default=0.1,
                    help="running-stat EMA rate; raise for short runs so "
                         "eval-mode BN converges quickly")
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--print-freq", type=int, default=10)
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from scaletorch_tpu.models.resnet import ResNetConfig, forward, init_params

    if args.data:
        x_all, y_all, classes = load_folder(args.data, args.image_size)
        args.num_classes = len(classes)
        split = int(0.9 * len(x_all))
        tx_, ty_ = x_all[:split], y_all[:split]
        vx_, vy_ = x_all[split:], y_all[split:]
    else:
        tx_, ty_ = synthetic_images(
            args.train_samples, args.num_classes, args.image_size)
        vx_, vy_ = synthetic_images(
            args.val_samples, args.num_classes, args.image_size, seed=1)

    cfg = ResNetConfig(
        depth=int(args.arch.replace("resnet", "")),
        num_classes=args.num_classes, width=args.width,
        image_size=args.image_size, bn_momentum=args.bn_momentum,
    )
    params, bn_state = init_params(jax.random.key(0), cfg)
    n_params = sum(p.size for p in jax.tree.leaves(params))

    devs = jax.devices()
    mesh = Mesh(np.asarray(devs), ("dp",))
    n_dev = len(devs)
    if args.batch_size % n_dev:
        raise SystemExit(f"--batch-size {args.batch_size} must divide over "
                         f"{n_dev} devices")
    if len(tx_) < args.batch_size:
        raise SystemExit(f"train set ({len(tx_)}) smaller than the global "
                         f"batch ({args.batch_size}); lower --batch-size")
    print(f"=> {args.arch}: {n_params / 1e6:.2f}M params, "
          f"{n_dev}-way data parallel, global batch {args.batch_size}")

    steps_per_epoch = max(len(tx_) // args.batch_size, 1)
    # StepLR x0.1 every lr_step_epochs (reference dist_train.py StepLR)
    schedule = optax.exponential_decay(
        args.lr, transition_steps=args.lr_step_epochs * steps_per_epoch,
        decay_rate=0.1, staircase=True,
    )
    tx = optax.chain(
        optax.add_decayed_weights(args.wd),
        optax.sgd(schedule, momentum=args.momentum),
    )
    opt_state = tx.init(params)

    batch_sh = NamedSharding(mesh, P("dp"))

    @jax.jit
    def train_step(params, bn_state, opt_state, images, labels):
        def loss_fn(p, s):
            logits, new_s = forward(p, s, images, cfg, train=True)
            ce = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), labels).mean()
            return ce, (new_s, logits)

        (loss, (bn_state2, logits)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, bn_state)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        t1, t5 = topk_correct(logits, labels)
        return params, bn_state2, opt_state, loss, t1, t5

    @jax.jit
    def eval_step(params, bn_state, images, labels):
        logits, _ = forward(params, bn_state, images, cfg, train=False)
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), labels).mean()
        t1, t5 = topk_correct(logits, labels)
        return ce, t1, t5

    def put(x):
        return jax.device_put(x, batch_sh)

    best_acc1, last_loss = 0.0, float("nan")
    rng = np.random.default_rng(0)
    for epoch in range(args.epochs):
        order = rng.permutation(len(tx_))
        t0, seen, c1 = time.time(), 0, 0
        for it in range(steps_per_epoch):
            idx = order[it * args.batch_size:(it + 1) * args.batch_size]
            params, bn_state, opt_state, loss, t1, t5 = train_step(
                params, bn_state, opt_state,
                put(jnp.asarray(tx_[idx])), put(jnp.asarray(ty_[idx])))
            last_loss = float(loss)
            seen += len(idx)
            c1 += int(t1)
            if (it + 1) % args.print_freq == 0 or it == steps_per_epoch - 1:
                ips = seen / (time.time() - t0)
                print(f"Epoch [{epoch}][{it + 1}/{steps_per_epoch}] "
                      f"loss {last_loss:.4f} acc@1 {100 * c1 / seen:.2f}% "
                      f"({ips:.0f} img/s)")

        # validation (reference validate(): top-1/top-5 over the val set).
        # Batches must divide over the mesh; trim to a device multiple and
        # report how many samples were actually scored.
        vtot, v1, v5, vloss = 0, 0, 0, 0.0
        vbs = args.batch_size
        usable = (len(vx_) // n_dev) * n_dev
        it0 = 0
        while it0 < usable:
            n = min(vbs, usable - it0)
            n = (n // n_dev) * n_dev
            sl = slice(it0, it0 + n)
            it0 += n
            ce, t1, t5 = eval_step(params, bn_state,
                                   put(jnp.asarray(vx_[sl])),
                                   put(jnp.asarray(vy_[sl])))
            vtot += n; v1 += int(t1); v5 += int(t5)
            vloss += float(ce) * n
        acc1 = 100 * v1 / max(vtot, 1)
        dropped = len(vx_) - usable
        print(f" * Val acc@1 {acc1:.2f}% acc@5 {100 * v5 / max(vtot, 1):.2f}% "
              f"loss {vloss / max(vtot, 1):.4f} ({vtot} samples"
              + (f", {dropped} dropped to fit the mesh)" if dropped else ")"))

        if args.checkpoint_dir and acc1 >= best_acc1:
            import pickle

            os.makedirs(args.checkpoint_dir, exist_ok=True)
            host = jax.tree.map(np.asarray, {"params": params,
                                             "bn_state": bn_state,
                                             "epoch": epoch, "acc1": acc1})
            with open(os.path.join(args.checkpoint_dir, "model_best.pkl"),
                      "wb") as f:
                pickle.dump(host, f)
            print(f"=> saved best (acc@1 {acc1:.2f}%)")
        best_acc1 = max(best_acc1, acc1)
    return best_acc1


if __name__ == "__main__":
    main()
