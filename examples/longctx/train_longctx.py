#!/usr/bin/env python
"""Long-context training demo — context parallelism over the cp axis.

The capability tour the reference demonstrates with its CP benchmark
rows (BASELINE.md: CP2-DP4 at seq 4096, CP4-DP2 at seq 8192): sequences
longer than one chip wants to attend over are sharded across the ``cp``
mesh axis and attention runs distributed, via either

  * ``--strategy ring``     — zigzag-striped ring attention (default):
    K/V blocks circulate the ring and every rank does equal causal work;
  * ``--strategy ulysses``  — all-to-all head scatter: each rank runs one
    full-sequence flash attention over a head subset (cp must divide the
    KV head count).

The loss is IDENTICAL to single-device attention (golden-tested in
tests/parallel/test_context_parallel.py, tests/ops/test_ulysses.py);
what CP buys is memory headroom and parallel attention FLOPs, so the
max trainable sequence scales with cp. Run on any mesh:

    # 8 virtual CPU devices: seq 2048 across cp=4
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/longctx/train_longctx.py --cp 4 --seq 2048

    python examples/longctx/train_longctx.py --cp 2 --strategy ulysses
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)


def main(argv=None) -> float:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cp", type=int, default=4)
    ap.add_argument("--dp", type=int, default=0,
                    help="0 = fill the remaining devices")
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--strategy", choices=["ring", "ulysses"], default="ring")
    ap.add_argument("--layout", choices=["zigzag", "contiguous"],
                    default="zigzag", help="ring sequence layout")
    args = ap.parse_args(argv)

    import jax

    from scaletorch_tpu.config import ScaleTorchTPUArguments
    from scaletorch_tpu.trainer.trainer import Trainer

    n_dev = len(jax.devices())
    dp = args.dp or max(n_dev // args.cp, 1)
    cfg = ScaleTorchTPUArguments(
        model_type="llama", hidden_size=64, intermediate_size=128,
        # 4 KV heads so the default --cp 4 works for ulysses too
        # (cp must divide the KV head count for the head-scatter path)
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        vocab_size=256, sequence_length=args.seq,
        max_position_embeddings=2 * args.seq,
        context_parallel_size=args.cp, data_parallel_size=dp,
        cp_layout=args.layout,
        attention_backend=args.strategy,
        # per-rank batch of 1: per-chip work stays fixed as the mesh
        # grows (micro_batch_size is PER dp rank; global = micro * dp)
        micro_batch_size=1, synthetic_data=True,
        total_train_steps=args.steps, dtype="float32",
        donate_params=False, log_frequency=max(args.steps // 4, 1),
    )
    trainer = Trainer(cfg)
    print(f"devices={n_dev} cp={args.cp} dp={dp} seq={args.seq} "
          f"strategy={args.strategy}"
          + (f" layout={args.layout}" if args.strategy == "ring" else ""))
    try:
        it = iter(trainer.loader)
        first = last = None
        for step in range(args.steps):
            batch = trainer._device_batch(next(it))
            trainer.params, trainer.opt_state, m = trainer.step_fn(
                trainer.params, trainer.opt_state, batch)
            last = float(m["loss"])
            if first is None:
                first = last
        tokens = args.steps * trainer.loader.tokens_per_step
        print(f"trained {args.steps} steps ({tokens} tokens at seq "
              f"{args.seq}): loss {first:.4f} -> {last:.4f}")
        return last
    finally:
        trainer.close()


if __name__ == "__main__":
    main()
