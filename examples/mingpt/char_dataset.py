"""Character-level dataset for the minGPT example.

Role parity with reference examples/torch_examples/minigpt/char_dataset.py
(CharDataset: read text, build stoi/itos, serve block_size+1 windows,
train/test split) in numpy — no torch Dataset machinery needed because the
training loop batches windows directly.

Hermetic default: with no --data_path the corpus is the Zen of Python
repeated (stdlib ``this``), so the example runs and visibly learns in
zero-egress environments; point --data_path at tiny-shakespeare (or any
text file) for the real thing.
"""

from __future__ import annotations

import codecs
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np


def default_corpus(repeats: int = 64) -> str:
    import this as zen  # noqa: PLC0415 — stdlib easter egg IS the corpus

    text = codecs.decode(zen.s, "rot13")
    return text * repeats


@dataclass
class CharDataset:
    """Fixed-window char-LM dataset over one text blob."""

    text: str
    block_size: int
    train_split: float = 0.9

    def __post_init__(self) -> None:
        chars = sorted(set(self.text))
        self.vocab_size = len(chars)
        self.stoi = {ch: i for i, ch in enumerate(chars)}
        self.itos = {i: ch for i, ch in enumerate(chars)}
        data = np.asarray([self.stoi[c] for c in self.text], np.int32)
        n_train = int(len(data) * self.train_split)
        self.train_data, self.test_data = data[:n_train], data[n_train:]

    def encode(self, s: str) -> np.ndarray:
        return np.asarray([self.stoi[c] for c in s], np.int32)

    def decode(self, ids) -> str:
        return "".join(self.itos[int(i)] for i in np.asarray(ids).ravel())

    def batches(self, split: str, batch_size: int, rng: np.random.Generator):
        """Infinite stream of (x [B, block], y [B, block]) windows."""
        data = self.train_data if split == "train" else self.test_data
        high = len(data) - self.block_size - 1
        assert high > 0, "corpus shorter than block_size"
        while True:
            starts = rng.integers(0, high, batch_size)
            x = np.stack([data[s:s + self.block_size] for s in starts])
            y = np.stack([data[s + 1:s + 1 + self.block_size] for s in starts])
            yield x, y


def load_dataset(
    data_path: Optional[str], block_size: int, train_split: float = 0.9
) -> Tuple[CharDataset, str]:
    if data_path:
        with open(data_path, encoding="utf-8") as f:
            return (CharDataset(f.read(), block_size, train_split),
                    data_path)
    return CharDataset(default_corpus(), block_size, train_split), "zen-of-python"
