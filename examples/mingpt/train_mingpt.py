#!/usr/bin/env python
"""minGPT char-LM training example (dense or MoE).

Counterpart of reference examples/torch_examples/minigpt/{main,trainer}.py:
a small GPT trained on character windows with AdamW + cosine schedule,
periodic eval loss, and a sampled continuation at the end. DP comes from
sharding the batch over all local devices inside one jitted step (the
reference drives the same loop through torchrun DDP).

BASELINE.json config 2 ("minGPT char-LM DP") is this program with the
default --use_moe false; --use_moe true exercises the educational
noisy-top-k MoE (reference examples moe.py).

Usage:
    python examples/mingpt/train_mingpt.py --steps 300
    python examples/mingpt/train_mingpt.py --data_path shakespeare.txt \
        --use_moe true --steps 2000
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def main(argv=None) -> float:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data_path", default=None)
    ap.add_argument("--block_size", type=int, default=128)
    ap.add_argument("--n_layer", type=int, default=4)
    ap.add_argument("--n_head", type=int, default=4)
    ap.add_argument("--n_embd", type=int, default=128)
    ap.add_argument("--use_moe", type=lambda s: s.lower() in ("1", "true"),
                    default=False)
    ap.add_argument("--num_experts", type=int, default=8)
    ap.add_argument("--top_k", type=int, default=2)
    ap.add_argument("--batch_size", type=int, default=64,
                    help="global batch (split over dp)")
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--warmup", type=int, default=20)
    ap.add_argument("--eval_interval", type=int, default=100)
    ap.add_argument("--eval_batches", type=int, default=8)
    ap.add_argument("--sample_tokens", type=int, default=64)
    ap.add_argument("--data_parallel", type=int, default=0,
                    help="dp degree; 0 = all local devices")
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from scaletorch_tpu.models import gpt_moe
    from examples.mingpt.char_dataset import load_dataset

    ds, source = load_dataset(args.data_path, args.block_size)
    print(f"corpus={source} chars={len(ds.text)} vocab={ds.vocab_size}")

    cfg = gpt_moe.GPTMoEConfig(
        block_size=args.block_size, vocab_size=ds.vocab_size,
        n_layer=args.n_layer, n_head=args.n_head, n_embd=args.n_embd,
        use_moe=args.use_moe, num_experts=args.num_experts,
        top_k=args.top_k,
    )
    params = gpt_moe.init_params(jax.random.PRNGKey(0), cfg)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    print(f"model: {n_params / 1e6:.2f}M params, moe={cfg.use_moe}")

    dp = args.data_parallel or len(jax.local_devices())
    mesh = Mesh(np.asarray(jax.devices()[:dp]), ("dp",))
    batch_sharding = NamedSharding(mesh, P("dp"))

    sched = optax.warmup_cosine_decay_schedule(
        0.0, args.lr, args.warmup, max(args.steps, args.warmup + 1)
    )
    tx = optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(sched))
    opt_state = tx.init(params)

    def loss_fn(p, x, y, key):
        logits, aux = gpt_moe.forward(
            p, x, cfg, noise_key=key if cfg.use_moe else None,
            return_aux=True,
        )
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        nll = -jnp.take_along_axis(logp, y[..., None], axis=-1).mean()
        return nll + aux, nll

    @jax.jit
    def train_step(p, opt_state, x, y, key):
        (loss, nll), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            p, x, y, key
        )
        updates, opt_state = tx.update(grads, opt_state, p)
        return optax.apply_updates(p, updates), opt_state, nll

    @jax.jit
    def eval_step(p, x, y):
        _, nll = loss_fn(p, x, y, None)
        return nll

    rng = np.random.default_rng(0)
    train_it = ds.batches("train", args.batch_size, rng)
    test_it = ds.batches("test", args.batch_size, rng)
    key = jax.random.PRNGKey(1)

    def put(x):
        return jax.device_put(x, batch_sharding)

    t0, last_eval = time.time(), float("inf")
    for step in range(1, args.steps + 1):
        x, y = next(train_it)
        key, sub = jax.random.split(key)
        params, opt_state, nll = train_step(params, opt_state, put(x), put(y), sub)
        if step % args.eval_interval == 0 or step == args.steps:
            evals = [
                float(eval_step(params, put(ex), put(ey)))
                for ex, ey in (next(test_it) for _ in range(args.eval_batches))
            ]
            last_eval = sum(evals) / len(evals)
            tok_s = step * args.batch_size * args.block_size / (time.time() - t0)
            print(f"step {step}/{args.steps} train_nll {float(nll):.4f} "
                  f"eval_nll {last_eval:.4f} tok/s {tok_s:,.0f} dp={dp}")

    prompt = ds.encode(ds.text[:16])[None, :]
    out = gpt_moe.generate(
        params, jnp.asarray(prompt), cfg,
        max_new_tokens=args.sample_tokens, temperature=0.8,
        key=jax.random.PRNGKey(2),
    )
    print("sample:", repr(ds.decode(np.asarray(out)[0])))
    return last_eval


if __name__ == "__main__":
    main()
