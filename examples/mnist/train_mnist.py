#!/usr/bin/env python
"""MNIST / LeNet training example — the framework's minimal end-to-end.

Covers the roles of the reference's four MNIST scripts
(examples/torch_examples/mnist/{basic,multigpu,torchrun,fsdp}_mnist.py) in
ONE program, because under single-controller SPMD they are the same
program:

  * basic        -> run on one device (--data_parallel 1)
  * multigpu /   -> the jitted step with batch sharded P('dp') over all
    torchrun        local devices (XLA inserts the gradient psum that DDP
                    does with bucketed all-reduce)
  * fsdp         -> --fsdp shards every parameter's leading dim over dp
                    (GSPMD's ZeRO-3: gather-on-use, scatter-on-grad — the
                    role of torch FSDP2's FlatParameter machinery)

Data: reads the standard idx files from --data_dir when present
(train-images-idx3-ubyte[.gz] etc.); otherwise falls back to a
deterministic synthetic digit set (class-conditional patterns) so the
example is hermetic in zero-egress environments.

Usage:
    python examples/mnist/train_mnist.py --epochs 2
    python examples/mnist/train_mnist.py --data_dir ~/mnist --fsdp
"""

from __future__ import annotations

import argparse
import gzip
import os
import struct
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))


def load_idx_images(path: str) -> np.ndarray:
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad magic {magic} in {path}"
        return np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)


def load_idx_labels(path: str) -> np.ndarray:
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad magic {magic} in {path}"
        return np.frombuffer(f.read(), np.uint8)


def _find(data_dir: str, stem: str):
    for suffix in ("", ".gz"):
        p = os.path.join(data_dir, stem + suffix)
        if os.path.exists(p):
            return p
    return None


def load_mnist(data_dir):
    """(train_x, train_y, test_x, test_y) as float32 [N,28,28,1] in [0,1]."""
    if data_dir:
        stems = ("train-images-idx3-ubyte", "train-labels-idx1-ubyte",
                 "t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")
        paths = [_find(data_dir, s) for s in stems]
        if all(paths):
            tx, ex = load_idx_images(paths[0]), load_idx_images(paths[2])
            ty, ey = load_idx_labels(paths[1]), load_idx_labels(paths[3])
            norm = lambda a: (a.astype(np.float32) / 255.0)[..., None]  # noqa: E731
            return norm(tx), ty.astype(np.int32), norm(ex), ey.astype(np.int32)
        missing = [s for s, p in zip(stems, paths) if p is None]
        print(f"missing MNIST idx files under {data_dir} "
              f"({', '.join(missing)}); using synthetic digits")
    return synthetic_digits(12000) + synthetic_digits(2000, seed=1)


def synthetic_digits(n: int, seed: int = 0):
    """Deterministic learnable stand-in: one FIXED random 28x28 pattern per
    class (shared by every split) + per-sample pixel noise. Not MNIST, but
    a real 10-class problem LeNet drives to high accuracy — keeps the
    example hermetic offline."""
    protos = np.random.default_rng(1234).uniform(
        0.0, 1.0, (10, 28, 28)).astype(np.float32)
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 10, n).astype(np.int32)
    x = protos[y] + rng.normal(0.0, 0.4, (n, 28, 28)).astype(np.float32)
    return np.clip(x, 0.0, 1.0)[..., None], y


def main(argv=None) -> float:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data_dir", default=None)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch_size", type=int, default=256,
                    help="global batch (split over dp)")
    ap.add_argument("--lr", type=float, default=1.0)
    ap.add_argument("--gamma", type=float, default=0.7,
                    help="StepLR decay per epoch (reference basic_mnist)")
    ap.add_argument("--fsdp", action="store_true",
                    help="shard parameters over dp (FSDP/ZeRO-3 role)")
    ap.add_argument("--data_parallel", type=int, default=0,
                    help="dp degree; 0 = all local devices")
    ap.add_argument("--log_interval", type=int, default=20)
    ap.add_argument("--limit_steps", type=int, default=0,
                    help="stop after N steps per epoch (CI)")
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from scaletorch_tpu.models import lenet

    dp = args.data_parallel or len(jax.local_devices())
    mesh = Mesh(np.asarray(jax.devices()[:dp]), ("dp",))
    batch_sharding = NamedSharding(mesh, P("dp"))
    replicated = NamedSharding(mesh, P())

    cfg = lenet.LeNetConfig()
    params = lenet.init_params(jax.random.PRNGKey(0), cfg)

    def param_sharding(x):
        if args.fsdp and x.ndim >= 1 and x.shape[0] % dp == 0:
            return NamedSharding(mesh, P("dp"))
        return replicated

    shardings = jax.tree.map(param_sharding, params)
    params = jax.tree.map(jax.device_put, params, shardings)

    tx_img, tx_lbl, ev_img, ev_lbl = load_mnist(args.data_dir)
    n = (len(tx_img) // args.batch_size) * args.batch_size
    steps_per_epoch = max(n // args.batch_size, 1)

    # Adadelta + per-epoch StepLR = reference basic_mnist.py's
    # optimizer/schedule pairing.
    sched = optax.exponential_decay(
        args.lr, transition_steps=1, decay_rate=args.gamma, staircase=True
    )
    tx = optax.adadelta(
        learning_rate=lambda count: sched(count // steps_per_epoch)
    )

    def loss_fn(p, x, y):
        logits = lenet.forward(p, x)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, y[:, None], axis=1).mean()
        return nll, logits

    @jax.jit
    def train_step(p, opt_state, x, y):
        (loss, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(p, x, y)
        updates, opt_state = tx.update(grads, opt_state, p)
        return optax.apply_updates(p, updates), opt_state, loss

    @jax.jit
    def eval_step(p, x, y):
        _, logits = loss_fn(p, x, y)
        return jnp.sum(jnp.argmax(logits, axis=-1) == y)

    tx_state = tx.init(params)

    rng = np.random.default_rng(0)
    last_loss = float("inf")
    for epoch in range(args.epochs):
        order = rng.permutation(len(tx_img))[:n]
        t0 = time.time()
        for step in range(steps_per_epoch):
            idx = order[step * args.batch_size:(step + 1) * args.batch_size]
            x = jax.device_put(tx_img[idx], batch_sharding)
            y = jax.device_put(tx_lbl[idx], batch_sharding)
            params, tx_state, loss = train_step(params, tx_state, x, y)
            if step % 16 == 15:
                # bound the async dispatch queue (a host sync every few
                # steps; the log line below also syncs when it fires)
                loss.block_until_ready()
            if step % args.log_interval == 0:
                print(f"epoch {epoch} step {step}/{steps_per_epoch} "
                      f"loss {float(loss):.4f}")
            if args.limit_steps and step + 1 >= args.limit_steps:
                break
        last_loss = float(loss)

        # test accuracy (reference run_epoch eval leg)
        ne = (len(ev_img) // args.batch_size) * args.batch_size
        correct = 0
        for step in range(ne // args.batch_size):
            sl = slice(step * args.batch_size, (step + 1) * args.batch_size)
            correct += int(eval_step(
                params,
                jax.device_put(ev_img[sl], batch_sharding),
                jax.device_put(ev_lbl[sl], batch_sharding),
            ))
        print(f"epoch {epoch}: test acc {correct}/{ne} "
              f"({100.0 * correct / max(ne, 1):.1f}%) "
              f"[{time.time() - t0:.1f}s, dp={dp}, fsdp={args.fsdp}]")
    return last_loss


if __name__ == "__main__":
    main()
