#!/usr/bin/env python
"""Mixture-of-Experts training demo — expert parallelism over the ep axis.

The capability tour of the reference's MoE rows (BASELINE.md 30B-A3B;
model_qwen3_moe.py): a Qwen3-MoE trains with its experts sharded across
the ``ep`` mesh axis and tokens moved by the capacity dispatch, with the
round-4 knobs exposed:

  * ``--dispatch einsum|index|auto`` — token-movement form. The one-hot
    einsums are 62% of step FLOPs at E=128/top-8 (AOT_30B_A3B.json); the
    index form moves exactly the O(N·k·H) routed rows. Identical math.
  * ``--sparse-step N`` / ``--dense-layers i j`` — interleaved
    dense/sparse architectures (HF ``decoder_sparse_step`` /
    ``mlp_only_layers``): dense layers run the plain SwiGLU MLP, sparse
    layers the routed experts, as contiguous segment scans.

Routing health (dropped token fraction, expert load CV) prints with the
step metrics — the operator-facing signal that the router is balanced.
Run on any mesh:

    # 8 virtual CPU devices: E=8 over ep=2, every layer sparse
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/moe/train_moe.py --ep 2

    # interleaved: layers 1,3 sparse / 0,2 dense, index-form dispatch
    python examples/moe/train_moe.py --ep 2 --sparse-step 2 --dispatch index
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)


def main(argv=None) -> float:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ep", type=int, default=2)
    ap.add_argument("--dp", type=int, default=0,
                    help="0 = fill the remaining devices")
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--experts", type=int, default=8)
    ap.add_argument("--dispatch", choices=["auto", "einsum", "index"],
                    default="auto")
    ap.add_argument("--sparse-step", type=int, default=1,
                    help="layer i is sparse iff (i+1) %% this == 0")
    ap.add_argument("--dense-layers", type=int, nargs="*", default=[],
                    help="layer indices forced dense (mlp_only_layers)")
    args = ap.parse_args(argv)

    import jax

    from scaletorch_tpu.config import ScaleTorchTPUArguments
    from scaletorch_tpu.trainer.trainer import Trainer

    n_dev = len(jax.devices())
    dp = args.dp or max(n_dev // args.ep, 1)
    cfg = ScaleTorchTPUArguments(
        model_type="qwen3_moe", hidden_size=64, intermediate_size=128,
        moe_intermediate_size=64, num_hidden_layers=4,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        num_experts=args.experts, num_experts_per_tok=2,
        # generous capacity for the demo: an untrained router is unbalanced
        # and the default 1.25 factor drops ~1/3 of tokens at init, which
        # drowns the first steps' learning signal
        moe_capacity_factor=2.0,
        moe_dispatch=args.dispatch,
        decoder_sparse_step=args.sparse_step,
        mlp_only_layers=args.dense_layers or None,
        vocab_size=256, sequence_length=args.seq,
        max_position_embeddings=2 * args.seq,
        expert_parallel_size=args.ep, data_parallel_size=dp,
        micro_batch_size=1, synthetic_data=True,
        total_train_steps=args.steps, dtype="float32",
        # demo-sized LR: the model is tiny and the run is seconds long
        learning_rate=1e-3, warmup_steps=0,
        donate_params=False, log_frequency=max(args.steps // 4, 1),
    )
    trainer = Trainer(cfg)
    layout = trainer.model_cfg.sparse_layout()
    print(f"devices={n_dev} ep={args.ep} dp={dp} experts={args.experts} "
          f"dispatch={trainer.model_cfg.resolved_moe_dispatch()} "
          f"sparse_layers={[i for i, s in enumerate(layout) if s]}")
    try:
        first = last = None
        drop = None
        for step in range(args.steps):
            m = trainer.step()  # public per-step API (draws from the loader)
            last = float(m["loss"])
            drop = float(m["moe_dropped_fraction"])
            if first is None:
                first = last
        print(f"trained {args.steps} steps: loss {first:.4f} -> {last:.4f} "
              f"(final dropped_fraction {drop:.2%})")
        return last
    finally:
        trainer.close()


if __name__ == "__main__":
    main()
