#!/usr/bin/env python
"""Pipeline-parallel training demo — the three SPMD schedules side by side.

A capability tour of the pipeline tier (parallel/pipeline_parallel.py):
the same tiny Llama trains over a pp-sharded layer stack under the
chosen schedule, and the script prints the schedule's exact tick
accounting before training so the trade is visible up front:

  * ``afab``            one fwd+bwd pipeline over all M microbatches —
                        bubble (pp-1)/(M+pp-1), O(M) boundary carries.
  * ``interleaved``     V virtual stages per rank on a circular ring —
                        bubble cut ~V x (needs L %% (pp*V) == 0).
  * ``memory_chunked``  1F1B's O(pp) boundary memory, a bubble per
                        chunk (reference-compat alias: ``1f1b``).

Run on any mesh:

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/pipeline/train_pp.py --engine interleaved --vpp 2
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)


def main(argv=None) -> float:
    ap = argparse.ArgumentParser()
    ap.add_argument("--engine", default="interleaved",
                    choices=["afab", "interleaved", "memory_chunked", "1f1b"])
    ap.add_argument("--pp", type=int, default=2)
    ap.add_argument("--vpp", type=int, default=2,
                    help="virtual stages per rank (interleaved only)")
    ap.add_argument("--accum", type=int, default=4,
                    help="microbatches per step (the pipeline's M)")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    args = ap.parse_args(argv)

    import jax

    from scaletorch_tpu.config import ScaleTorchTPUArguments
    from scaletorch_tpu.parallel.pipeline_parallel import (
        interleaved_tick_schedule,
    )
    from scaletorch_tpu.trainer.trainer import Trainer

    n_dev = len(jax.devices())
    vpp = args.vpp if args.engine == "interleaved" else 1
    m = args.accum
    if args.engine == "interleaved":
        acct = interleaved_tick_schedule(m, args.pp, vpp)
        print(f"interleaved pp={args.pp} vpp={vpp} M={m}: "
              f"{acct['ticks']} chunk-ticks, bubble "
              f"{acct['bubble_fraction']:.1%} (afab: "
              f"{acct['afab_bubble_fraction']:.1%}), predicted step time "
              f"{acct['relative_step_time']:.3f}x afab's")
    else:
        print(f"{args.engine} pp={args.pp} M={m}: "
              f"{m + args.pp - 1} stage-ticks fwd, bubble "
              f"{(args.pp - 1) / (m + args.pp - 1):.1%}")

    cfg = ScaleTorchTPUArguments(
        model_type="llama", hidden_size=64, intermediate_size=128,
        num_hidden_layers=args.pp * max(vpp, 2),  # divides pp*vpp
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        vocab_size=256, sequence_length=args.seq,
        max_position_embeddings=2 * args.seq,
        pipeline_parallel_size=args.pp,
        data_parallel_size=max(n_dev // args.pp, 1),
        pp_engine=args.engine, pp_virtual_stages=vpp,
        micro_batch_size=1, gradient_accumulation_steps=args.accum,
        synthetic_data=True, total_train_steps=args.steps, dtype="float32",
        learning_rate=1e-3, warmup_steps=0,
        donate_params=False, log_frequency=max(args.steps // 4, 1),
    )
    trainer = Trainer(cfg)
    try:
        first = last = None
        for _ in range(args.steps):
            m_out = trainer.step()  # public per-step API
            last = float(m_out["loss"])
            if first is None:
                first = last
        print(f"trained {args.steps} steps ({cfg.pp_engine}): "
              f"loss {first:.4f} -> {last:.4f}")
        return last
    finally:
        trainer.close()


if __name__ == "__main__":
    main()
