"""scaletorch_tpu — a TPU-native 5D-parallelism LLM pretraining framework.

A from-scratch JAX/XLA/Pallas re-design of the capability surface of
jianzhnie/ScaleTorch (a pure-Python torch.distributed framework; see
/root/repo/SURVEY.md for the full structural analysis). The parallelism
dimensions — DP, TP, PP (AFAB + 1F1B), CP (ring attention), SP, and EP
(MoE all-to-all) — are expressed over a single ``jax.sharding.Mesh`` with
named axes ``('dp', 'pp', 'cp', 'ep', 'tp')``, with explicit XLA
collectives (``psum``, ``all_gather``, ``psum_scatter``, ``all_to_all``,
``ppermute``) inside ``shard_map`` where manual control wins, and GSPMD
sharding annotations where the compiler wins.

Reference parity map (reference file -> this package):
  scaletorch/parallel/process_group.py  -> scaletorch_tpu.parallel.mesh
  scaletorch/dist/                      -> scaletorch_tpu.ops.collectives
  scaletorch/parallel/tensor_parallel/  -> scaletorch_tpu.parallel.tensor_parallel
  scaletorch/parallel/pipeline_parallel/-> scaletorch_tpu.parallel.pipeline_parallel
  scaletorch/parallel/context_parallel/ -> scaletorch_tpu.ops.ring_attention,
                                           scaletorch_tpu.parallel.context_parallel
  scaletorch/parallel/sequence_parallel/-> scaletorch_tpu.parallel.sequence_parallel
  scaletorch/parallel/expert_parallel/  -> scaletorch_tpu.parallel.expert_parallel
  scaletorch/models/                    -> scaletorch_tpu.models
  scaletorch/trainer/                   -> scaletorch_tpu.trainer
  scaletorch/data/                      -> scaletorch_tpu.data
  scaletorch/utils/                     -> scaletorch_tpu.utils

Beyond the reference: ``scaletorch_tpu.inference`` — the serving half
(KV-cache decode engine with continuous batching over the same mesh/TP
specs; see docs/inference.md).
"""

__version__ = "0.1.0"

# Order matters: compat backfills jax.shard_map / jax.lax.pvary /
# jax.typeof on pre-VMA jax builds before any other module (or the test
# suite) touches them. Tolerate a jax-less interpreter: the pure-AST
# analysis package (jaxlint, run by the dep-less CI lint job) imports
# this package but never needs jax.
try:
    from scaletorch_tpu import compat  # noqa: F401
except ImportError:
    pass
from scaletorch_tpu import env  # noqa: F401
