"""jaxlint — JAX-aware static analysis for scaletorch-tpu.

Run as ``python -m scaletorch_tpu.analysis [paths]``. Eight passes over
plain ASTs (nothing under analysis is imported):

=====  ======================================================
ST1xx  sharding-spec consistency (axis typos, dead spec keys)
ST2xx  trace-safety (Python control flow / host syncs in jit)
ST3xx  PRNG hygiene (key reuse, wall-clock seeds)
ST4xx  donation safety (read-after-donate)
ST5xx  retrace risk (literal args to jitted callables)
ST6xx  SPMD collective symmetry (host-divergent deadlocks)
ST9xx  host-thread concurrency (races, deadlocks, loop abuse)
       + the telemetry kind registry (ST907)
=====  ======================================================

``--tier deep`` adds the compiled tier (needs jax): the jaxpr/HLO
entry-point audit (ST7xx — ``jaxpr_audit.py``) and the per-entry comm
budget gate (ST8xx — ``budget.py`` against ``tools/comm_budget.json``).
``--tier memory`` compiles the same manifest and audits static HBM
accounting (ST10xx — ``memory.py`` against ``tools/hbm_budget.json``);
``--tier deep,memory`` runs both off one compile per entry.
``--tier concurrency`` runs only the ST9xx family (also part of the
default ast tier). ``--tier ownership`` runs the ST11xx
resource-conservation tier (``ownership.py`` — acquire/release
lifecycle, terminal-outcome funnels, span balance, rollback ordering);
it is pure-AST like the default tier but opt-in, so the default run
stays fast.

``--select`` accepts pass names or code families, case-insensitively:
``--select ST9`` (or ``st901``) runs the concurrency family.

Findings print as ``file:line: CODE severity message``; a checked-in
baseline (``tools/jaxlint_baseline.json``) suppresses pre-existing
findings so the CI gate only fails on NEW ones.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Set

from . import (
    concurrency,
    donation,
    ownership,
    prng,
    retrace,
    sharding,
    symmetry,
    telemetry_kinds,
    trace_safety,
)
from .core import (
    Finding,
    SourceModule,
    collect_files,
    load_baseline,
    save_baseline,
    split_by_baseline,
)
from .scopes import ProjectIndex

PASSES = {
    "sharding": sharding.run,
    "trace-safety": trace_safety.run,
    "prng": prng.run,
    "donation": donation.run,
    "retrace": retrace.run,
    "symmetry": symmetry.run,
    "concurrency": concurrency.run,
    "telemetry-kinds": telemetry_kinds.run,
}

# code family -> the AST passes that emit it (--select ST9, --tier
# concurrency). ST7/ST8 are deep-tier and deliberately absent: selecting
# them here is a usage error pointing at --tier deep.
FAMILIES = {
    "ST1": ("sharding",),
    "ST2": ("trace-safety",),
    "ST3": ("prng",),
    "ST4": ("donation",),
    "ST5": ("retrace",),
    "ST6": ("symmetry",),
    "ST9": ("concurrency", "telemetry-kinds"),
}
CONCURRENCY_PASSES = FAMILIES["ST9"]

# tier-only AST passes: run when their tier (or pass name) is selected,
# never as part of the default `--tier ast` sweep
TIER_ONLY_PASSES = {
    "ownership": ownership.run,
}
OWNERSHIP_PASSES = ("ownership",)

__all__ = [
    "Finding", "SourceModule", "ProjectIndex", "PASSES", "FAMILIES",
    "CONCURRENCY_PASSES", "TIER_ONLY_PASSES", "OWNERSHIP_PASSES",
    "collect_files", "load_baseline", "save_baseline", "split_by_baseline",
    "analyze", "analyze_paths", "resolve_select",
]


def resolve_select(select: Sequence[str]) -> List[str]:
    """Selector tokens -> pass names. Tokens are matched
    case-insensitively against pass names (``concurrency``) and code
    families (``ST9``, or any code like ``ST904`` — the family prefix
    wins). Unknown tokens raise ``ValueError`` naming every valid
    choice, so a typo'd selector is a loud usage error (exit 2), never
    a silently-green empty run."""
    wanted: List[str] = []
    valid_passes = {p.lower(): p for p in PASSES}
    valid_passes.update({p.lower(): p for p in TIER_ONLY_PASSES})
    for token in select:
        t = token.strip()
        if not t:
            continue
        low = t.lower()
        if low in valid_passes:
            name = valid_passes[low]
            if name not in wanted:
                wanted.append(name)
            continue
        # ST10 / ST10xx is the memory tier, not an AST pass — point at
        # the tier before the single-digit family parse (which would
        # otherwise read "st1001" as garbage, or nothing at all).
        if low.startswith("st10") and (
            len(low) == 4 or (len(low) == 6 and low[4:].isdigit())
        ):
            raise ValueError(
                f"selector {token!r} is the memory-tier family (ST10xx "
                "static HBM audit); run with --tier memory instead of "
                "--select"
            )
        # ST11 / ST11xx is the ownership tier — same precedent: the tier
        # flag is the supported spelling (the family maps 1:1 to it).
        if low.startswith("st11") and (
            len(low) == 4 or (len(low) == 6 and low[4:].isdigit())
        ):
            raise ValueError(
                f"selector {token!r} is the ownership-tier family "
                "(ST11xx resource lifecycle); run with --tier ownership "
                "instead of --select"
            )
        fam = None
        # a family is exactly "STn" or a full code "STnxx" — trailing
        # garbage ("ST9q") must NOT silently match a family
        if low.startswith("st") and len(low) in (3, 5) and \
                low[2:].isdigit():
            fam = f"ST{low[2]}"
        if fam in ("ST7", "ST8"):
            raise ValueError(
                f"selector {token!r} is a deep-tier family (ST7xx jaxpr/"
                "HLO audit, ST8xx comm budget); run with --tier deep "
                "instead of --select"
            )
        if fam in FAMILIES:
            for name in FAMILIES[fam]:
                if name not in wanted:
                    wanted.append(name)
            continue
        raise ValueError(
            f"unknown pass or family {token!r}; valid passes: "
            f"{', '.join(sorted(valid_passes.values()))}; valid families: "
            f"{', '.join(sorted(FAMILIES))}"
        )
    if not wanted:
        raise ValueError(
            f"empty selection; valid passes: "
            f"{', '.join(sorted(set(PASSES) | set(TIER_ONLY_PASSES)))}; "
            f"valid families: {', '.join(sorted(FAMILIES))}"
        )
    return wanted


def analyze(
    modules: Sequence[SourceModule],
    select: Optional[Sequence[str]] = None,
    extra_axes: Set[str] = frozenset(),
) -> List[Finding]:
    """Run the selected passes (default: all) over parsed modules."""
    index = ProjectIndex(modules)
    findings: List[Finding] = []
    wanted = set(resolve_select(select)) if select else set(PASSES)
    for name, pass_fn in {**PASSES, **TIER_ONLY_PASSES}.items():
        if name not in wanted:
            continue
        if name == "sharding":
            findings.extend(pass_fn(index, extra_axes))
        else:
            findings.extend(pass_fn(index))
    findings.sort(key=lambda f: (f.file, f.line, f.code))
    return findings


def analyze_paths(
    paths: Sequence[str],
    select: Optional[Sequence[str]] = None,
    extra_axes: Set[str] = frozenset(),
) -> tuple[List[Finding], List[Finding]]:
    """(findings, syntax_errors) for files/directories on disk."""
    modules, errors = collect_files(paths)
    return analyze(modules, select=select, extra_axes=extra_axes), errors
