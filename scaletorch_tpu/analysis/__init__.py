"""jaxlint — JAX-aware static analysis for scaletorch-tpu.

Run as ``python -m scaletorch_tpu.analysis [paths]``. Six passes over
plain ASTs (nothing under analysis is imported):

=====  ======================================================
ST1xx  sharding-spec consistency (axis typos, dead spec keys)
ST2xx  trace-safety (Python control flow / host syncs in jit)
ST3xx  PRNG hygiene (key reuse, wall-clock seeds)
ST4xx  donation safety (read-after-donate)
ST5xx  retrace risk (literal args to jitted callables)
ST6xx  SPMD collective symmetry (host-divergent deadlocks)
=====  ======================================================

``--tier deep`` adds the compiled tier (needs jax): the jaxpr/HLO
entry-point audit (ST7xx — ``jaxpr_audit.py``) and the per-entry comm
budget gate (ST8xx — ``budget.py`` against ``tools/comm_budget.json``).

Findings print as ``file:line: CODE severity message``; a checked-in
baseline (``tools/jaxlint_baseline.json``) suppresses pre-existing
findings so the CI gate only fails on NEW ones.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Set

from . import donation, prng, retrace, sharding, symmetry, trace_safety
from .core import (
    Finding,
    SourceModule,
    collect_files,
    load_baseline,
    save_baseline,
    split_by_baseline,
)
from .scopes import ProjectIndex

PASSES = {
    "sharding": sharding.run,
    "trace-safety": trace_safety.run,
    "prng": prng.run,
    "donation": donation.run,
    "retrace": retrace.run,
    "symmetry": symmetry.run,
}

__all__ = [
    "Finding", "SourceModule", "ProjectIndex", "PASSES",
    "collect_files", "load_baseline", "save_baseline", "split_by_baseline",
    "analyze", "analyze_paths",
]


def analyze(
    modules: Sequence[SourceModule],
    select: Optional[Sequence[str]] = None,
    extra_axes: Set[str] = frozenset(),
) -> List[Finding]:
    """Run the selected passes (default: all) over parsed modules."""
    index = ProjectIndex(modules)
    findings: List[Finding] = []
    wanted = set(select) if select else set(PASSES)
    unknown = wanted - set(PASSES)
    if unknown:
        raise ValueError(
            f"unknown pass(es) {sorted(unknown)}; available: {sorted(PASSES)}"
        )
    for name, pass_fn in PASSES.items():
        if name not in wanted:
            continue
        if name == "sharding":
            findings.extend(pass_fn(index, extra_axes))
        else:
            findings.extend(pass_fn(index))
    findings.sort(key=lambda f: (f.file, f.line, f.code))
    return findings


def analyze_paths(
    paths: Sequence[str],
    select: Optional[Sequence[str]] = None,
    extra_axes: Set[str] = frozenset(),
) -> tuple[List[Finding], List[Finding]]:
    """(findings, syntax_errors) for files/directories on disk."""
    modules, errors = collect_files(paths)
    return analyze(modules, select=select, extra_axes=extra_axes), errors
