"""CLI: ``python -m scaletorch_tpu.analysis [paths] [options]``.

Exit codes: 0 clean (or all findings baselined), 1 new findings or
syntax errors, 2 usage error. ``--write-baseline`` records the current
findings as the allowlist; the gate then only fails on regressions.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from . import PASSES, analyze_paths, load_baseline, save_baseline, split_by_baseline

DEFAULT_BASELINE = Path("tools") / "jaxlint_baseline.json"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m scaletorch_tpu.analysis",
        description="JAX-aware static analysis (jaxlint)",
    )
    parser.add_argument(
        "paths", nargs="*", default=["scaletorch_tpu"],
        help="files/directories to analyze (default: scaletorch_tpu)",
    )
    parser.add_argument(
        "--baseline", type=Path, default=None,
        help=f"baseline allowlist (default: {DEFAULT_BASELINE} if present)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding, ignoring any baseline file",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="write current findings to the baseline file and exit 0",
    )
    parser.add_argument(
        "--select", default=None, metavar="PASS[,PASS...]",
        help=f"run only these passes (available: {', '.join(sorted(PASSES))})",
    )
    parser.add_argument(
        "--extra-axes", default="", metavar="AXIS[,AXIS...]",
        help="additional mesh-axis names to treat as declared",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
    )
    args = parser.parse_args(argv)

    select = [s.strip() for s in args.select.split(",") if s.strip()] \
        if args.select else None
    extra_axes = {s.strip() for s in args.extra_axes.split(",") if s.strip()}
    try:
        findings, errors = analyze_paths(
            args.paths, select=select, extra_axes=extra_axes
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if DEFAULT_BASELINE.is_file() else None
    )
    if args.write_baseline:
        path = args.baseline or DEFAULT_BASELINE
        path.parent.mkdir(parents=True, exist_ok=True)
        save_baseline(path, findings)
        print(f"wrote {len(findings)} finding(s) to {path}")
        return 0

    suppressed_count = 0
    if baseline_path is not None and not args.no_baseline:
        findings, suppressed = split_by_baseline(
            findings, load_baseline(baseline_path)
        )
        suppressed_count = len(suppressed)

    findings = list(errors) + findings
    if args.format == "json":
        print(json.dumps(
            [f.__dict__ for f in findings], indent=2
        ))
    else:
        for f in findings:
            print(f.render())
        n_err = sum(1 for f in findings if f.severity == "error")
        n_warn = len(findings) - n_err
        tail = f" ({suppressed_count} baselined)" if suppressed_count else ""
        print(
            f"jaxlint: {n_err} error(s), {n_warn} warning(s){tail}",
            file=sys.stderr,
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
