"""CLI: ``python -m scaletorch_tpu.analysis [paths] [options]``.

Four tiers — ``--tier`` takes one or a comma list (``--tier
deep,memory`` keeps the CI deep-lint job a single invocation and a
single compile of the entry-point manifest):

* ``--tier ast`` (default) — the pure-AST passes (ST1xx-ST6xx + the
  ST9xx concurrency family). Never imports the code under analysis and
  needs no jax: this is the fast, dependency-free CI ``lint`` job.
* ``--tier concurrency`` — only the ST9xx family (thread-root/lockset
  race & deadlock detection plus the telemetry kind registry); the
  focused invocation is ``python -m scaletorch_tpu.analysis --select
  ST9 <paths>`` and this tier is its spelled-out twin for CI.
* ``--tier ownership`` — the ST11xx resource-conservation tier
  (acquire/release lifecycle over the CONTRACT table in
  analysis/ownership.py, terminal-outcome funnels, span balance,
  rollback ordering). Pure-AST, no jax; composes with the others
  (``--tier ast,concurrency,ownership`` is one process, one parse).
* ``--tier deep`` — additionally traces and compiles the registered
  entry-point manifest on virtual CPU meshes (jaxpr/HLO audit, ST7xx)
  and checks the per-entry comm budget (``tools/comm_budget.json``,
  ST8xx). Needs jax; run under ``JAX_PLATFORMS=cpu`` (the CLI arranges
  8 virtual devices itself when jax is not yet initialized).
* ``--tier memory`` — compiles the same manifest and checks static HBM
  accounting (ST10xx, analysis/memory.py) against the per-entry peak
  budget (``tools/hbm_budget.json``). When combined with ``deep``,
  each entry compiles once and feeds both audits.

An unknown tier is a loud exit-2 usage error, like an unknown pass.
Exit codes: 0 clean (or all findings baselined), 1 findings or syntax
errors, 2 usage error (unknown tier/pass/entry, typo'd path, unreadable
or malformed baseline/budget file). ``--write-baseline`` records
current AST findings as the allowlist; ``--write-budget`` /
``--write-hbm-budget`` record the current compiled comm / memory
reports as their budgets.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

from . import (
    CONCURRENCY_PASSES,
    FAMILIES,
    OWNERSHIP_PASSES,
    PASSES,
    analyze_paths,
    load_baseline,
    resolve_select,
    save_baseline,
    split_by_baseline,
)

DEFAULT_BASELINE = Path("tools") / "jaxlint_baseline.json"


def _render_sarif(findings) -> str:
    """SARIF 2.1.0, byte-stable: sorted keys, fixed indent, and nothing
    run-dependent (no timestamps, no absolute paths) — the same tree
    always serializes to the same bytes, so the uploaded scan diffs
    clean between identical runs."""
    rules = sorted({f.code for f in findings})
    doc = {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                   "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "jaxlint",
                "informationUri":
                    "https://github.com/jianzhnie/ScaleTorch",
                "rules": [{"id": code} for code in rules],
            }},
            "results": [{
                "ruleId": f.code,
                "level": "error" if f.severity == "error" else "warning",
                "message": {"text": f.message},
                "locations": [{"physicalLocation": {
                    "artifactLocation": {"uri": f.file},
                    "region": {"startLine": max(1, f.line)},
                }}],
            } for f in findings],
        }],
    }
    return json.dumps(doc, indent=2, sort_keys=True)


def _render_github(f) -> str:
    level = "error" if f.severity == "error" else "warning"
    # workflow-command escaping for the message payload
    msg = (f.message.replace("%", "%25").replace("\r", "%0D")
           .replace("\n", "%0A"))
    return (f"::{level} file={f.file},line={f.line},"
            f"title=jaxlint {f.code}::{msg}")


def _ensure_deep_env() -> None:
    """Arrange >= 8 virtual CPU devices for the deep tier. Env vars are
    read at first backend initialization, so this only helps when jax
    has not been initialized yet (the normal CLI case); under an
    already-initialized runtime (pytest) the audit checks the visible
    device count itself and reports ST700 if it is short."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m scaletorch_tpu.analysis",
        description="JAX-aware static analysis (jaxlint)",
    )
    parser.add_argument(
        "paths", nargs="*", default=["scaletorch_tpu"],
        help="files/directories to analyze (default: scaletorch_tpu)",
    )
    parser.add_argument(
        "--tier", default="ast", metavar="TIER[,TIER...]",
        help="comma list of: 'ast' = pure-AST passes only (no jax); "
             "'concurrency' = only the ST9xx thread-race/deadlock "
             "family; 'ownership' = the ST11xx resource-lifecycle tier "
             "(pure-AST, composes: --tier ast,concurrency,ownership); "
             "'deep' also runs the jaxpr/HLO entry-point audit "
             "and the comm-budget gate; 'memory' runs the static HBM "
             "audit and the hbm-budget gate over the same compiled "
             "manifest (e.g. --tier deep,memory compiles each entry "
             "once for both)",
    )
    parser.add_argument(
        "--baseline", type=Path, default=None,
        help=f"baseline allowlist (default: {DEFAULT_BASELINE} if present)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding, ignoring any baseline file",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="write current AST findings to the baseline file and exit 0",
    )
    parser.add_argument(
        "--select", default=None, metavar="PASS[,PASS...]",
        help="run only these passes or code families, case-insensitive "
             f"(passes: {', '.join(sorted(PASSES))}; families: "
             f"{', '.join(sorted(FAMILIES))} — e.g. --select ST9)",
    )
    parser.add_argument(
        "--extra-axes", default="", metavar="AXIS[,AXIS...]",
        help="additional mesh-axis names to treat as declared",
    )
    parser.add_argument(
        "--entries", default=None, metavar="NAME[,NAME...]",
        help="deep tier: audit only these manifest entries",
    )
    parser.add_argument(
        "--budget", type=Path, default=None,
        help="comm budget file (default: tools/comm_budget.json)",
    )
    parser.add_argument(
        "--write-budget", action="store_true",
        help="deep tier: write the current compiled comm reports as the "
             "budget and skip the comparison",
    )
    parser.add_argument(
        "--no-budget", action="store_true",
        help="deep tier: skip the comm-budget comparison",
    )
    parser.add_argument(
        "--hbm-budget", type=Path, default=None,
        help="hbm budget file (default: tools/hbm_budget.json)",
    )
    parser.add_argument(
        "--write-hbm-budget", action="store_true",
        help="memory tier: write the current compiled memory reports as "
             "the hbm budget and skip the comparison",
    )
    parser.add_argument(
        "--no-hbm-budget", action="store_true",
        help="memory tier: skip the hbm-budget comparison",
    )
    parser.add_argument(
        "--format", choices=("text", "json", "github", "sarif"),
        default="text",
        help="'github' emits GitHub Actions ::error/::warning "
             "annotations so findings render inline on PRs; 'sarif' "
             "emits a byte-stable SARIF 2.1.0 document for GitHub "
             "code scanning upload",
    )
    args = parser.parse_args(argv)

    known_tiers = ("ast", "concurrency", "ownership", "deep", "memory")
    tiers = [t.strip() for t in args.tier.split(",") if t.strip()]
    unknown = sorted(set(tiers) - set(known_tiers))
    if unknown or not tiers:
        # A typo'd tier must be a loud usage error, never a silently
        # green partial run — same contract as an unknown --select.
        print(
            f"error: unknown tier {', '.join(map(repr, unknown)) or '(empty)'}"
            f"; valid tiers: {', '.join(known_tiers)} "
            "(comma list, e.g. --tier deep,memory)",
            file=sys.stderr,
        )
        return 2

    if "deep" not in tiers and (
        args.write_budget or args.budget or args.no_budget
    ):
        print(
            "error: --write-budget/--budget/--no-budget need --tier deep",
            file=sys.stderr,
        )
        return 2
    if "memory" not in tiers and (
        args.hbm_budget or args.write_hbm_budget or args.no_hbm_budget
    ):
        print(
            "error: --hbm-budget/--write-hbm-budget/--no-hbm-budget need "
            "--tier memory",
            file=sys.stderr,
        )
        return 2
    need_compile = "deep" in tiers or "memory" in tiers
    if args.entries and not need_compile:
        print(
            "error: --entries needs --tier deep or --tier memory",
            file=sys.stderr,
        )
        return 2

    select = [s.strip() for s in args.select.split(",") if s.strip()] \
        if args.select else None
    # The pass pool the AST-tier part of this run draws from. `ast`
    # means every default pass; `concurrency`/`ownership` add (or, with
    # no `ast`, restrict to) their families.
    ast_pool: list = []
    if "ast" in tiers:
        ast_pool.extend(PASSES)
    if "concurrency" in tiers:
        ast_pool.extend(p for p in CONCURRENCY_PASSES if p not in ast_pool)
    if "ownership" in tiers:
        ast_pool.extend(p for p in OWNERSHIP_PASSES if p not in ast_pool)
    narrow = [t for t in ("concurrency", "ownership") if t in tiers]
    if narrow and "ast" not in tiers:
        # the tier IS a selection; an explicit --select narrows within it
        try:
            wanted = resolve_select(select) if select else list(ast_pool)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        narrowed = [p for p in wanted if p in ast_pool]
        if not narrowed:
            print(
                f"error: --select {args.select!r} selects nothing inside "
                f"--tier {','.join(narrow)} (its passes: "
                f"{', '.join(ast_pool)})",
                file=sys.stderr,
            )
            return 2
        select = narrowed
    elif "ownership" in tiers and select is None:
        # ast,...,ownership with no --select: run the default passes
        # PLUS the opt-in ownership pass in the one process
        select = ast_pool
    extra_axes = {s.strip() for s in args.extra_axes.split(",") if s.strip()}
    try:
        findings, errors = analyze_paths(
            args.paths, select=select, extra_axes=extra_axes
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if DEFAULT_BASELINE.is_file() else None
    )
    if args.write_baseline:
        path = args.baseline or DEFAULT_BASELINE
        path.parent.mkdir(parents=True, exist_ok=True)
        save_baseline(path, findings)
        print(f"wrote {len(findings)} finding(s) to {path}")
        return 0

    suppressed_count = 0
    if baseline_path is not None and not args.no_baseline:
        # An unreadable or malformed baseline must not traceback AND must
        # not silently ungate: it is a usage error, like a typo'd path.
        try:
            entries = load_baseline(baseline_path)
        except (OSError, json.JSONDecodeError, ValueError) as e:
            print(
                f"error: baseline {baseline_path} is unreadable or "
                f"malformed ({e}); fix it or rerun with --no-baseline / "
                "--write-baseline",
                file=sys.stderr,
            )
            return 2
        findings, suppressed = split_by_baseline(findings, entries)
        suppressed_count = len(suppressed)

    deep_findings = []
    if need_compile:
        _ensure_deep_env()
        from .jaxpr_audit import audit_compiled, compile_entry, load_entries

        entry_names = [s.strip() for s in args.entries.split(",")
                       if s.strip()] if args.entries else None
        # One compile per entry, shared by the deep and memory audits.
        entries, load_findings = load_entries(entry_names)
        deep_findings.extend(load_findings)
        compiled_entries = []
        for e in entries:
            ce, fs = compile_entry(e)
            deep_findings.extend(fs)
            if ce is not None:
                compiled_entries.append(ce)

    if "deep" in tiers:
        from . import budget as budget_mod

        reports = {}
        for ce in compiled_entries:
            fs, report = audit_compiled(ce)
            deep_findings.extend(fs)
            reports[ce.entry["name"]] = report
        budget_path = args.budget or budget_mod.DEFAULT_BUDGET
        if args.write_budget:
            if entry_names and budget_path.is_file():
                # A scoped re-baseline must not truncate the other
                # entries' budgets: merge into the existing file.
                try:
                    existing = budget_mod.load_budget(budget_path)
                except ValueError as e:
                    print(f"error: {e}", file=sys.stderr)
                    return 2
                reports = {**existing["entries"], **reports}
            budget_mod.write_budget(budget_path, reports)
            # status to stderr: --format json contracts stdout to be
            # exactly the findings array
            print(f"wrote comm budget for {len(reports)} entr"
                  f"{'y' if len(reports) == 1 else 'ies'} to {budget_path}",
                  file=sys.stderr)
        elif not args.no_budget:
            budget_findings, usage_error = budget_mod.check_budget_path(
                reports, budget_path
            )
            if usage_error is not None:
                print(f"error: {usage_error}", file=sys.stderr)
                return 2
            deep_findings.extend(budget_findings)

    if "memory" in tiers:
        from . import memory as memory_mod

        mem_reports = {}
        mem_tops = {}
        for ce in compiled_entries:
            fs, report, top = memory_mod.audit_compiled_memory(ce)
            deep_findings.extend(fs)
            mem_reports[ce.entry["name"]] = report
            mem_tops[ce.entry["name"]] = top
        hbm_path = args.hbm_budget or memory_mod.DEFAULT_HBM_BUDGET
        if args.write_hbm_budget:
            if entry_names and hbm_path.is_file():
                # scoped re-baseline merges, like --write-budget
                try:
                    existing = memory_mod.load_hbm_budget(hbm_path)
                except ValueError as e:
                    print(f"error: {e}", file=sys.stderr)
                    return 2
                mem_reports = {**existing["entries"], **mem_reports}
            memory_mod.write_hbm_budget(hbm_path, mem_reports)
            print(f"wrote hbm budget for {len(mem_reports)} entr"
                  f"{'y' if len(mem_reports) == 1 else 'ies'} to "
                  f"{hbm_path}",
                  file=sys.stderr)
        elif not args.no_hbm_budget:
            hbm_findings, usage_error = memory_mod.check_hbm_budget_path(
                mem_reports, hbm_path, tops=mem_tops
            )
            if usage_error is not None:
                print(f"error: {usage_error}", file=sys.stderr)
                return 2
            deep_findings.extend(hbm_findings)

    # Gate semantics: AST findings and syntax errors fail regardless of
    # severity (the historical contract — retrace warnings etc. are
    # actionable at the source line). Deep-tier WARNINGS do not gate:
    # they exist precisely for the jax-version-drift downgrade in
    # budget.py, where a red job no author can fix would be wrong — the
    # rendered ::warning annotation is the signal.
    gating = (
        list(errors) + findings
        + [f for f in deep_findings if f.severity == "error"]
    )
    findings = list(errors) + findings + deep_findings
    if args.format == "json":
        print(json.dumps(
            [f.__dict__ for f in findings], indent=2
        ))
    elif args.format == "sarif":
        print(_render_sarif(findings))
    elif args.format == "github":
        for f in findings:
            print(_render_github(f))
    else:
        for f in findings:
            print(f.render())
    if args.format not in ("json", "sarif"):
        n_err = sum(1 for f in findings if f.severity == "error")
        n_warn = len(findings) - n_err
        tail = f" ({suppressed_count} baselined)" if suppressed_count else ""
        tier = f" [{args.tier}]" if args.tier != "ast" else ""
        print(
            f"jaxlint{tier}: {n_err} error(s), {n_warn} warning(s){tail}",
            file=sys.stderr,
        )
    return 1 if gating else 0


if __name__ == "__main__":
    sys.exit(main())
