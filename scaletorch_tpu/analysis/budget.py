"""ST8xx — per-entry-point communication budget gate.

PR 5 attested the int8 gradient all-reduce's wire bytes once, in a test.
This module turns that one-off attestation into a standing contract: the
collectives each audited entry point compiles to — per named mesh axis
(counts + payload MB, from the jaxpr) and per (op, dtype) (ring-model
wire MB, from the compiled HLO) — are checked into
``tools/comm_budget.json``, and CI fails when a PR regresses bytes or
adds an unbudgeted collective:

ST801  an unbudgeted collective appeared (a new (op, dtype) wire class
       or a new named-axis group) — someone added cross-member traffic
       this entry never paid before
ST802  a budgeted quantity regressed beyond tolerance (per-key wire MB,
       per-axis payload MB / count, or the entry total)
ST803  the budget file itself is missing/malformed, or an audited entry
       has no budget — the gate cannot run blind

Dtype-class regressions are the sharp edge here: with the dp mean
configured int8, a silent fall-back to fp32 shows up BOTH as ST701
(jaxpr_audit) and as an ST802 byte regression on ``all-reduce:f32`` —
two independent detectors for the failure mode that silently forfeits
the 4x DCN win.

Re-baselining after an INTENTIONAL comm change:
``python -m scaletorch_tpu.analysis --tier deep --write-budget`` —
commit the JSON and say in the PR what changed and why (the budget diff
is the reviewable artifact).

Budgets are compiled-HLO facts and can drift a little across jax/XLA
releases; the file records the generating jax version, and on a version
mismatch regressions report as warnings (re-baseline advice) instead of
errors — and deep-tier warnings do NOT gate the CLI exit code
(``__main__.py``), so release drift annotates the PR without turning
the job red.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from .core import Finding

DEFAULT_BUDGET = Path("tools") / "comm_budget.json"
# Allowed growth before a budgeted quantity counts as regressed —
# covers float noise and benign instruction-scheduling drift.
DEFAULT_TOLERANCE_PCT = 10.0
# Absolute slack in MB: keys whose budget rounds to ~0 (scalar loss
# means, per-block scales) must not fail on +0.0004 MB of noise.
_ABS_SLACK_MB = 0.01

_BUDGET_FILE = "tools/comm_budget.json"  # finding location


def write_budget(
    path: Path, reports: Dict[str, dict], tolerance_pct: float =
    DEFAULT_TOLERANCE_PCT,
) -> None:
    """Persist per-entry comm reports as the checked-in budget."""
    try:
        import jax
        jax_version = jax.__version__
    except Exception:  # pragma: no cover — deep tier always has jax
        jax_version = "unknown"
    doc = {
        "version": 1,
        "jax": jax_version,
        "tolerance_pct": tolerance_pct,
        "note": (
            "Per-entry-point collective budget (analysis/budget.py). "
            "axes: jaxpr collectives per named mesh axis group; hlo: "
            "compiled wire bytes per (op, dtype) under the ring cost "
            "model (analysis/hlo.py). Regenerate after an INTENTIONAL "
            "comm change with `python -m scaletorch_tpu.analysis "
            "--tier deep --write-budget` and explain the diff in the PR."
        ),
        "entries": reports,
    }
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(doc, indent=1, sort_keys=True) + "\n",
                    encoding="utf-8")


def load_budget(path: Path) -> dict:
    """Parse the budget file; raises ValueError on unreadable/malformed
    content (the CLI maps that to a usage error, like a typo'd path)."""
    try:
        doc = json.loads(path.read_text(encoding="utf-8"))
    except OSError as exc:
        raise ValueError(f"cannot read comm budget {path}: {exc}") from exc
    except json.JSONDecodeError as exc:
        raise ValueError(
            f"comm budget {path} is not valid JSON: {exc}") from exc
    if not isinstance(doc, dict) or not isinstance(doc.get("entries"), dict):
        raise ValueError(
            f"comm budget {path} is malformed: expected an object with an "
            "'entries' mapping"
        )
    return doc


def check_budget(
    reports: Dict[str, dict],
    budget_doc: dict,
    *,
    tolerance_pct: Optional[float] = None,
) -> List[Finding]:
    """Compare freshly-audited comm reports against the checked-in
    budget. Every finding lands on tools/comm_budget.json — the file a
    re-baseline would touch."""
    try:
        import jax
        same_jax = budget_doc.get("jax") in (None, jax.__version__)
    except Exception:  # pragma: no cover
        same_jax = True
    severity = "error" if same_jax else "warning"
    drift_note = (
        "" if same_jax else
        f" [jax {budget_doc.get('jax')} budget vs a different installed "
        "jax — if the regression is release drift, re-baseline with "
        "--write-budget]"
    )
    tol = (
        tolerance_pct if tolerance_pct is not None
        else float(budget_doc.get("tolerance_pct", DEFAULT_TOLERANCE_PCT))
    )
    entries = budget_doc["entries"]
    out: List[Finding] = []

    def regressed(now: float, budgeted: float) -> bool:
        return now > budgeted * (1.0 + tol / 100.0) + _ABS_SLACK_MB

    for name, report in sorted(reports.items()):
        budget = entries.get(name)
        if budget is None:
            out.append(Finding(
                file=_BUDGET_FILE, line=1, code="ST803", severity="error",
                message=(
                    f"audited entry {name!r} has no comm budget — add it "
                    "with --write-budget so its collectives are gated"
                ),
            ))
            continue
        out.extend(_check_keyed(
            name, "hlo", "wire_mb", report.get("hlo", {}),
            budget.get("hlo", {}), regressed, severity, drift_note,
        ))
        out.extend(_check_keyed(
            name, "axes", "payload_mb", report.get("axes", {}),
            budget.get("axes", {}), regressed, severity, drift_note,
        ))
        now_total = float(report.get("total_wire_mb", 0.0))
        budget_total = float(budget.get("total_wire_mb", 0.0))
        if regressed(now_total, budget_total):
            out.append(Finding(
                file=_BUDGET_FILE, line=1, code="ST802", severity=severity,
                message=(
                    f"entry {name!r}: total wire bytes regressed — "
                    f"{now_total:.4f} MB vs budgeted {budget_total:.4f} MB "
                    f"(tolerance {tol:g}%){drift_note}"
                ),
            ))
    return out


def _check_keyed(
    entry: str,
    section: str,
    mb_field: str,
    now: Dict[str, dict],
    budgeted: Dict[str, dict],
    regressed,
    severity: str,
    drift_note: str,
) -> List[Finding]:
    out: List[Finding] = []
    label = "wire class" if section == "hlo" else "axis group"
    for key in sorted(now):
        slot = now[key]
        ref = budgeted.get(key)
        if ref is None:
            out.append(Finding(
                file=_BUDGET_FILE, line=1, code="ST801", severity=severity,
                message=(
                    f"entry {entry!r}: unbudgeted {label} {key!r} "
                    f"({int(slot.get('count', 0))} collective(s), "
                    f"{float(slot.get(mb_field, 0.0)):.4f} MB) — new "
                    "cross-member traffic; if intentional, re-baseline "
                    f"with --write-budget{drift_note}"
                ),
            ))
            continue
        now_mb = float(slot.get(mb_field, 0.0))
        ref_mb = float(ref.get(mb_field, 0.0))
        if regressed(now_mb, ref_mb):
            out.append(Finding(
                file=_BUDGET_FILE, line=1, code="ST802", severity=severity,
                message=(
                    f"entry {entry!r}: {label} {key!r} regressed — "
                    f"{now_mb:.4f} MB vs budgeted {ref_mb:.4f} MB"
                    f"{drift_note}"
                ),
            ))
        now_n = int(slot.get("count", 0))
        ref_n = int(ref.get("count", 0))
        if now_n > ref_n:
            out.append(Finding(
                file=_BUDGET_FILE, line=1, code="ST802", severity=severity,
                message=(
                    f"entry {entry!r}: {label} {key!r} collective count "
                    f"grew {ref_n} -> {now_n} (per-collective latency is "
                    f"paid per instance){drift_note}"
                ),
            ))
    return out


def check_budget_path(
    reports: Dict[str, dict], path: Path
) -> Tuple[List[Finding], Optional[str]]:
    """(findings, usage_error). A missing/malformed budget file is a
    usage error string (exit 2 at the CLI), not a finding crash."""
    if not path.is_file():
        return [], (
            f"comm budget {path} not found — generate it with "
            "`python -m scaletorch_tpu.analysis --tier deep "
            "--write-budget` (or pass --no-budget to skip the gate)"
        )
    try:
        doc = load_budget(path)
    except ValueError as exc:
        return [], str(exc)
    return check_budget(reports, doc), None
