"""Shared path-sensitive obligation walker for the ownership tier.

An intraprocedural abstract interpreter over one function body. The
*contract* (analysis/ownership.py) classifies calls into acquires and
releases; this module owns the control-flow reasoning: branch forking
with state merge at joins, exception edges into in-function handlers,
``finally`` execution on early returns, loop bodies with break/continue
collection, and ``None``-refinement for maybe-None acquires (the
``PageAllocator.alloc`` all-or-nothing contract).

The abstract state maps local variable names to *obligation* sets and
each obligation to a set of statuses reachable at the current program
point:

  ``live``      acquired, not yet discharged — a leak if it reaches a
                normal exit (return / fall-off-end).
  ``released``  a release ran on this path — a second release is a
                double-release (ST1102).
  ``done``      ownership escaped: stored to an attribute/container,
                returned, yielded, passed to a sink call, or aliased.
  ``none``      refined to None (``if x is None:``) — nothing was
                acquired on this path.

Precision beats recall, deliberately (docs/static_analysis.md "known
limits"): uncaught-exception propagation and explicit ``raise`` exits
are not leak-checked (only edges into *in-function* handlers are
modeled), reads never discharge or flag, aliasing (``y = x[0]``)
discharges rather than transfers, and acquires whose result is not
bound to a plain local name are untracked.
"""

from __future__ import annotations

import ast
import dataclasses
import itertools
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from .scopes import dotted_name

# classify_call(call) results --------------------------------------------------
# ("acquire", kind, maybe_none)       obligation on the assignment target
# ("acquire_arg", kind)               obligation on the (Name) first arg
#                                     (single `recv.retain(x)`)
# ("acquire_recv", kind)              obligation on the (Name) receiver
#                                     (`t.start()` on a typed Thread)
# ("release", kind, operand_expr)     discharges the operand's obligations
# ("release_recv", kinds)             `.close()` / `.join()` on the receiver
Classifier = Callable[[ast.Call], Optional[tuple]]


@dataclasses.dataclass
class Obligation:
    oid: int
    kind: str          # "pages" | "file" | "socket" | "thread"
    line: int
    desc: str          # rendered acquire site, e.g. "self.allocator.alloc(n)"
    maybe_none: bool


@dataclasses.dataclass
class Leak:
    obligation: Obligation
    exit_line: int
    exit_kind: str     # "return" | "end"


@dataclasses.dataclass
class DoubleRelease:
    obligation: Obligation
    line: int
    desc: str


@dataclasses.dataclass
class OwnStore:
    """``self.X[i] = v`` where ``v`` carries a pages obligation — marks
    ``X`` as an owning container (the ST1101 empty-store rule)."""

    attr: str
    line: int


@dataclasses.dataclass
class ReleaseLoop:
    """``for p in <iterable>: recv.release(p)`` over a non-local
    iterable (``self.X[i]``) — the discharge side of the owning-
    container rule."""

    attr: Optional[str]   # X when the iterable is self.X[...] / self.X
    line: int


class _State:
    """Bindings (var -> oid set) + statuses (oid -> status set)."""

    __slots__ = ("bind", "status")

    def __init__(self) -> None:
        self.bind: Dict[str, Set[int]] = {}
        self.status: Dict[int, Set[str]] = {}

    def copy(self) -> "_State":
        st = _State()
        st.bind = {k: set(v) for k, v in self.bind.items()}
        st.status = {k: set(v) for k, v in self.status.items()}
        return st

    @staticmethod
    def merge(states: Sequence["_State"]) -> "_State":
        out = _State()
        for st in states:
            for var, oids in st.bind.items():
                out.bind.setdefault(var, set()).update(oids)
            for oid, ss in st.status.items():
                out.status.setdefault(oid, set()).update(ss)
        return out


class FunctionWalk:
    """Walk one function body under a call classifier; collect leaks,
    double releases, owning-container stores and release loops."""

    def __init__(self, fn: ast.AST, classify_call: Classifier,
                 oid_counter: Optional[itertools.count] = None) -> None:
        self.fn = fn
        self.classify = classify_call
        self._oids = oid_counter or itertools.count(1)
        self.obligations: Dict[int, Obligation] = {}
        self.leaks: List[Leak] = []
        self.double_releases: List[DoubleRelease] = []
        self.own_stores: List[OwnStore] = []
        self.empty_stores: List[OwnStore] = []
        self.release_loops: List[ReleaseLoop] = []
        self.returns_owned = False       # a return expr used a pages oid
        self.params = {
            a.arg for a in (fn.args.posonlyargs + fn.args.args
                            + fn.args.kwonlyargs)
        }
        if fn.args.vararg:
            self.params.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            self.params.add(fn.args.kwarg.arg)
        # loop frames: (breaks, continues) state collectors
        self._frames: List[Tuple[List[_State], List[_State]]] = []
        # pending finally bodies (innermost last) for early returns
        self._finals: List[List[ast.stmt]] = []
        self._in_final = False

    def run(self) -> "FunctionWalk":
        st = self._exec_block(self.fn.body, _State())
        if st is not None:
            self._check_exit(st, getattr(self.fn, "end_lineno", None)
                             or self.fn.lineno, "end")
        return self

    # -- statements --------------------------------------------------------
    def _exec_block(self, stmts: Sequence[ast.stmt],
                    st: Optional[_State]) -> Optional[_State]:
        for stmt in stmts:
            if st is None:
                break
            st = self._exec_stmt(stmt, st)
        return st

    def _exec_stmt(self, stmt: ast.stmt, st: _State) -> Optional[_State]:
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._escape_uses(stmt.value, st, returning=True)
            self._run_pending_finals(st)
            self._check_exit(st, stmt.lineno, "return")
            return None
        if isinstance(stmt, ast.Raise):
            # error exits are exempt by design (the raising path already
            # failed; flagging it would drown real leaks in noise)
            return None
        if isinstance(stmt, ast.Break):
            if self._frames:
                self._frames[-1][0].append(st)
            return None
        if isinstance(stmt, ast.Continue):
            if self._frames:
                self._frames[-1][1].append(st)
            return None
        if isinstance(stmt, ast.If):
            return self._exec_if(stmt, st)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return self._exec_for(stmt, st)
        if isinstance(stmt, ast.While):
            return self._exec_loop_body(stmt, st)
        if isinstance(stmt, ast.Try):
            return self._exec_try(stmt, st)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            # `with open(p) as f:` — the context manager releases; the
            # acquire inside a withitem never becomes an obligation
            for item in stmt.items:
                self._scan_expr(item.context_expr, st, in_with=True)
            return self._exec_block(stmt.body, st)
        if isinstance(stmt, ast.Assign):
            return self._exec_assign(stmt, st)
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                fake = ast.Assign(targets=[stmt.target], value=stmt.value)
                ast.copy_location(fake, stmt)
                return self._exec_assign(fake, st)
            return st
        if isinstance(stmt, ast.AugAssign):
            self._escape_uses(stmt.value, st)
            return st
        if isinstance(stmt, ast.Expr):
            self._scan_expr(stmt.value, st)
            return st
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return st  # nested defs get their own walk
        if isinstance(stmt, (ast.Assert, ast.Delete, ast.Pass, ast.Global,
                             ast.Nonlocal, ast.Import, ast.ImportFrom)):
            return st
        # anything else: conservatively scan for calls and escapes
        for call in ast.walk(stmt):
            if isinstance(call, ast.Call):
                self._scan_expr(call, st)
                break
        return st

    def _exec_if(self, stmt: ast.If, st: _State) -> Optional[_State]:
        self._scan_test(stmt.test, st)
        then_st, else_st = st.copy(), st
        self._refine(stmt.test, then_st, truthy=True)
        self._refine(stmt.test, else_st, truthy=False)
        a = self._exec_block(stmt.body, then_st)
        b = self._exec_block(stmt.orelse, else_st)
        outs = [s for s in (a, b) if s is not None]
        return _State.merge(outs) if outs else None

    def _exec_for(self, stmt, st: _State) -> Optional[_State]:
        rel = self._release_loop_parts(stmt, st)
        if rel is not None:
            iterable, kind, desc = rel
            if isinstance(iterable, ast.Name) and iterable.id in st.bind:
                self._apply_release(iterable.id, kind, st, stmt.lineno, desc)
            else:
                self.release_loops.append(ReleaseLoop(
                    attr=self._self_attr_of(iterable), line=stmt.lineno))
            return st
        ret = self._retain_loop_var(stmt)
        if ret is not None:
            var, line, desc = ret
            self._acquire(st, var, "pages", False, line, desc)
            return st
        # plain loop: iteration is a read, not an escape
        return self._exec_loop_body(stmt, st)

    def _exec_loop_body(self, stmt, st: _State) -> Optional[_State]:
        if isinstance(stmt, ast.While):
            self._scan_test(stmt.test, st)
        self._frames.append(([], []))
        body_out = self._exec_block(stmt.body, st.copy())
        breaks, continues = self._frames.pop()
        outs = [st] + [s for s in [body_out] + continues if s is not None]
        after = _State.merge(outs)
        if stmt.orelse:
            after = self._exec_block(stmt.orelse, after)
        outs2 = [s for s in [after] + breaks if s is not None]
        return _State.merge(outs2) if outs2 else None

    def _exec_try(self, stmt: ast.Try, st: _State) -> Optional[_State]:
        body_entry = st.copy()
        snapshots: List[_State] = [body_entry]
        if stmt.finalbody:
            self._finals.append(stmt.finalbody)
        cur: Optional[_State] = st
        for s in stmt.body:
            if cur is None:
                break
            snapshots.append(cur.copy())
            cur = self._exec_stmt(s, cur)
        if cur is not None and stmt.orelse:
            cur = self._exec_block(stmt.orelse, cur)
        outs = [cur] if cur is not None else []
        for handler in stmt.handlers:
            h_out = self._exec_block(handler.body, _State.merge(snapshots))
            if h_out is not None:
                outs.append(h_out)
        if stmt.finalbody:
            self._finals.pop()
        merged = _State.merge(outs) if outs else None
        if stmt.finalbody:
            if merged is None:
                # all paths returned/raised; the return paths already ran
                # the finally via _run_pending_finals
                return None
            return self._exec_block(stmt.finalbody, merged)
        return merged

    def _run_pending_finals(self, st: _State) -> None:
        """A return inside try/finally runs the pending finalbodies
        before the leak check (the ``finally: release`` idiom)."""
        if self._in_final or not self._finals:
            return
        self._in_final = True
        try:
            for fb in reversed(self._finals):
                out = self._exec_block(fb, st)
                if out is None:
                    break
        finally:
            self._in_final = False

    def _exec_assign(self, stmt: ast.Assign, st: _State) -> _State:
        value = stmt.value
        targets = stmt.targets
        acq = self._classify(value)
        if acq is not None and acq[0] == "acquire":
            _, kind, maybe_none = acq
            desc = ast.unparse(value.func)
            if len(targets) == 1 and isinstance(targets[0], ast.Name):
                self._acquire(st, targets[0].id, kind, maybe_none,
                              stmt.lineno, desc)
            # stored straight to an attribute/subscript: escaped at birth
            for arg in value.args + [kw.value for kw in value.keywords]:
                self._escape_uses(arg, st)
            return st
        if isinstance(value, ast.Name) and st.bind.get(value.id):
            # alias / tuple-unpack TRANSFERS the obligations (the
            # ``shared, pages = reserved`` shape) instead of discharging
            # them: releases and escapes through any alias still apply
            oids = set(st.bind[value.id])
            for target in targets:
                self._bind_alias(target, value, oids, st)
            return st
        self._scan_expr(value, st)
        self._escape_uses(value, st)
        for target in targets:
            self._assign_target(target, value, st)
        return st

    def _bind_alias(self, target: ast.AST, value: ast.Name, oids: Set[int],
                    st: _State) -> None:
        if isinstance(target, ast.Name):
            st.bind[target.id] = set(oids)
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind_alias(elt, value, oids, st)
            return
        # stored to an attribute / container slot: ownership escapes
        self._assign_target(target, value, st)
        self._escape_uses(value, st)

    def _assign_target(self, target: ast.AST, value: ast.AST,
                       st: _State) -> None:
        if isinstance(target, ast.Name):
            # rebinding: the old obligations lose their reference (a
            # still-live one will flag at exit), the name starts fresh
            st.bind[target.id] = set()
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._assign_target(elt, value, st)
            return
        if isinstance(target, ast.Subscript):
            attr = self._self_attr_of(target.value)
            if attr is not None:
                if isinstance(value, ast.Name) and any(
                    "pages" == self.obligations[oid].kind
                    for oid in st.bind.get(value.id, ())
                    if oid in self.obligations
                ):
                    self.own_stores.append(OwnStore(attr=attr,
                                                    line=target.lineno))
                elif _is_empty_literal(value):
                    self.empty_stores.append(OwnStore(attr=attr,
                                                      line=target.lineno))

    # -- expressions -------------------------------------------------------
    def _scan_expr(self, expr: ast.AST, st: _State,
                   in_with: bool = False) -> None:
        """Apply acquire/release/escape semantics to one expression
        statement (or with-item / condition sub-expression)."""
        if not isinstance(expr, ast.Call):
            for call in (n for n in ast.walk(expr)
                         if isinstance(n, ast.Call)):
                self._scan_expr(call, st, in_with=in_with)
            return
        cls = self._classify(expr)
        if cls is not None:
            tag = cls[0]
            if tag == "acquire":
                # unbound acquire (incl. with-items): untracked by design
                for arg in expr.args + [kw.value for kw in expr.keywords]:
                    self._escape_uses(arg, st)
                return
            if tag == "acquire_arg":
                _, kind = cls
                if expr.args and isinstance(expr.args[0], ast.Name):
                    self._acquire(st, expr.args[0].id, kind, False,
                                  expr.lineno, ast.unparse(expr.func))
                return
            if tag == "acquire_recv":
                _, kind = cls
                recv = expr.func.value
                if isinstance(recv, ast.Name):
                    self._acquire(st, recv.id, kind, False, expr.lineno,
                                  ast.unparse(expr))
                return
            if tag == "release":
                _, kind, operand = cls
                if isinstance(operand, ast.Name) and operand.id in st.bind:
                    self._apply_release(operand.id, kind, st, expr.lineno,
                                        ast.unparse(expr.func))
                return
            if tag == "release_recv":
                _, kinds = cls
                recv = expr.func.value
                if isinstance(recv, ast.Name) and recv.id in st.bind:
                    for kind in kinds:
                        self._apply_release(recv.id, kind, st, expr.lineno,
                                            ast.unparse(expr.func))
                return
        # unclassified call: arguments escape (sinks — radix.insert,
        # list.append, channel.transfer, user callables); a method
        # *receiver* is only read
        for arg in expr.args + [kw.value for kw in expr.keywords]:
            self._escape_uses(arg, st)
            self._scan_expr(arg, st)
        if isinstance(expr.func, ast.Attribute):
            self._scan_expr(expr.func.value, st)

    def _scan_test(self, test: ast.AST, st: _State) -> None:
        """Conditions: reads don't escape, but nested calls still count
        (acquires/releases inside a test are rare but legal)."""
        for call in (n for n in ast.walk(test) if isinstance(n, ast.Call)):
            self._scan_expr(call, st)
            break

    def _escape_uses(self, expr: ast.AST, st: _State,
                     returning: bool = False) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Name) and node.id in st.bind:
                for oid in st.bind[node.id]:
                    ss = st.status.get(oid)
                    if ss is None or ss == {"none"}:
                        continue
                    if returning and oid in self.obligations and \
                            self.obligations[oid].kind == "pages":
                        self.returns_owned = True
                    st.status[oid] = {"done"}

    # -- contract application ----------------------------------------------
    def _classify(self, expr: ast.AST) -> Optional[tuple]:
        if isinstance(expr, ast.Call):
            return self.classify(expr)
        return None

    def _acquire(self, st: _State, var: str, kind: str, maybe_none: bool,
                 line: int, desc: str) -> None:
        oid = next(self._oids)
        self.obligations[oid] = Obligation(
            oid=oid, kind=kind, line=line, desc=desc, maybe_none=maybe_none)
        st.bind.setdefault(var, set()).add(oid)
        st.status[oid] = {"live"}

    def _apply_release(self, var: str, kind: str, st: _State, line: int,
                       desc: str) -> None:
        for oid in st.bind.get(var, ()):
            ob = self.obligations.get(oid)
            if ob is None or ob.kind != kind:
                continue
            ss = st.status.get(oid, set())
            if ss == {"none"}:
                continue
            if "released" in ss:
                self.double_releases.append(
                    DoubleRelease(obligation=ob, line=line, desc=desc))
            if "done" in ss and "live" not in ss and "released" not in ss:
                continue  # escaped ownership: release belongs to the sink
            st.status[oid] = {"released"}

    # -- refinement ---------------------------------------------------------
    def _refine(self, test: ast.AST, st: _State, truthy: bool) -> None:
        """``if x is None:`` / ``if x is not None:`` (optionally behind
        ``not`` or as the first operand of an ``and``) narrows x."""
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            self._refine(test.operand, st, not truthy)
            return
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
            if truthy and test.values:
                self._refine(test.values[0], st, True)
            return
        if not (isinstance(test, ast.Compare) and len(test.ops) == 1
                and isinstance(test.comparators[0], ast.Constant)
                and test.comparators[0].value is None
                and isinstance(test.left, ast.Name)):
            return
        is_none = isinstance(test.ops[0], ast.Is)
        if not is_none and not isinstance(test.ops[0], ast.IsNot):
            return
        var = test.left.id
        none_branch = (is_none == truthy)
        for oid in st.bind.get(var, ()):
            ss = st.status.get(oid)
            if ss is None:
                continue
            if none_branch:
                st.status[oid] = {"none"}
            else:
                ss.discard("none")
                if not ss:
                    st.status[oid] = {"done"}  # unreachable combination

    # -- exits ---------------------------------------------------------------
    def _check_exit(self, st: _State, line: int, kind: str) -> None:
        seen: Set[int] = set()
        for oid, ss in sorted(st.status.items()):
            if oid in seen or "live" not in ss:
                continue
            seen.add(oid)
            ob = self.obligations.get(oid)
            if ob is not None:
                self.leaks.append(Leak(obligation=ob, exit_line=line,
                                       exit_kind=kind))

    # -- loop-shape recognition ----------------------------------------------
    def _release_loop_parts(self, stmt, st: _State):
        """``for p in X: recv.release(p)`` (one or more release calls on
        the loop target, nothing else) -> (iterable, kind)."""
        if not isinstance(stmt.target, ast.Name) or stmt.orelse:
            return None
        kind = None
        desc = ""
        for body_stmt in stmt.body:
            if not (isinstance(body_stmt, ast.Expr)
                    and isinstance(body_stmt.value, ast.Call)):
                return None
            cls = self._classify(body_stmt.value)
            if cls is None or cls[0] != "release":
                return None
            operand = cls[2]
            if not (isinstance(operand, ast.Name)
                    and operand.id == stmt.target.id):
                return None
            kind = cls[1]
            desc = ast.unparse(body_stmt.value.func)
        return (stmt.iter, kind, desc) if kind is not None else None

    def _retain_loop_var(self, stmt):
        """``for p in X: recv.retain(p)`` -> (X, line, desc): the loop
        acquires one reference per element of X."""
        if not (isinstance(stmt.target, ast.Name)
                and isinstance(stmt.iter, ast.Name) and not stmt.orelse):
            return None
        descs = []
        for body_stmt in stmt.body:
            if not (isinstance(body_stmt, ast.Expr)
                    and isinstance(body_stmt.value, ast.Call)):
                return None
            cls = self._classify(body_stmt.value)
            if cls is None or cls[0] != "acquire_arg":
                return None
            call = body_stmt.value
            if not (call.args and isinstance(call.args[0], ast.Name)
                    and call.args[0].id == stmt.target.id):
                return None
            descs.append(ast.unparse(call.func))
        if not descs:
            return None
        return stmt.iter.id, stmt.lineno, descs[0]

    # -- helpers --------------------------------------------------------------
    @staticmethod
    def _self_attr_of(expr: ast.AST) -> Optional[str]:
        """``self.X`` / ``self.X[...]`` -> ``X``."""
        if isinstance(expr, ast.Subscript):
            expr = expr.value
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            return expr.attr
        return None


def _is_empty_literal(value: ast.AST) -> bool:
    if isinstance(value, (ast.List, ast.Tuple, ast.Set)):
        return not value.elts
    if isinstance(value, ast.Dict):
        return not value.keys
    return False


def call_tail(call: ast.Call) -> Optional[str]:
    """Last dotted component of the callee, e.g. ``release`` for
    ``self.allocator.release``."""
    d = dotted_name(call.func)
    if d is None:
        return None
    return d.split(".")[-1]
