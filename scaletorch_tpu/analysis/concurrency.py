"""ST9xx — host-thread race & deadlock hazards (the concurrency tier).

The serving stack is genuinely concurrent: an asyncio gateway loop, one
``EngineWorker`` thread per replica, watchdog/exporter threads, and
SIGUSR1/SIGTERM handlers all share state. Every concurrency bug so far
(the SpanTracer plain-``Lock`` deadlock under a SIGUSR1 handler, the
disconnect-vs-channel race, the dead-worker reap race) was caught by
human review, not by jaxlint. This pass is the static dual of those
reviews, in the spirit of lightweight lockset race detection, built on
``threads.ThreadModel`` (thread roots, typed call graph, effective
locksets):

ST901  shared mutable attribute (dict/list/set mutation, augmented
       assignment, non-atomic read-modify-write) mutated from two or
       more thread roots with *no lock at all* on at least two of
       them; error. Plain attribute rebinding (``self.flag = True``)
       is atomic enough under the GIL and never flags — the watchdog
       beat-write idiom. A discipline where every mutation from one
       root is locked is trusted (state-machine exclusion, e.g. the
       gateway's reap-lock) — the detector targets *unlocked*
       write-write races.
ST902  asyncio loop state (``asyncio.Event``/``Queue``/``Task``/loop
       methods) touched from a non-loop root without going through
       ``call_soon_threadsafe``/``run_coroutine_threadsafe``; error.
       The sanctioned trampoline itself never flags.
ST903  known-blocking call (``time.sleep``, sync ``queue`` ops,
       ``subprocess``, ``Thread.join``, ``threading.Event.wait``,
       threading-lock ``acquire``, ``Future.result``) directly inside
       a coroutine body — it stalls every request sharing the loop;
       warning (wrap in ``run_in_executor``).
ST904  a signal-handler-reachable function acquires a NON-reentrant
       ``threading.Lock`` that the main path also acquires — the
       handler interrupting the holder mid-critical-section deadlocks
       the process (the PR 8 SpanTracer bug, caught before review);
       error. ``RLock`` never flags.
ST905  bare ``lock.acquire()`` not immediately followed by
       ``try/finally: lock.release()`` (and not a ``with``) — the lock
       leaks on any exception in between; error.
ST906  lock-order cycle: some path acquires A then B while another
       acquires B then A (AB–BA deadlock), computed over the
       root-propagated acquisition graph; error.

Like every jaxlint pass this is pure-AST — nothing under analysis is
imported — and it holds the zero-false-positive bar: the real
``gateway.py``/``spans.py``/``export.py``/``resilience_distributed.py``
patterns (trampolined ``call_soon_threadsafe`` puts, the reap-lock
discipline, the RLock'd tracer, watchdog beat writes) lint clean, and
injection tests reverting the historical review fixes must flag.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Set, Tuple

from .core import Finding
from .scopes import ProjectIndex
from .threads import LOOP_ROOT, LockId, RootId, ThreadModel

# roots the ST901 rule treats as concurrent mutation contexts
_CONCRETE_KINDS = ("thread", "signal", "loop", "caller")


def run(index: ProjectIndex) -> List[Finding]:
    model = ThreadModel(index)
    findings: List[Finding] = []
    findings.extend(_check_st901(model))
    findings.extend(_check_st902(model))
    findings.extend(_check_st903(model))
    findings.extend(_check_st904(model))
    findings.extend(_check_st905(model))
    findings.extend(_check_st906(model))
    return findings


# ---------------------------------------------------------------------------
# ST901 — unlocked cross-root mutation
# ---------------------------------------------------------------------------

def _check_st901(model: ThreadModel) -> List[Finding]:
    out: List[Finding] = []
    for key, per_root in sorted(model.attr_map.items()):
        # mutation records per concrete root
        mut_roots: Dict[RootId, List] = {}
        for rid, recs in per_root.items():
            if rid[0] not in _CONCRETE_KINDS:
                continue
            muts = [(acc, eff) for acc, eff in recs if acc.mutation]
            if muts:
                mut_roots[rid] = muts
        if len(mut_roots) < 2:
            continue
        # a root is "unlocked" when at least one of its mutations holds
        # no lock at all on some path
        unlocked = {
            rid: [(acc, eff) for acc, eff in muts if not eff]
            for rid, muts in mut_roots.items()
        }
        unlocked = {rid: m for rid, m in unlocked.items() if m}
        if len(unlocked) < 2:
            continue
        # anchor the finding at the first unlocked mutation site
        rids = sorted(unlocked)
        acc0, _ = min(
            (pair for rid in rids for pair in unlocked[rid]),
            key=lambda p: p[0].line,
        )
        cls, attr = key
        file = _file_of_class(model, cls) or "<unknown>"
        others = ", ".join(model.describe_root(r) for r in rids)
        out.append(Finding(
            file=file, line=acc0.line, code="ST901", severity="error",
            message=(
                f"shared attribute `{cls}.{attr}` is mutated "
                f"(`{acc0.desc}`) from {len(rids)} thread roots with no "
                f"lock held on any of them ({others}) — concurrent "
                "unlocked writes race; hold one lock at every mutation "
                "site, or confine the attribute to a single thread and "
                "trampoline updates to it"
            ),
        ))
    return out


def _file_of_class(model: ThreadModel, cls: str) -> str:
    ms = model.class_ms.get(cls)
    return ms.sm.rel if ms is not None else ""


# ---------------------------------------------------------------------------
# ST902 — loop state touched off-loop
# ---------------------------------------------------------------------------

def _check_st902(model: ThreadModel) -> List[Finding]:
    out: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()
    for touch, fi, rid in sorted(
            model.loop_touch_hits,
            key=lambda t: (t[1].ms.sm.rel, t[0].line)):
        if rid == LOOP_ROOT or rid[0] not in ("thread", "signal", "caller"):
            continue
        anchor = (fi.ms.sm.rel, touch.line)
        if anchor in seen:
            continue
        seen.add(anchor)
        out.append(Finding(
            file=fi.ms.sm.rel, line=touch.line, code="ST902",
            severity="error",
            message=(
                f"asyncio loop state touched via `{touch.desc}` from "
                f"{model.describe_root(rid)} — asyncio objects are not "
                "thread-safe off their loop; trampoline with "
                "`loop.call_soon_threadsafe(...)` or "
                "`asyncio.run_coroutine_threadsafe(...)`"
            ),
        ))
    return out


# ---------------------------------------------------------------------------
# ST903 — blocking call on the event loop
# ---------------------------------------------------------------------------

def _check_st903(model: ThreadModel) -> List[Finding]:
    out: List[Finding] = []
    for fn, facts in model.facts.items():
        fi = model.funcs[fn]
        if not fi.is_async:
            continue
        for blk in facts.blocking:
            out.append(Finding(
                file=fi.ms.sm.rel, line=blk.line, code="ST903",
                severity="warning",
                message=(
                    f"blocking call `{blk.desc}` inside coroutine "
                    f"`{fi.name}` — it stalls the event loop and every "
                    "request sharing it; await an async equivalent or "
                    "wrap it in `loop.run_in_executor(...)`"
                ),
            ))
    out.sort(key=lambda f: (f.file, f.line))
    return out


# ---------------------------------------------------------------------------
# ST904 — non-reentrant lock shared between a signal handler and main path
# ---------------------------------------------------------------------------

def _check_st904(model: ThreadModel) -> List[Finding]:
    out: List[Finding] = []
    for lid, per_root in sorted(model.lock_holders.items()):
        kind = model.lock_kinds.get(lid)
        if kind != "lock":
            continue
        sig_hits = [
            (acq, fi) for rid, recs in per_root.items()
            if rid in model.signal_roots for acq, fi in recs
        ]
        if not sig_hits:
            continue
        # any acquisition on a non-signal context (main path, a worker
        # thread, the loop, cross-thread callers) can be interrupted by
        # the handler while holding the lock
        main_hits = [
            (acq, fi) for rid, recs in per_root.items()
            if rid not in model.signal_roots for acq, fi in recs
        ]
        if not main_hits:
            continue
        acq, fi = min(sig_hits, key=lambda p: (p[1].ms.sm.rel, p[0].line))
        # prefer a witness at a different site than the anchor so the
        # message shows the two colliding paths, not the same line twice
        macq, mfi = min(
            main_hits,
            key=lambda p: (p[0].line == acq.line and p[1].ms is fi.ms,
                           p[1].ms.sm.rel, p[0].line))
        sig_root = next(iter(
            rid for rid, recs in per_root.items()
            if rid in model.signal_roots))
        out.append(Finding(
            file=fi.ms.sm.rel, line=acq.line, code="ST904",
            severity="error",
            message=(
                f"non-reentrant lock `{model.lock_name(lid)}` is acquired "
                f"here on a path reachable from {model.describe_root(sig_root)} "
                f"and also on the main path (e.g. `{mfi.name}` at "
                f"{mfi.ms.sm.rel}:{macq.line}) — a signal interrupting the "
                "holder re-enters and deadlocks the process; use "
                "`threading.RLock`, or set a flag in the handler and do "
                "the work outside it"
            ),
        ))
    return out


# ---------------------------------------------------------------------------
# ST905 — acquire() without try/finally release
# ---------------------------------------------------------------------------

def _check_st905(model: ThreadModel) -> List[Finding]:
    out: List[Finding] = []
    for fn, facts in model.facts.items():
        fi = model.funcs[fn]
        for acq in facts.acquires:
            if acq.style == "bare" and not acq.safe_release:
                out.append(Finding(
                    file=fi.ms.sm.rel, line=acq.line, code="ST905",
                    severity="error",
                    message=(
                        f"`{model.lock_name(acq.lock)}.acquire()` without "
                        "`with` or an immediate `try/finally: release()` — "
                        "any exception before the release leaks the lock "
                        "and wedges every other acquirer; use `with "
                        "lock:`"
                    ),
                ))
    out.sort(key=lambda f: (f.file, f.line))
    return out


# ---------------------------------------------------------------------------
# ST906 — lock-order cycles (AB–BA deadlock)
# ---------------------------------------------------------------------------

def _check_st906(model: ThreadModel) -> List[Finding]:
    edges: Dict[LockId, Set[LockId]] = {}
    for (a, b) in model.order_edges:
        edges.setdefault(a, set()).add(b)
    out: List[Finding] = []
    reported: Set[FrozenSet[LockId]] = set()
    for (a, b), (acq, fi) in sorted(
            model.order_edges.items(),
            key=lambda kv: (kv[1][1].ms.sm.rel, kv[1][0].line)):
        if _reaches(edges, b, a):
            cyc = frozenset((a, b))
            if cyc in reported:
                continue
            reported.add(cyc)
            out.append(Finding(
                file=fi.ms.sm.rel, line=acq.line, code="ST906",
                severity="error",
                message=(
                    f"lock-order cycle: this path acquires "
                    f"`{model.lock_name(b)}` while holding "
                    f"`{model.lock_name(a)}`, but another path acquires "
                    f"them in the opposite order — two threads taking "
                    "opposite orders deadlock (AB–BA); impose one global "
                    "order or collapse to a single lock"
                ),
            ))
    return out


def _reaches(edges: Dict[LockId, Set[LockId]], src: LockId,
             dst: LockId) -> bool:
    seen: Set[LockId] = set()
    stack = [src]
    while stack:
        cur = stack.pop()
        if cur == dst:
            return True
        if cur in seen:
            continue
        seen.add(cur)
        stack.extend(edges.get(cur, ()))
    return False
