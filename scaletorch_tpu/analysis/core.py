"""jaxlint core — findings, file collection, baseline, pass driver.

The analyzer is a plain-AST tool: it never imports the code under
analysis (so it runs in CI without jax/TPU initialisation and cannot be
confused by import-time side effects). Each pass receives the parsed
module plus the cross-module context built by ``scopes.ProjectIndex``
(declared mesh axes, jit-scope map, param-key universe) and yields
``Finding`` records.

Findings print as ``file:line: CODE severity message`` and are matched
against a checked-in baseline (``tools/jaxlint_baseline.json``) on
``(file, code, message)`` — deliberately not on line numbers, so
unrelated edits above a baselined finding don't resurrect it.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from pathlib import Path
from typing import Iterable, List, Optional, Sequence

SEVERITIES = ("error", "warning")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic. ``file`` is repo-relative with forward slashes."""

    file: str
    line: int
    code: str
    severity: str
    message: str

    def render(self) -> str:
        return f"{self.file}:{self.line}: {self.code} {self.severity} {self.message}"

    def baseline_key(self) -> tuple:
        return (self.file, self.code, self.message)


@dataclasses.dataclass
class SourceModule:
    """A parsed file plus the metadata passes need."""

    path: Path          # absolute
    rel: str            # repo-relative, forward slashes (finding file field)
    module: str         # dotted module name guess, e.g. scaletorch_tpu.models.llama
    source: str
    tree: ast.Module


def _module_name(path: Path, root: Path) -> str:
    try:
        rel = path.resolve().relative_to(root.resolve())
    except ValueError:
        rel = Path(path.name)
    parts = list(rel.with_suffix("").parts)
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def collect_files(
    paths: Sequence[str], root: Optional[Path] = None
) -> tuple[List[SourceModule], List[Finding]]:
    """Expand files/directories into parsed ``SourceModule``s.

    Returns ``(modules, errors)`` — unparseable files become a JL000
    syntax-error finding rather than crashing the run.
    """
    root = (root or Path.cwd()).resolve()
    files: List[Path] = []
    for p in paths:
        pp = Path(p)
        if pp.is_dir():
            files.extend(sorted(pp.rglob("*.py")))
        elif pp.is_file() and pp.suffix == ".py":
            files.append(pp)
        else:
            # A typo'd path must NOT turn the gate silently green.
            raise ValueError(
                f"path is not a directory or .py file: {p}"
            )
    modules: List[SourceModule] = []
    errors: List[Finding] = []
    seen = set()
    for f in files:
        af = f.resolve()
        if af in seen or "__pycache__" in af.parts:
            continue
        seen.add(af)
        try:
            rel = str(af.relative_to(root)).replace(os.sep, "/")
        except ValueError:
            rel = str(f).replace(os.sep, "/")
        source = af.read_text(encoding="utf-8")
        try:
            tree = ast.parse(source, filename=rel)
        except SyntaxError as e:
            errors.append(Finding(
                file=rel, line=e.lineno or 1, code="JL000", severity="error",
                message=f"syntax error: {e.msg}",
            ))
            continue
        modules.append(SourceModule(
            path=af, rel=rel, module=_module_name(af, root), source=source,
            tree=tree,
        ))
    return modules, errors


# ---- baseline ---------------------------------------------------------------

def load_baseline(path: Path) -> List[dict]:
    """Baseline entries; raises OSError/JSONDecodeError/ValueError on an
    unreadable or malformed file — the CLI maps those to a usage error
    (exit 2) so a mangled baseline can neither traceback nor silently
    turn the gate green."""
    data = json.loads(path.read_text(encoding="utf-8"))
    if isinstance(data, dict):
        entries = data.get("findings", [])
    else:
        entries = data
    if not isinstance(entries, list) or not all(
        isinstance(e, dict) for e in entries
    ):
        raise ValueError(
            "baseline must be a list of {file, code, message} objects "
            "(or {\"findings\": [...]})"
        )
    return list(entries)


def save_baseline(path: Path, findings: Iterable[Finding]) -> None:
    entries = sorted(
        (
            {"file": f.file, "code": f.code, "message": f.message}
            for f in findings
        ),
        key=lambda e: (e["file"], e["code"], e["message"]),
    )
    path.write_text(
        json.dumps({"version": 1, "findings": entries}, indent=2) + "\n",
        encoding="utf-8",
    )


def split_by_baseline(
    findings: Sequence[Finding], baseline_entries: Sequence[dict]
) -> tuple[List[Finding], List[Finding]]:
    """(new, suppressed). Each baseline entry absorbs at most as many
    findings as it appears times — a second identical regression still
    fails the gate."""
    budget: dict[tuple, int] = {}
    for e in baseline_entries:
        key = (e.get("file"), e.get("code"), e.get("message"))
        budget[key] = budget.get(key, 0) + 1
    new: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        key = f.baseline_key()
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            suppressed.append(f)
        else:
            new.append(f)
    return new, suppressed
