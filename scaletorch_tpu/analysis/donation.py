"""ST4xx — donation safety.

``donate_argnums`` lets XLA reuse an input buffer for an output — and
invalidates the Python-side array. Reading it afterwards returns
garbage or raises, depending on backend (CPU ignores donation, so the
bug ships: it only fires on TPU). The inference engine's donated KV
caches are exactly this hazard.

ST401  a name passed in a donated position of a jitted call is read
       again later in the same scope without being reassigned first

The resolver follows the factory idiom (``step = make_decode_step(…)``)
across modules, so donated positions declared in ``decode.py`` protect
call sites in ``engine.py``.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .core import Finding
from .scopes import (
    FuncNode,
    JitInfo,
    ModuleScopes,
    ProjectIndex,
    collect_jitted_callables,
    dotted_name,
)


def run(index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for ms in index.scopes.values():
        findings.extend(_check_module(index, ms))
    return findings


def _enclosing_body(ms: ModuleScopes, node: ast.AST) -> Optional[FuncNode]:
    cur = ms.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = ms.parents.get(cur)
    return None


def _donated_arg_names(call: ast.Call, info: JitInfo) -> List[str]:
    """Dotted names (``cache``, ``self.cache``) passed in donated
    positions."""
    out: List[str] = []
    donate_idx = info.donate_argnums or set()
    donate_names = info.donate_argnames or set()
    for i, arg in enumerate(call.args):
        if i in donate_idx:
            d = dotted_name(arg)
            if d:
                out.append(d)
    for kw in call.keywords:
        if kw.arg in donate_names:
            d = dotted_name(kw.value)
            if d:
                out.append(d)
    return out


def _assigned_names(stmt: ast.AST) -> Set[str]:
    """Dotted names (re)bound by a statement, including attribute
    targets like ``self.cache``."""
    names: Set[str] = set()
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        targets = [stmt.target]
    for t in targets:
        for n in ast.walk(t):
            if isinstance(n, (ast.Name, ast.Attribute)):
                d = dotted_name(n)
                if d:
                    names.add(d)
    return names


def _check_module(index: ProjectIndex, ms: ModuleScopes) -> List[Finding]:
    jitted = collect_jitted_callables(index, ms)
    donating = {
        name: info for name, info in jitted.items()
        if (info.donate_argnums or info.donate_argnames)
    }
    if not donating:
        return []
    out: List[Finding] = []
    for call in ast.walk(ms.sm.tree):
        if not isinstance(call, ast.Call):
            continue
        cname = dotted_name(call.func)
        info = donating.get(cname) if cname else None
        if info is None:
            continue
        scope = _enclosing_body(ms, call)
        if scope is None:
            continue
        call_end = getattr(call, "end_lineno", call.lineno)
        rebound_here = _assigned_names(_enclosing_stmt(ms, call))
        for name in _donated_arg_names(call, info):
            if name in rebound_here:
                continue  # cache = step(..., cache): rebound by this very stmt
            finding = _read_after_donate(ms, scope, call_end, name)
            if finding is not None:
                out.append(finding)
    return out


def _enclosing_stmt(ms: ModuleScopes, node: ast.AST) -> ast.AST:
    cur: ast.AST = node
    while cur in ms.parents and not isinstance(cur, ast.stmt):
        cur = ms.parents[cur]
    return cur


def _read_after_donate(
    ms: ModuleScopes,
    scope: FuncNode,
    call_end: int,
    name: str,
) -> Optional[Finding]:
    """Line-ordered scan of the enclosing function: a Load of ``name``
    after the donating call, before any rebinding, is a use of a dead
    buffer."""
    events: List[tuple] = []  # (lineno, kind) kind: 0=assign, 1=load
    for node in ast.walk(scope):
        line = getattr(node, "lineno", None)
        if line is None or line <= call_end:
            continue
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                             ast.For, ast.AsyncFor)):
            if name in _assigned_names(node):
                events.append((line, 0, node))
        if (
            isinstance(node, (ast.Name, ast.Attribute))
            and isinstance(getattr(node, "ctx", None), ast.Load)
            and dotted_name(node) == name
        ):
            events.append((line, 1, node))
    events.sort(key=lambda e: (e[0], e[1]))
    for line, kind, node in events:
        if kind == 0:
            # rebinding from an expression that READS the dead name is
            # still a bug (x = x + 1 after donate) — AugAssign or self-read
            if isinstance(node, ast.AugAssign):
                return _finding(ms, line, name)
            value = getattr(node, "value", None) or getattr(node, "iter", None)
            if value is not None and any(
                isinstance(n, (ast.Name, ast.Attribute))
                and dotted_name(n) == name
                for n in ast.walk(value)
            ):
                return _finding(ms, line, name)
            return None
        return _finding(ms, line, name)
    return None


def _finding(ms: ModuleScopes, line: int, name: str) -> Finding:
    return Finding(
        file=ms.sm.rel, line=line, code="ST401", severity="error",
        message=(
            f"'{name}' is read after being passed in a donated position — "
            "the buffer is invalidated by donate_argnums (works on CPU, "
            "garbage on TPU); rebind the result or drop donation"
        ),
    )
