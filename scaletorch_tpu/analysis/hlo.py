"""Compiled-HLO collective parser — shared analysis infrastructure.

Historically this lived in ``ops/quantized_collectives.py`` (it was born
as the attestation backend for the int8 all-reduce's "4x fewer wire
bytes" claim), but it is analysis code, not numerics: the byte
attestation test, ``tools/aot_cp_crossover.py``, the deep-tier jaxpr/HLO
audit (``analysis/jaxpr_audit.py``) and the comm-budget gate
(``analysis/budget.py``) all read compiled HLO through it. The old
import path re-exports for back-compat.

Pure stdlib (``re`` over HLO text) — importing this module never pulls
in jax, so the pure-AST lint tier stays jax-free.

Two levels of API:

* ``parse_collectives(hlo_text)`` — one ``HloCollective`` record per
  collective instruction (op, payload dtype, result bytes, replica-group
  size, ring-model wire bytes, line number).
* ``collective_wire_bytes(hlo_text)`` — the historical aggregate:
  ``{"by_op": {(op, dtype): bytes}, "total": bytes}``.
"""

from __future__ import annotations

import dataclasses
import re
from typing import List

# result side may be one array or a tuple: `= f32[4,8]{1,0} all-reduce(`
# or `= (f32[4]{0}, /*index=5*/f32[4]{0}, ...) all-to-all(` — long tuples
# carry /*index=N*/ comments, so '=' may appear inside the result part.
_HLO_COLLECTIVE_RE = re.compile(
    r"= *(\(?[a-z0-9]+\[.*?) "
    r"(all-reduce|all-gather|all-to-all|reduce-scatter|"
    r"collective-permute)(?:-start)?\("
)
_HLO_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([\d,]*)\]")
_HLO_GROUP_RE = re.compile(
    r"replica_groups=(\{\{[^}]*\}[^}]*\}|\[[^\]]*\]<=\[[^\]]*\])"
)
_HLO_PAIRS_RE = re.compile(r"source_target_pairs=\{(\{[^=]*?\})\}")

_DTYPE_BYTES = {"f64": 8, "f32": 4, "u32": 4, "s32": 4, "bf16": 2,
                "f16": 2, "s8": 1, "u8": 1, "pred": 1}


@dataclasses.dataclass(frozen=True)
class HloCollective:
    """One collective instruction from a compiled HLO module."""

    op: str             # all-reduce | all-gather | all-to-all | ...
    dtype: str          # first payload dtype in the result shape (f32, s8…)
    result_bytes: int   # total bytes of the result shape(s)
    group_size: int     # participants per replica group (1 = trivial)
    wire_bytes: float   # ring/bidirectional-exchange cost-model estimate
    line_no: int        # 1-based line in the HLO text (diagnostics)


def _replica_group_size(group_match) -> int:
    """Participants per replica group, from either HLO syntax:
    ``{{0,2},{1,3}}`` (explicit) or ``[4,2]<=[8]`` (iota: groups x size)."""
    if group_match is None:
        return 1
    text = group_match.group(1)
    if text.startswith("{"):
        first = text[1:].split("}", 1)[0].lstrip("{")
        return len([t for t in first.split(",") if t.strip()])
    dims = text.split("<=", 1)[0].strip("[]").split(",")
    return int(dims[1]) if len(dims) > 1 else 1


def parse_collectives(hlo_text: str) -> List[HloCollective]:
    """Every non-trivial collective instruction in a compiled HLO module.

    Cost model (ring/bidirectional-exchange, from the RESULT shape and
    replica-group size g):

        all-reduce:          2 * bytes * (g-1)/g
        all-gather/all-to-all:   bytes * (g-1)/g
        reduce-scatter:          bytes * (g-1)        (result is 1/g)
        collective-permute:      bytes                (one hop)

    Trivial groups (g == 1 — e.g. a pmean over a size-1 mesh axis, which
    XLA still emits as an all-reduce instruction) move nothing and are
    excluded.
    """
    out: List[HloCollective] = []
    for line_no, line in enumerate(hlo_text.splitlines(), start=1):
        m = _HLO_COLLECTIVE_RE.search(line)
        if not m:
            continue
        result_part, op = m.groups()
        nbytes = 0
        dt = None
        for dt_i, shape in _HLO_SHAPE_RE.findall(result_part):
            elems = 1
            for d in shape.split(","):
                if d.strip():
                    elems *= int(d)
            nbytes += elems * _DTYPE_BYTES.get(dt_i, 4)
            dt = dt or dt_i
        if not nbytes:
            continue
        # Async '-start' forms return (operand-alias, output[, ...]) —
        # summing the tuple double-counts the payload relative to the
        # sync form's result-shape convention. Halving restores parity
        # (exact for the symmetric permute/all-reduce pairs, and for
        # all-gather-start's in+out = out·(1+1/g) it slightly
        # UNDER-counts — never inflates a backend's bytes).
        if f"{op}-start(" in line and result_part.lstrip().startswith("("):
            nbytes //= 2
        if op == "collective-permute":
            # a permute carries source_target_pairs, not replica_groups;
            # each participating device ships its full shard one hop
            pairs = _HLO_PAIRS_RE.search(line)
            if pairs is None or not pairs.group(1).strip("{}").strip():
                continue
            group = 2
            wire = float(nbytes)
        else:
            group = _replica_group_size(_HLO_GROUP_RE.search(line))
            if group <= 1:
                continue
            wire = {
                "all-reduce": 2.0 * nbytes * (group - 1) / group,
                "all-gather": nbytes * (group - 1) / group,
                "all-to-all": nbytes * (group - 1) / group,
                "reduce-scatter": float(nbytes) * (group - 1),
            }[op]
        out.append(HloCollective(
            op=op, dtype=dt or "f32", result_bytes=nbytes,
            group_size=group, wire_bytes=wire, line_no=line_no,
        ))
    return out


def collective_wire_bytes(hlo_text: str) -> dict:
    """Per-(op, dtype) wire-byte totals for the collectives in a compiled
    HLO module — the attestation backend for "the int8 path really moves
    ~4x fewer bytes" (tests/ops/test_quantized_collectives.py), for the
    ring-vs-ulysses CP comparison (tools/aot_cp_crossover.py), and for
    the per-entry-point comm budget (analysis/budget.py).

    Returns ``{"by_op": {(op, dtype): bytes}, "total": bytes}`` (see
    ``parse_collectives`` for the cost model and exclusions).
    """
    by_op: dict = {}
    total = 0.0
    for rec in parse_collectives(hlo_text):
        by_op[(rec.op, rec.dtype)] = (
            by_op.get((rec.op, rec.dtype), 0.0) + rec.wire_bytes
        )
        total += rec.wire_bytes
    return {"by_op": by_op, "total": total}
