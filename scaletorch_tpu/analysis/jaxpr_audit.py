"""ST7xx — deep-tier jaxpr/HLO semantic audit of compiled entry points.

The AST tier (ST1xx-ST6xx) reasons about source text; this tier reasons
about what XLA actually lowered. It abstractly traces a manifest of
registered entry points — the SPMD train step, the declarative train
step, the inference prefill/decode steps — on virtual CPU meshes
(``--xla_force_host_platform_device_count``; no TPU, no real compute:
every argument is a ``ShapeDtypeStruct``) and walks the jaxpr and the
compiled HLO to check invariants the AST cannot see:

ST700  entry point failed to build/trace/compile (the audit itself is
       part of the contract — a manifest entry that stops compiling is
       a finding, not a skip)
ST701  wire-dtype mismatch on the quantized axis: the config says the
       dp-edge gradient all-reduce is int8, but the lowered program
       moves large non-int8 payloads over that axis (or no int8
       collective at all) — the silent forfeiture of the 4x wire-byte
       win that PR 5 attested once; this makes it a standing gate
ST702  donation annotations did not survive lowering (no
       input/output aliasing in the compiled module) — on TPU that is
       a whole extra params+opt-state footprint in HBM
ST703  a collective over an axis the schedule expects hoisted (the
       single-flush gradient reduction) appears INSIDE a scan/while
       body — it would fire once per microbatch instead of once per
       step
ST704  a single collective result exceeds the entry's replication cap
       (several times the parameter footprint) — the signature of a
       large intermediate silently replicated across the mesh

Each entry point's builder lives NEXT TO the entry point it audits
(``parallel/spmd.audit_entry``, ``trainer/train_step.audit_entry``,
``inference/decode.audit_entry_prefill``/``_decode``/
``_paged_decode``) and returns a
plain dict — the runtime modules never import the analyzer. This module
imports jax and is only pulled in by the ``--tier deep`` CLI path and
its tests; the pure-AST tier stays jax-free.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Tuple

from .core import Finding
from .hlo import parse_collectives

# (entry name, module, builder attr) — the registered deep-audit entry
# points. The name is declared here (and echoed by the builder) so
# --entries can filter BEFORE running any builder.
MANIFEST: Tuple[Tuple[str, str, str], ...] = (
    ("spmd_train_step", "scaletorch_tpu.parallel.spmd", "audit_entry"),
    ("declarative_train_step", "scaletorch_tpu.trainer.train_step",
     "audit_entry"),
    ("prefill_step", "scaletorch_tpu.inference.decode",
     "audit_entry_prefill"),
    ("decode_step", "scaletorch_tpu.inference.decode",
     "audit_entry_decode"),
    ("paged_decode_step", "scaletorch_tpu.inference.decode",
     "audit_entry_paged_decode"),
    ("disagg_prefill_slice", "scaletorch_tpu.inference.disagg",
     "audit_entry_prefill_slice"),
    ("disagg_decode_slice", "scaletorch_tpu.inference.disagg",
     "audit_entry_decode_slice"),
)

# jaxpr primitives that move bytes between mesh members. pvary /
# pbroadcast are type-level VMA ops (no wire) and deliberately absent.
_COLLECTIVE_PRIMS = {
    "psum", "psum2", "psum_invariant", "pmin", "pmax", "all_to_all",
    "all_gather", "all_gather_invariant", "reduce_scatter", "ppermute",
}
_LOOP_PRIMS = {"scan", "while"}

# Payloads at or below this many elements over the quantized axis are
# sidecar traffic (the per-block fp32 scales, scalar loss/metric means)
# and exempt from the ST701 wire-dtype check.
_SMALL_ELEMS = 4096

_WIRE_DTYPE = {"int8": "int8", "bf16": "bfloat16", "fp32": "float32"}


@dataclasses.dataclass(frozen=True)
class JaxprCollective:
    """One collective equation from a traced entry point."""

    prim: str
    axes: Tuple[str, ...]
    dtype: str          # first operand dtype
    elems: int          # max(total operand, total result) elements
    bytes: int          # same, in bytes
    in_loop: bool       # inside a scan/while body


def _aval_stats(vars_) -> Tuple[int, int]:
    elems = 0
    nbytes = 0
    for v in vars_:
        aval = getattr(v, "aval", None)
        if aval is None or not hasattr(aval, "shape"):
            continue
        n = 1
        for d in aval.shape:
            n *= int(d)
        elems += n
        nbytes += n * getattr(aval.dtype, "itemsize", 4)
    return elems, nbytes


def _sub_jaxprs(eqn):
    for v in eqn.params.values():
        if hasattr(v, "eqns"):            # raw Jaxpr (shard_map bodies)
            yield v
        elif hasattr(v, "jaxpr"):         # ClosedJaxpr (pjit/scan/remat)
            yield v.jaxpr
        elif isinstance(v, (list, tuple)):  # cond branches etc.
            for b in v:
                if hasattr(b, "eqns"):
                    yield b
                elif hasattr(b, "jaxpr"):
                    yield b.jaxpr


def collect_jaxpr_collectives(jaxpr) -> List[JaxprCollective]:
    """Every collective equation in ``jaxpr``, recursively, with the
    named mesh axes it runs over and whether a scan/while body holds it."""
    out: List[JaxprCollective] = []

    def walk(jx, in_loop: bool) -> None:
        for eqn in jx.eqns:
            prim = eqn.primitive.name
            if prim in _COLLECTIVE_PRIMS:
                axes = eqn.params.get("axes",
                                      eqn.params.get("axis_name", ()))
                if not isinstance(axes, (tuple, list)):
                    axes = (axes,)
                axes = tuple(str(a) for a in axes if a is not None)
                in_e, in_b = _aval_stats(eqn.invars)
                out_e, out_b = _aval_stats(eqn.outvars)
                dtypes = [
                    str(v.aval.dtype) for v in eqn.invars
                    if hasattr(v, "aval") and hasattr(v.aval, "dtype")
                ]
                out.append(JaxprCollective(
                    prim=prim, axes=axes, dtype=dtypes[0] if dtypes else "?",
                    elems=max(in_e, out_e), bytes=max(in_b, out_b),
                    in_loop=in_loop,
                ))
            for sub in _sub_jaxprs(eqn):
                walk(sub, in_loop or prim in _LOOP_PRIMS)

    walk(jaxpr, False)
    return out


# -- entry loading ------------------------------------------------------------

def load_entries(
    names: Optional[Sequence[str]] = None,
) -> Tuple[List[dict], List[Finding]]:
    """Build the manifest's entry dicts; builder failures become ST700
    findings instead of crashing the whole audit."""
    import importlib

    entries: List[dict] = []
    errors: List[Finding] = []
    known = [name for name, _, _ in MANIFEST]
    if names:
        for n in sorted(set(names) - set(known)):
            errors.append(Finding(
                file="scaletorch_tpu/analysis/jaxpr_audit.py", line=1,
                code="ST700", severity="error",
                message=f"unknown audit entry {n!r}; known: {sorted(known)}",
            ))
    for name, mod_name, attr in MANIFEST:
        if names and name not in names:
            continue  # scoped runs never execute unselected builders
        try:
            mod = importlib.import_module(mod_name)
            entry = getattr(mod, attr)()
        except Exception as exc:
            errors.append(Finding(
                file=mod_name.replace(".", "/") + ".py", line=1,
                code="ST700", severity="error",
                message=f"audit entry builder {mod_name}.{attr} failed: "
                        f"{exc!r}",
            ))
            continue
        if entry["name"] != name:
            errors.append(Finding(
                file=mod_name.replace(".", "/") + ".py", line=1,
                code="ST700", severity="error",
                message=(
                    f"audit entry builder {mod_name}.{attr} returned name "
                    f"{entry['name']!r} but the manifest registers it as "
                    f"{name!r}"
                ),
            ))
            continue
        entries.append(entry)
    return entries, errors


# -- the shared compile -------------------------------------------------------

@dataclasses.dataclass
class CompiledEntry:
    """One manifest entry traced and compiled exactly once — the shared
    substrate of the deep tier (ST7xx/ST8xx, this module) and the memory
    tier (ST10xx, analysis/memory.py), so ``--tier deep,memory`` pays a
    single compile per entry."""

    entry: dict
    jaxpr: object          # ClosedJaxpr from the abstract trace
    compiled: object       # jax Compiled (memory_analysis() lives here)
    compiled_text: str     # compiled HLO text


def compile_entry(
    entry: dict,
) -> Tuple[Optional["CompiledEntry"], List[Finding]]:
    """Trace/lower/compile one built entry on the virtual mesh. Failures
    become ST700 findings (the audit itself is part of the contract), in
    which case the CompiledEntry is None."""
    import jax

    name = entry["name"]
    file = entry["file"]
    findings: List[Finding] = []

    ndev = len(jax.devices())
    if ndev < entry.get("min_devices", 1):
        findings.append(Finding(
            file=file, line=1, code="ST700", severity="error",
            message=(
                f"audit entry {name!r} needs >= {entry['min_devices']} "
                f"devices but only {ndev} are visible — run under "
                "JAX_PLATFORMS=cpu with XLA_FLAGS="
                "--xla_force_host_platform_device_count=8 "
                "(the --tier deep CLI sets this up when jax is not yet "
                "initialized)"
            ),
        ))
        return None, findings

    try:
        traced = entry["fn"].trace(*entry["args"])
        jaxpr = traced.jaxpr
        lowered = (traced.lower() if hasattr(traced, "lower")
                   else entry["fn"].lower(*entry["args"]))
        compiled = lowered.compile()
        compiled_text = compiled.as_text()
    except Exception as exc:
        findings.append(Finding(
            file=file, line=1, code="ST700", severity="error",
            message=f"audit entry {name!r} failed to trace/compile: {exc!r}",
        ))
        return None, findings
    return CompiledEntry(
        entry=entry, jaxpr=jaxpr, compiled=compiled,
        compiled_text=compiled_text,
    ), findings


# -- the audit ----------------------------------------------------------------

def audit_compiled(ce: "CompiledEntry") -> Tuple[List[Finding], dict]:
    """The ST7xx checks + comm report over an already-compiled entry."""
    entry = ce.entry
    cols = collect_jaxpr_collectives(ce.jaxpr)
    hlo_cols = parse_collectives(ce.compiled_text)

    findings: List[Finding] = []
    findings.extend(_check_wire_dtype(entry, cols))
    findings.extend(_check_donation(entry, ce.compiled_text))
    findings.extend(_check_hoisting(entry, cols))
    findings.extend(_check_replication(entry, hlo_cols))
    return findings, _comm_report(cols, hlo_cols)


def audit_entry(entry: dict) -> Tuple[List[Finding], Optional[dict]]:
    """(findings, comm report) for one built entry point. The report
    feeds the comm-budget gate (analysis/budget.py) and is None when the
    entry failed to compile."""
    ce, findings = compile_entry(entry)
    if ce is None:
        return findings, None
    fs, report = audit_compiled(ce)
    return findings + fs, report


def audit_all(
    names: Optional[Sequence[str]] = None,
) -> Tuple[List[Finding], Dict[str, dict]]:
    """Audit every manifest entry (or the named subset). Returns the
    findings plus per-entry comm reports for the budget gate."""
    entries, findings = load_entries(names)
    reports: Dict[str, dict] = {}
    for entry in entries:
        fs, report = audit_entry(entry)
        findings.extend(fs)
        if report is not None:
            reports[entry["name"]] = report
    return findings, reports


# -- checks -------------------------------------------------------------------

def _check_wire_dtype(entry: dict, cols: List[JaxprCollective]
                      ) -> List[Finding]:
    qa = entry.get("quantized_axis")
    if not qa:
        return []
    axis, cfg_dtype = qa
    want = _WIRE_DTYPE.get(cfg_dtype, cfg_dtype)
    if want == "float32":
        return []  # nothing quantized to verify
    on_axis = [c for c in cols if axis in c.axes]
    out: List[Finding] = []
    offenders = [
        c for c in on_axis if c.elems > _SMALL_ELEMS and c.dtype != want
    ]
    for c in offenders:
        out.append(Finding(
            file=entry["file"], line=1, code="ST701", severity="error",
            message=(
                f"entry {entry['name']!r}: configured {cfg_dtype} wire on "
                f"axis {axis!r}, but the lowered program runs `{c.prim}` "
                f"over {c.axes} with {c.elems} {c.dtype} elements — the "
                "quantized all-reduce was silently bypassed (wire bytes "
                f"~{4 if want == 'int8' else 2}x over budget on the DCN "
                "edge)"
            ),
        ))
    if not any(c.dtype == want for c in on_axis):
        out.append(Finding(
            file=entry["file"], line=1, code="ST701", severity="error",
            message=(
                f"entry {entry['name']!r}: configured {cfg_dtype} wire on "
                f"axis {axis!r}, but no {want} collective over that axis "
                "was lowered at all — the quantized path is not in the "
                "compiled program"
            ),
        ))
    return out


def _check_donation(entry: dict, compiled_text: str) -> List[Finding]:
    if not entry.get("expect_donation"):
        return []
    # non-empty alias map; whitespace-tolerant so XLA print-format drift
    # across releases doesn't fake a lost donation
    if re.search(r"input_output_alias=\{\s*\{", compiled_text):
        return []
    return [Finding(
        file=entry["file"], line=1, code="ST702", severity="error",
        message=(
            f"entry {entry['name']!r} declares donated arguments but the "
            "compiled module has no input/output aliasing — donation was "
            "lost in lowering (on TPU this doubles the step's persistent "
            "HBM: params/opt-state or KV cache are copied, not updated "
            "in place)"
        ),
    )]


def _check_hoisting(entry: dict, cols: List[JaxprCollective]
                    ) -> List[Finding]:
    hoisted = set(entry.get("hoisted_axes", ()))
    if not hoisted:
        return []
    out: List[Finding] = []
    for c in cols:
        bad = hoisted & set(c.axes)
        if c.in_loop and bad:
            out.append(Finding(
                file=entry["file"], line=1, code="ST703", severity="error",
                message=(
                    f"entry {entry['name']!r}: `{c.prim}` over "
                    f"{sorted(bad)} runs INSIDE a scan/while body — the "
                    "schedule expects this axis reduced once per step "
                    "after accumulation (the no_sync single-flush "
                    "contract), not once per microbatch"
                ),
            ))
    return out


def _check_replication(entry: dict, hlo_cols) -> List[Finding]:
    cap_mb = entry.get("max_collective_result_mb")
    if not cap_mb:
        return []
    out: List[Finding] = []
    for rec in hlo_cols:
        mb = rec.result_bytes / 1e6
        if mb > cap_mb:
            out.append(Finding(
                file=entry["file"], line=1, code="ST704", severity="error",
                message=(
                    f"entry {entry['name']!r}: a `{rec.op}` result is "
                    f"{mb:.2f} MB (> cap {cap_mb:.2f} MB, several times "
                    "the parameter footprint) — a large intermediate is "
                    "being replicated across the mesh instead of staying "
                    "sharded"
                ),
            ))
    return out


# -- comm report (budget backend) ---------------------------------------------

def _comm_report(cols: List[JaxprCollective], hlo_cols) -> dict:
    """Per-named-axis counts/payload (jaxpr view) + per-(op, dtype) wire
    bytes (compiled view) — the two ledgers the comm budget pins."""
    axes: Dict[str, Dict[str, float]] = {}
    for c in cols:
        key = ",".join(sorted(c.axes)) or "<unnamed>"
        slot = axes.setdefault(key, {"count": 0, "payload_mb": 0.0})
        slot["count"] += 1
        slot["payload_mb"] += c.bytes / 1e6
    hlo: Dict[str, Dict[str, float]] = {}
    total = 0.0
    for rec in hlo_cols:
        key = f"{rec.op}:{rec.dtype}"
        slot = hlo.setdefault(key, {"count": 0, "wire_mb": 0.0})
        slot["count"] += 1
        slot["wire_mb"] += rec.wire_bytes / 1e6
        total += rec.wire_bytes / 1e6
    for slot in axes.values():
        slot["payload_mb"] = round(slot["payload_mb"], 4)
    for slot in hlo.values():
        slot["wire_mb"] = round(slot["wire_mb"], 4)
    return {"axes": axes, "hlo": hlo, "total_wire_mb": round(total, 4)}
