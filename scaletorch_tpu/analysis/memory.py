"""ST10xx — static HBM accounting and the standing peak-memory budget.

PR 5's one-time HLO wire-byte attestation became PR 6's standing
``comm_budget.json`` gate; this module is the same move for the other
scarce resource. Every deep-tier manifest entry (the SPMD train step,
the declarative quantized-DP step, prefill/decode/paged-decode) is
compiled on the virtual CPU mesh and its memory accounting — argument /
temp / output / alias bytes, from ``compiled.memory_analysis()`` when
the backend provides it, else from a jaxpr buffer-liveness estimator —
is checked against ``tools/hbm_budget.json`` with the same slack /
re-baseline / jax-version-downgrade semantics as the comm budget:

ST1001  peak/temp/argument bytes over budget (or budgeted donation
        alias savings lost, or no budget row at all) — the refactor
        that silently costs HBM
ST1002  donation ineffective: the entry declares donated arguments but
        the compiled module's input/output alias savings don't cover
        their bytes — the runtime twin of ST702 (which only asks
        whether ANY alias survived)
ST1003  precision leak: large fp32 buffers lowered in a bf16-configured
        entry outside the allowlisted accumulation set (softmax, loss,
        optimizer moments, quantization scales)
ST1004  remat violation: a configured checkpoint policy whose scan-body
        residuals still survive to the backward at full-activation
        scale
ST1005  pool-sizing mismatch: the engine's ``kv_cache_bytes`` for the
        audited layout disagrees with the compiled cache/pool buffer
        bytes — admission math and XLA must share one source of truth

The XLA numbers are exact compiled facts (buffer assignment, donation
aliasing, fusion all applied); the liveness estimator is a linear walk
of the jaxpr that ignores fusion and donation reuse, so it OVERSTATES
peaks — it exists so the tier still runs (and still attributes the
top-k live allocations to source lines via eqn provenance) on backends
whose ``memory_analysis()`` reports nothing. A budget row records which
source produced it; comparing across sources downgrades to a warning,
like jax-version drift.

Like ST7xx/ST8xx, the per-entry contract fields (``donated_min_mb``,
``compute_dtype``, ``kv_cache``, …) are pinned in the builders next to
the entry points — a config mutation fails the gate loudly instead of
relaxing it. This module imports jax lazily and is only pulled in by
the ``--tier memory`` CLI path and its tests.
"""

from __future__ import annotations

import dataclasses
import json
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from .core import Finding
from .jaxpr_audit import _sub_jaxprs

DEFAULT_HBM_BUDGET = Path("tools") / "hbm_budget.json"
# Same growth tolerance story as the comm budget: float noise plus
# benign buffer-assignment drift across compiles.
DEFAULT_TOLERANCE_PCT = 10.0
# Absolute slack in MB: entries whose budget rounds to ~0 must not fail
# on a few KB of scheduling noise.
_ABS_SLACK_MB = 0.25

_BUDGET_FILE = "tools/hbm_budget.json"  # finding location
_TOP_K = 8

# Function-name substrings (matched over the eqn's user stack frames)
# whose fp32 intermediates are legitimate in a bf16 entry: numerically
# fragile accumulations the mixed-precision recipe deliberately keeps
# wide. Entries can extend this via the ``fp32_allow`` contract field.
_FP32_ALLOW = (
    "softmax", "loss", "cross_entropy", "entropy", "logsumexp",
    "norm", "moment", "adam", "lamb", "adafactor", "optimizer",
    "scale", "quant", "rope", "rotary",
)


@dataclasses.dataclass(frozen=True)
class TopAllocation:
    """One live buffer at the estimated peak, attributed to source."""

    nbytes: int
    shape: Tuple[int, ...]
    dtype: str
    site: str       # "file:line (function)" from eqn provenance


@dataclasses.dataclass(frozen=True)
class MemoryAccounting:
    """Per-entry memory ledger. ``peak_bytes`` follows tools/aot_memory's
    formula — arguments + temps + generated code (outputs alias temps or
    arguments in XLA's accounting; summing them double-counts)."""

    argument_bytes: int
    output_bytes: int
    temp_bytes: int
    alias_bytes: int
    generated_code_bytes: int
    peak_bytes: int
    source: str     # "xla" | "jaxpr-liveness"


# ---- XLA accounting ---------------------------------------------------------

def accounting_from_compiled(compiled) -> Optional[MemoryAccounting]:
    """``compiled.memory_analysis()`` as a :class:`MemoryAccounting`, or
    None when the backend provides nothing usable (the caller then falls
    back to the jaxpr liveness estimator)."""
    try:
        m = compiled.memory_analysis()
    except Exception:
        return None
    if m is None:
        return None
    try:
        arg = int(m.argument_size_in_bytes)
        temp = int(m.temp_size_in_bytes)
        out = int(m.output_size_in_bytes)
        alias = int(m.alias_size_in_bytes)
        code = int(m.generated_code_size_in_bytes)
    except (AttributeError, TypeError):
        return None
    if arg == 0 and temp == 0 and out == 0:
        return None  # a backend that stubs the stats out
    return MemoryAccounting(
        argument_bytes=arg, output_bytes=out, temp_bytes=temp,
        alias_bytes=alias, generated_code_bytes=code,
        peak_bytes=arg + temp + code, source="xla",
    )


# ---- jaxpr buffer-liveness estimator ----------------------------------------

def _var_nbytes(v) -> int:
    aval = getattr(v, "aval", None)
    if aval is None or not hasattr(aval, "shape"):
        return 0
    n = 1
    for d in aval.shape:
        n *= int(d)
    return n * getattr(getattr(aval, "dtype", None), "itemsize", 4)


def _var_shape_dtype(v) -> Tuple[Tuple[int, ...], str]:
    aval = getattr(v, "aval", None)
    if aval is None or not hasattr(aval, "shape"):
        return (), "?"
    return tuple(int(d) for d in aval.shape), str(getattr(aval, "dtype", "?"))


def _eqn_site(eqn) -> str:
    """``file:line (function)`` of the closest user frame, for the top-k
    attribution and the ST1003 message."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return (f"{frame.file_name}:{frame.start_line} "
                    f"({frame.function_name})")
    except Exception:
        pass
    return "<unknown>"


def _eqn_frame_names(eqn) -> List[str]:
    try:
        from jax._src import source_info_util

        return [f.function_name
                for f in source_info_util.user_frames(eqn.source_info)]
    except Exception:
        return []


def _is_literal(v) -> bool:
    # core.Literal carries its value inline; only Vars have liveness
    return hasattr(v, "val")


def _estimate(jx) -> Tuple[int, int, List[TopAllocation]]:
    """One jaxpr level: ``(peak_bytes, input_bytes, top_live_at_peak)``.

    A linear walk in program order: inputs live from the start, each
    equation's outputs allocate, every buffer frees after its last use.
    Sub-jaxprs (pjit/scan/remat bodies, cond branches) contribute their
    own peak *minus* their inputs (already live at the call site) while
    their equation executes. Scan residual stacking is captured by the
    scan equation's ys outvars at this level. No fusion, no donation
    reuse — a deliberate overestimate (see module docstring).
    """
    jx = getattr(jx, "jaxpr", jx)   # ClosedJaxpr also has .eqns — unwrap
    invs = list(getattr(jx, "constvars", ())) + list(jx.invars)
    live: Dict[int, TopAllocation] = {}
    for v in invs:
        shape, dtype = _var_shape_dtype(v)
        live[id(v)] = TopAllocation(
            nbytes=_var_nbytes(v), shape=shape, dtype=dtype,
            site="<argument>",
        )

    last_use: Dict[int, int] = {}
    for i, eqn in enumerate(jx.eqns):
        for v in eqn.invars:
            if not _is_literal(v):
                last_use[id(v)] = i
    n_eqns = len(jx.eqns)
    for v in jx.outvars:
        if not _is_literal(v):
            last_use[id(v)] = n_eqns     # outputs are never freed

    input_bytes = sum(a.nbytes for a in live.values())
    live_bytes = input_bytes
    peak = live_bytes
    top = sorted(live.values(), key=lambda a: -a.nbytes)[:_TOP_K]

    for i, eqn in enumerate(jx.eqns):
        inner_temp = 0
        for sub in _sub_jaxprs(eqn):
            sp, sa, _ = _estimate(sub)
            inner_temp = max(inner_temp, max(0, sp - sa))
        site = _eqn_site(eqn)
        out_allocs = []
        for v in eqn.outvars:
            shape, dtype = _var_shape_dtype(v)
            out_allocs.append(TopAllocation(
                nbytes=_var_nbytes(v), shape=shape, dtype=dtype, site=site,
            ))
        out_bytes = sum(a.nbytes for a in out_allocs)
        cand = live_bytes + inner_temp + out_bytes
        if cand > peak:
            peak = cand
            snapshot = list(live.values()) + out_allocs
            if inner_temp:
                snapshot.append(TopAllocation(
                    nbytes=inner_temp, shape=(), dtype="<body temps>",
                    site=site,
                ))
            top = sorted(snapshot, key=lambda a: -a.nbytes)[:_TOP_K]
        for v, alloc in zip(eqn.outvars, out_allocs):
            live[id(v)] = alloc
        live_bytes += out_bytes
        # free everything whose last use was this equation (including
        # never-used outputs — DropVars die immediately)
        for v in list(eqn.invars) + list(eqn.outvars):
            if _is_literal(v):
                continue
            if last_use.get(id(v), i) <= i and id(v) in live:
                live_bytes -= live.pop(id(v)).nbytes
    return peak, input_bytes, top


def estimate_jaxpr_memory(
    jaxpr,
) -> Tuple[MemoryAccounting, List[TopAllocation]]:
    """Buffer-liveness estimate over a (Closed)Jaxpr — the
    always-available fallback accounting, plus the top-k live
    allocations at the estimated peak for source attribution."""
    jx = getattr(jaxpr, "jaxpr", jaxpr)
    peak, input_bytes, top = _estimate(jx)
    output_bytes = sum(_var_nbytes(v) for v in jx.outvars)
    return MemoryAccounting(
        argument_bytes=input_bytes, output_bytes=output_bytes,
        temp_bytes=max(0, peak - input_bytes), alias_bytes=0,
        generated_code_bytes=0, peak_bytes=peak, source="jaxpr-liveness",
    ), top


def entry_accounting(ce) -> Tuple[MemoryAccounting, List[TopAllocation]]:
    """Accounting for one :class:`~.jaxpr_audit.CompiledEntry` — XLA's
    stats when the backend reports them, the liveness estimate
    otherwise. The top-k attribution always comes from the jaxpr walk
    (XLA's stats carry no per-buffer provenance)."""
    est, top = estimate_jaxpr_memory(ce.jaxpr)
    return accounting_from_compiled(ce.compiled) or est, top


# ---- contract checks (ST1002-ST1005) ----------------------------------------

def _alias_bytes_from_hlo(compiled_text: str, entry: dict) -> int:
    """Fallback alias accounting when ``memory_analysis()`` is absent:
    sum the flattened argument avals named by the compiled module's
    ``input_output_alias`` map."""
    import jax

    from scaletorch_tpu.inference.kv_cache import cache_nbytes

    header = next(
        (ln for ln in compiled_text.splitlines()
         if "input_output_alias=" in ln), "",
    )
    flat = jax.tree_util.tree_leaves(entry["args"])
    total = 0
    for m in re.finditer(r"\((\d+),\s*\{\}", header):
        idx = int(m.group(1))
        if idx < len(flat):
            total += cache_nbytes(flat[idx])
    return total


def _check_donation_bytes(
    entry: dict, acct: MemoryAccounting, compiled_text: str
) -> List[Finding]:
    want_mb = entry.get("donated_min_mb")
    if not entry.get("expect_donation") or not want_mb:
        return []
    if acct.source == "xla":
        alias_mb = acct.alias_bytes / 1e6
    else:
        alias_mb = _alias_bytes_from_hlo(compiled_text, entry) / 1e6
    if alias_mb >= want_mb:
        return []
    return [Finding(
        file=entry["file"], line=1, code="ST1002", severity="error",
        message=(
            f"entry {entry['name']!r}: declared donated arguments should "
            f"alias >= {want_mb:.4f} MB of outputs but the compiled "
            f"module only aliases {alias_mb:.4f} MB — donation is "
            "ineffective (on TPU the un-aliased bytes are a second copy "
            "of params/opt-state or KV cache held across the step)"
        ),
    )]


def _iter_eqns(jx):
    for eqn in jx.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from _iter_eqns(sub)


def _check_precision(entry: dict, jaxpr) -> List[Finding]:
    contract = str(entry.get("compute_dtype") or "")
    if contract not in ("bf16", "bfloat16"):
        return []
    min_elems = int(entry.get("fp32_large_elems", 1 << 20))
    allow = _FP32_ALLOW + tuple(entry.get("fp32_allow", ()))
    jx = getattr(jaxpr, "jaxpr", jaxpr)
    by_site: Dict[str, Tuple[int, int]] = {}   # site -> (count, max bytes)
    for eqn in _iter_eqns(jx):
        for v in eqn.outvars:
            shape, dtype = _var_shape_dtype(v)
            if dtype != "float32":
                continue
            elems = 1
            for d in shape:
                elems *= d
            if elems < min_elems:
                continue
            # synthetic frames ("<lambda>", "<module>") carry no
            # semantic name — they must not satisfy the allowlist
            # ("lamb" would match every "<lambda>")
            frames = [f.lower() for f in _eqn_frame_names(eqn)
                      if not f.startswith("<")]
            if any(a in f for f in frames for a in allow):
                continue
            site = _eqn_site(eqn)
            n, mx = by_site.get(site, (0, 0))
            by_site[site] = (n + 1, max(mx, _var_nbytes(v)))
    out: List[Finding] = []
    for site, (n, mx) in sorted(by_site.items()):
        out.append(Finding(
            file=entry["file"], line=1, code="ST1003", severity="error",
            message=(
                f"entry {entry['name']!r} is configured bf16 but lowers "
                f"{n} large fp32 buffer(s) (up to {mx / 1e6:.4f} MB, >= "
                f"{min_elems} elements) at {site} — outside the "
                "allowlisted accumulation set (softmax/loss/optimizer "
                "moments/quantization scales); an accidental fp32 "
                "residual doubles that activation's HBM and memory "
                "bandwidth"
            ),
        ))
    return out


def _scan_residual_bytes(jx) -> int:
    """Bytes of per-iteration residuals stacked by scan equations (the
    ys outputs beyond the carry) — what survives an accumulation /
    layer scan into the backward."""
    total = 0
    for eqn in _iter_eqns(jx):
        if eqn.primitive.name != "scan":
            continue
        num_carry = int(eqn.params.get("num_carry", 0))
        for v in eqn.outvars[num_carry:]:
            total += _var_nbytes(v)
    return total


def _check_remat(entry: dict, jaxpr) -> List[Finding]:
    policy = entry.get("remat_policy")
    cap_mb = entry.get("residual_cap_mb")
    if not policy or cap_mb is None:
        return []
    jx = getattr(jaxpr, "jaxpr", jaxpr)
    resid_mb = _scan_residual_bytes(jx) / 1e6
    if resid_mb <= cap_mb:
        return []
    return [Finding(
        file=entry["file"], line=1, code="ST1004", severity="error",
        message=(
            f"entry {entry['name']!r}: checkpoint policy {policy!r} is "
            f"configured but {resid_mb:.4f} MB of scan-body residuals "
            f"still survive to the backward (cap {cap_mb:.4f} MB) — the "
            "policy is not rematerializing; activations are stored at "
            "full scale as if gradient checkpointing were off"
        ),
    )]


def _check_pool_sizing(entry: dict) -> List[Finding]:
    kc = entry.get("kv_cache")
    if not kc:
        return []
    from scaletorch_tpu.inference.kv_cache import cache_nbytes, kv_cache_bytes

    expected = kv_cache_bytes(
        kc["cfg"], kc["batch"], kc["max_seq"], kc.get("dtype"),
        layout=kc.get("layout", "dense"), page_size=kc.get("page_size"),
        num_pages=kc.get("num_pages"),
    )
    actual = cache_nbytes(entry["args"][kc["arg_index"]])
    if actual == expected:
        return []
    return [Finding(
        file=entry["file"], line=1, code="ST1005", severity="error",
        message=(
            f"entry {entry['name']!r}: engine kv_cache_bytes sizes the "
            f"{kc.get('layout', 'dense')} cache at {expected} bytes but "
            f"the compiled entry's cache/pool buffers are {actual} bytes "
            "— admission math and the compiled program have drifted "
            "apart (bench_decode's HBM column and page-budget shedding "
            "are computed from the former, XLA allocates the latter)"
        ),
    )]


def check_memory(
    entry: dict, acct: MemoryAccounting, jaxpr, compiled_text: str
) -> List[Finding]:
    """The contract checks for one compiled entry (the budget gate,
    ST1001, is separate — :func:`check_hbm_budget`)."""
    out: List[Finding] = []
    out.extend(_check_donation_bytes(entry, acct, compiled_text))
    out.extend(_check_precision(entry, jaxpr))
    out.extend(_check_remat(entry, jaxpr))
    out.extend(_check_pool_sizing(entry))
    return out


# ---- per-entry report + audit drivers ---------------------------------------

def memory_report(acct: MemoryAccounting) -> dict:
    """The budget-file row for one entry: MB ledger + which accounting
    produced it (XLA stats vs the liveness estimate are not comparable;
    the gate downgrades cross-source diffs to warnings)."""
    return {
        "argument_mb": round(acct.argument_bytes / 1e6, 4),
        "output_mb": round(acct.output_bytes / 1e6, 4),
        "temp_mb": round(acct.temp_bytes / 1e6, 4),
        "alias_mb": round(acct.alias_bytes / 1e6, 4),
        "peak_mb": round(acct.peak_bytes / 1e6, 4),
        "source": acct.source,
    }


def audit_compiled_memory(
    ce,
) -> Tuple[List[Finding], dict, List[TopAllocation]]:
    """(contract findings, budget row, top-k attribution) for one
    :class:`~.jaxpr_audit.CompiledEntry`."""
    acct, top = entry_accounting(ce)
    findings = check_memory(ce.entry, acct, ce.jaxpr, ce.compiled_text)
    return findings, memory_report(acct), top


def audit_memory_all(
    names: Optional[Sequence[str]] = None,
) -> Tuple[List[Finding], Dict[str, dict], Dict[str, List[TopAllocation]]]:
    """Compile the manifest (or the named subset) and run the memory
    audit — the standalone twin of ``jaxpr_audit.audit_all`` for tests
    and the single-tier CLI path."""
    from .jaxpr_audit import compile_entry, load_entries

    entries, findings = load_entries(names)
    reports: Dict[str, dict] = {}
    tops: Dict[str, List[TopAllocation]] = {}
    for entry in entries:
        ce, fs = compile_entry(entry)
        findings.extend(fs)
        if ce is None:
            continue
        fs, report, top = audit_compiled_memory(ce)
        findings.extend(fs)
        reports[entry["name"]] = report
        tops[entry["name"]] = top
    return findings, reports, tops


# ---- the HBM budget gate (ST1001) -------------------------------------------

def write_hbm_budget(
    path: Path, reports: Dict[str, dict],
    tolerance_pct: float = DEFAULT_TOLERANCE_PCT,
) -> None:
    """Persist per-entry memory reports as the checked-in budget."""
    try:
        import jax
        jax_version = jax.__version__
    except Exception:  # pragma: no cover — the memory tier always has jax
        jax_version = "unknown"
    # The generating jax version is stamped PER ROW, not only file-wide:
    # a scoped `--entries X --write-hbm-budget` merges fresh rows next to
    # rows measured under an older jax, and each must keep its own stamp
    # or the cross-version warning downgrade breaks for the stale ones.
    rows = {
        name: {**report, "jax": report.get("jax", jax_version)}
        for name, report in reports.items()
    }
    doc = {
        "version": 1,
        "jax": jax_version,
        "tolerance_pct": tolerance_pct,
        "note": (
            "Per-entry-point HBM budget (analysis/memory.py). Ledger "
            "from compiled.memory_analysis() on the virtual-mesh "
            "compile ('source': 'xla') or the jaxpr buffer-liveness "
            "estimator ('jaxpr-liveness'); peak = argument + temp + "
            "generated code. Regenerate after an INTENTIONAL memory "
            "change with `python -m scaletorch_tpu.analysis --tier "
            "memory --write-hbm-budget` and explain the diff in the PR."
        ),
        "entries": rows,
    }
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(doc, indent=1, sort_keys=True) + "\n",
                    encoding="utf-8")


def load_hbm_budget(path: Path) -> dict:
    """Parse the budget file; ValueError on unreadable/malformed content
    (the CLI maps that to a usage error, like a typo'd path)."""
    try:
        doc = json.loads(path.read_text(encoding="utf-8"))
    except OSError as exc:
        raise ValueError(f"cannot read hbm budget {path}: {exc}") from exc
    except json.JSONDecodeError as exc:
        raise ValueError(
            f"hbm budget {path} is not valid JSON: {exc}") from exc
    if not isinstance(doc, dict) or not isinstance(doc.get("entries"), dict):
        raise ValueError(
            f"hbm budget {path} is malformed: expected an object with an "
            "'entries' mapping"
        )
    return doc


def _top_note(tops: Optional[Dict[str, List[TopAllocation]]],
              name: str) -> str:
    top = (tops or {}).get(name) or []
    shown = [t for t in top if t.site != "<argument>"][:3]
    if not shown:
        return ""
    return " [largest live allocations: " + "; ".join(
        f"{t.nbytes / 1e6:.2f} MB {t.dtype}{list(t.shape)} at {t.site}"
        for t in shown
    ) + "]"


def check_hbm_budget(
    reports: Dict[str, dict],
    budget_doc: dict,
    *,
    tolerance_pct: Optional[float] = None,
    tops: Optional[Dict[str, List[TopAllocation]]] = None,
) -> List[Finding]:
    """Compare fresh memory reports against the checked-in budget.
    Findings land on tools/hbm_budget.json — the file a re-baseline
    would touch. Same downgrade rules as the comm budget: a different
    installed jax, or a different accounting source, reports warnings
    (re-baseline advice) instead of errors."""
    try:
        import jax
        cur_jax = jax.__version__
    except Exception:  # pragma: no cover
        cur_jax = None
    tol = (
        tolerance_pct if tolerance_pct is not None
        else float(budget_doc.get("tolerance_pct", DEFAULT_TOLERANCE_PCT))
    )
    entries = budget_doc["entries"]
    out: List[Finding] = []

    def grew(now: float, budgeted: float) -> bool:
        return now > budgeted * (1.0 + tol / 100.0) + _ABS_SLACK_MB

    def shrank(now: float, budgeted: float) -> bool:
        return now < budgeted * (1.0 - tol / 100.0) - _ABS_SLACK_MB

    for name, report in sorted(reports.items()):
        budget = entries.get(name)
        if budget is None:
            out.append(Finding(
                file=_BUDGET_FILE, line=1, code="ST1001", severity="error",
                message=(
                    f"audited entry {name!r} has no hbm budget — add it "
                    "with --write-hbm-budget so its peak memory is gated"
                ),
            ))
            continue
        # per-row jax stamp (scoped re-baselines mix generations in one
        # file); fall back to the file-wide stamp for older budgets
        row_jax = budget.get("jax", budget_doc.get("jax"))
        same_jax = cur_jax is None or row_jax in (None, cur_jax)
        same_source = report.get("source") == budget.get("source")
        soft = not (same_jax and same_source)
        severity = "warning" if soft else "error"
        drift_note = "" if not soft else (
            " [budget from "
            + (f"jax {row_jax}" if not same_jax
               else f"source {budget.get('source')!r} vs now "
                    f"{report.get('source')!r}")
            + " — if the change is environment drift, re-baseline with "
            "--write-hbm-budget]"
        )
        for field in ("peak_mb", "temp_mb", "argument_mb"):
            now_mb = float(report.get(field, 0.0))
            ref_mb = float(budget.get(field, 0.0))
            if grew(now_mb, ref_mb):
                out.append(Finding(
                    file=_BUDGET_FILE, line=1, code="ST1001",
                    severity=severity,
                    message=(
                        f"entry {name!r}: {field} over budget — "
                        f"{now_mb:.4f} MB vs budgeted {ref_mb:.4f} MB "
                        f"(tolerance {tol:g}% + {_ABS_SLACK_MB} MB)"
                        f"{_top_note(tops, name)}{drift_note}"
                    ),
                ))
        now_alias = float(report.get("alias_mb", 0.0))
        ref_alias = float(budget.get("alias_mb", 0.0))
        if shrank(now_alias, ref_alias):
            out.append(Finding(
                file=_BUDGET_FILE, line=1, code="ST1001", severity=severity,
                message=(
                    f"entry {name!r}: donation alias savings shrank — "
                    f"{now_alias:.4f} MB aliased vs budgeted "
                    f"{ref_alias:.4f} MB; the lost bytes become a second "
                    f"resident copy in HBM{drift_note}"
                ),
            ))
    return out


def check_hbm_budget_path(
    reports: Dict[str, dict], path: Path,
    tops: Optional[Dict[str, List[TopAllocation]]] = None,
) -> Tuple[List[Finding], Optional[str]]:
    """(findings, usage_error). A missing/malformed budget file is a
    usage error string (exit 2 at the CLI), not a finding crash."""
    if not path.is_file():
        return [], (
            f"hbm budget {path} not found — generate it with "
            "`python -m scaletorch_tpu.analysis --tier memory "
            "--write-hbm-budget` (or pass --no-hbm-budget to skip the "
            "gate)"
        )
    try:
        doc = load_hbm_budget(path)
    except ValueError as exc:
        return [], str(exc)
    return check_hbm_budget(reports, doc, tops=tops), None
