"""Ownership tier (ST11xx): static resource-conservation and
lifecycle analysis for the serving host path.

The serving invariants — every page exactly one live owner, every
request exactly one terminal outcome, every span a balanced begin/end —
are enforced at runtime by ``check_conservation`` and the fault-drill
suites. This tier makes them *static*: a declarative ``CONTRACT`` table
of acquire/release/transfer APIs checked along every path (branches,
exception edges into in-function handlers, early returns) by the
shared walker in ``cfg.py``, on top of ``threads.py``'s typed-only
resolution so precision beats recall.

======  =====================================================
ST1101  acquired resource leaks on some path (not released,
        stored, returned, or transferred to a sink)
ST1102  double-release along one path
ST1103  terminal-outcome write outside the designated funnel
ST1104  unbalanced request spans (begin without end/instant)
ST1105  rollback-path asymmetry (source released before the
        destination in a transfer handler)
======  =====================================================

Known limits (docs/static_analysis.md): raise/uncaught-exception exits
are exempt, acquires not bound to a plain local are untracked, owning
containers are discovered globally by attribute name, and span balance
is judged across the whole analyzed set.
"""

from __future__ import annotations

import ast
import itertools
from typing import Dict, List, Optional, Set, Tuple

from .cfg import FunctionWalk, call_tail
from .core import Finding
from .scopes import ProjectIndex, dotted_name
from .threads import ThreadModel

# ---------------------------------------------------------------------------
# the contract table (docs/static_analysis.md renders this verbatim)
# ---------------------------------------------------------------------------

CONTRACT = {
    # refcounted page pools: alloc/allocate acquire (may return None —
    # the all-or-nothing contract), retain acquires one ref per page
    # argument, release discharges one ref.
    "allocators": {
        "classes": ("PageAllocator",),
        "acquire": ("alloc", "allocate"),
        "acquire_ref": ("retain",),
        "release": ("release",),
    },
    # OS handles: acquire by exact dotted callee (``os.open``/``urlopen``
    # etc. deliberately absent), discharged by ``with`` or ``.close()``.
    "handles": {
        "acquire": {
            "open": "file",
            "io.open": "file",
            "socket.socket": "socket",
            "socket.create_connection": "socket",
        },
        "release": ("close",),
    },
    # threads stored on self must be joined by *some* method of the
    # owning class (the drain path); locals are path-checked unless the
    # constructor says daemon=True (declared fire-and-forget).
    "threads": {"acquire": "start", "release": "join"},
    # terminal-outcome funnels: every call of the key must be lexically
    # inside the named function (exactly-one-terminal, ST1103).
    "funnels": {
        "record_outcome": "_finalize",
        "record_response": "_record_outcome",
    },
    # terminal stores: ``self.<attr>[...] = ...`` only inside the funnel
    "outcome_stores": {"_results": "_finalize"},
    # request spans: async_event(ph, name, ...) with ph in b/e/n; every
    # "b" name needs an "e" or "n" somewhere in the analyzed set
    "spans": {"event": "async_event"},
}

_KIND_NOUN = {
    "pages": "page ownership",
    "file": "a file handle",
    "socket": "a socket",
    "thread": "a running thread",
}
_KIND_VERB = {
    "pages": "releases",
    "file": "closes",
    "socket": "closes",
    "thread": "joins",
}


def _root_name(expr: ast.AST) -> Optional[str]:
    """Leftmost Name of a chain: ``h.pages[i]`` -> ``h``."""
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def _is_daemon_ctor(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is True:
            return True
    return False


def _has_return_none(fn: ast.AST) -> bool:
    """An own-body ``return``/``return None`` (nested defs excluded)."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Return) and (
            node.value is None
            or (isinstance(node.value, ast.Constant)
                and node.value.value is None)
        ):
            return True
        stack.extend(ast.iter_child_nodes(node))
    return False


class _OwnershipModel:
    """MRO-aware view over ``ThreadModel``'s typed world, plus the
    per-function walks and the cross-function registries the five
    checks read."""

    def __init__(self, model: ThreadModel) -> None:
        self.model = model
        self._mro_cache: Dict[str, List[str]] = {}
        self._lt_cache: Dict[ast.AST, Dict[str, str]] = {}
        self._walks: List[Tuple[object, FunctionWalk]] = []
        self._own_attrs: Set[str] = set()
        self._oids = itertools.count(1)

    # -- typing ------------------------------------------------------------
    def mro(self, cls: str) -> List[str]:
        got = self._mro_cache.get(cls)
        if got is not None:
            return got
        out: List[str] = []
        seen: Set[str] = set()
        stack = [cls]
        while stack:
            c = stack.pop(0)
            if c in seen or c not in self.model.classes:
                continue
            seen.add(c)
            out.append(c)
            for b in self.model.classes[c].bases:
                d = dotted_name(b)
                if d is not None:
                    stack.append(d.split(".")[-1])
        self._mro_cache[cls] = out
        return out

    def attr_type(self, cls: Optional[str], attr: str) -> Optional[str]:
        if cls is None:
            return None
        for c in self.mro(cls):
            t = self.model.attr_types.get((c, attr))
            if t is not None:
                return t
        return None

    def resolve_method(self, cls: str, name: str) -> Optional[ast.AST]:
        for c in self.mro(cls):
            fn = self.model.methods.get((c, name))
            if fn is not None:
                return fn
        return None

    def _recv_type(self, expr: ast.AST, cls: Optional[str],
                   local_types: Dict[str, str]) -> Optional[str]:
        if isinstance(expr, ast.Name):
            if expr.id == "self":
                return cls
            return local_types.get(expr.id)
        if isinstance(expr, ast.Attribute):
            base = self._recv_type(expr.value, cls, local_types)
            if base is not None and not base.startswith("ext:"):
                return self.attr_type(base, expr.attr)
            return None
        if isinstance(expr, ast.Call):
            return self.model._ctor_kind(expr)
        return None

    def local_types(self, fn: ast.AST) -> Dict[str, str]:
        got = self._lt_cache.get(fn)
        if got is not None:
            return got
        fi = self.model.funcs.get(fn)
        cls = fi.class_name if fi is not None else None
        out: Dict[str, str] = {}
        args = fn.args
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            t = self.model._ann_type(a.annotation)
            if t is not None:
                out.setdefault(a.arg, t)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                v = node.value
                name = node.targets[0].id
                if isinstance(v, ast.Call):
                    k = self.model._ctor_kind(v)
                    if k == "ext:thread" and _is_daemon_ctor(v):
                        continue  # declared fire-and-forget
                    if k is not None:
                        out.setdefault(name, k)
                elif isinstance(v, ast.Attribute) and \
                        isinstance(v.value, ast.Name) and \
                        v.value.id == "self":
                    t = self.attr_type(cls, v.attr)
                    if t is not None:
                        out.setdefault(name, t)
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                t = self.model._ann_type(node.annotation)
                if t is not None:
                    out.setdefault(node.target.id, t)
        self._lt_cache[fn] = out
        return out

    # -- the call classifier (CONTRACT -> cfg.Classifier) -------------------
    def classifier(self, fi, owned: Dict[ast.AST, bool]):
        cls = fi.class_name
        lts = self.local_types(fi.node)
        alloc = CONTRACT["allocators"]
        handles = CONTRACT["handles"]

        def classify(call: ast.Call) -> Optional[tuple]:
            d = dotted_name(call.func)
            hk = handles["acquire"].get(d) if d is not None else None
            if hk is not None:
                return ("acquire", hk, False)
            if not isinstance(call.func, ast.Attribute):
                return None
            tail = call.func.attr
            rtype = self._recv_type(call.func.value, cls, lts)
            if rtype in alloc["classes"]:
                if tail in alloc["acquire"]:
                    return ("acquire", "pages", True)
                if tail in alloc["acquire_ref"]:
                    return ("acquire_arg", "pages")
                if tail in alloc["release"]:
                    op = call.args[0] if call.args else None
                    return ("release", "pages", op)
            if rtype == "ext:thread":
                if tail == CONTRACT["threads"]["acquire"]:
                    return ("acquire_recv", "thread")
                if tail == CONTRACT["threads"]["release"]:
                    return ("release_recv", ("thread",))
            if tail in handles["release"]:
                return ("release_recv", ("file", "socket"))
            # a call of a method whose return value carries page
            # ownership (round-1 discovery) is itself an acquire
            if rtype is not None and not rtype.startswith("ext:"):
                m = self.resolve_method(rtype, tail)
                if m is not None and m in owned:
                    return ("acquire", "pages", owned[m])
            return None

        return classify

    # -- walks + ST1101/ST1102 ---------------------------------------------
    def check_lifecycles(self) -> List[Finding]:
        todo = [fi for fi in self.model.funcs.values()
                if not isinstance(fi.node, ast.Lambda)]
        # round 1..n: fixpoint the owned-returning method set, so
        # `reserved = self._reserve_pages(req)` is an acquire in round 2
        owned: Dict[ast.AST, bool] = {}
        for _ in range(4):
            changed = False
            for fi in todo:
                if fi.node in owned:
                    continue
                w = FunctionWalk(fi.node, self.classifier(fi, owned)).run()
                if w.returns_owned:
                    owned[fi.node] = _has_return_none(fi.node)
                    changed = True
            if not changed:
                break
        out: List[Finding] = []
        for fi in todo:
            w = FunctionWalk(fi.node, self.classifier(fi, owned),
                             oid_counter=self._oids).run()
            self._walks.append((fi, w))
            for s in w.own_stores:
                self._own_attrs.add(s.attr)
            # the acquire side of an owned-returning method is, by
            # construction, discharged by its return — its own leaks on
            # *non*-return paths still count, so keep them
            for leak in w.leaks:
                ob = leak.obligation
                exit_desc = ("return" if leak.exit_kind == "return"
                             else "end of the function")
                out.append(Finding(
                    file=fi.ms.sm.rel, line=ob.line, code="ST1101",
                    severity="error",
                    message=(
                        f"`{ob.desc}` acquires {_KIND_NOUN[ob.kind]} here, "
                        f"but a path reaching the {exit_desc} at line "
                        f"{leak.exit_line} neither {_KIND_VERB[ob.kind]} it "
                        "nor stores/returns/transfers it — leaked "
                        "ownership; discharge it on every path "
                        "(try/finally) or hand it to a sink"
                    )))
            for dr in w.double_releases:
                ob = dr.obligation
                out.append(Finding(
                    file=fi.ms.sm.rel, line=dr.line, code="ST1102",
                    severity="error",
                    message=(
                        f"`{dr.desc}(...)` releases again what this path "
                        f"already released (acquired via `{ob.desc}` at "
                        f"line {ob.line}) — a double release corrupts the "
                        "refcount/free-list; release exactly once per path"
                    )))
        return out

    # -- owning containers (the retire-path empty-store rule) ---------------
    def check_containers(self) -> List[Finding]:
        out: List[Finding] = []
        for fi, w in self._walks:
            for store in w.empty_stores:
                if store.attr not in self._own_attrs:
                    continue
                if any(rl.attr == store.attr and rl.line < store.line
                       for rl in w.release_loops):
                    continue
                out.append(Finding(
                    file=fi.ms.sm.rel, line=store.line, code="ST1101",
                    severity="error",
                    message=(
                        f"`self.{store.attr}[...]` is emptied here, but "
                        f"`{store.attr}` owns pages (pages are stored "
                        "into it elsewhere) and no release loop over "
                        f"`self.{store.attr}` precedes the clear in this "
                        "function — the dropped pages leak from the "
                        "pool; release each page before emptying the slot"
                    )))
        return out

    # -- stored threads: started somewhere, joined nowhere -------------------
    def check_threads(self) -> List[Finding]:
        starts: Dict[Tuple[str, str], Tuple[object, int]] = {}
        joins: Set[Tuple[str, str]] = set()
        for fi in self.model.funcs.values():
            cls = fi.class_name
            if cls is None or isinstance(fi.node, ast.Lambda):
                continue
            for call in ast.walk(fi.node):
                if not (isinstance(call, ast.Call)
                        and isinstance(call.func, ast.Attribute)):
                    continue
                recv = call.func.value
                if not (isinstance(recv, ast.Attribute)
                        and isinstance(recv.value, ast.Name)
                        and recv.value.id == "self"):
                    continue
                if self.attr_type(cls, recv.attr) != "ext:thread":
                    continue
                owner = next((c for c in self.mro(cls)
                              if (c, recv.attr) in self.model.attr_types),
                             cls)
                key = (owner, recv.attr)
                if call.func.attr == CONTRACT["threads"]["acquire"]:
                    starts.setdefault(key, (fi, call.lineno))
                elif call.func.attr == CONTRACT["threads"]["release"]:
                    joins.add(key)
        out: List[Finding] = []
        for key in sorted(starts):
            if key in joins:
                continue
            cls, attr = key
            fi, line = starts[key]
            out.append(Finding(
                file=fi.ms.sm.rel, line=line, code="ST1101",
                severity="error",
                message=(
                    f"thread `self.{attr}` (class `{cls}`) is started "
                    "here but no method of the class ever joins it — the "
                    "stop/drain path cannot bound shutdown; join it "
                    "(with a timeout) after signalling stop"
                )))
        return out

    # -- terminal-outcome funnels (ST1103) -----------------------------------
    def _enclosing_func(self, ms, node: ast.AST) -> Optional[ast.AST]:
        cur = ms.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = ms.parents.get(cur)
        return None

    def check_funnels(self) -> List[Finding]:
        out: List[Finding] = []
        funnels = CONTRACT["funnels"]
        stores = CONTRACT["outcome_stores"]
        for ms in self.model.index.scopes.values():
            for node in ast.walk(ms.sm.tree):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr in funnels:
                    want = funnels[node.func.attr]
                    encl = self._enclosing_func(ms, node)
                    fname = encl.name if encl is not None else None
                    if fname != want:
                        out.append(Finding(
                            file=ms.sm.rel, line=node.lineno,
                            code="ST1103", severity="error",
                            message=(
                                f"terminal outcome recorded via "
                                f"`{node.func.attr}(...)` outside its "
                                f"designated funnel `{want}` (here: "
                                f"`{fname or '<module>'}`) — exactly-one-"
                                "terminal is only auditable when every "
                                f"terminal write routes through `{want}`"
                            )))
                elif isinstance(node, ast.Assign):
                    for t in node.targets:
                        if not (isinstance(t, ast.Subscript)
                                and isinstance(t.value, ast.Attribute)
                                and isinstance(t.value.value, ast.Name)
                                and t.value.value.id == "self"
                                and t.value.attr in stores):
                            continue
                        want = stores[t.value.attr]
                        encl = self._enclosing_func(ms, node)
                        fname = encl.name if encl is not None else None
                        if fname != want:
                            out.append(Finding(
                                file=ms.sm.rel, line=node.lineno,
                                code="ST1103", severity="error",
                                message=(
                                    f"terminal result stored into "
                                    f"`self.{t.value.attr}[...]` outside "
                                    f"its designated funnel `{want}` "
                                    f"(here: `{fname or '<module>'}`) — "
                                    "route terminal stores through "
                                    f"`{want}` so each request ends "
                                    "exactly once"
                                )))
        return out

    # -- request spans (ST1104) ----------------------------------------------
    def _span_wrappers(self) -> Dict[str, tuple]:
        """Functions forwarding (ph, name) into ``async_event`` — maps
        wrapper name -> ((kind, val), (kind, val)) where kind is
        ``const`` or ``param`` (position excluding self)."""
        event = CONTRACT["spans"]["event"]
        wrappers: Dict[str, object] = {}
        for fi in self.model.funcs.values():
            node = fi.node
            if isinstance(node, ast.Lambda):
                continue
            params = [a.arg for a in node.args.posonlyargs + node.args.args]
            if params and params[0] == "self":
                params = params[1:]
            for call in ast.walk(node):
                if not (isinstance(call, ast.Call)
                        and isinstance(call.func, ast.Attribute)
                        and call.func.attr == event
                        and len(call.args) >= 2):
                    continue
                spec = []
                for argexpr in call.args[:2]:
                    if isinstance(argexpr, ast.Constant) and \
                            isinstance(argexpr.value, str):
                        spec.append(("const", argexpr.value))
                    elif isinstance(argexpr, ast.Name) and \
                            argexpr.id in params:
                        spec.append(("param", params.index(argexpr.id)))
                    else:
                        spec = None
                        break
                if spec is None:
                    continue
                prev = wrappers.get(node.name)
                if prev is not None and prev != tuple(spec):
                    wrappers[node.name] = "ambiguous"
                else:
                    wrappers[node.name] = tuple(spec)
        return {k: v for k, v in wrappers.items() if v != "ambiguous"}

    @staticmethod
    def _const_names(expr: ast.AST) -> List[str]:
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return [expr.value]
        if isinstance(expr, ast.IfExp):
            return (_OwnershipModel._const_names(expr.body)
                    + _OwnershipModel._const_names(expr.orelse))
        return []

    def check_spans(self) -> List[Finding]:
        event = CONTRACT["spans"]["event"]
        wrappers = self._span_wrappers()
        begins: Dict[str, Tuple[str, int]] = {}
        end_sites: Dict[str, Tuple[str, int]] = {}
        closers: Set[str] = set()
        instants: Set[str] = set()

        def record(ph, names, rel, line) -> None:
            for nm in names:
                if ph == "b":
                    begins.setdefault(nm, (rel, line))
                elif ph == "e":
                    closers.add(nm)
                    end_sites.setdefault(nm, (rel, line))
                elif ph == "n":
                    instants.add(nm)

        for ms in self.model.index.scopes.values():
            for call in ast.walk(ms.sm.tree):
                if not isinstance(call, ast.Call):
                    continue
                tail = call_tail(call)
                if tail == event and len(call.args) >= 2:
                    if isinstance(call.args[0], ast.Constant):
                        record(call.args[0].value,
                               self._const_names(call.args[1]),
                               ms.sm.rel, call.lineno)
                elif tail in wrappers:
                    ph_spec, name_spec = wrappers[tail]
                    ph = None
                    if ph_spec[0] == "const":
                        ph = ph_spec[1]
                    elif ph_spec[1] < len(call.args) and \
                            isinstance(call.args[ph_spec[1]], ast.Constant):
                        ph = call.args[ph_spec[1]].value
                    if ph is None:
                        continue
                    if name_spec[0] == "const":
                        names = [name_spec[1]]
                    elif name_spec[1] < len(call.args):
                        names = self._const_names(call.args[name_spec[1]])
                    else:
                        names = []
                    record(ph, names, ms.sm.rel, call.lineno)
        out: List[Finding] = []
        for name in sorted(begins):
            if name in closers or name in instants:
                continue
            rel, line = begins[name]
            out.append(Finding(
                file=rel, line=line, code="ST1104", severity="error",
                message=(
                    f"request span `{name}` is begun here (ph=\"b\") but "
                    "nothing in the analyzed set ever ends it (ph=\"e\") "
                    "or marks it instant (ph=\"n\") — the async track "
                    "renders an unterminated span; emit the closing "
                    "event on every terminal path"
                )))
        for name in sorted(end_sites):
            if name in begins:
                continue
            rel, line = end_sites[name]
            out.append(Finding(
                file=rel, line=line, code="ST1104", severity="error",
                message=(
                    f"request span `{name}` is ended here (ph=\"e\") but "
                    "nothing in the analyzed set ever begins it "
                    "(ph=\"b\") — an end without a begin is dropped by "
                    "the trace viewer; begin the span where the phase "
                    "starts"
                )))
        return out

    # -- rollback-path ordering (ST1105) -------------------------------------
    def _handler_release_events(self, handler, cls, lts, params):
        """Ordered (line, receiver, provenance, desc) for allocator
        releases in one except-handler body. Provenance is ``param``
        (operand rooted at a function parameter), ``local`` or ``self``."""
        alloc = CONTRACT["allocators"]
        events = []
        skip: Set[int] = set()

        def recv_of(call):
            if not (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and call.func.attr in alloc["release"]):
                return None
            rtype = self._recv_type(call.func.value, cls, lts)
            if rtype in alloc["classes"]:
                return ast.unparse(call.func.value)
            return None

        def provenance(root: Optional[str]) -> str:
            if root == "self":
                return "self"
            if root in params:
                return "param"
            return "local"

        for stmt in handler.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.For) and \
                        isinstance(sub.target, ast.Name):
                    body_calls = [s.value for s in sub.body
                                  if isinstance(s, ast.Expr)
                                  and isinstance(s.value, ast.Call)]
                    if len(body_calls) == len(sub.body) and body_calls \
                            and all(recv_of(c) is not None
                                    and c.args
                                    and isinstance(c.args[0], ast.Name)
                                    and c.args[0].id == sub.target.id
                                    for c in body_calls):
                        events.append((
                            sub.lineno, recv_of(body_calls[0]),
                            provenance(_root_name(sub.iter)),
                            ast.unparse(sub.iter),
                        ))
                        skip.update(id(c) for c in body_calls)
                elif isinstance(sub, ast.Call) and id(sub) not in skip:
                    recv = recv_of(sub)
                    if recv is not None and sub.args:
                        events.append((
                            sub.lineno, recv,
                            provenance(_root_name(sub.args[0])),
                            ast.unparse(sub.args[0]),
                        ))
        events.sort(key=lambda e: e[0])
        return events

    def check_rollback(self) -> List[Finding]:
        out: List[Finding] = []
        for ms in self.model.index.scopes.values():
            for node in ast.walk(ms.sm.tree):
                if not isinstance(node, ast.Try):
                    continue
                encl = self._enclosing_func(ms, node)
                if encl is None:
                    continue
                fi = self.model.funcs.get(encl)
                cls = fi.class_name if fi is not None else None
                lts = self.local_types(encl)
                params = {a.arg for a in encl.args.args
                          + encl.args.kwonlyargs} - {"self"}
                for handler in node.handlers:
                    events = self._handler_release_events(
                        handler, cls, lts, params)
                    if len({e[1] for e in events}) < 2:
                        continue
                    for i, (line, recv, prov, desc) in enumerate(events):
                        if prov != "param":
                            continue
                        later = next(
                            (e for e in events[i + 1:]
                             if e[2] == "local" and e[1] != recv), None)
                        if later is None:
                            continue
                        out.append(Finding(
                            file=ms.sm.rel, line=line, code="ST1105",
                            severity="error",
                            message=(
                                "rollback handler releases the transfer "
                                f"source first (`{recv}.release` over "
                                f"`{desc}`, which came in as a parameter) "
                                "before the destination "
                                f"(`{later[1]}.release` over `{later[3]}` "
                                f"at line {later[0]}) — release the "
                                "destination's newly acquired pages "
                                "first, then the source, so a fault "
                                "between the two cannot orphan pages "
                                "that still have a live owner"
                            )))
                        break
        return out


def run(index: ProjectIndex) -> List[Finding]:
    model = ThreadModel(index)
    om = _OwnershipModel(model)
    findings = om.check_lifecycles()
    findings += om.check_containers()
    findings += om.check_threads()
    findings += om.check_funnels()
    findings += om.check_spans()
    findings += om.check_rollback()
    findings.sort(key=lambda f: (f.file, f.line, f.code))
    return findings
