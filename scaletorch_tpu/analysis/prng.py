"""ST3xx — PRNG key hygiene.

JAX keys are values, not stateful generators: feeding one key to two
sampling calls gives **correlated** (often identical) draws, and the
run still "works". The pass tracks key-like names through each function
body in statement order:

ST301  a key passed to a second sampling call with no intervening
       ``jax.random.split``/``fold_in`` reassignment (loop bodies are
       walked twice so cross-iteration reuse is caught)
ST302  a key seeded from wall-clock/OS entropy (``time.*``,
       ``os.urandom``, ``np.random``) inside a jit scope — the seed is
       baked in at trace time, so every call reuses it

Key-like names: parameters/variables matching ``key``/``rng``/
``*_key``/``*_rng``/``prng*`` or assigned from ``PRNGKey``/``key``/
``split``/``fold_in`` calls.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding
from .scopes import ModuleScopes, ProjectIndex, dotted_name, tail_name

_KEY_NAME_RE = re.compile(r"^(key|rng|prng\w*|\w+_key|\w+_rng|keys|rngs)$")
# jax.random.* that CONSUME a key (first arg or key=)
_SAMPLERS = {
    "uniform", "normal", "categorical", "bernoulli", "gumbel", "randint",
    "truncated_normal", "exponential", "beta", "gamma", "poisson", "choice",
    "permutation", "shuffle", "bits", "laplace", "logistic", "cauchy",
    "dirichlet", "multivariate_normal", "rademacher", "ball", "orthogonal",
    "t", "loggamma", "binomial", "geometric",
}
_KEY_MAKERS = {"PRNGKey", "key", "split", "fold_in", "clone", "wrap_key_data"}
_ENTROPY_SOURCES = ("time.", "os.urandom", "random.random", "np.random", "numpy.random")


def run(index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for ms in index.scopes.values():
        findings.extend(_check_module(ms))
    return findings


def _is_random_call(node: ast.Call, wanted: Set[str]) -> Optional[str]:
    """'categorical' if node is jax.random.categorical(...) (or
    random.categorical via `from jax import random`), else None."""
    d = dotted_name(node.func)
    if not d:
        return None
    parts = d.split(".")
    if parts[-1] not in wanted:
        return None
    if len(parts) >= 2 and parts[-2] in ("random", "jrandom", "jr"):
        return parts[-1]
    return None


def _key_arg_names(node: ast.Call) -> List[ast.Name]:
    """Name nodes passed in key position(s) of a sampler call."""
    out: List[ast.Name] = []
    if node.args and isinstance(node.args[0], ast.Name):
        out.append(node.args[0])
    for kw in node.keywords:
        if kw.arg in ("key", "rng", "seed") and isinstance(kw.value, ast.Name):
            out.append(kw.value)
    return out


class _FnChecker:
    """Linear walk of one function body tracking consumed keys."""

    def __init__(self, ms: ModuleScopes, fn) -> None:
        self.ms = ms
        self.fn = fn
        # name -> line of the sampling call that consumed it (None = fresh)
        self.consumed: Dict[str, int] = {}
        self.key_names: Set[str] = set()
        args = fn.args
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            if _KEY_NAME_RE.match(a.arg):
                self.key_names.add(a.arg)
        self.findings: List[Finding] = []
        self.reported: Set[Tuple[int, str]] = set()

    def _reset(self, name: str) -> None:
        self.consumed.pop(name, None)

    def _consume(self, name_node: ast.Name) -> None:
        name = name_node.id
        if name not in self.key_names:
            return
        prev = self.consumed.get(name)
        if prev is not None:
            key = (name_node.lineno, name)
            if key not in self.reported:
                self.reported.add(key)
                self.findings.append(Finding(
                    file=self.ms.sm.rel, line=name_node.lineno, code="ST301",
                    severity="error",
                    message=(
                        f"PRNG key '{name}' reused by a second sampling call "
                        f"(first consumed at line {prev}) without an "
                        "intervening jax.random.split/fold_in — draws will "
                        "be correlated"
                    ),
                ))
        else:
            self.consumed[name] = name_node.lineno

    def _scan_expr(self, expr: ast.AST) -> None:
        for node in ast.walk(expr):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(node, ast.Call) and _is_random_call(node, _SAMPLERS):
                for name_node in _key_arg_names(node):
                    self._consume(name_node)

    def _target_names(self, target: ast.AST) -> List[str]:
        if isinstance(target, ast.Name):
            return [target.id]
        if isinstance(target, (ast.Tuple, ast.List)):
            out: List[str] = []
            for el in target.elts:
                out.extend(self._target_names(el))
            return out
        if isinstance(target, ast.Starred):
            return self._target_names(target.value)
        return []

    def _observe_assign(self, targets: List[ast.AST], value: ast.AST) -> None:
        names: List[str] = []
        for t in targets:
            names.extend(self._target_names(t))
        from_maker = (
            isinstance(value, ast.Call) and _is_random_call(value, _KEY_MAKERS)
        ) or (
            # keys = split(...); k = keys[0] — subscript of a key var
            isinstance(value, ast.Subscript)
            and isinstance(value.value, ast.Name)
            and value.value.id in self.key_names
        )
        for name in names:
            if from_maker or _KEY_NAME_RE.match(name):
                if from_maker:
                    self.key_names.add(name)
                self._reset(name)
            elif name in self.key_names:
                # rebound to something else entirely: stop tracking
                self.key_names.discard(name)
                self._reset(name)

    def walk(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(stmt, ast.Assign):
                self._scan_expr(stmt.value)
                self._observe_assign(stmt.targets, stmt.value)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                self._scan_expr(stmt.value)
                self._observe_assign([stmt.target], stmt.value)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._scan_expr(stmt.iter)
                # iterating over split keys binds a fresh key per step
                for name in self._target_names(stmt.target):
                    if _KEY_NAME_RE.match(name):
                        self.key_names.add(name)
                # walk twice: second pass catches cross-iteration reuse of
                # keys consumed in pass one and never reset inside the body
                self.walk(stmt.body)
                for name in self._target_names(stmt.target):
                    self._reset(name)
                self.walk(stmt.body)
                self.walk(stmt.orelse)
            elif isinstance(stmt, ast.While):
                self._scan_expr(stmt.test)
                self.walk(stmt.body)
                self.walk(stmt.body)
                self.walk(stmt.orelse)
            elif isinstance(stmt, ast.If):
                self._scan_expr(stmt.test)
                before = dict(self.consumed)
                self.walk(stmt.body)
                after_body = self.consumed
                self.consumed = dict(before)
                self.walk(stmt.orelse)
                # merge: consumed on either branch counts as consumed
                for k, v in after_body.items():
                    self.consumed.setdefault(k, v)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._scan_expr(item.context_expr)
                self.walk(stmt.body)
            elif isinstance(stmt, ast.Try):
                self.walk(stmt.body)
                for handler in stmt.handlers:
                    self.walk(handler.body)
                self.walk(stmt.orelse)
                self.walk(stmt.finalbody)
            elif isinstance(stmt, ast.Return) and stmt.value is not None:
                self._scan_expr(stmt.value)
            elif isinstance(stmt, ast.Expr):
                self._scan_expr(stmt.value)
            elif isinstance(stmt, ast.AugAssign):
                self._scan_expr(stmt.value)


def _check_module(ms: ModuleScopes) -> List[Finding]:
    out: List[Finding] = []
    traced_nodes = {fn for fn, _ in ms.traced_functions()}
    for node in ast.walk(ms.sm.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        checker = _FnChecker(ms, node)
        checker.walk(node.body)
        out.extend(checker.findings)
        if node in traced_nodes:
            out.extend(_check_entropy_seeds(ms, node))
    return out


def _check_entropy_seeds(ms: ModuleScopes, fn: ast.AST) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call)
                and tail_name(node.func) in ("PRNGKey", "key")):
            continue
        for arg in node.args:
            for inner in ast.walk(arg):
                if not isinstance(inner, ast.Call):
                    continue
                d = dotted_name(inner.func) or ""
                if any(d.startswith(src) or d == src.rstrip(".")
                       for src in _ENTROPY_SOURCES):
                    out.append(Finding(
                        file=ms.sm.rel, line=node.lineno, code="ST302",
                        severity="error",
                        message=(
                            f"PRNG key seeded from `{d}` inside a jit scope — "
                            "the seed is a trace-time constant, every call "
                            "reuses the same key"
                        ),
                    ))
    return out
