"""ST5xx — retrace risk at jitted call sites.

``jax.jit`` caches on the pytree *structure* and dtypes of its
arguments. Call-site literals defeat the cache or bloat it:

ST501  a dict/list literal passed to a jitted callable — structure is
       rebuilt per call; a changed key set or length retraces silently
       (lists also hash as pytrees of leaves: N leaves = N tracer args)
ST502  a bare Python scalar literal in a position not covered by
       ``static_argnums``/``static_argnames`` — weak-typed tracing
       means the same callable invoked elsewhere with an array (or a
       numpy scalar) of a different dtype traces again

Both are warnings: each individual site works; the cost appears when a
second call site disagrees, which is exactly when nobody is looking.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Finding
from .scopes import (
    ModuleScopes,
    ProjectIndex,
    collect_jitted_callables,
    dotted_name,
)


def run(index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for ms in index.scopes.values():
        findings.extend(_check_module(index, ms))
    return findings


def _check_module(index: ProjectIndex, ms: ModuleScopes) -> List[Finding]:
    jitted = collect_jitted_callables(index, ms)
    if not jitted:
        return []
    out: List[Finding] = []
    for call in ast.walk(ms.sm.tree):
        if not isinstance(call, ast.Call):
            continue
        cname = dotted_name(call.func)
        info = jitted.get(cname) if cname else None
        if info is None:
            continue
        static_idx = info.static_argnums
        static_names = info.static_argnames
        for i, arg in enumerate(call.args):
            if static_idx is None or i in static_idx:
                continue  # static (or unknown argnums: stay quiet)
            out.extend(_check_arg(ms, cname, arg, f"positional arg {i}"))
        for kw in call.keywords:
            if kw.arg is None:
                continue
            if static_names is None or kw.arg in static_names:
                continue
            out.extend(_check_arg(ms, cname, kw.value, f"keyword {kw.arg}="))
    return out


def _check_arg(
    ms: ModuleScopes, cname: str, arg: ast.AST, where: str
) -> List[Finding]:
    if isinstance(arg, (ast.Dict, ast.List)):
        kind = "dict" if isinstance(arg, ast.Dict) else "list"
        return [Finding(
            file=ms.sm.rel, line=arg.lineno, code="ST501", severity="warning",
            message=(
                f"{kind} literal passed to jitted `{cname}` ({where}) — jit "
                "caches on pytree structure, a changed key set/length "
                "retraces silently; pass arrays/tuples or mark the arg static"
            ),
        )]
    if isinstance(arg, ast.Constant) and type(arg.value) in (int, float, bool):
        return [Finding(
            file=ms.sm.rel, line=arg.lineno, code="ST502", severity="warning",
            message=(
                f"Python scalar literal {arg.value!r} passed to jitted "
                f"`{cname}` ({where}) outside static_argnums — weak-typed "
                "tracing retraces when another site passes an array; use "
                "static_argnums or jnp.asarray"
            ),
        )]
    return []
