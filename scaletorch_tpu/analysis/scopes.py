"""Jit-scope resolver + taint engine for jaxlint.

Two questions every pass keeps asking are answered here, once:

1. **Which code is traced?** A function body is a traced scope when it
   is decorated/wrapped with ``jax.jit``/``pjit``/``shard_map``, passed
   as the body of a ``lax`` higher-order primitive (``scan``/``cond``/
   ``while_loop``/``fori_loop``/``switch``), handed to a tracing
   transform (``vmap``/``grad``/``value_and_grad``/``checkpoint``/
   ``remat``/``custom_vjp``), or lexically nested inside any of the
   above (inner helpers trace with their parent). ``ProjectIndex``
   resolves this across the whole analyzed file set, including the
   factory idiom this codebase uses everywhere::

       def make_decode_step(...):
           def decode(params, tokens, ...):
               ...
           return jax.jit(decode, donate_argnums=(4,))

   — ``decode`` is a jit scope, ``make_decode_step`` is a *jit factory*
   and names bound from its call sites are jitted callables carrying
   the factory's static/donate argnums (imports followed module to
   module, best effort).

2. **Which values are tracers?** ``TaintTracker`` runs a linear,
   order-sensitive walk over a traced function body: parameters start
   tainted (minus ``static_argnums``/``static_argnames``), assignment
   propagates taint, reassignment from untainted expressions clears it.
   Static facts about a tracer — ``.shape``/``.ndim``/``.dtype``/
   ``.size``/``len()``/``isinstance()`` and ``is None`` tests — are
   sanitizers: branching on them is trace-time-safe and must not flag.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple, Union

from .core import SourceModule

FuncNode = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]

# Callables whose function-valued argument is traced.
_JIT_WRAPPERS = {"jit", "pjit"}
_SHARD_WRAPPERS = {"shard_map"}
_TRACING_TRANSFORMS = {
    "vmap", "pmap", "grad", "value_and_grad", "checkpoint", "remat",
    "custom_vjp", "custom_jvp", "linearize", "jvp", "vjp", "hessian",
    "jacfwd", "jacrev",
}
# lax.<hof>(body, ...) — argument index -> which positions hold bodies.
_LAX_HOFS = {
    "scan": (0,),
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "cond": (1, 2),
    "switch": None,  # every arg after the index may be a branch
    "associative_scan": (0,),
    "map": (0,),
    "custom_root": (0, 1, 2),
}

_SANITIZER_ATTRS = {
    "shape", "ndim", "dtype", "size", "itemsize", "sharding", "aval",
    "nbytes", "weak_type",
}
_SANITIZER_CALLS = {"len", "isinstance", "type", "hasattr", "getattr", "id"}


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.lax.scan' for Attribute/Name chains, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def tail_name(node: ast.AST) -> Optional[str]:
    """Last component of a dotted name ('scan' for jax.lax.scan)."""
    d = dotted_name(node)
    return d.rsplit(".", 1)[-1] if d else None


def _const_int_set(node: Optional[ast.AST]) -> Optional[Set[int]]:
    """Evaluate a static_argnums/donate_argnums literal. None = dynamic."""
    if node is None:
        return set()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[int] = set()
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                out.add(el.value)
            else:
                return None
        return out
    if isinstance(node, ast.IfExp):
        # the `(4,) if donate else ()` idiom: union both arms (conservative)
        a = _const_int_set(node.body)
        b = _const_int_set(node.orelse)
        return None if a is None or b is None else a | b
    return None


def _const_str_set(node: Optional[ast.AST]) -> Optional[Set[str]]:
    if node is None:
        return set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.add(el.value)
            else:
                return None
        return out
    return None


@dataclasses.dataclass
class JitInfo:
    """How one function/callable is traced."""

    kind: str  # "jit" | "shard_map" | "lax_body" | "transform"
    node: Optional[FuncNode] = None
    # None means "declared but not statically evaluable" (dynamic expr).
    static_argnums: Optional[Set[int]] = dataclasses.field(default_factory=set)
    static_argnames: Optional[Set[str]] = dataclasses.field(default_factory=set)
    donate_argnums: Optional[Set[int]] = dataclasses.field(default_factory=set)
    donate_argnames: Optional[Set[str]] = dataclasses.field(default_factory=set)

    def merged_with_call(self, call: ast.Call) -> "JitInfo":
        """JitInfo for ``jax.jit(f, static_argnums=..., donate_argnums=...)``."""
        info = JitInfo(kind=self.kind, node=self.node)
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                info.static_argnums = _const_int_set(kw.value)
            elif kw.arg == "static_argnames":
                info.static_argnames = _const_str_set(kw.value)
            elif kw.arg == "donate_argnums":
                info.donate_argnums = _const_int_set(kw.value)
            elif kw.arg == "donate_argnames":
                info.donate_argnames = _const_str_set(kw.value)
        return info


def _is_jit_callable(call_func: ast.AST) -> bool:
    return tail_name(call_func) in _JIT_WRAPPERS


def _is_shard_map(call_func: ast.AST) -> bool:
    return tail_name(call_func) in _SHARD_WRAPPERS


def _is_transform(call_func: ast.AST) -> bool:
    return tail_name(call_func) in _TRACING_TRANSFORMS


def _lax_body_positions(call_func: ast.AST) -> Optional[Tuple[int, ...]]:
    t = tail_name(call_func)
    if t not in _LAX_HOFS:
        return None
    d = dotted_name(call_func) or t
    # accept lax.scan / jax.lax.scan / bare scan-from-lax-import
    if "." in d and not (d.endswith(f"lax.{t}")):
        return None
    pos = _LAX_HOFS[t]
    return tuple(range(8)) if pos is None else pos


class ModuleScopes:
    """Per-module scope facts: traced functions, jitted names, factories."""

    def __init__(self, sm: SourceModule) -> None:
        self.sm = sm
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(sm.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        # every named function, by (possibly shadowed) bare name, innermost last
        self.functions: Dict[str, List[ast.FunctionDef]] = {}
        for node in ast.walk(sm.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.setdefault(node.name, []).append(node)
        # directly-traced function nodes -> JitInfo
        self.traced: Dict[FuncNode, JitInfo] = {}
        # module-level callable names known to be jitted (g = jax.jit(f, ...))
        self.jitted_names: Dict[str, JitInfo] = {}
        # top-level functions that RETURN a jitted callable
        self.factories: Dict[str, JitInfo] = {}
        # import map: local name -> (module, original name)
        self.imports: Dict[str, Tuple[str, str]] = {}
        self._collect_imports()
        self._collect_traced()
        self._collect_factories()

    # -- imports --------------------------------------------------------------
    def _collect_imports(self) -> None:
        pkg_parts = self.sm.module.split(".")
        for node in ast.walk(self.sm.tree):
            if isinstance(node, ast.ImportFrom):
                if node.level:  # relative import
                    base = pkg_parts[: len(pkg_parts) - node.level]
                    mod = ".".join(base + ([node.module] if node.module else []))
                else:
                    mod = node.module or ""
                for alias in node.names:
                    self.imports[alias.asname or alias.name] = (mod, alias.name)

    # -- traced scopes --------------------------------------------------------
    def _resolve_local_fn(self, name_node: ast.AST, at: ast.AST) -> Optional[ast.FunctionDef]:
        """Resolve a Name argument to the function it most plausibly
        references (same bare name; prefer a sibling in the same scope)."""
        if isinstance(name_node, ast.Lambda):
            return None
        if not isinstance(name_node, ast.Name):
            return None
        cands = self.functions.get(name_node.id)
        if not cands:
            return None
        if len(cands) == 1:
            return cands[0]
        enclosing = self._enclosing_function(at)
        for c in cands:
            if self._enclosing_function(c) is enclosing:
                return c
        return cands[-1]

    def _enclosing_function(self, node: ast.AST) -> Optional[FuncNode]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return cur
            cur = self.parents.get(cur)
        return None

    def _mark(self, fn: Optional[FuncNode], info: JitInfo) -> None:
        if fn is None:
            return
        prev = self.traced.get(fn)
        if prev is None or (prev.kind != "jit" and info.kind == "jit"):
            self.traced[fn] = info

    def _collect_traced(self) -> None:
        for node in ast.walk(self.sm.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    info = self._decorator_jit_info(dec)
                    if info is not None:
                        self._mark(node, info)
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if _is_jit_callable(func) or _is_shard_map(func):
                kind = "jit" if _is_jit_callable(func) else "shard_map"
                info = JitInfo(kind=kind).merged_with_call(node)
                target = node.args[0] if node.args else None
                if isinstance(target, ast.Lambda):
                    info.node = target
                    self._mark(target, info)
                else:
                    fn = self._resolve_local_fn(target, node) if target else None
                    if fn is not None:
                        info.node = fn
                        self._mark(fn, info)
                # g = jax.jit(f, ...) binds a jitted callable name
                parent = self.parents.get(node)
                if isinstance(parent, ast.Assign):
                    for t in parent.targets:
                        if isinstance(t, ast.Name):
                            self.jitted_names[t.id] = info
            elif _is_transform(func):
                target = node.args[0] if node.args else None
                if isinstance(target, ast.Lambda):
                    self._mark(target, JitInfo(kind="transform", node=target))
                else:
                    fn = self._resolve_local_fn(target, node) if target else None
                    if fn is not None:
                        self._mark(fn, JitInfo(kind="transform", node=fn))
            else:
                positions = _lax_body_positions(func)
                if positions is not None:
                    for i in positions:
                        if i >= len(node.args):
                            break
                        arg = node.args[i]
                        if isinstance(arg, ast.Lambda):
                            self._mark(arg, JitInfo(kind="lax_body", node=arg))
                        else:
                            fn = self._resolve_local_fn(arg, node)
                            if fn is not None:
                                self._mark(fn, JitInfo(kind="lax_body", node=fn))

    def _decorator_jit_info(self, dec: ast.AST) -> Optional[JitInfo]:
        if _is_jit_callable(dec) or _is_shard_map(dec):
            return JitInfo(kind="jit" if _is_jit_callable(dec) else "shard_map")
        if isinstance(dec, ast.Call):
            if _is_jit_callable(dec.func) or _is_shard_map(dec.func):
                kind = "jit" if _is_jit_callable(dec.func) else "shard_map"
                return JitInfo(kind=kind).merged_with_call(dec)
            # @partial(jax.jit, static_argnames=...)
            if tail_name(dec.func) == "partial" and dec.args:
                inner = dec.args[0]
                if _is_jit_callable(inner) or _is_shard_map(inner):
                    kind = "jit" if _is_jit_callable(inner) else "shard_map"
                    return JitInfo(kind=kind).merged_with_call(dec)
                if _is_transform(inner):
                    return JitInfo(kind="transform")
        if _is_transform(dec):
            return JitInfo(kind="transform")
        return None

    # -- factories ------------------------------------------------------------
    def _returned_jit_info(self, fn: ast.FunctionDef) -> Optional[JitInfo]:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            v = node.value
            if isinstance(v, ast.Call) and (_is_jit_callable(v.func) or _is_shard_map(v.func)):
                kind = "jit" if _is_jit_callable(v.func) else "shard_map"
                return JitInfo(kind=kind).merged_with_call(v)
            # `return step` where step = jax.jit(...) earlier in the body
            if isinstance(v, ast.Name):
                for inner in ast.walk(fn):
                    if (
                        isinstance(inner, ast.Assign)
                        and isinstance(inner.value, ast.Call)
                        and (_is_jit_callable(inner.value.func)
                             or _is_shard_map(inner.value.func))
                        and any(isinstance(t, ast.Name) and t.id == v.id
                                for t in inner.targets)
                    ):
                        kind = ("jit" if _is_jit_callable(inner.value.func)
                                else "shard_map")
                        return JitInfo(kind=kind).merged_with_call(inner.value)
        return None

    def _collect_factories(self) -> None:
        for node in self.sm.tree.body:
            if isinstance(node, ast.FunctionDef):
                info = self._returned_jit_info(node)
                if info is not None:
                    self.factories[node.name] = info

    # -- queries --------------------------------------------------------------
    def is_traced(self, fn: FuncNode) -> Optional[JitInfo]:
        """JitInfo if ``fn`` or any lexical ancestor is a traced scope."""
        cur: Optional[ast.AST] = fn
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                info = self.traced.get(cur)
                if info is not None:
                    # nested helpers inherit tracedness but not argnums
                    if cur is fn:
                        return info
                    return JitInfo(kind=info.kind, node=fn)
            cur = self.parents.get(cur)
        return None

    def traced_functions(self) -> List[Tuple[FuncNode, JitInfo]]:
        """Every function body that traces, including nested helpers."""
        out: List[Tuple[FuncNode, JitInfo]] = []
        for node in ast.walk(self.sm.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                info = self.is_traced(node)
                if info is not None:
                    out.append((node, info))
        return out


class ProjectIndex:
    """Cross-module facts shared by all passes."""

    def __init__(self, modules: Sequence[SourceModule]) -> None:
        self.modules = list(modules)
        self.scopes: Dict[str, ModuleScopes] = {
            sm.rel: ModuleScopes(sm) for sm in modules
        }
        self.by_module: Dict[str, ModuleScopes] = {
            ms.sm.module: ms for ms in self.scopes.values()
        }
        self.declared_axes: Set[str] = self._find_declared_axes()
        self.param_keys: Set[str] = self._collect_param_keys()

    # -- mesh axes ------------------------------------------------------------
    def _find_declared_axes(self) -> Set[str]:
        """Axis names from ``MESH_AXES = (...)`` in the analyzed set; the
        sharding pass falls back to the package source when linting a
        subset that excludes parallel/mesh.py."""
        for sm in self.modules:
            axes = find_mesh_axes(sm.tree)
            if axes:
                return axes
        return set()

    # -- param-key universe ---------------------------------------------------
    def _collect_param_keys(self) -> Set[str]:
        """All string dict keys used OUTSIDE ``*_specs`` functions — the
        universe a spec tree's keys must reference."""
        keys: Set[str] = set()
        for ms in self.scopes.values():
            spec_fns = [
                fns[-1] for name, fns in ms.functions.items()
                if name.endswith("_specs")
            ]
            spec_nodes: Set[ast.AST] = set()
            for fn in spec_fns:
                spec_nodes.update(ast.walk(fn))
            for node in ast.walk(ms.sm.tree):
                if node in spec_nodes:
                    continue
                if isinstance(node, ast.Dict):
                    for k in node.keys:
                        if isinstance(k, ast.Constant) and isinstance(k.value, str):
                            keys.add(k.value)
                elif isinstance(node, ast.Subscript):
                    sl = node.slice
                    if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                        keys.add(sl.value)
        return keys

    # -- jitted-callable resolution -------------------------------------------
    def resolve_factory(self, ms: ModuleScopes, call_func: ast.AST) -> Optional[JitInfo]:
        """JitInfo when ``call_func`` names a jit factory (local or
        imported), else None."""
        name = dotted_name(call_func)
        if name is None:
            return None
        bare = name.rsplit(".", 1)[-1]
        if name in ms.factories or bare in ms.factories:
            return ms.factories.get(name) or ms.factories[bare]
        imp = ms.imports.get(name) or ms.imports.get(bare)
        if imp is not None:
            target = self.by_module.get(imp[0])
            if target is not None and imp[1] in target.factories:
                return target.factories[imp[1]]
        return None


def find_mesh_axes(tree: ast.Module) -> Optional[Set[str]]:
    """``MESH_AXES`` value from a module, handling both plain and
    annotated assignment (the package uses ``MESH_AXES: tuple[...] = …``)."""
    for node in ast.walk(tree):
        value: Optional[ast.AST] = None
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "MESH_AXES" for t in node.targets
        ):
            value = node.value
        elif (
            isinstance(node, ast.AnnAssign)
            and isinstance(node.target, ast.Name)
            and node.target.id == "MESH_AXES"
        ):
            value = node.value
        if value is not None:
            axes = _const_str_set(value)
            if axes:
                return axes
    return None


def collect_jitted_callables(
    index: ProjectIndex, ms: ModuleScopes
) -> Dict[str, JitInfo]:
    """Names in ``ms`` bound to jitted callables, keyed by the dotted
    name call sites use (``step``, ``self._decode`` …).

    Covers direct wrapping (``g = jax.jit(f, …)``) and the factory
    idiom (``g = make_decode_step(…)`` where the factory — local or
    imported — returns a ``jax.jit``-wrapped function), so the donation
    and retrace passes see the same callables the runtime does.
    """
    out: Dict[str, JitInfo] = dict(ms.jitted_names)
    for node in ast.walk(ms.sm.tree):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        call = node.value
        info: Optional[JitInfo] = None
        if _is_jit_callable(call.func) or _is_shard_map(call.func):
            kind = "jit" if _is_jit_callable(call.func) else "shard_map"
            info = JitInfo(kind=kind).merged_with_call(call)
        else:
            info = index.resolve_factory(ms, call.func)
        if info is None:
            continue
        for t in node.targets:
            name = dotted_name(t)
            if name:
                out[name] = info
    return out


# ---- taint ------------------------------------------------------------------

class TaintTracker:
    """Order-sensitive tracer-taint tracking for one traced function."""

    def __init__(self, fn: FuncNode, info: JitInfo) -> None:
        self.fn = fn
        self.tainted: Set[str] = set()
        args = fn.args
        names: List[str] = [a.arg for a in args.posonlyargs + args.args]
        static_idx = info.static_argnums if info.static_argnums is not None else set()
        static_names = info.static_argnames if info.static_argnames is not None else set()
        for i, n in enumerate(names):
            if i in static_idx or n in static_names:
                continue
            self.tainted.add(n)
        for a in args.kwonlyargs:
            if a.arg not in static_names:
                self.tainted.add(a.arg)
        if args.vararg:
            self.tainted.add(args.vararg.arg)
        if args.kwarg:
            self.tainted.add(args.kwarg.arg)
        # `self` in methods is config, not a tracer
        self.tainted.discard("self")
        # names bound to lambdas that map tracers to static facts
        # (vma_of = lambda x: getattr(jax.typeof(x), "vma", ()) …)
        self.sanitizer_names: Set[str] = set()

    # -- expression tainting --------------------------------------------------
    def is_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _SANITIZER_ATTRS:
                return False
            return self.is_tainted(node.value)
        if isinstance(node, ast.Call):
            fname = tail_name(node.func)
            if fname in _SANITIZER_CALLS or fname in self.sanitizer_names \
                    or fname == "typeof":
                return False
            return (
                any(self.is_tainted(a) for a in node.args)
                or any(self.is_tainted(kw.value) for kw in node.keywords)
                or (isinstance(node.func, ast.Attribute)
                    and self.is_tainted(node.func.value))
            )
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            return self._comprehension_tainted(node)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` is a static structure test
            if (
                all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops)
                and all(isinstance(c, ast.Constant) and c.value is None
                        for c in node.comparators)
            ):
                return False
            return self.is_tainted(node.left) or any(
                self.is_tainted(c) for c in node.comparators
            )
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return False
        return any(
            self.is_tainted(child) for child in ast.iter_child_nodes(node)
        )

    def _comprehension_tainted(self, node: ast.AST) -> bool:
        """A comprehension's taint is its ELEMENT expression's taint with
        the comprehension targets tainted from their iterables — not the
        iterable's taint itself ([f(x) for x in leaves] is untainted when
        f maps tracers to static facts)."""
        saved = set(self.tainted)
        try:
            for gen in node.generators:
                self._observe_loop(gen.target, gen.iter)
            for gen in node.generators:
                if any(self.is_tainted(cond) for cond in gen.ifs):
                    return True
            if isinstance(node, ast.DictComp):
                return self.is_tainted(node.key) or self.is_tainted(node.value)
            return self.is_tainted(node.elt)
        finally:
            self.tainted = saved

    # -- statement effects ----------------------------------------------------
    def _assign_target_names(self, target: ast.AST) -> List[str]:
        if isinstance(target, ast.Name):
            return [target.id]
        if isinstance(target, (ast.Tuple, ast.List)):
            out: List[str] = []
            for el in target.elts:
                out.extend(self._assign_target_names(el))
            return out
        if isinstance(target, ast.Starred):
            return self._assign_target_names(target.value)
        return []

    def observe(self, stmt: ast.stmt) -> None:
        """Update taint for one top-level statement (no recursion into
        compound bodies — callers walk those explicitly)."""
        if isinstance(stmt, ast.Assign):
            if isinstance(stmt.value, ast.Lambda):
                self._observe_lambda_alias(stmt)
                return
            t = self.is_tainted(stmt.value)
            for target in stmt.targets:
                for name in self._assign_target_names(target):
                    (self.tainted.add if t else self.tainted.discard)(name)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            if isinstance(stmt.target, ast.Name):
                t = self.is_tainted(stmt.value)
                (self.tainted.add if t else self.tainted.discard)(stmt.target.id)
        elif isinstance(stmt, ast.AugAssign):
            if isinstance(stmt.target, ast.Name) and self.is_tainted(stmt.value):
                self.tainted.add(stmt.target.id)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._observe_loop(stmt.target, stmt.iter)
        elif isinstance(stmt, ast.With):
            for item in stmt.items:
                if item.optional_vars is not None and self.is_tainted(item.context_expr):
                    for name in self._assign_target_names(item.optional_vars):
                        self.tainted.add(name)

    def _observe_lambda_alias(self, stmt: ast.Assign) -> None:
        """``f = lambda x: <expr>``: if <expr> is untainted even with the
        lambda's params tainted, ``f(...)`` maps tracers to static facts
        and becomes a sanitizer for this scope."""
        lam = stmt.value
        assert isinstance(lam, ast.Lambda)
        saved = set(self.tainted)
        try:
            for a in lam.args.posonlyargs + lam.args.args + lam.args.kwonlyargs:
                self.tainted.add(a.arg)
            body_tainted = self.is_tainted(lam.body)
        finally:
            self.tainted = saved
        for target in stmt.targets:
            for name in self._assign_target_names(target):
                self.tainted.discard(name)
                if not body_tainted:
                    self.sanitizer_names.add(name)
                else:
                    self.sanitizer_names.discard(name)

    def _observe_loop(self, target: ast.AST, iter_expr: ast.AST) -> None:
        """Taint loop targets from the iterable — element-wise through
        ``zip``/``enumerate`` so iterating a traced pytree alongside a
        static host list doesn't taint the static elements."""
        if (
            isinstance(iter_expr, ast.Call)
            and isinstance(iter_expr.func, ast.Name)
            and isinstance(target, ast.Tuple)
        ):
            fname = iter_expr.func.id
            if fname == "zip" and len(iter_expr.args) == len(target.elts):
                for src, tgt in zip(iter_expr.args, target.elts):
                    self._observe_loop(tgt, src)
                return
            if fname == "enumerate" and len(target.elts) == 2 and iter_expr.args:
                for name in self._assign_target_names(target.elts[0]):
                    self.tainted.discard(name)
                self._observe_loop(target.elts[1], iter_expr.args[0])
                return
        t = self.is_tainted(iter_expr)
        for name in self._assign_target_names(target):
            (self.tainted.add if t else self.tainted.discard)(name)
