"""ST1xx — sharding-spec consistency.

GSPMD treats an axis name that is not in the mesh as **replicated** and
says nothing: a ``PartitionSpec("tpp")`` typo silently turns a
tensor-parallel matmul into a fully-replicated one. This pass makes the
mesh's axis vocabulary (``MESH_AXES`` in ``parallel/mesh.py``) the
single source of truth and flags:

ST101  an axis string used in a ``PartitionSpec``/``P`` call, an
       ``*_axis=`` keyword/default/assignment, or an ``axis_name=``
       keyword that is not a declared mesh axis
ST102  a key in a ``*_param_specs``/``*_cache_specs`` spec tree that no
       param tree anywhere in the analyzed set defines (a spec for a
       key the model never creates shards nothing — the partner typo
       class to ST101)
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional, Set

from .core import Finding, SourceModule
from .scopes import ProjectIndex, find_mesh_axes, tail_name

_SPEC_CALLS = {"PartitionSpec", "P"}
# Default vocabulary when the analyzed set doesn't include parallel/mesh.py
# and the package source isn't on disk next to this file.
_FALLBACK_AXES = {"dp", "pp", "cp", "ep", "tp"}


def _axes_from_package() -> Optional[Set[str]]:
    mesh_py = Path(__file__).resolve().parent.parent / "parallel" / "mesh.py"
    if not mesh_py.is_file():
        return None
    try:
        tree = ast.parse(mesh_py.read_text(encoding="utf-8"))
    except SyntaxError:
        return None
    return find_mesh_axes(tree)


def declared_axes(index: ProjectIndex, extra: Set[str] = frozenset()) -> Set[str]:
    axes = set(index.declared_axes) or _axes_from_package() or set(_FALLBACK_AXES)
    return axes | set(extra)


def _str_constants(node: ast.AST) -> List[ast.Constant]:
    """String literals in an axis-bearing expression. Nested calls are
    pruned: in ``tuple(a for a in axes if a in getattr(t, "vma", ()))``
    the "vma" belongs to getattr, not to the axis vocabulary."""
    out: List[ast.Constant] = []
    stack: List[ast.AST] = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.append(n)
            continue
        if isinstance(n, ast.Call):
            continue
        stack.extend(ast.iter_child_nodes(n))
    return out


def _is_axis_name(name: str) -> bool:
    return (
        name in ("axis", "axes", "axis_name")
        or name.endswith("_axis")
        or name.endswith("_axes")
    )


def _is_spec_fn(name: str) -> bool:
    return name.endswith("_param_specs") or name.endswith("_cache_specs")


def run(index: ProjectIndex, extra_axes: Set[str] = frozenset()) -> List[Finding]:
    axes = declared_axes(index, extra_axes)
    findings: List[Finding] = []
    for sm in index.modules:
        findings.extend(_check_module(sm, axes, index.param_keys))
    return findings


def _check_module(
    sm: SourceModule, axes: Set[str], param_keys: Set[str]
) -> List[Finding]:
    out: List[Finding] = []

    def bad_axis(const: ast.Constant, where: str) -> None:
        # The declared-axes list deliberately stays OUT of the message:
        # baseline entries match on (file, code, message), and embedding
        # the vocabulary would invalidate every baselined ST101 whenever
        # a mesh axis is added (see parallel/mesh.py MESH_AXES).
        out.append(Finding(
            file=sm.rel, line=const.lineno, code="ST101", severity="error",
            message=(
                f"axis '{const.value}' in {where} is not a declared mesh "
                f"axis — GSPMD silently treats it as replicated"
            ),
        ))

    for node in ast.walk(sm.tree):
        # PartitionSpec("tp", ...) / P(None, ("dp", "ep"), ...) literals
        if isinstance(node, ast.Call) and tail_name(node.func) in _SPEC_CALLS:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for const in _str_constants(arg):
                    if const.value not in axes:
                        bad_axis(const, "PartitionSpec")
        # f(..., tp_axis="tp", axis="cp", shard_axes=("tp", "pp")) keywords
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg and _is_axis_name(kw.arg):
                    for const in _str_constants(kw.value):
                        if const.value not in axes:
                            bad_axis(const, f"keyword {kw.arg}=")
        # def f(..., tp_axis: str = "tp", axes=("tp", "pp")) defaults
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = node.args
            pos = a.posonlyargs + a.args
            for arg, default in zip(pos[len(pos) - len(a.defaults):], a.defaults):
                if _is_axis_name(arg.arg):
                    for const in _str_constants(default):
                        if const.value not in axes:
                            bad_axis(const, f"default of {arg.arg}")
            for arg, default in zip(a.kwonlyargs, a.kw_defaults):
                if default is not None and _is_axis_name(arg.arg):
                    for const in _str_constants(default):
                        if const.value not in axes:
                            bad_axis(const, f"default of {arg.arg}")
        # seq_axis = "cp" / all_axes = ("dp",) + ... / "pp" if pp else None
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if any(_is_axis_name(t) for t in targets):
                for const in _str_constants(node.value):
                    if const.value not in axes:
                        bad_axis(const, f"assignment to {', '.join(targets)}")

    # ST102: spec-tree keys must reference keys some param tree defines
    for fn_node in ast.walk(sm.tree):
        if not isinstance(fn_node, ast.FunctionDef) or not _is_spec_fn(fn_node.name):
            continue
        spec_keys: List[ast.Constant] = []
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Dict):
                spec_keys.extend(
                    k for k in node.keys
                    if isinstance(k, ast.Constant) and isinstance(k.value, str)
                )
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        sl = t.slice
                        if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                            spec_keys.append(sl)
        unknown = [k for k in spec_keys if k.value not in param_keys]
        # If NO key resolves, the param-defining module is simply outside
        # the analyzed set (subset run) — stay quiet rather than flag the
        # whole tree. A genuine typo shows up as a minority of unknowns.
        if unknown and len(unknown) < len(spec_keys):
            for k in unknown:
                out.append(_st102(sm, k, fn_node.name))
    return out


def _st102(sm: SourceModule, const: ast.Constant, fn: str) -> Finding:
    return Finding(
        file=sm.rel, line=const.lineno, code="ST102", severity="error",
        message=(
            f"spec key '{const.value}' in {fn} does not match any param-tree "
            f"key in the analyzed modules — the spec silently shards nothing"
        ),
    )
