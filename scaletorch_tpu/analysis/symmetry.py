"""ST6xx — SPMD collective symmetry (host-level deadlock hazards).

Every cross-host collective in this codebase — ``DecisionBus`` gathers
and broadcasts, ``jax.experimental.multihost_utils`` helpers, the
``dist.py`` object collectives, orbax checkpoint save/restore (which
are cross-process collectives on multi-host runs) — must be entered by
EVERY host or by NONE: a host that skips one leaves its peers blocked
inside a collective nobody will complete. The hang watchdog
(resilience_distributed.HangWatchdog) catches that at runtime, 43 exits
later; this pass is its static dual — it flags collectives reachable
only under *host-divergent* conditions:

ST601  collective reachable only under a rank-divergent branch
       (``process_index()``/``is_main``/rank comparisons, including the
       complement via a divergent early return/raise) — the classic
       one-sided gather; error.
ST602  collective inside an ``except`` handler — exceptions are
       host-local (one host's OSError is not its peers'), so a retry
       or fallback collective in a handler re-enters without the
       fleet; warning (a DecisionBus-agreed retry is the fix, see
       utils/checkpoint.py).
ST603  collective guarded by per-host filesystem / environment /
       wall-clock state (``os.path.exists``, ``os.environ``,
       ``time.*``) — uniform on a lucky day, divergent the day the
       shared FS lags on one host; warning.

What never flags (the protocol this repo actually uses, see
``CoordinatedResilience``): collectives entered unconditionally with
rank-divergent *computation* around them (``if bus.is_main: decision =
form(...)`` then ``broadcast_from_main(decision)`` outside the branch),
rank-divergent RESULT visibility after the collective (``out =
all_gather(x); return out if is_main else None``), IfExp payloads
(``broadcast([obj if is_main else None])``), branches on uniform facts
(``process_count() == 1``, config flags), and host-local actions under
rank guards (log files, directory retirement — not collectives).
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Tuple

from .core import Finding
from .scopes import ModuleScopes, ProjectIndex, dotted_name, tail_name

# -- collective classification ------------------------------------------------

# jax.experimental.multihost_utils — every one of these is a cross-host
# collective (sync_global_devices is the barrier the others build on).
_MULTIHOST_TAILS = {
    "sync_global_devices", "process_allgather", "broadcast_one_to_all",
    "assert_equal",
}
# scaletorch_tpu.dist object collectives + barrier.
_DIST_TAILS = {
    "all_gather_object", "broadcast_object_list", "gather_object",
    "collect_results", "barrier", "global_barrier",
}
# DecisionBus protocol methods — collective when called on a bus-like
# receiver (…bus / self._bus / decision_bus) or on ``self`` inside a
# *Bus class. `all_gather`/`broadcast` alone are too generic to match
# without the receiver check (jax.lax.all_gather is a device collective
# inside symmetric traced code, not a host hazard).
_BUS_METHODS = {
    "all_gather", "broadcast", "broadcast_from_main", "agree_all",
    "agree_any",
}
_BUS_RECEIVER_RE = re.compile(r"(^|\.|_)bus$|(^|\.|_)bus(\.|_)", re.I)
# orbax checkpoint collectives — save/restore/drain are cross-process on
# multi-host runs. Matched only on checkpoint-ish receivers so
# ``threading.Event.wait`` or ``img.save`` never flag.
_CKPT_METHODS = {"save", "restore", "wait", "wait_until_finished",
                 "load_latest"}
_CKPT_RECEIVER_RE = re.compile(
    r"ckpt|checkpoint|mngr|(^|\.|_)mgr$|(^|\.|_)manager$|orbax|(^|\.)ocp\.",
    re.I,
)

# -- divergence classification ------------------------------------------------

# Calls whose result differs per host. process_count()/device_count()
# are deliberately absent: they are uniform across the fleet.
_RANK_CALL_TAILS = {"process_index", "is_main_process", "getpid",
                    "gethostname"}
# Names/attribute tails that hold a per-host identity when they appear
# inside a branch condition.
_RANK_NAME_TAILS = {"is_main", "is_main_process", "process_index", "rank",
                    "local_rank", "process_id", "host_id"}
# Per-host filesystem probes.
_FS_CALL_TAILS = {"exists", "isfile", "isdir", "is_file", "is_dir",
                  "stat", "getsize", "listdir", "glob", "iterdir"}
# Per-host environment reads.
_ENV_CALL_TAILS = {"getenv", "env_override", "get_env"}
# Wall clocks.
_CLOCK_DOTTED = {
    "time.time", "time.monotonic", "time.perf_counter", "time.process_time",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
}


def _collective_desc(node: ast.Call, class_name: Optional[str]
                     ) -> Optional[str]:
    """Dotted description when ``node`` is a host-level collective."""
    d = dotted_name(node.func) or ""
    t = tail_name(node.func)
    if t in _MULTIHOST_TAILS or "multihost_utils." in d:
        return d or t
    if t in _DIST_TAILS:
        return d or t
    if isinstance(node.func, ast.Attribute):
        recv = dotted_name(node.func.value) or ""
        if node.func.attr in _BUS_METHODS:
            if _BUS_RECEIVER_RE.search(recv):
                return d
            if recv == "self" and class_name and class_name.endswith("Bus"):
                return d
        if node.func.attr in _CKPT_METHODS and _CKPT_RECEIVER_RE.search(recv):
            return d
    return None


def _divergence_kind(expr: ast.AST) -> Optional[Tuple[str, str]]:
    """(kind, what) when ``expr`` depends on host-divergent state; kind
    is 'rank' (ST601) or 'hostlocal' (ST603)."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            d = dotted_name(node.func) or ""
            t = tail_name(node.func)
            if t in _RANK_CALL_TAILS:
                return ("rank", f"{d or t}()")
            if t in _FS_CALL_TAILS or d.startswith("os.path."):
                return ("hostlocal", f"{d or t}()")
            if t in _ENV_CALL_TAILS:
                return ("hostlocal", f"{d or t}()")
            if d in _CLOCK_DOTTED or d.startswith("time."):
                return ("hostlocal", f"{d}()")
        elif isinstance(node, ast.Attribute):
            if node.attr in _RANK_NAME_TAILS:
                return ("rank", dotted_name(node) or node.attr)
        elif isinstance(node, ast.Name):
            if node.id in _RANK_NAME_TAILS:
                return ("rank", node.id)
        elif isinstance(node, ast.Subscript):
            base = dotted_name(node.value) or ""
            if base.endswith("os.environ") or base == "environ":
                return ("hostlocal", f"{base}[...]")
    return None


def _condition_src(test: ast.AST) -> str:
    try:
        src = ast.unparse(test)
    except Exception:  # pragma: no cover — unparse covers all exprs we see
        src = "<condition>"
    return src if len(src) <= 60 else src[:57] + "..."


def _always_exits(body: List[ast.stmt]) -> bool:
    """True when every path through ``body`` leaves the enclosing scope
    or loop iteration (return/raise/continue/break at top level)."""
    return any(
        isinstance(s, (ast.Return, ast.Raise, ast.Continue, ast.Break))
        for s in body
    )


# -- the pass -----------------------------------------------------------------

def run(index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for ms in index.scopes.values():
        findings.extend(_check_module(ms))
    return findings


def _check_module(ms: ModuleScopes) -> List[Finding]:
    out: List[Finding] = []
    # class context for each function (for the self-inside-*Bus rule)
    class_of = {}
    for node in ast.walk(ms.sm.tree):
        if isinstance(node, ast.ClassDef):
            for child in ast.walk(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    class_of.setdefault(child, node.name)
    for node in ast.walk(ms.sm.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _walk_body(ms, node.body, [], class_of.get(node), out)
    return out


# guard: (kind, description) — kind in {'rank', 'hostlocal', 'except'}
Guard = Tuple[str, str]


def _walk_body(
    ms: ModuleScopes,
    body: List[ast.stmt],
    guards: List[Guard],
    class_name: Optional[str],
    out: List[Finding],
) -> None:
    guards = list(guards)
    for stmt in body:
        # nested defs get their own walk (fresh guard context: they may
        # be called from anywhere, so outer guards don't transfer)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        _check_calls(ms, stmt, guards, class_name, out)
        if isinstance(stmt, ast.If):
            div = _divergence_kind(stmt.test)
            inner = guards + [_as_guard(div, stmt.test)] if div else guards
            _walk_body(ms, stmt.body, inner, class_name, out)
            _walk_body(ms, stmt.orelse, inner, class_name, out)
            # `if <divergent>: return` — the REST of this body runs only
            # on the complement host set, which is just as one-sided.
            if div and _always_exits(stmt.body) and not stmt.orelse:
                guards = guards + [_as_guard(div, stmt.test, complement=True)]
        elif isinstance(stmt, ast.While):
            div = _divergence_kind(stmt.test)
            inner = guards + [_as_guard(div, stmt.test)] if div else guards
            _walk_body(ms, stmt.body, inner, class_name, out)
            _walk_body(ms, stmt.orelse, inner, class_name, out)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            _walk_body(ms, stmt.body, guards, class_name, out)
            _walk_body(ms, stmt.orelse, guards, class_name, out)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            _walk_body(ms, stmt.body, guards, class_name, out)
        elif isinstance(stmt, ast.Try):
            _walk_body(ms, stmt.body, guards, class_name, out)
            for handler in stmt.handlers:
                _walk_body(
                    ms, handler.body,
                    guards + [("except", _handler_desc(handler))],
                    class_name, out,
                )
            _walk_body(ms, stmt.orelse, guards, class_name, out)
            _walk_body(ms, stmt.finalbody, guards, class_name, out)


def _as_guard(div: Optional[Tuple[str, str]], test: ast.AST,
              complement: bool = False) -> Guard:
    kind, what = div if div else ("rank", "<divergent>")
    cond = _condition_src(test)
    if complement:
        return (kind, f"the complement of `{cond}` (divergent early exit)")
    return (kind, f"`{cond}` (via {what})")


def _handler_desc(handler: ast.ExceptHandler) -> str:
    if handler.type is None:
        return "bare `except:`"
    return f"`except {_condition_src(handler.type)}`"


def _check_calls(
    ms: ModuleScopes,
    stmt: ast.stmt,
    guards: List[Guard],
    class_name: Optional[str],
    out: List[Finding],
) -> None:
    if not guards:
        return
    # Only this statement's own expressions — compound bodies are walked
    # with their own guard context.
    headers: List[ast.AST]
    if isinstance(stmt, (ast.If, ast.While)):
        headers = [stmt.test]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        headers = [stmt.iter]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        headers = [item.context_expr for item in stmt.items]
    elif isinstance(stmt, ast.Try):
        headers = []
    else:
        headers = [stmt]
    for header in headers:
        for node in _walk_pruned(header):
            if not isinstance(node, ast.Call):
                continue
            desc = _collective_desc(node, class_name)
            if desc is None:
                continue
            out.append(_finding_for(ms, node, desc, guards))


def _walk_pruned(root: ast.AST):
    """``ast.walk`` that does NOT descend into nested lambdas/defs:
    defining a callback under a divergent guard is not executing a
    collective there (ast.walk alone would still yield the lambda
    body's calls — its children are queued before the skip)."""
    stack = [root]
    while stack:
        node = stack.pop()
        if node is not root and isinstance(
            node, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _finding_for(
    ms: ModuleScopes, node: ast.AST, desc: str, guards: List[Guard]
) -> Finding:
    kinds = [g[0] for g in guards]
    if "rank" in kinds:
        g = guards[kinds.index("rank")]
        return Finding(
            file=ms.sm.rel, line=getattr(node, "lineno", 1), code="ST601",
            severity="error",
            message=(
                f"host-level collective `{desc}` is reachable only under "
                f"the rank-divergent condition {g[1]} — hosts that skip it "
                "leave peers blocked inside the collective (fleet "
                "deadlock); enter it on every host, or make the decision "
                "collective first (DecisionBus)"
            ),
        )
    if "except" in kinds:
        g = guards[kinds.index("except")]
        return Finding(
            file=ms.sm.rel, line=getattr(node, "lineno", 1), code="ST602",
            severity="warning",
            message=(
                f"host-level collective `{desc}` runs inside {g[1]} — "
                "exceptions are host-local, so this host re-enters a "
                "collective its peers never reach; gather the per-host "
                "outcomes first and retry in lockstep (the "
                "utils/checkpoint.py coordinated-retry pattern)"
            ),
        )
    g = guards[kinds.index("hostlocal")]
    return Finding(
        file=ms.sm.rel, line=getattr(node, "lineno", 1), code="ST603",
        severity="warning",
        message=(
            f"host-level collective `{desc}` is guarded by per-host state "
            f"{g[1]} — filesystem/env/clock reads may disagree across "
            "hosts (one host skips, peers block); agree on the value over "
            "the bus before branching"
        ),
    )
