"""ST907 — telemetry JSONL ``kind`` strings must be registered.

Every consumer of the schema-versioned JSONL stream (slo_check, fleet
log aggregation, the offline histogram merger) dispatches on the
``kind`` field, and ``telemetry/export.py`` documents ``KNOWN_KINDS``
as the kinds consumers can rely on. A new emitter added without
registering its kind — the ``gateway_metrics``-style drift this pass
exists for — ships records no consumer knows to parse, and nothing
crashes: the data is just silently unconsumed.

The pass finds every string-literal kind handed to the telemetry
exporter (``<...>exporter.emit("kind", ...)`` and the
``telemetry.export("kind", ...)`` facade) and checks it against the
``KNOWN_KINDS`` tuple, read from ``telemetry/export.py`` in the
analyzed set or — when linting a subset that excludes it — from the
installed package source (the same fallback the sharding pass uses for
``MESH_AXES``). Variable kinds (the facade's pass-through) and call
sites outside the package (tests emitting free-form kinds) are not the
target and never flag.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional, Set

from .core import Finding
from .scopes import ProjectIndex, dotted_name

_REGISTRY_NAME = "KNOWN_KINDS"


def _kinds_from_tree(tree: ast.Module) -> Optional[Set[str]]:
    for node in ast.walk(tree):
        targets: List[ast.AST] = []
        value: Optional[ast.AST] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign):
            targets, value = [node.target], node.value
        if value is None or not any(
            isinstance(t, ast.Name) and t.id == _REGISTRY_NAME
            for t in targets
        ):
            continue
        if isinstance(value, (ast.Tuple, ast.List, ast.Set)):
            out: Set[str] = set()
            for el in value.elts:
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    out.add(el.value)
                else:
                    return None  # dynamic registry: don't guess
            return out
    return None


def _load_registry(index: ProjectIndex) -> Optional[Set[str]]:
    for sm in index.modules:
        if sm.module.endswith("telemetry.export") or \
                sm.rel.endswith("telemetry/export.py"):
            return _kinds_from_tree(sm.tree)
    # linting a subset: fall back to the installed package source
    export_py = Path(__file__).resolve().parent.parent / "telemetry" \
        / "export.py"
    if export_py.is_file():
        try:
            return _kinds_from_tree(ast.parse(export_py.read_text(
                encoding="utf-8")))
        except (OSError, SyntaxError):
            return None
    return None


def _is_exporter_recv(d: str) -> bool:
    tail = d.rsplit(".", 1)[-1]
    return tail in ("exporter", "_exporter") or tail.endswith("_exporter")


def run(index: ProjectIndex) -> List[Finding]:
    registry = _load_registry(index)
    if registry is None:
        return []  # no registry visible: nothing to check against
    findings: List[Finding] = []
    for ms in index.scopes.values():
        for node in ast.walk(ms.sm.tree):
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute):
                continue
            recv = dotted_name(node.func.value) or ""
            if node.func.attr == "emit" and _is_exporter_recv(recv):
                pass
            elif node.func.attr == "export" and \
                    recv.rsplit(".", 1)[-1] in ("telemetry", "_telemetry"):
                pass
            else:
                continue
            if not node.args:
                continue
            kind = node.args[0]
            if not (isinstance(kind, ast.Constant)
                    and isinstance(kind.value, str)):
                continue  # variable kind: the facade pass-through
            if kind.value not in registry:
                findings.append(Finding(
                    file=ms.sm.rel, line=node.lineno, code="ST907",
                    severity="error",
                    message=(
                        f"JSONL kind '{kind.value}' is not registered in "
                        "telemetry/export.py KNOWN_KINDS — consumers "
                        "dispatch on the kind field and silently drop "
                        "unknown ones; add it to the registry (additive, "
                        "schema version stays)"
                    ),
                ))
    findings.sort(key=lambda f: (f.file, f.line))
    return findings
