"""Thread-root & lockset resolver for the concurrency tier (ST9xx).

The concurrency pass keeps asking the same three questions the jit
passes ask ``scopes.py``:

1. **Which code runs on which thread?** ``ThreadModel`` discovers the
   *thread roots* of the analyzed set — ``threading.Thread(target=...)``
   targets (methods, local defs, lambdas), handlers registered via
   ``signal.signal``/``loop.add_signal_handler``, every ``async def``
   (one shared asyncio-loop root — coroutine bodies execute on the
   event loop no matter which thread constructs them), and the *caller*
   root of a thread-owning class (its public methods are, by
   construction, invoked from some other thread than the one it spawns).
   Closures are attributed to where they are *executed*, not where they
   are defined: a closure handed to ``self._inbox.put`` runs wherever
   ``self._inbox.get()`` results are invoked (the worker-inbox
   trampoline this codebase's gateway uses), a callable handed to
   ``call_soon_threadsafe``/``run_coroutine_threadsafe`` runs on the
   loop, a method assigned to a callback attribute (``engine.on_tokens =
   self._hook``) runs wherever ``self.on_tokens(...)`` is called.

2. **Which calls reach which functions?** A deliberately *typed-only*
   call graph: ``self.m()`` resolves to the enclosing class's method,
   ``x.m()`` resolves only when ``x``'s class is statically known (a
   ``self.a = C(...)`` / ``C.from_*(...)`` assignment, an annotation
   naming a package class, or a local bound from one of those).
   Name-only "any method called m" matching is deliberately NOT done:
   over-approximate reachability turns into false races, and the
   concurrency tier holds the same zero-false-positive bar as the rest
   of jaxlint. Under-approximation (a missed edge) only costs recall.

3. **Which locks are held where?** Lock objects are attributes/globals
   assigned ``threading.Lock()``/``RLock()``/``Semaphore()``; held-sets
   are propagated from each root through the call graph (``with lock:``
   scopes and the locks held at a call site flow into the callee), so a
   mutation's *effective* lockset reflects the whole path from its
   root, not just its lexical ``with`` nesting.

Known limitations (documented in docs/static_analysis.md): attribute
identity is per-class (``self._x`` in class C), so aliased cross-object
state is invisible; unresolvable dynamic calls drop edges (never add
them); exclusion protocols that serialize by state machine rather than
by lock (one side locked, the other provably-not-concurrent) are
respected by flagging only when *two or more* roots mutate with no lock
at all.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .scopes import ModuleScopes, ProjectIndex, dotted_name, tail_name

# ---------------------------------------------------------------------------
# vocabulary
# ---------------------------------------------------------------------------

# threading.X() constructors -> lock kind ("lock" is non-reentrant).
_LOCK_CTORS = {
    "Lock": "lock", "RLock": "rlock", "Semaphore": "lock",
    "BoundedSemaphore": "lock",
}
# external object kinds the typer tracks (receiver methods on these are
# never resolved to package functions; some drive pass rules directly)
_EXTERNAL_CTORS = {
    ("threading", "Event"): "tevent",
    ("threading", "Thread"): "thread",
    ("threading", "Condition"): "rlock",   # backed by an RLock
    ("queue", "Queue"): "queue",
    ("queue", "SimpleQueue"): "queue",
    ("queue", "LifoQueue"): "queue",
    ("queue", "PriorityQueue"): "queue",
    ("asyncio", "Event"): "aevent",
    ("asyncio", "Queue"): "aqueue",
    ("asyncio", "Lock"): "alock",
    ("asyncio", "Condition"): "alock",
    ("asyncio", "get_event_loop"): "aloop",
    ("asyncio", "get_running_loop"): "aloop",
    ("asyncio", "new_event_loop"): "aloop",
    ("asyncio", "ensure_future"): "atask",
    ("asyncio", "create_task"): "atask",
    ("asyncio", "run_coroutine_threadsafe"): "cfuture",
}
# callables whose function-valued argument executes on the event loop
_LOOP_SINKS = {
    "call_soon_threadsafe", "run_coroutine_threadsafe", "call_soon",
    "call_later", "call_at", "ensure_future", "create_task",
    "run_until_complete",
}
# mutating container methods: self.x.append(...) is a mutation of self.x
MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "update", "pop", "popleft", "popitem", "remove", "discard", "clear",
    "setdefault", "sort", "reverse",
}

LOOP_ROOT = ("loop", "asyncio event loop")
MAIN_ROOT = ("main", "main path")

FuncNode = ast.AST  # FunctionDef | AsyncFunctionDef | Lambda


def _qualname(ms: ModuleScopes, node: FuncNode) -> str:
    parts: List[str] = []
    cur: Optional[ast.AST] = node
    while cur is not None and not isinstance(cur, ast.Module):
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            parts.append(cur.name)
        elif isinstance(cur, ast.Lambda):
            parts.append(f"<lambda:{cur.lineno}>")
        elif isinstance(cur, ast.ClassDef):
            parts.append(cur.name)
        cur = ms.parents.get(cur)
    return ".".join(reversed(parts)) or "<module>"


@dataclasses.dataclass
class FuncInfo:
    node: FuncNode
    ms: ModuleScopes
    name: str
    qualname: str
    class_name: Optional[str]
    is_async: bool


LockId = Tuple[str, str]     # (class-or-module scope, attr/name)
AttrKey = Tuple[str, str]    # (class name, dotted attr under self)
RootId = Tuple[str, str]     # (kind, description) — kind in
                             # {"thread", "signal", "loop", "caller"}


@dataclasses.dataclass
class Access:
    key: AttrKey
    line: int
    mutation: bool
    desc: str                     # rendered source-ish description
    locks: FrozenSet[LockId]      # lexically-held locks at the site


@dataclasses.dataclass
class Acquire:
    lock: LockId
    kind: str                     # "lock" | "rlock" | "alock"
    line: int
    style: str                    # "with" | "bare" | "guarded"
    locks_before: FrozenSet[LockId]
    safe_release: bool            # bare acquire paired with try/finally


@dataclasses.dataclass
class LoopTouch:
    desc: str
    line: int


@dataclasses.dataclass
class BlockingCall:
    desc: str
    line: int


@dataclasses.dataclass
class FuncFacts:
    """Intra-procedural facts for one function body (own statements;
    nested defs/lambdas get their own facts and are linked by edges)."""

    accesses: List[Access] = dataclasses.field(default_factory=list)
    acquires: List[Acquire] = dataclasses.field(default_factory=list)
    # (callee FuncInfo, lexically-held locks at the call site)
    calls: List[Tuple["FuncInfo", FrozenSet[LockId]]] = \
        dataclasses.field(default_factory=list)
    loop_touches: List[LoopTouch] = dataclasses.field(default_factory=list)
    blocking: List[BlockingCall] = dataclasses.field(default_factory=list)


class ThreadModel:
    """Roots, typed call graph, per-root effective locksets."""

    def __init__(self, index: ProjectIndex) -> None:
        self.index = index
        self.funcs: Dict[FuncNode, FuncInfo] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        self.class_ms: Dict[str, ModuleScopes] = {}
        self.methods: Dict[Tuple[str, str], FuncNode] = {}
        # (class, attr) -> package class name | "ext:<kind>"
        self.attr_types: Dict[Tuple[str, str], str] = {}
        # (module, global name) -> lock kind, for module-level locks
        self.global_locks: Dict[LockId, str] = {}
        self.lock_kinds: Dict[LockId, str] = {}
        # callback registries
        self.cb_by_class_attr: Dict[Tuple[str, str], Set[FuncNode]] = {}
        self.cb_by_attr: Dict[str, Set[FuncNode]] = {}
        self._pending_bindings: List[tuple] = []
        # closures enqueued into (class, queue-attr)
        self.queue_payloads: Dict[Tuple[str, str], Set[FuncNode]] = {}
        # roots
        self.roots: Dict[RootId, Set[FuncNode]] = {}
        self.signal_roots: Set[RootId] = set()
        self.thread_owning_classes: Set[str] = set()
        # results of propagation
        self.facts: Dict[FuncNode, FuncFacts] = {}
        self.func_roots: Dict[FuncNode, Set[RootId]] = {}
        # attr -> root -> list of (Access, effective lockset)
        self.attr_map: Dict[
            AttrKey, Dict[RootId, List[Tuple[Access, FrozenSet[LockId]]]]
        ] = {}
        # lock -> root -> list of (line, file, FuncInfo)
        self.lock_holders: Dict[
            LockId, Dict[RootId, List[Tuple[Acquire, "FuncInfo"]]]
        ] = {}
        # lock-order edges: (A, B) -> (Acquire, FuncInfo) witness
        self.order_edges: Dict[
            Tuple[LockId, LockId], Tuple[Acquire, "FuncInfo"]
        ] = {}
        self.loop_touch_hits: List[Tuple[LoopTouch, FuncInfo, RootId]] = []

        self._collect_defs()
        self._collect_types_and_registries()
        self._collect_roots()
        self._build_facts()
        self._propagate()

    # -- phase 1: definitions ------------------------------------------------
    def _collect_defs(self) -> None:
        for ms in self.index.scopes.values():
            for node in ast.walk(ms.sm.tree):
                if isinstance(node, ast.ClassDef):
                    self.classes[node.name] = node
                    self.class_ms[node.name] = ms
                    for child in node.body:
                        if isinstance(child, (ast.FunctionDef,
                                              ast.AsyncFunctionDef)):
                            self.methods[(node.name, child.name)] = child
            for node in ast.walk(ms.sm.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                    cls = self._enclosing_class(ms, node)
                    name = node.name if not isinstance(node, ast.Lambda) \
                        else f"<lambda:{node.lineno}>"
                    self.funcs[node] = FuncInfo(
                        node=node, ms=ms, name=name,
                        qualname=f"{ms.sm.module}:{_qualname(ms, node)}",
                        class_name=cls,
                        is_async=isinstance(node, ast.AsyncFunctionDef),
                    )

    def _enclosing_class(self, ms: ModuleScopes,
                         node: ast.AST) -> Optional[str]:
        cur = ms.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur.name
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a method's nested closure still belongs to the class
                cur = ms.parents.get(cur)
                continue
            cur = ms.parents.get(cur)
        return None

    # -- phase 2: types, locks, callback registries --------------------------
    def _ctor_kind(self, call: ast.Call) -> Optional[str]:
        """'ClassName' | 'ext:<kind>' for a constructor-ish call."""
        d = dotted_name(call.func)
        if d is None:
            return None
        parts = d.split(".")
        tailp = parts[-1]
        base = parts[-2] if len(parts) >= 2 else None
        if tailp in _LOCK_CTORS and (base in (None, "threading")):
            return f"ext:{_LOCK_CTORS[tailp]}"
        for (mod, name), kind in _EXTERNAL_CTORS.items():
            if tailp == name and (base in (None, mod)):
                return f"ext:{kind}"
        # package class: C(...) or C.from_x(...) / C.default(...)
        if tailp in self.classes:
            return tailp
        if base in self.classes:
            return base
        return None

    def _ann_type(self, ann: Optional[ast.AST]) -> Optional[str]:
        """Package class (or external kind) named inside an annotation."""
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
        for node in ast.walk(ann):
            d = dotted_name(node)
            if d is None:
                continue
            parts = d.split(".")
            if parts[-1] in self.classes:
                return parts[-1]
            if len(parts) >= 2:
                kind = _EXTERNAL_CTORS.get((parts[-2], parts[-1]))
                if kind:
                    return f"ext:{kind}"
        return None

    def _func_ref(self, ms: ModuleScopes, node: ast.AST,
                  cls: Optional[str]) -> Optional[FuncNode]:
        """Resolve a function *reference* (not a call): ``self._m``,
        a bare local name, an imported name, or an inline lambda."""
        if isinstance(node, ast.Lambda):
            return node
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and node.value.id == "self" \
                and cls is not None:
            return self.methods.get((cls, node.attr))
        if isinstance(node, ast.Name):
            cands = ms.functions.get(node.id)
            if cands:
                return cands[-1]
            imp = ms.imports.get(node.id)
            if imp is not None:
                target = self.index.by_module.get(imp[0])
                if target is not None:
                    cands = target.functions.get(imp[1])
                    if cands:
                        return cands[-1]
        return None

    def _collect_types_and_registries(self) -> None:
        for ms in self.index.scopes.values():
            mod = ms.sm.module
            for node in ast.walk(ms.sm.tree):
                value: Optional[ast.AST] = None
                targets: List[ast.AST] = []
                if isinstance(node, ast.Assign):
                    value, targets = node.value, node.targets
                elif isinstance(node, ast.AnnAssign):
                    targets = [node.target]
                    value = node.value
                    # annotation-driven attr/param typing
                    t = self._ann_type(node.annotation)
                    if t is not None:
                        self._type_target(ms, node.target, t)
                if value is None:
                    continue
                vtype = self._ctor_kind(value) \
                    if isinstance(value, ast.Call) else None
                for target in targets:
                    if vtype is not None:
                        self._type_target(ms, target, vtype)
                        if vtype.startswith("ext:") and \
                                vtype[4:] in ("lock", "rlock"):
                            self._register_lock(ms, mod, target, vtype[4:])
                    # callback registry: X.attr = <func ref>
                    if isinstance(target, ast.Attribute):
                        cls = self._enclosing_class(ms, node)
                        ref = self._func_ref(ms, value, cls)
                        if ref is not None:
                            self.cb_by_attr.setdefault(
                                target.attr, set()).add(ref)
            # param-annotation typing + self.attr = param bindings
            self._collect_param_bindings(ms)
        # call-site registries run AFTER every module's types are known:
        # the typed-receiver guard in _bind_callsite_args and the
        # queue-attr check both read attr_types across modules
        self._index_call_sites()
        for cls, mname, fn, params, param_attr in self._pending_bindings:
            self._bind_callsite_args(cls, mname, fn, params, param_attr)
        for ms in self.index.scopes.values():
            # X.attr.append(ref) and queue.put(ref) registries
            self._collect_call_registries(ms)

    def _type_target(self, ms: ModuleScopes, target: ast.AST,
                     vtype: str) -> None:
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self":
            cls = self._enclosing_class(ms, target)
            if cls is not None:
                self.attr_types.setdefault((cls, target.attr), vtype)

    def _register_lock(self, ms: ModuleScopes, mod: str, target: ast.AST,
                       kind: str) -> None:
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self":
            cls = self._enclosing_class(ms, target)
            if cls is not None:
                self.lock_kinds[(cls, target.attr)] = kind
        elif isinstance(target, ast.Name):
            cls = self._enclosing_class(ms, target)
            if cls is None:
                self.global_locks[(mod, target.id)] = kind
                self.lock_kinds[(mod, target.id)] = kind

    def _collect_param_bindings(self, ms: ModuleScopes) -> None:
        """Two jobs per method: params annotated with package classes
        become local types, and ``self.attr = param`` makes *call-site
        arguments* for that param feed the (class, attr) callback
        registry — the ``snapshotter.install(self._live_snapshot)`` /
        ``HangWatchdog(crash_report=...)`` wiring."""
        for (cls, mname), fn in list(self.methods.items()):
            if self.class_ms.get(cls) is not ms:
                continue
            assert isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
            params = [a.arg for a in fn.args.args]
            all_params = fn.args.args + fn.args.kwonlyargs
            all_names = {a.arg for a in all_params}
            param_attr: Dict[str, str] = {}
            for stmt in ast.walk(fn):
                if isinstance(stmt, ast.Assign) and \
                        isinstance(stmt.value, ast.Name) and \
                        stmt.value.id in all_names:
                    for t in stmt.targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self":
                            param_attr[stmt.value.id] = t.attr
                            # param annotation types the attr too
                            for a in all_params:
                                if a.arg == stmt.value.id:
                                    at = self._ann_type(a.annotation)
                                    if at is not None:
                                        self.attr_types.setdefault(
                                            (cls, t.attr), at)
            if not param_attr:
                continue
            self._pending_bindings.append(
                (cls, mname, fn, params, param_attr))

    def _index_call_sites(self) -> None:
        """One walk over every tree, bucketing calls by attribute name
        and by constructor tail. ``_bind_callsite_args`` used to rescan
        every module per pending binding — the dominant cost of the
        whole analyzer on this repo; the buckets make each binding a
        dictionary lookup over only the calls that can match."""
        self._calls_by_attr: Dict[str, List[tuple]] = {}
        self._calls_by_ctor: Dict[str, List[tuple]] = {}
        for ms2 in self.index.scopes.values():
            for call in ast.walk(ms2.sm.tree):
                if not isinstance(call, ast.Call):
                    continue
                if isinstance(call.func, ast.Attribute):
                    self._calls_by_attr.setdefault(
                        call.func.attr, []).append((ms2, call))
                d = dotted_name(call.func)
                if d is not None:
                    self._calls_by_ctor.setdefault(
                        d.split(".")[-1], []).append((ms2, call))

    def _bind_callsite_args(self, cls: str, mname: str, fn: ast.AST,
                            params: List[str],
                            param_attr: Dict[str, str]) -> None:
        """Find calls of ``cls.mname`` (typed ``recv.m(...)`` or the
        constructor ``C(...)``) and record function-valued args."""
        if mname == "__init__":
            sites = self._calls_by_ctor.get(cls, ())
        else:
            sites = self._calls_by_attr.get(mname, ())
        for ms2, call in sites:
            caller_cls = None
            if mname != "__init__":
                # attribute call of this method name. When the
                # receiver's class is statically known it must BE
                # `cls` — binding a callback into a same-named
                # method of a different class fabricates roots and
                # false races. Unknown receivers stay bound (the
                # over-approximation recall needs), bounded by the
                # param-name match.
                caller_cls = self._enclosing_class(ms2, call)
                rtype = self._recv_type(call.func.value, caller_cls, {})
                if rtype is not None and rtype != cls:
                    continue
            if caller_cls is None:
                caller_cls = self._enclosing_class(ms2, call)
            offset = 1  # skip self
            for i, arg in enumerate(call.args):
                idx = i + offset
                if idx < len(params) and params[idx] in param_attr:
                    ref = self._func_ref(ms2, arg, caller_cls)
                    if ref is not None:
                        self.cb_by_class_attr.setdefault(
                            (cls, param_attr[params[idx]]), set()
                        ).add(ref)
            for kw in call.keywords:
                if kw.arg in param_attr:
                    ref = self._func_ref(ms2, kw.value, caller_cls)
                    if ref is not None:
                        self.cb_by_class_attr.setdefault(
                            (cls, param_attr[kw.arg]), set()
                        ).add(ref)

    def _collect_call_registries(self, ms: ModuleScopes) -> None:
        for call in ast.walk(ms.sm.tree):
            if not isinstance(call, ast.Call) or \
                    not isinstance(call.func, ast.Attribute):
                continue
            attr = call.func.attr
            cls = self._enclosing_class(ms, call)
            if attr in ("append", "add") and call.args and \
                    isinstance(call.func.value, ast.Attribute):
                ref = self._func_ref(ms, call.args[0], cls)
                if ref is not None:
                    self.cb_by_attr.setdefault(
                        call.func.value.attr, set()).add(ref)
            if attr in ("put", "put_nowait") and call.args:
                recv = call.func.value
                if isinstance(recv, ast.Attribute) and \
                        isinstance(recv.value, ast.Name) and \
                        recv.value.id == "self" and cls is not None and \
                        self.attr_types.get((cls, recv.attr)) == "ext:queue":
                    ref = self._func_ref(ms, call.args[0], cls)
                    if ref is not None:
                        self.queue_payloads.setdefault(
                            (cls, recv.attr), set()).add(ref)

    # -- phase 3: roots ------------------------------------------------------
    def _collect_roots(self) -> None:
        for ms in self.index.scopes.values():
            for call in ast.walk(ms.sm.tree):
                if not isinstance(call, ast.Call):
                    continue
                d = dotted_name(call.func) or ""
                t = tail_name(call.func)
                cls = self._enclosing_class(ms, call)
                if t == "Thread" and (d in ("Thread", "threading.Thread")):
                    target = None
                    for kw in call.keywords:
                        if kw.arg == "target":
                            target = self._func_ref(ms, kw.value, cls)
                    if target is not None and target in self.funcs:
                        fi = self.funcs[target]
                        rid = ("thread", fi.qualname)
                        self.roots.setdefault(rid, set()).add(target)
                        if cls is not None:
                            self.thread_owning_classes.add(cls)
                        elif fi.class_name is not None:
                            self.thread_owning_classes.add(fi.class_name)
                elif (d in ("signal.signal",)
                      or t == "add_signal_handler") and len(call.args) >= 2:
                    handler = self._func_ref(ms, call.args[1], cls)
                    if handler is not None and handler in self.funcs:
                        fi = self.funcs[handler]
                        rid = ("signal", fi.qualname)
                        self.roots.setdefault(rid, set()).add(handler)
                        self.signal_roots.add(rid)
                elif t in _LOOP_SINKS:
                    for arg in call.args[:1]:
                        ref = self._func_ref(ms, arg, cls)
                        if ref is not None and ref in self.funcs:
                            self.roots.setdefault(LOOP_ROOT, set()).add(ref)
        # every async def executes on the loop
        for node, fi in self.funcs.items():
            if fi.is_async:
                self.roots.setdefault(LOOP_ROOT, set()).add(node)
        # caller root: public sync methods of thread-owning classes
        for cls in self.thread_owning_classes:
            rid = ("caller", cls)
            cnode = self.classes.get(cls)
            if cnode is None:
                continue
            for child in cnode.body:
                if isinstance(child, ast.FunctionDef) and \
                        not child.name.startswith("_"):
                    self.roots.setdefault(rid, set()).add(child)

    # -- phase 4: intra-procedural facts -------------------------------------
    def _lock_id(self, ms: ModuleScopes, expr: ast.AST,
                 cls: Optional[str]) -> Optional[Tuple[LockId, str]]:
        """(lock id, kind) when ``expr`` names a known lock object."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                and cls is not None:
            lid = (cls, expr.attr)
            kind = self.lock_kinds.get(lid)
            return (lid, kind) if kind else None
        if isinstance(expr, ast.Name):
            lid = (ms.sm.module, expr.id)
            kind = self.global_locks.get(lid)
            return (lid, kind) if kind else None
        return None

    def _chain_key(self, expr: ast.AST, cls: Optional[str],
                   local_types: Dict[str, str]) -> Optional[AttrKey]:
        """Attr key for a ``self.a[.b]`` / ``typedlocal.b`` chain."""
        if not isinstance(expr, ast.Attribute):
            return None
        base = expr.value
        if isinstance(base, ast.Name):
            if base.id == "self" and cls is not None:
                return (cls, expr.attr)
            btype = local_types.get(base.id)
            if btype and not btype.startswith("ext:"):
                return (btype, expr.attr)
            return None
        if isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name):
            if base.value.id == "self" and cls is not None:
                btype = self.attr_types.get((cls, base.attr))
                if btype and not btype.startswith("ext:"):
                    return (btype, expr.attr)
                return (cls, f"{base.attr}.{expr.attr}")
        return None

    def _recv_type(self, expr: ast.AST, cls: Optional[str],
                   local_types: Dict[str, str]) -> Optional[str]:
        """Static type of a receiver expression, when known."""
        if isinstance(expr, ast.Name):
            if expr.id == "self":
                return cls
            return local_types.get(expr.id)
        if isinstance(expr, ast.Attribute):
            btype = self._recv_type(expr.value, cls, local_types)
            if btype and not btype.startswith("ext:"):
                return self.attr_types.get((btype, expr.attr))
        if isinstance(expr, ast.Call):
            return self._ctor_kind(expr)
        return None

    def _build_facts(self) -> None:
        for node, fi in self.funcs.items():
            self.facts[node] = self._analyze_func(fi)

    def _analyze_func(self, fi: FuncInfo) -> FuncFacts:
        facts = FuncFacts()
        node = fi.node
        if isinstance(node, ast.Lambda):
            body: List[ast.stmt] = []
            self._scan_expr(fi, node.body, frozenset(), {}, facts)
            return facts
        body = node.body  # type: ignore[union-attr]
        local_types: Dict[str, str] = {}
        # params annotated with package classes become typed locals
        for a in (node.args.args + node.args.kwonlyargs):
            t = self._ann_type(a.annotation)
            if t is not None:
                local_types[a.arg] = t
        # callable candidates for locals bound from queues / registries
        local_callables: Dict[str, Set[FuncNode]] = {}
        self._scan_block(fi, body, frozenset(), local_types,
                         local_callables, facts)
        return facts

    def _scan_block(self, fi: FuncInfo, body: Sequence[ast.stmt],
                    locks: FrozenSet[LockId], local_types: Dict[str, str],
                    local_callables: Dict[str, Set[FuncNode]],
                    facts: FuncFacts) -> None:
        for i, stmt in enumerate(body):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested defs analyzed separately
            if isinstance(stmt, ast.Assign):
                self._observe_assign(fi, stmt, locks, local_types,
                                     local_callables, facts)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                t = self._ann_type(stmt.annotation)
                if t is not None and isinstance(stmt.target, ast.Name):
                    local_types[stmt.target.id] = t
                self._scan_expr(fi, stmt.value, locks, local_types, facts,
                                local_callables)
            elif isinstance(stmt, ast.AugAssign):
                # `self.x += 1` mutates self.x; `self.x[k] += 1` is a
                # read-modify-write of the container self.x
                target = stmt.target
                if isinstance(target, ast.Subscript):
                    target = target.value
                key = self._chain_key(target, fi.class_name, local_types)
                if key is not None:
                    facts.accesses.append(Access(
                        key=key, line=stmt.lineno, mutation=True,
                        desc=self._render(stmt.target), locks=locks))
                self._scan_expr(fi, stmt.value, locks, local_types, facts,
                                local_callables)
            elif isinstance(stmt, ast.Delete):
                for t in stmt.targets:
                    if isinstance(t, ast.Subscript):
                        key = self._chain_key(t.value, fi.class_name,
                                              local_types)
                        if key is not None:
                            facts.accesses.append(Access(
                                key=key, line=stmt.lineno, mutation=True,
                                desc=self._render(t.value), locks=locks))
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner = set(locks)
                for item in stmt.items:
                    lk = self._lock_id(fi.ms, item.context_expr,
                                       fi.class_name)
                    if lk is not None:
                        lid, kind = lk
                        acq = Acquire(
                            lock=lid, kind=kind,
                            line=item.context_expr.lineno, style="with",
                            locks_before=frozenset(inner),
                            safe_release=True)
                        facts.acquires.append(acq)
                        inner.add(lid)
                        if fi.is_async:
                            # a threading lock (never asyncio.Lock —
                            # those aren't in lock_kinds) blocks the
                            # whole loop while contended
                            facts.blocking.append(BlockingCall(
                                desc=f"with {self._render(item.context_expr)}"
                                     f": (threading lock)",
                                line=item.context_expr.lineno))
                    else:
                        self._scan_expr(fi, item.context_expr, locks,
                                        local_types, facts, local_callables)
                self._scan_block(fi, stmt.body, frozenset(inner),
                                 local_types, local_callables, facts)
            elif isinstance(stmt, ast.If):
                self._scan_expr(fi, stmt.test, locks, local_types, facts,
                                local_callables)
                self._scan_block(fi, stmt.body, locks, local_types,
                                 local_callables, facts)
                self._scan_block(fi, stmt.orelse, locks, local_types,
                                 local_callables, facts)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._observe_for(fi, stmt, locks, local_types,
                                  local_callables, facts)
                self._scan_block(fi, stmt.body, locks, local_types,
                                 local_callables, facts)
                self._scan_block(fi, stmt.orelse, locks, local_types,
                                 local_callables, facts)
            elif isinstance(stmt, ast.While):
                self._scan_expr(fi, stmt.test, locks, local_types, facts,
                                local_callables)
                self._scan_block(fi, stmt.body, locks, local_types,
                                 local_callables, facts)
                self._scan_block(fi, stmt.orelse, locks, local_types,
                                 local_callables, facts)
            elif isinstance(stmt, ast.Try):
                self._scan_block(fi, stmt.body, locks, local_types,
                                 local_callables, facts)
                for handler in stmt.handlers:
                    self._scan_block(fi, handler.body, locks, local_types,
                                     local_callables, facts)
                self._scan_block(fi, stmt.orelse, locks, local_types,
                                 local_callables, facts)
                self._scan_block(fi, stmt.finalbody, locks, local_types,
                                 local_callables, facts)
            elif isinstance(stmt, ast.Expr):
                acquired = self._observe_expr_stmt(
                    fi, stmt, i, body, locks, local_types,
                    local_callables, facts)
                if acquired is not None:
                    # a bare acquire() holds the lock for the rest of
                    # this block (released in the paired finally or
                    # leaked — either way the critical section below IS
                    # protected, and ST901 must not call it unlocked)
                    locks = locks | {acquired}
            elif isinstance(stmt, ast.Return) and stmt.value is not None:
                self._scan_expr(fi, stmt.value, locks, local_types, facts,
                                local_callables)
            elif isinstance(stmt, (ast.Assert, ast.Raise)):
                for child in ast.iter_child_nodes(stmt):
                    self._scan_expr(fi, child, locks, local_types, facts,
                                    local_callables)

    def _observe_assign(self, fi: FuncInfo, stmt: ast.Assign,
                        locks: FrozenSet[LockId],
                        local_types: Dict[str, str],
                        local_callables: Dict[str, Set[FuncNode]],
                        facts: FuncFacts) -> None:
        # subscript store: self.x[k] = v  -> mutation of self.x
        for t in stmt.targets:
            if isinstance(t, ast.Subscript):
                key = self._chain_key(t.value, fi.class_name, local_types)
                if key is not None:
                    facts.accesses.append(Access(
                        key=key, line=stmt.lineno, mutation=True,
                        desc=self._render(t.value), locks=locks))
            elif isinstance(t, ast.Attribute):
                # read-modify-write: self.x = self.x + 1
                key = self._chain_key(t, fi.class_name, local_types)
                if key is not None and self._reads_key(
                        stmt.value, key, fi.class_name, local_types):
                    facts.accesses.append(Access(
                        key=key, line=stmt.lineno, mutation=True,
                        desc=self._render(t), locks=locks))
        # local typing
        if isinstance(stmt.value, ast.Call):
            # x = C(...) / x = threading.Event() here; x = self.engine
            # (attr-type copy) below
            vtype = self._ctor_kind(stmt.value)
            if vtype is not None:
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        local_types[t.id] = vtype
            # fn = self.<queue>.get() -> queued-closure candidates
            if isinstance(stmt.value.func, ast.Attribute) and \
                    stmt.value.func.attr in ("get", "get_nowait"):
                recv = stmt.value.func.value
                if isinstance(recv, ast.Attribute) and \
                        isinstance(recv.value, ast.Name) and \
                        recv.value.id == "self" and fi.class_name:
                    qkey = (fi.class_name, recv.attr)
                    if qkey in self.queue_payloads:
                        for t in stmt.targets:
                            if isinstance(t, ast.Name):
                                local_callables[t.id] = \
                                    self.queue_payloads[qkey]
        elif isinstance(stmt.value, (ast.Name, ast.Attribute)):
            vtype = self._recv_type(stmt.value, fi.class_name, local_types)
            if vtype is not None:
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        local_types[t.id] = vtype
        self._scan_expr(fi, stmt.value, locks, local_types, facts,
                        local_callables)

    def _observe_for(self, fi: FuncInfo, stmt, locks, local_types,
                     local_callables, facts) -> None:
        # for cb in self.<registry-attr>: cb() -> callback candidates
        it = stmt.iter
        if isinstance(it, ast.Attribute) and it.attr in self.cb_by_attr and \
                isinstance(stmt.target, ast.Name):
            local_callables[stmt.target.id] = self.cb_by_attr[it.attr]
        self._scan_expr(fi, it, locks, local_types, facts, local_callables)

    def _observe_expr_stmt(self, fi: FuncInfo, stmt: ast.Expr, i: int,
                           body: Sequence[ast.stmt],
                           locks: FrozenSet[LockId],
                           local_types: Dict[str, str],
                           local_callables: Dict[str, Set[FuncNode]],
                           facts: FuncFacts) -> Optional[LockId]:
        """Returns the lock id when the statement is a bare
        ``lock.acquire()`` — the caller extends the held set for the
        rest of the block."""
        call = stmt.value
        if isinstance(call, ast.Call) and \
                isinstance(call.func, ast.Attribute) and \
                call.func.attr == "acquire":
            lk = self._lock_id(fi.ms, call.func.value, fi.class_name)
            if lk is not None:
                lid, kind = lk
                safe = self._release_in_following_finally(
                    body, i, call.func.value)
                facts.acquires.append(Acquire(
                    lock=lid, kind=kind, line=stmt.lineno, style="bare",
                    locks_before=locks, safe_release=safe))
                if fi.is_async:
                    facts.blocking.append(BlockingCall(
                        desc=f"{self._render(call.func.value)}.acquire() "
                             f"(threading lock)", line=stmt.lineno))
                return lid
        self._scan_expr(fi, call, locks, local_types, facts, local_callables)
        return None

    def _release_in_following_finally(self, body: Sequence[ast.stmt],
                                      i: int, recv: ast.AST) -> bool:
        """``x.acquire()`` directly followed by ``try: ... finally:
        x.release()`` is the safe bare-acquire idiom."""
        want = self._render(recv)
        if i + 1 < len(body) and isinstance(body[i + 1], ast.Try):
            for s in body[i + 1].finalbody:  # type: ignore[union-attr]
                for node in ast.walk(s):
                    if isinstance(node, ast.Call) and \
                            isinstance(node.func, ast.Attribute) and \
                            node.func.attr == "release" and \
                            self._render(node.func.value) == want:
                        return True
        return False

    def _reads_key(self, expr: ast.AST, key: AttrKey,
                   cls: Optional[str], local_types: Dict[str, str]) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Attribute) and \
                    self._chain_key(node, cls, local_types) == key:
                return True
        return False

    def _render(self, node: ast.AST) -> str:
        try:
            return ast.unparse(node)
        except Exception:  # pragma: no cover
            return "<expr>"

    # -- expression scan: calls, mutator methods, loop touches ---------------
    def _scan_expr(self, fi: FuncInfo, expr: ast.AST,
                   locks: FrozenSet[LockId], local_types: Dict[str, str],
                   facts: FuncFacts,
                   local_callables: Optional[Dict[str, Set[FuncNode]]] = None,
                   ) -> None:
        local_callables = local_callables or {}
        for node in self._walk_own(expr):
            if not isinstance(node, ast.Call):
                continue
            self._observe_call(fi, node, locks, local_types,
                               local_callables, facts)

    def _walk_own(self, root: ast.AST):
        """Walk an expression without descending into nested lambdas
        (their bodies are separate functions)."""
        stack = [root]
        while stack:
            node = stack.pop()
            if node is not root and isinstance(
                    node, (ast.Lambda, ast.FunctionDef,
                           ast.AsyncFunctionDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _observe_call(self, fi: FuncInfo, call: ast.Call,
                      locks: FrozenSet[LockId],
                      local_types: Dict[str, str],
                      local_callables: Dict[str, Set[FuncNode]],
                      facts: FuncFacts) -> None:
        func = call.func
        # direct call of a local/imported function or closure candidate
        if isinstance(func, ast.Name):
            if func.id in local_callables:
                for cand in local_callables[func.id]:
                    if cand in self.funcs:
                        facts.calls.append((self.funcs[cand], locks))
                return
            ref = self._func_ref(fi.ms, func, fi.class_name)
            if ref is not None and ref in self.funcs:
                facts.calls.append((self.funcs[ref], locks))
            return
        if not isinstance(func, ast.Attribute):
            return
        attr = func.attr
        recv = func.value
        # mutator method on a tracked attr chain: self.x.append(...)
        if attr in MUTATORS:
            key = self._chain_key(recv, fi.class_name, local_types)
            if key is not None:
                facts.accesses.append(Access(
                    key=key, line=call.lineno, mutation=True,
                    desc=f"{self._render(recv)}.{attr}(...)", locks=locks))
        rtype = self._recv_type(recv, fi.class_name, local_types)
        # asyncio loop-state touches (judged per-root later)
        if rtype in ("ext:aevent", "ext:aqueue", "ext:atask", "ext:aloop"):
            flagged = {
                "ext:aevent": {"set", "clear"},
                "ext:aqueue": {"put_nowait", "get_nowait"},
                "ext:atask": {"cancel"},
                "ext:aloop": {"call_soon", "call_later", "call_at",
                              "create_task", "stop"},
            }[rtype]
            if attr in flagged:
                facts.loop_touches.append(LoopTouch(
                    desc=f"{self._render(recv)}.{attr}(...)",
                    line=call.lineno))
        # blocking calls inside coroutine bodies (ST903)
        if fi.is_async:
            self._observe_blocking(fi, call, rtype, facts)
        # typed method resolution
        if rtype is not None and not rtype.startswith("ext:"):
            method = self.methods.get((rtype, attr))
            if method is not None:
                facts.calls.append((self.funcs[method], locks))
                return
            # stored-callback attr on a typed receiver
            for cand in self.cb_by_class_attr.get((rtype, attr), ()):
                if cand in self.funcs:
                    facts.calls.append((self.funcs[cand], locks))
            if (rtype, attr) in self.cb_by_class_attr:
                return
        # callback registry by bare attr name (engine.on_tokens(...))
        if rtype is None and attr in self.cb_by_attr:
            for cand in self.cb_by_attr[attr]:
                if cand in self.funcs:
                    facts.calls.append((self.funcs[cand], locks))

    _BLOCKING_DOTTED = {
        "time.sleep", "os.system", "os.wait", "os.waitpid",
        "subprocess.run", "subprocess.call", "subprocess.check_call",
        "subprocess.check_output", "subprocess.Popen",
        "urllib.request.urlopen", "requests.get", "requests.post",
        "socket.create_connection",
    }

    def _observe_blocking(self, fi: FuncInfo, call: ast.Call,
                          rtype: Optional[str], facts: FuncFacts) -> None:
        d = dotted_name(call.func) or ""
        attr = call.func.attr if isinstance(call.func, ast.Attribute) else d
        if d in self._BLOCKING_DOTTED:
            facts.blocking.append(BlockingCall(desc=f"{d}(...)",
                                               line=call.lineno))
            return
        if rtype == "ext:queue" and attr in ("get", "put", "join"):
            facts.blocking.append(BlockingCall(
                desc=f"{self._render(call.func.value)}.{attr}(...) "
                     f"(blocking queue op)", line=call.lineno))
        elif rtype == "ext:tevent" and attr == "wait":
            facts.blocking.append(BlockingCall(
                desc=f"{self._render(call.func.value)}.wait(...) "
                     f"(threading.Event)", line=call.lineno))
        elif rtype == "ext:thread" and attr == "join":
            facts.blocking.append(BlockingCall(
                desc=f"{self._render(call.func.value)}.join(...)",
                line=call.lineno))
        elif rtype in ("ext:lock", "ext:rlock") and attr == "acquire":
            facts.blocking.append(BlockingCall(
                desc=f"{self._render(call.func.value)}.acquire() "
                     f"(threading lock)", line=call.lineno))
        elif rtype == "ext:cfuture" and attr == "result":
            facts.blocking.append(BlockingCall(
                desc=f"{self._render(call.func.value)}.result(...)",
                line=call.lineno))

    # -- phase 5: propagation -------------------------------------------------
    def _propagate(self) -> None:
        seen: Set[Tuple[FuncNode, RootId, FrozenSet[LockId]]] = set()
        work: List[Tuple[FuncNode, RootId, FrozenSet[LockId]]] = []
        for rid, seeds in self.roots.items():
            for fn in seeds:
                work.append((fn, rid, frozenset()))
        self._run_worklist(work, seen)
        # implicit main path: every function no explicit root reaches is
        # callable from the interpreter's main thread. Seeded AFTER the
        # explicit phase so signal-handler-only code (reachable solely
        # from its registration) is NOT blanket-attributed to main —
        # that distinction is exactly what ST904 measures.
        work = [
            (fn, MAIN_ROOT, frozenset()) for fn in self.facts
            if fn not in self.func_roots and not self.funcs[fn].is_async
        ]
        self._run_worklist(work, seen)

    def _run_worklist(
        self,
        work: List[Tuple[FuncNode, RootId, FrozenSet[LockId]]],
        seen: Set[Tuple[FuncNode, RootId, FrozenSet[LockId]]],
    ) -> None:
        while work:
            fn, rid, entry = work.pop()
            state = (fn, rid, entry)
            if state in seen or fn not in self.facts:
                continue
            seen.add(state)
            self.func_roots.setdefault(fn, set()).add(rid)
            fi = self.funcs[fn]
            facts = self.facts[fn]
            for acc in facts.accesses:
                eff = entry | acc.locks
                self.attr_map.setdefault(acc.key, {}).setdefault(
                    rid, []).append((acc, eff))
            for acq in facts.acquires:
                held = entry | acq.locks_before
                self.lock_holders.setdefault(acq.lock, {}).setdefault(
                    rid, []).append((acq, fi))
                for h in held:
                    if h != acq.lock:
                        self.order_edges.setdefault((h, acq.lock), (acq, fi))
            for touch in facts.loop_touches:
                self.loop_touch_hits.append((touch, fi, rid))
            for callee_fi, call_locks in facts.calls:
                if callee_fi.is_async and rid != LOOP_ROOT:
                    continue  # coroutine body executes on the loop
                work.append((callee_fi.node, rid, entry | call_locks))

    # -- queries --------------------------------------------------------------
    def describe_root(self, rid: RootId) -> str:
        kind, what = rid
        if kind == "thread":
            return f"thread root `{what}`"
        if kind == "signal":
            return f"signal handler `{what}`"
        if kind == "loop":
            return "the asyncio event loop"
        if kind == "main":
            return "the main path"
        return f"cross-thread callers of `{what}` (thread-owning class)"

    def lock_name(self, lid: LockId) -> str:
        return f"{lid[0]}.{lid[1]}"
