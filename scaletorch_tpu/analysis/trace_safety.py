"""ST2xx — trace-safety inside jit scopes.

Everything here is a "works in eager, breaks or silently degrades under
jit" hazard. The pass walks every traced scope (see ``scopes``) with a
taint tracker seeded from the scope's non-static parameters:

ST201  Python ``if``/``while``/``assert`` on a traced value — raises
       TracerBoolConversionError at best, silently bakes one branch in
       at worst; use ``lax.cond``/``lax.select``/``jnp.where``
ST202  ``float()``/``int()``/``bool()``/``.item()``/``.tolist()`` on a
       traced value — a device→host sync that blocks dispatch
ST203  ``np.*`` call on a traced value — falls back to host numpy,
       breaking the trace (use ``jnp``)
ST204  ``print`` in a traced scope — runs once at trace time, not per
       step; use ``jax.debug.print``
ST205  wall-clock reads (``time.time``/``perf_counter``/
       ``datetime.now``) in a traced scope — a constant baked in at
       trace time

Branching on static facts (``.shape``/``.dtype``/``len()``/``is None``)
is idiomatic and never flagged — that is the taint tracker's job.
"""

from __future__ import annotations

import ast
from typing import List, Set

from .core import Finding, SourceModule
from .scopes import ModuleScopes, ProjectIndex, TaintTracker, dotted_name, tail_name

_CAST_CALLS = {"float", "int", "bool", "complex"}
_SYNC_METHODS = {"item", "tolist", "block_until_ready", "copy_to_host_async"}
_CLOCK_CALLS = {
    "time.time", "time.perf_counter", "time.monotonic", "time.process_time",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
}


def _numpy_aliases(sm: SourceModule) -> Set[str]:
    aliases: Set[str] = set()
    for node in ast.walk(sm.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "numpy":
                    aliases.add(alias.asname or "numpy")
    return aliases


def run(index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for ms in index.scopes.values():
        findings.extend(_check_module(ms))
    return findings


def _check_module(ms: ModuleScopes) -> List[Finding]:
    out: List[Finding] = []
    np_aliases = _numpy_aliases(ms.sm)
    for fn, info in ms.traced_functions():
        if isinstance(fn, ast.Lambda):
            continue  # no statements to branch on; calls are caught in parents
        tracker = TaintTracker(fn, info)
        _walk_body(ms, fn.body, tracker, np_aliases, out)
    return out


def _walk_body(
    ms: ModuleScopes,
    body: List[ast.stmt],
    tracker: TaintTracker,
    np_aliases: Set[str],
    out: List[Finding],
) -> None:
    for stmt in body:
        # nested defs are traced scopes of their own pass (fresh params)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        _check_calls(ms, stmt, tracker, np_aliases, out)
        if isinstance(stmt, ast.If):
            if tracker.is_tainted(stmt.test):
                out.append(_finding(
                    ms, stmt, "ST201", "error",
                    "Python `if` on a traced value inside a jit scope — "
                    "use lax.cond / lax.select / jnp.where",
                ))
            _walk_body(ms, stmt.body, tracker, np_aliases, out)
            _walk_body(ms, stmt.orelse, tracker, np_aliases, out)
        elif isinstance(stmt, ast.While):
            if tracker.is_tainted(stmt.test):
                out.append(_finding(
                    ms, stmt, "ST201", "error",
                    "Python `while` on a traced value inside a jit scope — "
                    "use lax.while_loop / lax.fori_loop",
                ))
            _walk_body(ms, stmt.body, tracker, np_aliases, out)
            _walk_body(ms, stmt.orelse, tracker, np_aliases, out)
        elif isinstance(stmt, ast.Assert):
            if tracker.is_tainted(stmt.test):
                out.append(_finding(
                    ms, stmt, "ST201", "error",
                    "`assert` on a traced value inside a jit scope — "
                    "use checkify or debug.check",
                ))
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            tracker.observe(stmt)
            _walk_body(ms, stmt.body, tracker, np_aliases, out)
            _walk_body(ms, stmt.orelse, tracker, np_aliases, out)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            tracker.observe(stmt)
            _walk_body(ms, stmt.body, tracker, np_aliases, out)
        elif isinstance(stmt, ast.Try):
            _walk_body(ms, stmt.body, tracker, np_aliases, out)
            for handler in stmt.handlers:
                _walk_body(ms, handler.body, tracker, np_aliases, out)
            _walk_body(ms, stmt.orelse, tracker, np_aliases, out)
            _walk_body(ms, stmt.finalbody, tracker, np_aliases, out)
        else:
            tracker.observe(stmt)


def _check_calls(
    ms: ModuleScopes,
    stmt: ast.stmt,
    tracker: TaintTracker,
    np_aliases: Set[str],
    out: List[Finding],
) -> None:
    # look at expressions belonging to this statement only, not nested
    # compound bodies (those are walked with their own taint state)
    headers: List[ast.AST] = []
    if isinstance(stmt, (ast.If, ast.While)):
        headers = [stmt.test]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        headers = [stmt.iter]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        headers = [item.context_expr for item in stmt.items]
    elif isinstance(stmt, ast.Try):
        headers = []
    else:
        headers = [stmt]
    for header in headers:
        for node in ast.walk(header):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func) or ""
            t = tail_name(node.func)
            args_tainted = any(tracker.is_tainted(a) for a in node.args) or any(
                tracker.is_tainted(kw.value) for kw in node.keywords
            )
            if isinstance(node.func, ast.Name) and t in _CAST_CALLS and args_tainted:
                out.append(_finding(
                    ms, node, "ST202", "error",
                    f"`{t}()` on a traced value forces a device→host sync "
                    "inside a jit scope",
                ))
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _SYNC_METHODS
                and tracker.is_tainted(node.func.value)
            ):
                out.append(_finding(
                    ms, node, "ST202", "error",
                    f"`.{node.func.attr}()` on a traced value forces a "
                    "device→host sync inside a jit scope",
                ))
            elif (
                np_aliases
                and "." in d
                and d.split(".", 1)[0] in np_aliases
                and args_tainted
            ):
                out.append(_finding(
                    ms, node, "ST203", "error",
                    f"`{d}()` on a traced value runs host numpy inside a jit "
                    "scope — use jnp",
                ))
            elif isinstance(node.func, ast.Name) and t == "print":
                out.append(_finding(
                    ms, node, "ST204", "warning",
                    "`print` inside a jit scope runs once at trace time — "
                    "use jax.debug.print",
                ))
            elif d in _CLOCK_CALLS:
                out.append(_finding(
                    ms, node, "ST205", "warning",
                    f"`{d}()` inside a jit scope is baked in as a trace-time "
                    "constant",
                ))


def _finding(
    ms: ModuleScopes, node: ast.AST, code: str, severity: str, message: str
) -> Finding:
    return Finding(
        file=ms.sm.rel, line=getattr(node, "lineno", 1), code=code,
        severity=severity, message=message,
    )
