"""In-process benchmark runner shared by bench.py, tools/, and the sweep.

Role parity with the measurement core of reference
``scripts/benchmark_comprehensive.py:337-470`` (run_config + metric
parsing) and ``tools/bench_single.py``: build a Trainer from a config,
run warmup (compile) steps, time the steady window, report
tokens/s / tokens/s/chip / MFU / final loss / device memory.

Hermetic: synthetic data, random init — identical math/comms to real
training (the reference benchmarks with a real dataset but the step work
is the same; synthetic keeps the harness self-contained on any chip).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional


def benchmark_config(
    cfg, warmup: int = 3, steps: int = 10, progress=None
) -> Dict[str, Any]:
    """Run one timed benchmark for a ScaleTorchTPUArguments config.

    Returns {tokens_per_second, tokens_per_second_per_chip, mfu, loss,
    step_time_s, memory_gb, num_params, num_chips}. ``progress`` is an
    optional callback taking a stage name ("trainer_built", "compiled",
    "timed") — bench.py's hang classifier.
    """
    import jax

    from scaletorch_tpu.trainer.trainer import Trainer
    from scaletorch_tpu.utils.device import device_memory_stats
    from scaletorch_tpu.utils.misc import get_mfu, get_num_params

    progress = progress or (lambda stage: None)
    trainer = Trainer(cfg)
    progress("trainer_built")
    try:
        # Drive trainer.step (the public per-step API, not trainer.train)
        # so timing excludes the metrics/logging machinery and the final
        # loss is always captured.
        m = {}
        for _ in range(warmup):  # compile + stabilise
            m = trainer.step()
        jax.block_until_ready(trainer.params)
        progress("compiled")

        t0 = time.perf_counter()
        for _ in range(steps):
            m = trainer.step()
        # Completion barrier: a host readback of the final loss (which
        # data-depends on every step's param update) cannot return before
        # the work is done, unlike block_until_ready on some remote-tunnel
        # backends.
        final_loss = float(m["loss"])
        jax.block_until_ready(trainer.params)
        elapsed = time.perf_counter() - t0
        progress("timed")

        tok_s = trainer.loader.tokens_per_step * steps / elapsed
        num_chips = len(jax.devices())
        n_params = get_num_params(trainer.params)
        is_moe = cfg.model_type == "qwen3_moe"
        # MoE MFU counts active params per token (reference README.md:123-128).
        mfu_params = trainer.model_cfg.num_active_params() if is_moe else n_params
        mfu = get_mfu(
            tok_s,
            mfu_params,
            trainer.model_cfg.num_hidden_layers,
            trainer.model_cfg.num_attention_heads,
            trainer.model_cfg.actual_head_dim,
            cfg.sequence_length,
            num_chips=num_chips,
        )
        mem = device_memory_stats()
        return {
            "tokens_per_second": round(tok_s, 1),
            "tokens_per_second_per_chip": round(tok_s / num_chips, 1),
            "mfu": round(mfu, 2),
            "loss": round(final_loss, 4),
            "step_time_s": round(elapsed / steps, 4),
            "memory_gb": round(mem["peak_bytes_in_use"] / 1e9, 2)
            if mem.get("peak_bytes_in_use")
            else None,
            "num_params": n_params,
            "num_chips": num_chips,
        }
    finally:
        trainer.close()


def make_bench_args(
    model: str,
    *,
    seq: int,
    micro_bs: int = 1,
    grad_accum: int = 1,
    gc: bool = False,
    tp: int = 1,
    pp: int = 1,
    dp: int = 1,
    cp: int = 1,
    ep: int = 1,
    sp: bool = False,
    pp_engine: str = "afab",
    dtype: str = "bfloat16",
    remat_policy: str = "nothing_saveable",
    extra: Optional[Dict[str, Any]] = None,
):
    """Build ScaleTorchTPUArguments for a named preset + run shape
    (the kwargs mirror one row of the reference CONFIGS table,
    benchmark_comprehensive.py:55-174)."""
    from scaletorch_tpu.config import ScaleTorchTPUArguments
    from scaletorch_tpu.models.presets import preset

    kwargs = dict(
        preset(model),
        sequence_length=seq,
        micro_batch_size=micro_bs,
        gradient_accumulation_steps=grad_accum,
        gradient_checkpointing=gc,
        remat_policy=remat_policy,
        tensor_parallel_size=tp,
        pipeline_parallel_size=pp,
        data_parallel_size=dp,
        context_parallel_size=cp,
        expert_parallel_size=ep,
        sequence_parallel=sp,
        pp_engine=pp_engine,
        synthetic_data=True,
        dtype=dtype,
        max_grad_norm=1.0,
        log_frequency=10_000,  # silence per-step logging during timing
        total_train_steps=1_000_000,  # trainer.train(num_steps=...) drives
    )
    kwargs.update(extra or {})
    return ScaleTorchTPUArguments(**kwargs)
