"""jax API compatibility layer — one module, imported once, at package init.

The container's jax 0.4.37 predates three APIs the SPMD stack is written
against (the "old-jax compat guards" that took the quick tier 273->382
lived inline in rms_norm / MeshManager / pvary_missing; this module is
that pattern promoted to a single backfill point):

  * ``jax.shard_map``          — lives at ``jax.experimental.shard_map``
                                 in 0.4.x. Backfilled with
                                 ``check_rep=False``: the old replication
                                 checker predates several primitives'
                                 rep rules (pallas_call, all_to_all in
                                 some layouts) and its rejection is a
                                 strict superset of what the new
                                 check_vma machinery enforces.
  * ``jax.lax.pvary``          — the VMA varying-axes marker. On builds
                                 without the VMA type system there is no
                                 bookkeeping to update: identity.
  * ``jax.typeof``             — backfilled with ``jax.core.get_aval``;
                                 the returned aval has no ``.vma``, which
                                 every caller already tolerates via
                                 ``getattr(typeof(x), "vma", ())``.

Gradient semantics: the one place where identity-``pvary`` is NOT enough
is differentiating *inside* a ``shard_map`` body through a forward
``psum`` (the Megatron g-function sites: row-parallel outputs, the
vocab-parallel embedding/CE reductions). New jax's VMA machinery gives
the cotangent of the psum *input* as the (replicated) output cotangent —
a collective-free backward. Old shard_map without rep rewriting instead
transposes psum to psum, inflating every upstream gradient by the axis
size (measured, not theory: a 2-rank tp mesh yields exactly 2x). The fix
is ``psum_replicated_ct`` below: the same psum, with the new-jax
cotangent rule stated explicitly as a ``custom_vjp`` on old builds. Its
correctness requires the cotangent arriving from downstream to be
replicated over ``axis`` — true at every call site, because everything
downstream of these reductions (residual stream, loss) is replicated
over tp. On new jax it IS ``jax.lax.psum`` (the custom_vjp would only
hide the native VMA bookkeeping).

Import-order contract: ``scaletorch_tpu/__init__`` imports this module
before any other package module, so every caller (and the test suite,
which imports the package via conftest) sees one consistent jax surface.
"""

from __future__ import annotations

from functools import partial

import jax

# Feature probes BEFORE any backfill: these flags describe the real jax,
# not the shimmed one.
HAS_VMA: bool = hasattr(jax.lax, "pvary")
HAS_SHARD_MAP: bool = hasattr(jax, "shard_map")


def _backfill_shard_map() -> None:
    from jax.experimental.shard_map import shard_map as _old_shard_map

    def shard_map(f=None, *, mesh, in_specs, out_specs, **kwargs):
        """``jax.shard_map`` signature over the 0.4.x implementation.

        ``check_vma``/``axis_names`` (new-jax knobs) are accepted and
        dropped; replication checking runs as ``check_rep=False`` (see
        module docstring).
        """
        kwargs.pop("check_vma", None)
        kwargs.pop("axis_names", None)
        if kwargs:
            # Never swallow semantics: an unknown (likely newer-jax)
            # kwarg must fail loudly, not run with different behavior.
            raise TypeError(
                f"shard_map backfill got unsupported kwargs "
                f"{sorted(kwargs)} on jax {jax.__version__}"
            )
        if f is None:  # decorator / partial-application form
            return partial(
                shard_map, mesh=mesh, in_specs=in_specs,
                out_specs=out_specs, **kwargs,
            )
        return _old_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )

    jax.shard_map = shard_map


def _backfill_pvary() -> None:
    def pvary(x, axis_name):
        """No VMA type system to update on this build: identity."""
        del axis_name
        return x

    jax.lax.pvary = pvary


def _backfill_typeof() -> None:
    def typeof(x):
        return jax.core.get_aval(x)

    jax.typeof = typeof


def _backfill_axis_size() -> None:
    def axis_size(axis_name):
        # The pre-0.5 idiom: psum of a concrete 1 over a named axis is
        # evaluated eagerly to the (static) axis size, under tracing too.
        return jax.lax.psum(1, axis_name)

    jax.lax.axis_size = axis_size


if not HAS_SHARD_MAP:
    _backfill_shard_map()
if not HAS_VMA:
    _backfill_pvary()
if not hasattr(jax, "typeof"):
    _backfill_typeof()
if not hasattr(jax.lax, "axis_size"):
    _backfill_axis_size()


# ---------------------------------------------------------------------------
# psum with the new-jax cotangent rule, explicit.
# ---------------------------------------------------------------------------
if HAS_VMA:
    def psum_replicated_ct(x, axis):
        """On VMA builds this is exactly ``jax.lax.psum`` — the type
        system already derives the replicated-cotangent backward."""
        return jax.lax.psum(x, axis)
else:
    @partial(jax.custom_vjp, nondiff_argnums=(1,))
    def psum_replicated_ct(x, axis):
        return jax.lax.psum(x, axis)

    def _psum_fwd(x, axis):
        return jax.lax.psum(x, axis), None

    def _psum_bwd(axis, _res, ct):
        # The output is replicated over ``axis`` and so (at every call
        # site — see module docstring) is its cotangent: each shard's
        # contribution to the sum sees the full output cotangent.
        return (ct,)

    psum_replicated_ct.defvjp(_psum_fwd, _psum_bwd)


def pallas_tpu_compiler_params(pltpu_module, **kwargs):
    """``pltpu.CompilerParams`` was ``TPUCompilerParams`` before jax 0.6;
    build whichever this jax ships.

    ``dimension_semantics`` entries are normalized to the string spelling
    ("parallel"/"arbitrary"): old-jax Mosaic lowering interpolates each
    entry into an MLIR attribute verbatim, so the ``pltpu.PARALLEL``
    /``ARBITRARY`` pipeline objects fail attribute parsing there, while
    the strings are accepted by every jax we support.
    """
    dims = kwargs.get("dimension_semantics")
    if dims is not None:
        by_id = {
            id(getattr(pltpu_module, name, None)): name.lower()
            for name in ("PARALLEL", "ARBITRARY", "CORE_PARALLEL")
        }
        kwargs["dimension_semantics"] = tuple(
            by_id.get(id(d), d) for d in dims
        )
    cls = getattr(pltpu_module, "CompilerParams", None)
    if cls is None:
        cls = pltpu_module.TPUCompilerParams
    return cls(**kwargs)
