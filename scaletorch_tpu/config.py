"""Training configuration: composed dataclasses + CLI parsing.

Parity with reference scaletorch/trainer/config.py:31-461 — eight argument
dataclasses (Data/Model/Parallel/LrScheduler/Optimizer/Training/Checkpoint/
Logging) composed by multiple inheritance into one ``ScaleTorchTPUArguments``
parsed by HF ``HfArgumentParser`` (reference train.py:61-62). Validation
invariants kept identical:

  * every parallel dim >= 1; pp_engine in {"1f1b", "afab"} (config.py:155-173)
  * seq_len % cp_size == 0 (config.py:425-433)
  * global_batch_size == dp * micro_batch_size * grad_accum (config.py:435-439)
  * world_size == dp * pp * cp * ep * tp (config.py:444-460)
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class DataArguments:
    dataset_name: Optional[str] = field(
        default=None,
        metadata={"help": "HF hub dataset name or local path (json/jsonl/dir)."},
    )
    dataset_text_key: str = field(
        default="text", metadata={"help": "Column holding raw text."}
    )
    tokenizer_name_or_path: Optional[str] = field(
        default=None, metadata={"help": "Tokenizer; defaults to model path."}
    )
    sequence_length: int = field(
        default=1024, metadata={"help": "Training sequence length."}
    )
    tokenize_strategy: str = field(
        default="concat_chunk",
        metadata={"help": "Registered tokenize strategy (default concat+chunk)."},
    )
    num_proc: int = field(default=4, metadata={"help": "Tokenization processes."})
    synthetic_data: bool = field(
        default=False,
        metadata={"help": "Use an on-device synthetic token stream (benchmarks)."},
    )
    synthetic_vocab_size: Optional[int] = field(
        default=None,
        metadata={"help": "Cap the synthetic stream's sampled token ids "
                          "below the model vocab (default: model vocab)."},
    )
    data_read_retries: int = field(
        default=2,
        metadata={"help": "Retries (exponential backoff) around each "
                          "step-batch read before the region is "
                          "skipped-and-logged (storage-backed token "
                          "arrays can be transiently unreadable)."},
    )
    data_retry_base_delay: float = field(
        default=0.05,
        metadata={"help": "First batch-read retry delay in seconds; "
                          "doubles per attempt."},
    )
    data_max_skipped_batches: int = field(
        default=16,
        metadata={"help": "Abort when more than this many step batches "
                          "stay unreadable after retries (a broken — not "
                          "flaky — data source must not be silently "
                          "consumed as skips). 0 = unlimited."},
    )


@dataclass
class ModelArguments:
    model_name_or_path: Optional[str] = field(
        default=None,
        metadata={"help": "HF checkpoint dir/name to configure + load from."},
    )
    load_pretrained_weights: bool = field(
        default=False,
        metadata={
            "help": "Load HF safetensors weights from model_name_or_path "
            "(otherwise random init with its architecture; reference "
            "random-init fallback, checkpoint.py:90-97)."
        },
    )
    model_type: str = field(
        default="llama",
        metadata={"help": "llama | qwen3 | qwen3_moe | gpt_moe | lenet | mingpt"},
    )
    # Architecture overrides (used when model_name_or_path is unset).
    hidden_size: int = 2048
    intermediate_size: Optional[int] = None
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    num_key_value_heads: Optional[int] = None
    head_dim: Optional[int] = None
    vocab_size: int = 32000
    max_position_embeddings: int = 32768
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-6
    tie_word_embeddings: bool = False
    attention_backend: str = field(
        default="auto",
        metadata={"help": "auto | flash | flash_jax | ring | ulysses | "
                          "sdpa — with cp > 1, auto picks ring vs "
                          "ulysses from mesh topology + head geometry "
                          "(parallel/cp_select.resolve_cp_backend, "
                          "attested by AOT_CP_CROSSOVER.json); without "
                          "CP it resolves like the reference "
                          "(FLASH_ATTEN->flash, else sdpa). flash_jax "
                          "is jax's reference TPU kernel for on-chip "
                          "A/B; an explicit backend is always honored."},
    )
    # MoE knobs (qwen3_moe / gpt_moe)
    num_experts: int = 8
    num_experts_per_tok: int = 2
    moe_intermediate_size: Optional[int] = None
    moe_capacity_factor: float = 1.25
    router_aux_loss_coef: float = 0.001
    router_z_loss_coef: float = 0.0
    moe_dispatch: str = field(
        default="auto",
        metadata={"help": "auto | einsum | index — capacity-dispatch token "
                          "movement. einsum = GShard one-hot (dense MXU, "
                          "O(N·E·C·H)); index = scatter/gather of the "
                          "O(N·k·H) moving rows. auto picks index at every "
                          "expert count (the one-hot cost is E-independent "
                          "and always the larger compile — "
                          "AOT_DISPATCH_CROSSOVER.json)."},
    )
    # Interleaved dense/sparse architecture (HF Qwen3MoeConfig knobs):
    # layer i is sparse iff i not in mlp_only_layers and (i+1) %
    # decoder_sparse_step == 0. Defaults leave the architecture to the HF
    # config when --model_name_or_path is set.
    mlp_only_layers: Optional[List[int]] = field(
        default=None,
        metadata={"help": "Layer indices forced to a dense SwiGLU MLP "
                          "(qwen3_moe; space-separated). Omitted = keep the "
                          "HF checkpoint's value; pass a single -1 to "
                          "explicitly CLEAR a checkpoint's list (argparse "
                          "nargs='+' cannot express an empty list)."},
    )
    decoder_sparse_step: Optional[int] = field(
        default=None,
        metadata={"help": "A qwen3_moe layer is sparse only when (idx+1) "
                          "is divisible by this (1 = every layer sparse). "
                          "Omitted = keep the HF checkpoint's value; an "
                          "explicit value (including 1) overrides it."},
    )


@dataclass
class ParallelArguments:
    data_parallel_size: int = field(default=1, metadata={"help": "DP degree."})
    tensor_parallel_size: int = field(default=1, metadata={"help": "TP degree."})
    pipeline_parallel_size: int = field(default=1, metadata={"help": "PP degree."})
    context_parallel_size: int = field(default=1, metadata={"help": "CP degree."})
    cp_layout: str = field(
        default="zigzag",
        metadata={"help": "contiguous | zigzag — CP sequence-shard layout. "
                          "zigzag stripes the sequence so every ring rank "
                          "does equal causal work (parallel/zigzag.py); "
                          "contiguous matches the reference's skewed ring."},
    )
    expert_parallel_size: int = field(default=1, metadata={"help": "EP degree."})
    # Default differs from the reference (pipeline_parallel_engine='1f1b',
    # config.py:155-173) BY MEASUREMENT: in the SPMD design afab already
    # has 1F1B's bubble fraction and is ~1.25x faster than the chunked
    # memory-bounded schedule — see tools/pp_schedule_compare.py.
    pp_engine: str = field(
        default="afab",
        metadata={"help": "Pipeline schedule: 'afab' = one fwd+bwd SPMD "
                          "pipeline (1F1B-equivalent bubble (pp-1)/(accum+pp-1), "
                          "O(accum) boundary-activation memory); "
                          "'interleaved' = virtual-stage circular pipeline "
                          "(bubble cut ~pp_virtual_stages x, the SPMD form "
                          "of Megatron interleaved 1F1B; needs "
                          "num_hidden_layers %% (pp*vpp) == 0 and costs "
                          "vpp x the boundary-activation memory); "
                          "'memory_chunked' = chunked accumulation (1F1B's "
                          "O(pp) boundary memory; 1.28x slower at pp4/accum8, "
                          "matching the 1.27x tick-count prediction — "
                          "tools/pp_schedule_compare.py). "
                          "'1f1b' is accepted as a reference-compat alias for "
                          "memory_chunked and WARNS: under SPMD lockstep it "
                          "is not a throughput win. Prefer interleaved when "
                          "layers divide evenly and memory allows, else afab."},
    )
    pp_virtual_stages: int = field(
        default=1,
        metadata={"help": "Virtual stages per pp rank for "
                          "pp_engine='interleaved' (Megatron "
                          "virtual-pipeline chunks). Each rank owns this "
                          "many non-contiguous layer chunks; the pipeline "
                          "bubble shrinks by ~this factor. >= 2 with the "
                          "interleaved engine, or 0 = auto (largest "
                          "divisor <= 4 of the per-rank layer count); "
                          "1 otherwise."},
    )
    sequence_parallel: bool = field(
        default=False, metadata={"help": "Megatron-style SP over the tp axis."}
    )
    num_microbatches: Optional[int] = field(
        default=None,
        metadata={"help": "PP microbatches; defaults to gradient_accumulation_steps."},
    )
    grad_allreduce_dtype: str = field(
        default="fp32",
        metadata={"help": "fp32 | bf16 | int8 — wire format of the "
                          "gradient mean over grad_allreduce_axis (the "
                          "bandwidth-bound DCN edge on multi-host "
                          "meshes). int8 is the block-scaled quantized "
                          "all-reduce (ops/quantized_collectives.py, "
                          "~4x fewer bytes; grad cosine vs fp32 >= "
                          "0.999); bf16 halves bytes with a plain cast. "
                          "Other data axes and the tp/pp psums stay "
                          "fp32 (they ride ICI)."},
    )
    grad_allreduce_axis: str = field(
        default="dp",
        metadata={"help": "Mesh axis the quantized/bf16 gradient mean "
                          "runs over ('dp' or 'cp'); the remaining data "
                          "axes reduce in fp32 first."},
    )
    grad_allreduce_block_size: int = field(
        default=256,
        metadata={"help": "Elements per absmax-scale block for "
                          "grad_allreduce_dtype='int8' (fp32 scale per "
                          "block: overhead 4/block_size)."},
    )

    def __post_init__(self) -> None:
        for name in (
            "data_parallel_size",
            "tensor_parallel_size",
            "pipeline_parallel_size",
            "context_parallel_size",
            "expert_parallel_size",
        ):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} must be >= 1, got {getattr(self, name)}")
        if self.pp_engine not in ("afab", "memory_chunked", "1f1b",
                                  "interleaved"):
            raise ValueError(
                "pp_engine must be 'afab', 'interleaved', 'memory_chunked' "
                f"or the reference-compat alias '1f1b', got {self.pp_engine!r}"
            )
        if self.pp_engine == "interleaved":
            if self.pp_virtual_stages < 2 and self.pp_virtual_stages != 0:
                raise ValueError(
                    "pp_engine='interleaved' needs pp_virtual_stages >= 2, "
                    "or 0 for auto (largest divisor <= 4 of the per-rank "
                    f"layer count); got {self.pp_virtual_stages}. With 1 "
                    "virtual stage per rank the schedule IS afab — use "
                    "pp_engine='afab'"
                )
        elif self.pp_virtual_stages != 1:
            raise ValueError(
                f"pp_virtual_stages={self.pp_virtual_stages} requires "
                f"pp_engine='interleaved' (got {self.pp_engine!r})"
            )
        if self.pp_engine == "1f1b":
            # Honest-semantics guard (VERDICT r3 weak #3): this framework's
            # chunked schedule matches 1F1B's MEMORY bound, not its
            # schedule — under SPMD lockstep it is measured ~1.28x
            # SLOWER than afab (tools/pp_schedule_compare.py). An operator
            # porting reference configs must not get that regression
            # silently under the familiar flag name.
            self.pp_engine = "memory_chunked"
            if self.pipeline_parallel_size > 1:
                import warnings

                warnings.warn(
                    "pp_engine='1f1b' selects the memory_chunked schedule: "
                    "it bounds boundary activations at O(pp) like 1F1B but "
                    "is SLOWER than 'afab' (measured 1.28x at pp4/accum8, "
                    "matching the 1.27x tick-count prediction — "
                    "tools/pp_schedule_compare.py; afab already has 1F1B's "
                    "bubble fraction under SPMD lockstep). Use "
                    "pp_engine='afab' — or 'interleaved' to CUT the bubble "
                    "— unless activation memory is the binding constraint; "
                    "use 'memory_chunked' to silence this warning.",
                    RuntimeWarning,
                    stacklevel=2,
                )
        if self.cp_layout not in ("contiguous", "zigzag"):
            raise ValueError(
                f"cp_layout must be 'contiguous' or 'zigzag', got {self.cp_layout!r}"
            )
        if self.sequence_parallel and self.tensor_parallel_size == 1:
            raise ValueError("sequence_parallel requires tensor_parallel_size > 1")
        if self.grad_allreduce_dtype not in ("fp32", "bf16", "int8"):
            raise ValueError(
                "grad_allreduce_dtype must be 'fp32', 'bf16' or 'int8', "
                f"got {self.grad_allreduce_dtype!r}"
            )
        if self.grad_allreduce_axis not in ("dp", "cp"):
            raise ValueError(
                "grad_allreduce_axis must be 'dp' or 'cp' (a gradient-mean "
                f"data axis), got {self.grad_allreduce_axis!r}"
            )
        if self.grad_allreduce_block_size < 8:
            raise ValueError(
                "grad_allreduce_block_size must be >= 8, got "
                f"{self.grad_allreduce_block_size}"
            )


@dataclass
class DistributedArguments:
    """Multi-host bootstrap knobs (reference dist/utils.py:78-143 init_dist).

    All optional: 'auto' detects SLURM/MPI/env launchers and stays
    single-process when none is present.
    """

    distributed_launcher: str = field(
        default="auto",
        metadata={"help": "auto | env | slurm | mpi | none — how to discover "
                          "the coordinator (reference init_dist launcher)."},
    )
    coordinator_address: Optional[str] = field(
        default=None,
        metadata={"help": "host:port of process 0 (env launcher); defaults to "
                          "JAX_COORDINATOR_ADDRESS or MASTER_ADDR:MASTER_PORT."},
    )
    num_processes: Optional[int] = field(
        default=None, metadata={"help": "Total process count (env launcher)."}
    )
    process_id: Optional[int] = field(
        default=None, metadata={"help": "This process's rank (env launcher)."}
    )

    def __post_init__(self) -> None:
        if self.distributed_launcher not in ("auto", "env", "slurm", "mpi", "none"):
            raise ValueError(
                f"distributed_launcher must be auto|env|slurm|mpi|none, "
                f"got {self.distributed_launcher!r}"
            )


@dataclass
class LrSchedulerArguments:
    lr_scheduler_type: str = field(
        default="cosine",
        metadata={"help": "linear | cosine | polynomial | step | onecycle | constant"},
    )
    warmup_steps: int = 0
    warmup_ratio: float = 0.0
    min_lr_ratio: float = 0.1
    step_size: int = 1000          # for 'step'
    step_gamma: float = 0.9        # for 'step'
    poly_power: float = 1.0        # for 'polynomial'


@dataclass
class OptimizerArguments:
    optimizer_name: str = field(
        default="adamw", metadata={"help": "adamw | adam | sgd | lamb | adafactor"}
    )
    learning_rate: float = 3e-4
    weight_decay: float = 0.01
    adam_beta1: float = 0.9
    adam_beta2: float = 0.95
    adam_epsilon: float = 1e-8
    max_grad_norm: float = 1.0
    momentum: float = 0.9  # sgd


@dataclass
class TrainingArguments:
    micro_batch_size: int = 1
    gradient_accumulation_steps: int = 1
    eval_frequency: int = field(
        default=0,
        metadata={"help": "Run validation every N optimizer steps (0 = off)."},
    )
    eval_steps: int = field(
        default=8, metadata={"help": "Validation batches per evaluation."}
    )
    eval_dataset_name: Optional[str] = field(
        default=None,
        metadata={"help": "Held-out dataset (json/jsonl/hub). Synthetic runs "
                          "use a disjoint synthetic stream when unset."},
    )
    global_batch_size: Optional[int] = field(
        default=None,
        metadata={"help": "If set, must equal dp * micro_batch_size * grad_accum."},
    )
    total_train_steps: int = 100
    seed: int = 42
    dtype: str = field(default="bfloat16", metadata={"help": "bfloat16|float32"})
    param_dtype: str = field(
        default="float32",
        metadata={"help": "Master-weight storage dtype: float32 (fp32 master "
                          "weights, higher precision than the reference) or "
                          "bfloat16 (torch-parity: params AND adam moments in "
                          "bf16 — 1/2 and 1/4 the optimizer memory, what the "
                          "reference's bf16 AdamW actually stores). Compute "
                          "always runs in `dtype`."},
    )
    gradient_checkpointing: bool = field(
        default=False, metadata={"help": "jax.checkpoint each decoder layer."}
    )
    remat_policy: str = field(
        default="nothing_saveable",
        metadata={"help": "GC remat policy: nothing_saveable | dots_saveable | "
                          "dots_with_no_batch_dims_saveable | save_attn."},
    )
    donate_params: bool = field(
        default=True, metadata={"help": "Donate param/opt buffers in the jitted step."}
    )


@dataclass
class CheckpointArguments:
    checkpoint_dir: Optional[str] = None
    save_frequency: int = 0
    resume_from_checkpoint: bool = False
    resume: str = field(
        default="off",
        metadata={"help": "off | auto | must — 'auto' resumes from the "
                          "latest checkpoint in checkpoint_dir when one "
                          "exists and trains from scratch otherwise (what "
                          "a restarted preempted job wants); 'must' fails "
                          "fast when no checkpoint is found; 'off' never "
                          "resumes (resume_from_checkpoint=true is kept as "
                          "a compat alias for 'auto')."},
    )
    async_checkpointing: bool = True
    keep_n_checkpoints: int = 3
    checkpoint_retries: int = field(
        default=3,
        metadata={"help": "Retries (with exponential backoff + jitter) "
                          "around each checkpoint save/restore attempt "
                          "before giving up on it."},
    )
    checkpoint_retry_base_delay: float = field(
        default=0.5,
        metadata={"help": "First retry delay in seconds; doubles per "
                          "attempt, capped at 16x."},
    )
    checkpoint_verify: bool = field(
        default=False,
        metadata={"help": "After each successful save, read back the "
                          "checkpoint's metadata/tree structure and "
                          "compare against the in-memory spec; a "
                          "mismatch retires the step immediately (via "
                          "the unreadable-step retirement path) instead "
                          "of being discovered at restore time. Opt-in: "
                          "it drains async saves before verifying."},
    )

    def __post_init__(self) -> None:
        if self.resume not in ("off", "auto", "must"):
            raise ValueError(
                f"resume must be 'off', 'auto' or 'must', got {self.resume!r}"
            )
        if self.resume == "must" and not self.checkpoint_dir:
            # 'must' exists to fail FAST — silently training from scratch
            # because the restart spec forgot checkpoint_dir defeats it
            raise ValueError(
                "--resume must requires --checkpoint_dir"
            )
        if self.checkpoint_retries < 0:
            raise ValueError(
                f"checkpoint_retries must be >= 0, got {self.checkpoint_retries}"
            )


@dataclass
class ResilienceArguments:
    """Fault-tolerance knobs (scaletorch_tpu/resilience.py): divergence
    sentinel policy, preemption handling, and fault-injection hooks."""

    nonfinite_guard: bool = field(
        default=True,
        metadata={"help": "Reject optimizer updates with non-finite loss/"
                          "grad-norm inside the jitted train step (params "
                          "and optimizer state keep their previous values "
                          "for that step)."},
    )
    divergence_policy: str = field(
        default="skip",
        metadata={"help": "skip | rollback | abort — what the host-side "
                          "sentinel does on an anomalous (non-finite or "
                          "spiking) loss. 'rollback' restores the last "
                          "good checkpoint and fast-forwards the data "
                          "stream past the bad region."},
    )
    loss_spike_factor: float = field(
        default=0.0,
        metadata={"help": "Treat loss > factor * EMA(loss) as an anomaly "
                          "(0 = only non-finite losses are anomalous)."},
    )
    loss_ema_beta: float = field(
        default=0.98, metadata={"help": "EMA decay for the loss baseline."}
    )
    max_consecutive_anomalies: int = field(
        default=3,
        metadata={"help": "Abort after this many consecutive anomalous "
                          "steps under any policy (0 = never)."},
    )
    max_rollbacks: int = field(
        default=3,
        metadata={"help": "Abort after this many sentinel-triggered "
                          "rollbacks (0 = unlimited)."},
    )
    sentinel_frequency: int = field(
        default=-1,
        metadata={"help": "Sample the loss on the host every N steps for "
                          "the sentinel (forces a device sync on sampled "
                          "steps). -1 (default) follows log_frequency — "
                          "those steps already pay the sync for logging, "
                          "so the sentinel adds none; 0 disables the host "
                          "sentinel (the in-step nonfinite_guard still "
                          "applies); 1 samples every step for the "
                          "tightest detection latency."},
    )
    handle_preemption: bool = field(
        default=True,
        metadata={"help": "Install SIGTERM/SIGINT handlers during train() "
                          "that request an emergency checkpoint at the "
                          "next step boundary and exit cleanly. On "
                          "multi-process runs the stop flag is "
                          "all-gathered (--ft_coordinate) so any one "
                          "host's preemption triggers a collective "
                          "emergency save on every host."},
    )
    ft_coordinate: bool = field(
        default=True,
        metadata={"help": "Coordinate resilience control decisions "
                          "across hosts on multi-process runs: host 0 "
                          "forms each decision (sentinel action, stop "
                          "request, checkpoint retry/fallback) from the "
                          "all-gathered per-host observations and "
                          "broadcasts it, so every host acts in "
                          "lockstep. Costs one small object gather + "
                          "broadcast per optimizer step. Env override: "
                          "SCALETORCH_TPU_FT_COORDINATE."},
    )
    ft_hang_timeout: float = field(
        default=0.0,
        metadata={"help": "Hang-watchdog timeout in seconds (0 = off): "
                          "if no train-loop progress (data fetch, step "
                          "dispatch, checkpoint) lands within this "
                          "window, dump all thread stacks + the monitor "
                          "ring buffer to a crash report and exit with "
                          "code 43 so the launcher restarts the job "
                          "instead of hanging on a dead collective. Env "
                          "override: SCALETORCH_TPU_FT_HANG_TIMEOUT."},
    )
    crash_report_dir: str = field(
        default="results",
        metadata={"help": "Directory for crash_report_step<N>.json "
                          "post-mortems written on sentinel aborts, "
                          "rollback-budget exhaustion and watchdog "
                          "fires."},
    )
    # Elastic continuation (resilience_distributed.ElasticCoordinator):
    # survive host loss by remeshing onto the survivors, not restarting
    elastic: bool = field(
        default=False,
        metadata={"help": "Elastic training fleet: when a host dies or "
                          "hangs past elastic_deadline_seconds, the "
                          "survivors agree a new membership epoch, "
                          "shrink the dp axis, restore from the latest "
                          "checkpoint onto the smaller mesh and continue "
                          "to total_train_steps; relaunched hosts rejoin "
                          "at the next checkpoint boundary. Requires "
                          "--resume auto|must and a checkpoint_dir, and "
                          "a geometry whose dp divides by the host count "
                          "(tp/pp/cp/ep must not span hosts)."},
    )
    elastic_min_hosts: int = field(
        default=1,
        metadata={"help": "Refuse to continue (abort to the fleet-restart "
                          "fallback, exit 43) when a shrink would leave "
                          "fewer than this many hosts."},
    )
    elastic_heartbeat_seconds: float = field(
        default=2.0,
        metadata={"help": "Cadence of each host's liveness heartbeat file "
                          "in the membership store (operator-visible "
                          "only; detection itself is the bounded "
                          "deadline on every epoch-bus collective)."},
    )
    elastic_deadline_seconds: float = field(
        default=10.0,
        metadata={"help": "Bounded deadline on elastic epoch-bus "
                          "collectives and suspect rounds: a peer that "
                          "misses it is declared lost and the fleet "
                          "remeshes without it."},
    )
    # Fault injection (testing/drills; env vars SCALETORCH_TPU_FT_* override)
    ft_nan_at_step: int = field(
        default=0,
        metadata={"help": "Inject a NaN loss after optimizer step k "
                          "(0 = off; fires once)."},
    )
    ft_fail_saves: int = field(
        default=0,
        metadata={"help": "Fail the first n checkpoint save attempts with "
                          "a retriable I/O error (0 = off)."},
    )
    ft_sigterm_at_step: int = field(
        default=0,
        metadata={"help": "Deliver SIGTERM to this process after optimizer "
                          "step k (0 = off; fires once)."},
    )
    ft_sigterm_host: int = field(
        default=-1,
        metadata={"help": "Restrict ft_sigterm_at_step to one process "
                          "index (-1 = every host) — the multi-host "
                          "drill where exactly one worker is preempted "
                          "and the fleet must still stop together. Env "
                          "override: SCALETORCH_TPU_FT_SIGTERM_HOST."},
    )
    ft_hang_at_step: int = field(
        default=0,
        metadata={"help": "Stall the step boundary once after optimizer "
                          "step k (0 = off), simulating a dead "
                          "collective for the hang watchdog. Env "
                          "override: SCALETORCH_TPU_FT_HANG_STEP."},
    )
    ft_hang_seconds: float = field(
        default=120.0,
        metadata={"help": "Duration of the injected ft_hang_at_step "
                          "stall."},
    )
    ft_bad_batch_at_step: int = field(
        default=0,
        metadata={"help": "Make every read of data-stream position k "
                          "raise a retriable I/O error (0 = off) — "
                          "corrupt-shard injection for the loader's "
                          "retry + skip-and-log path. Env override: "
                          "SCALETORCH_TPU_FT_BAD_BATCH_STEP."},
    )
    ft_slow_step_at_step: int = field(
        default=0,
        metadata={"help": "Telemetry drill: stall optimizer step k at "
                          "its boundary for ft_slow_step_seconds "
                          "(0 = off; fires once) so the slow-step "
                          "detector arms an anomaly-triggered profiler "
                          "window (telemetry/profiling.py). Env "
                          "override: SCALETORCH_TPU_FT_SLOW_STEP_STEP."},
    )
    ft_slow_step_seconds: float = field(
        default=0.5,
        metadata={"help": "Duration of the injected ft_slow_step_at_step "
                          "stall. Env override: "
                          "SCALETORCH_TPU_FT_SLOW_STEP_SECONDS."},
    )
    ft_kill_host_at_step: int = field(
        default=0,
        metadata={"help": "Elastic drill: hard-kill the ft_kill_host-"
                          "selected host after optimizer step k (0 = "
                          "off; fires once) — survivors must remesh and "
                          "continue. Env override: "
                          "SCALETORCH_TPU_FT_KILL_HOST_STEP."},
    )
    ft_kill_host: int = field(
        default=-1,
        metadata={"help": "Process index the ft_kill_host_at_step / "
                          "ft_host_hang_elastic drills target (-1 = "
                          "every host — only meaningful in simulated-"
                          "host tests). Env override: "
                          "SCALETORCH_TPU_FT_KILL_HOST."},
    )
    ft_host_hang_elastic: int = field(
        default=0,
        metadata={"help": "Elastic drill: stall the ft_kill_host-selected "
                          "host past the elastic epoch-bus deadline once "
                          "after optimizer step k (0 = off) — the fleet "
                          "must evict it and it must park-and-rejoin. "
                          "Env override: "
                          "SCALETORCH_TPU_FT_HOST_HANG_ELASTIC."},
    )
    ft_host_hang_seconds: float = field(
        default=30.0,
        metadata={"help": "Duration of the injected ft_host_hang_elastic "
                          "stall (size it past elastic_deadline_seconds)."},
    )
    # Serving fault injection (inference.resilience.ServingFaultInjector;
    # steps are 1-based DECODE steps of the engine's lifetime)
    ft_serve_nan_at_step: int = field(
        default=0,
        metadata={"help": "Serving drill: NaN-poison one slot's KV cache "
                          "before decode step k (0 = off; fires once) so "
                          "its logits go non-finite — drives the "
                          "quarantine path. Env override: "
                          "SCALETORCH_TPU_FT_SERVE_NAN_STEP."},
    )
    ft_serve_nan_slot: int = field(
        default=0,
        metadata={"help": "Slot index the ft_serve_nan_at_step drill "
                          "poisons (falls back to the first active slot). "
                          "Env override: SCALETORCH_TPU_FT_SERVE_NAN_SLOT."},
    )
    ft_serve_slow_at_step: int = field(
        default=0,
        metadata={"help": "Serving drill: stall the engine once before "
                          "decode step k (0 = off) for "
                          "ft_serve_slow_seconds — the wedged-dispatch "
                          "drill for the serving stall watchdog (exit "
                          "code 44). Env override: "
                          "SCALETORCH_TPU_FT_SERVE_SLOW_STEP."},
    )
    ft_serve_slow_seconds: float = field(
        default=30.0,
        metadata={"help": "Duration of the injected ft_serve_slow_at_step "
                          "stall. Env override: "
                          "SCALETORCH_TPU_FT_SERVE_SLOW_SECONDS."},
    )
    ft_serve_submit_storm_at_step: int = field(
        default=0,
        metadata={"help": "Serving drill: inject a burst of "
                          "ft_serve_submit_storm_count requests at decode "
                          "step k (0 = off) — drives bounded admission "
                          "and oldest-first shedding. Env override: "
                          "SCALETORCH_TPU_FT_SERVE_SUBMIT_STORM_STEP."},
    )
    ft_serve_submit_storm_count: int = field(
        default=8,
        metadata={"help": "Number of requests the submit-storm drill "
                          "injects. Env override: "
                          "SCALETORCH_TPU_FT_SERVE_SUBMIT_STORM_COUNT."},
    )
    ft_serve_deadline_storm_at_step: int = field(
        default=0,
        metadata={"help": "Serving drill: force every in-flight request's "
                          "deadline into the past at decode step k "
                          "(0 = off) — drives the timeout paths at "
                          "admission and mid-decode. Env override: "
                          "SCALETORCH_TPU_FT_SERVE_DEADLINE_STORM_STEP."},
    )
    # Gateway fault injection (serving/gateway.py; the counting unit is
    # 1-based HTTP requests, not decode steps)
    ft_gw_tenant_storm_at: int = field(
        default=0,
        metadata={"help": "Gateway drill: when the k-th generate request "
                          "arrives (0 = off; fires once), one synthetic "
                          "'storm' tenant floods the admission queue with "
                          "ft_gw_tenant_storm_count requests — drives "
                          "weighted-fair queueing and shed-before-latency "
                          "backpressure (429 + Retry-After). Env override: "
                          "SCALETORCH_TPU_FT_GW_TENANT_STORM_AT."},
    )
    ft_gw_tenant_storm_count: int = field(
        default=8,
        metadata={"help": "Number of requests the gateway tenant-storm "
                          "drill injects. Env override: "
                          "SCALETORCH_TPU_FT_GW_TENANT_STORM_COUNT."},
    )
    ft_gw_replica_down_at: int = field(
        default=0,
        metadata={"help": "Gateway drill: when the k-th request is "
                          "dispatched to a replica (0 = off; fires once), "
                          "the router marks that replica dead mid-stream "
                          "— its in-flight requests end 'aborted', queued "
                          "requests re-route to the survivors. Env "
                          "override: "
                          "SCALETORCH_TPU_FT_GW_REPLICA_DOWN_AT."},
    )

    def __post_init__(self) -> None:
        if self.divergence_policy not in ("skip", "rollback", "abort"):
            raise ValueError(
                "divergence_policy must be 'skip', 'rollback' or 'abort', "
                f"got {self.divergence_policy!r}"
            )
        if self.loss_spike_factor != 0 and self.loss_spike_factor <= 1.0:
            # a factor in (0, 1] flags virtually every healthy step
            # (loss ~= EMA) as a spike and aborts within a few steps
            raise ValueError(
                "loss_spike_factor must be 0 (off) or > 1 (spike when "
                f"loss > factor * EMA), got {self.loss_spike_factor}"
            )
        if not 0.0 <= self.loss_ema_beta < 1.0:
            raise ValueError(
                f"loss_ema_beta must be in [0, 1), got {self.loss_ema_beta}"
            )
        if self.sentinel_frequency < -1:
            raise ValueError(
                "sentinel_frequency must be -1 (follow log_frequency), 0 "
                f"(off) or a positive period, got {self.sentinel_frequency}"
            )
        for name in ("max_consecutive_anomalies",
                     "max_rollbacks", "ft_nan_at_step", "ft_fail_saves",
                     "ft_sigterm_at_step", "ft_hang_at_step",
                     "ft_bad_batch_at_step", "ft_slow_step_at_step",
                     "ft_kill_host_at_step", "ft_host_hang_elastic",
                     "ft_serve_nan_at_step",
                     "ft_serve_nan_slot", "ft_serve_slow_at_step",
                     "ft_serve_submit_storm_at_step",
                     "ft_serve_deadline_storm_at_step",
                     "ft_gw_tenant_storm_at", "ft_gw_replica_down_at"):
            if getattr(self, name) < 0:
                raise ValueError(
                    f"{name} must be >= 0, got {getattr(self, name)}")
        if self.ft_hang_timeout < 0:
            raise ValueError(
                f"ft_hang_timeout must be >= 0 (0 disables the "
                f"watchdog), got {self.ft_hang_timeout}"
            )
        if self.ft_hang_seconds <= 0:
            raise ValueError(
                f"ft_hang_seconds must be > 0, got {self.ft_hang_seconds}"
            )
        if self.ft_sigterm_host < -1:
            raise ValueError(
                f"ft_sigterm_host must be -1 (any host) or a process "
                f"index, got {self.ft_sigterm_host}"
            )
        if self.ft_kill_host < -1:
            raise ValueError(
                f"ft_kill_host must be -1 (any host) or a process "
                f"index, got {self.ft_kill_host}"
            )
        if self.ft_host_hang_seconds <= 0:
            raise ValueError(
                f"ft_host_hang_seconds must be > 0, "
                f"got {self.ft_host_hang_seconds}"
            )
        if self.elastic_min_hosts < 1:
            raise ValueError(
                f"elastic_min_hosts must be >= 1, "
                f"got {self.elastic_min_hosts}"
            )
        if self.elastic_heartbeat_seconds <= 0:
            raise ValueError(
                f"elastic_heartbeat_seconds must be > 0, "
                f"got {self.elastic_heartbeat_seconds}"
            )
        if self.elastic_deadline_seconds <= 0:
            raise ValueError(
                f"elastic_deadline_seconds must be > 0, "
                f"got {self.elastic_deadline_seconds}"
            )
        if self.ft_slow_step_seconds <= 0:
            raise ValueError(
                f"ft_slow_step_seconds must be > 0, "
                f"got {self.ft_slow_step_seconds}"
            )
        if self.ft_serve_slow_seconds <= 0:
            raise ValueError(
                f"ft_serve_slow_seconds must be > 0, "
                f"got {self.ft_serve_slow_seconds}"
            )
        if self.ft_serve_submit_storm_count < 1:
            raise ValueError(
                f"ft_serve_submit_storm_count must be >= 1, "
                f"got {self.ft_serve_submit_storm_count}"
            )
        if self.ft_gw_tenant_storm_count < 1:
            raise ValueError(
                f"ft_gw_tenant_storm_count must be >= 1, "
                f"got {self.ft_gw_tenant_storm_count}"
            )


@dataclass
class ServingArguments:
    """Serving-gateway knobs (scaletorch_tpu/serving/): the async HTTP
    front door — bind address, tenant fairness/rate limits, admission
    backpressure, and multi-replica routing. Consumed by
    ``scripts/serve.py`` and ``serving.gateway.ServingGateway``."""

    serve_host: str = field(
        default="127.0.0.1",
        metadata={"help": "Gateway bind address."},
    )
    serve_port: int = field(
        default=8000,
        metadata={"help": "Gateway bind port (0 = ephemeral; the chosen "
                          "port is logged and exposed as gateway.port)."},
    )
    serve_tenants: str = field(
        default="",
        metadata={"help": "Tenant spec 'name:weight[:rate[:burst]],...' — "
                          "WFQ weight plus an optional token-bucket rate "
                          "limit (request-cost units/s) and burst. Unknown "
                          "tenants get weight serve_default_weight and no "
                          "rate limit. Example: "
                          "'free:1:100:200,pro:4,batch:0.5'."},
    )
    serve_default_weight: float = field(
        default=1.0,
        metadata={"help": "WFQ weight for tenants not named in "
                          "serve_tenants."},
    )
    serve_max_backlog: int = field(
        default=256,
        metadata={"help": "Gateway admission backlog bound (all tenants). "
                          "Beyond it new arrivals are shed (HTTP 429 with "
                          "Retry-After) — backpressure degrades to "
                          "shedding before it degrades to latency."},
    )
    serve_free_page_watermark: float = field(
        default=0.05,
        metadata={"help": "Paged engines only: when the page pool's free "
                          "fraction sits below this watermark AND the "
                          "gateway backlog is non-empty, new arrivals are "
                          "shed instead of queued (the pool gauge drives "
                          "admission, not wishful queueing)."},
    )
    serve_default_ttl_s: float = field(
        default=0.0,
        metadata={"help": "Deadline applied to requests that carry no "
                          "ttl_s of their own (0 = none). Expired "
                          "requests end 'timeout' (HTTP 504)."},
    )
    serve_replicas: int = field(
        default=1,
        metadata={"help": "In-process engine replicas behind the "
                          "prefix-aware router (scripts/serve.py)."},
    )
    serve_disagg: str = field(
        default="",
        metadata={"help": "Disaggregated prefill/decode serving "
                          "(inference/disagg.py): 'P:D' device counts "
                          "for the prefill and decode slices, or 'auto' "
                          "to size the split from tools/hbm_budget.json "
                          "per-phase rows. '' = colocated (default). "
                          "Paged cache layout only."},
    )
    serve_slo_path: str = field(
        default="",
        metadata={"help": "SLO target file (tools/slo.json grammar, see "
                          "serving/slo.py); when set, /healthz carries a "
                          "live 'slo' verdict and tools/slo_check.py "
                          "grades the telemetry artifacts against it. "
                          "'' disables."},
    )
    serve_slo_preset: str = field(
        default="tiny",
        metadata={"help": "Preset name inside serve_slo_path."},
    )

    def __post_init__(self) -> None:
        if self.serve_port < 0:
            raise ValueError(
                f"serve_port must be >= 0, got {self.serve_port}")
        if self.serve_default_weight <= 0:
            raise ValueError(
                f"serve_default_weight must be > 0, "
                f"got {self.serve_default_weight}")
        if self.serve_max_backlog < 1:
            raise ValueError(
                f"serve_max_backlog must be >= 1, "
                f"got {self.serve_max_backlog}")
        if not 0.0 <= self.serve_free_page_watermark < 1.0:
            raise ValueError(
                f"serve_free_page_watermark must be in [0, 1), "
                f"got {self.serve_free_page_watermark}")
        if self.serve_default_ttl_s < 0:
            raise ValueError(
                f"serve_default_ttl_s must be >= 0, "
                f"got {self.serve_default_ttl_s}")
        if self.serve_replicas < 1:
            raise ValueError(
                f"serve_replicas must be >= 1, got {self.serve_replicas}")
        if self.serve_tenants:
            # delegate the spec grammar to its single home so the CLI
            # fails at parse time, not mid-serve
            from scaletorch_tpu.serving.admission import parse_tenant_spec

            parse_tenant_spec(self.serve_tenants)
        if self.serve_disagg:
            # same single-home delegation for the slice-split grammar
            # (pure host parsing — no jax work at config time)
            from scaletorch_tpu.inference.disagg import parse_disagg_spec

            parse_disagg_spec(self.serve_disagg)
        if self.serve_slo_path:
            # same parse-time discipline for the SLO file: a typo'd
            # path or malformed target key fails the CLI, not /healthz
            from scaletorch_tpu.serving.slo import load_slo, preset_targets

            try:
                preset_targets(load_slo(self.serve_slo_path),
                               self.serve_slo_preset)
            except OSError as exc:
                raise ValueError(
                    f"serve_slo_path {self.serve_slo_path!r} is not "
                    f"readable: {exc}") from None


@dataclass
class TelemetryArguments:
    """Observability knobs (scaletorch_tpu/telemetry/): span tracing,
    anomaly-triggered profiling, straggler detection, JSONL export.
    Everything except straggler detection is enabled by setting
    ``telemetry_dir`` (env override SCALETORCH_TPU_TELEMETRY_DIR,
    present-wins — an explicitly empty value cancels it); stragglers
    ride the existing multi-host decision gather and need no
    directory."""

    telemetry_dir: Optional[str] = field(
        default=None,
        metadata={"help": "Enable telemetry and write its artifacts here: "
                          "trace_proc<N>.trace.json (Chrome trace events, "
                          "Perfetto-loadable host-side spans), "
                          "events_proc<N>.jsonl (schema-versioned metrics "
                          "stream), profiles/ (jax.profiler captures), "
                          "live_snapshot_<n>.json (SIGUSR1 dumps). Unset "
                          "= telemetry off (instrumentation costs one "
                          "branch per site). Env override: "
                          "SCALETORCH_TPU_TELEMETRY_DIR."},
    )
    trace_max_events: int = field(
        default=200_000,
        metadata={"help": "Cap on span events written to the trace file "
                          "(week-long runs stay disk-bounded; the drop "
                          "count is reported, and the in-memory tail for "
                          "crash reports keeps the NEWEST events "
                          "regardless)."},
    )
    span_tail_size: int = field(
        default=256,
        metadata={"help": "Span events retained in memory for crash "
                          "reports and SIGUSR1 live snapshots."},
    )
    profile_on_slow_step: float = field(
        default=0.0,
        metadata={"help": "Arm a bounded jax.profiler window when a "
                          "step's wall time exceeds this factor x its "
                          "EMA (0 = off; must be > 1 otherwise). "
                          "Requires telemetry_dir."},
    )
    profile_window_steps: int = field(
        default=3,
        metadata={"help": "Steps each anomaly-triggered profiler window "
                          "captures."},
    )
    profile_max_captures: int = field(
        default=1,
        metadata={"help": "Maximum anomaly-triggered profiler windows per "
                          "run (a persistently slow run must not fill "
                          "the disk with profiles)."},
    )
    profile_steps: str = field(
        default="",
        metadata={"help": "Manual profiler window 'start:stop' (steps; "
                          "[start, stop), 1-based): capture these steps "
                          "regardless of the slow-step detector. Env "
                          "override: SCALETORCH_TPU_PROFILE_STEPS."},
    )
    straggler_factor: float = field(
        default=2.0,
        metadata={"help": "Flag a host as a straggler when its step wall "
                          "time stays above this factor x the fleet "
                          "median (0 = off; must be > 1 otherwise). "
                          "Multi-host only; observations ride the "
                          "existing per-step coordination gather — zero "
                          "new collectives."},
    )
    straggler_patience: int = field(
        default=3,
        metadata={"help": "Consecutive over-threshold observations before "
                          "a host is flagged (raises the straggler_flags "
                          "counter and logs the host index)."},
    )

    def __post_init__(self) -> None:
        if self.profile_on_slow_step != 0 and self.profile_on_slow_step <= 1.0:
            raise ValueError(
                "profile_on_slow_step must be 0 (off) or > 1 (spike when "
                f"step_time > factor * EMA), got {self.profile_on_slow_step}"
            )
        if self.profile_window_steps < 1:
            raise ValueError(
                f"profile_window_steps must be >= 1, "
                f"got {self.profile_window_steps}"
            )
        if self.profile_max_captures < 0:
            raise ValueError(
                f"profile_max_captures must be >= 0, "
                f"got {self.profile_max_captures}"
            )
        if self.profile_steps:
            from scaletorch_tpu.telemetry.profiling import parse_profile_steps

            parse_profile_steps(self.profile_steps)  # raises on bad spec
        if self.profile_on_slow_step or self.profile_steps:
            # profiling captures land under the telemetry dir — without
            # one the knob would be a silent no-op and the operator would
            # wait forever for a window that never arms
            from scaletorch_tpu.telemetry import telemetry_dir_from_config

            if telemetry_dir_from_config(self) is None:
                raise ValueError(
                    "profile_on_slow_step / profile_steps need a telemetry "
                    "directory to write captures into: set --telemetry_dir "
                    "(or SCALETORCH_TPU_TELEMETRY_DIR)"
                )
        if self.straggler_factor != 0 and self.straggler_factor <= 1.0:
            raise ValueError(
                "straggler_factor must be 0 (off) or > 1 (flag when "
                f"step_time > factor * median), got {self.straggler_factor}"
            )
        if self.straggler_patience < 1:
            raise ValueError(
                f"straggler_patience must be >= 1, "
                f"got {self.straggler_patience}"
            )
        if self.trace_max_events < 1 or self.span_tail_size < 1:
            raise ValueError(
                "trace_max_events and span_tail_size must be >= 1, got "
                f"{self.trace_max_events} / {self.span_tail_size}"
            )


@dataclass
class LoggingArguments:
    log_frequency: int = 1
    log_file: Optional[str] = None
    log_format: str = field(
        default="text",
        metadata={"help": "text | json — console/file log format. 'json' "
                          "emits one JSON object per line (metrics step "
                          "records as-is with ts/level/proc added, plain "
                          "messages wrapped as {'msg': ...}) so fleet "
                          "log aggregation never parses the "
                          "' | '-joined human lines."},
    )
    performance_log_dir: Optional[str] = field(
        default=None,
        metadata={"help": "Dump the per-step metrics history as JSON here at "
                          "the end of training (reference monitor.py role)."},
    )
    verbose: bool = field(
        default=False, metadata={"help": "DEBUG-level logging."}
    )
    wandb_project: Optional[str] = field(
        default=None,
        metadata={"help": "Log metrics to this wandb project (reference "
                          "metrics.py:95-114); silently skipped if wandb "
                          "is not installed."},
    )
    wandb_run_name: Optional[str] = None


@dataclass
class ScaleTorchTPUArguments(
    DataArguments,
    ModelArguments,
    ParallelArguments,
    DistributedArguments,
    LrSchedulerArguments,
    OptimizerArguments,
    TrainingArguments,
    CheckpointArguments,
    ResilienceArguments,
    ServingArguments,
    TelemetryArguments,
    LoggingArguments,
):
    """All training arguments, composed (reference config.py:393-403)."""

    def __post_init__(self) -> None:
        ParallelArguments.__post_init__(self)
        DistributedArguments.__post_init__(self)
        CheckpointArguments.__post_init__(self)
        ResilienceArguments.__post_init__(self)
        ServingArguments.__post_init__(self)
        TelemetryArguments.__post_init__(self)
        if self.log_format not in ("text", "json"):
            raise ValueError(
                f"log_format must be 'text' or 'json', got {self.log_format!r}"
            )
        for name in ("data_read_retries", "data_max_skipped_batches"):
            if getattr(self, name) < 0:
                raise ValueError(
                    f"{name} must be >= 0, got {getattr(self, name)}")
        # resume_from_checkpoint predates the tri-state knob: keep it as a
        # compat alias for --resume auto (never weaken an explicit 'must').
        if self.resume_from_checkpoint and self.resume == "off":
            self.resume = "auto"
        if self.elastic:
            # An elastic remesh IS a restore: every shrink/grow restores
            # the latest checkpoint onto the new topology, so a config
            # that cannot resume — or whose geometry cannot shed a host —
            # must be refused at parse time, not at the first host loss.
            if not self.checkpoint_dir:
                raise ValueError(
                    "--elastic requires --checkpoint_dir: every membership "
                    "transition restores from the latest checkpoint"
                )
            if self.resume == "off":
                raise ValueError(
                    "--elastic requires --resume auto|must: survivors (and "
                    "relaunched hosts) continue by restoring, never from "
                    "scratch"
                )
            if self.num_processes:
                if self.elastic_min_hosts > self.num_processes:
                    raise ValueError(
                        f"--elastic_min_hosts {self.elastic_min_hosts} > "
                        f"--num_processes {self.num_processes}: the fleet "
                        "could never satisfy its own floor — lower "
                        "elastic_min_hosts or launch more hosts"
                    )
                if (self.num_processes > 1
                        and self.data_parallel_size % self.num_processes):
                    raise ValueError(
                        f"--elastic needs data_parallel_size "
                        f"{self.data_parallel_size} divisible by "
                        f"num_processes {self.num_processes} so each host "
                        "holds whole dp replicas; otherwise tp/pp/cp/ep "
                        "span hosts and no host can be shed — raise dp or "
                        "disable --elastic"
                    )
        if self.sequence_length % self.context_parallel_size != 0:
            raise ValueError(
                f"sequence_length {self.sequence_length} not divisible by "
                f"context_parallel_size {self.context_parallel_size}"
            )
        if (self.context_parallel_size > 1 and self.cp_layout == "zigzag"
                # ulysses owns whole heads — the zigzag layout (and its
                # stricter divisibility) never applies to it. 'auto' may
                # resolve to ulysses too (topology-aware selection needs
                # the mesh, which doesn't exist at config time), so its
                # divisibility is checked by the Trainer AFTER
                # resolve_cp_backend settles the backend.
                and self.attention_backend not in ("ulysses", "auto")
                and self.sequence_length % (2 * self.context_parallel_size)):
            raise ValueError(
                f"cp_layout='zigzag' needs sequence_length "
                f"{self.sequence_length} divisible by 2*cp "
                f"({2 * self.context_parallel_size}); use cp_layout="
                f"'contiguous' for odd stripe splits"
            )
        if self.sequence_parallel:
            seq_local = self.sequence_length // self.context_parallel_size
            if seq_local % self.tensor_parallel_size != 0:
                raise ValueError(
                    f"sequence_parallel needs per-cp-rank sequence {seq_local} "
                    f"divisible by tensor_parallel_size {self.tensor_parallel_size}"
                )
        # ep shards the batch too (each ep rank gets distinct tokens and
        # exchanges them by expert ownership — unlike the reference, which
        # replicates data across ep ranks, dataloader.py:170-186), so the
        # effective data-parallel width is dp * ep.
        expected_gbs = (
            self.data_parallel_size
            * self.expert_parallel_size
            * self.micro_batch_size
            * self.gradient_accumulation_steps
        )
        if self.global_batch_size is None:
            self.global_batch_size = expected_gbs
        elif self.global_batch_size != expected_gbs:
            raise ValueError(
                f"global_batch_size {self.global_batch_size} != dp * ep * "
                f"micro_bs * grad_accum = {expected_gbs}"
            )
        if self.num_microbatches is None:
            self.num_microbatches = self.gradient_accumulation_steps
        elif self.num_microbatches != self.gradient_accumulation_steps:
            # The batch's accumulation dim IS the pipeline microbatch dim
            # (one scan feeds both), so a divergent value would silently be
            # ignored — reject it instead.
            raise ValueError(
                f"num_microbatches ({self.num_microbatches}) must equal "
                f"gradient_accumulation_steps ({self.gradient_accumulation_steps}); "
                "set gradient_accumulation_steps to control PP microbatching"
            )

    @property
    def world_size(self) -> int:
        return (
            self.data_parallel_size
            * self.pipeline_parallel_size
            * self.context_parallel_size
            * self.expert_parallel_size
            * self.tensor_parallel_size
        )

    def validate_world_size(self, num_devices: int) -> None:
        """Parity: reference config.py:444-460."""
        if self.world_size != num_devices:
            raise ValueError(
                f"parallel dims product {self.world_size} != available device "
                f"count {num_devices}"
            )

    def mesh_kwargs(self) -> dict:
        return dict(
            dp=self.data_parallel_size,
            pp=self.pipeline_parallel_size,
            cp=self.context_parallel_size,
            ep=self.expert_parallel_size,
            tp=self.tensor_parallel_size,
        )


def parse_args(args=None) -> ScaleTorchTPUArguments:
    """CLI entry parsing, HfArgumentParser-style (reference train.py:61-62)."""
    from transformers import HfArgumentParser

    parser = HfArgumentParser(ScaleTorchTPUArguments)
    (cfg,) = parser.parse_args_into_dataclasses(args=args)
    return cfg


def asdict_shallow(cfg) -> dict:
    return dataclasses.asdict(cfg)
