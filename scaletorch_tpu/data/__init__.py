"""Data pipeline: tokenization strategies + micro-batch CP-aware loading."""

from scaletorch_tpu.data.dataset import (  # noqa: F401
    DatasetProcessor,
    register_tokenize_strategy,
)
from scaletorch_tpu.data.dataloader import (  # noqa: F401
    MicroBatchDataLoader,
    SyntheticDataLoader,
)
