"""Micro-batch data loading for the SPMD train step.

Parity with reference scaletorch/data/dataloader.py:16-292
(MicroBatchDataLoader): global batch = micro_bs x grad_accum x dp
(:107-109), shifted next-token targets + absolute position ids (:119-233),
seeded shuffling with epoch bump (DistributedSampler parity, :170-186,255-258),
drop_last semantics.

TPU-native difference: the reference's per-rank collate slices the sequence
for this cp_rank and samples for this dp_rank, because every process feeds
only its own device. Under JAX's single-controller SPMD the loader yields
the **global** step batch ``[accum, dp * micro_bs, seq]`` and the jitted
step's input sharding ``P(None, 'dp', 'cp')`` performs exactly that
dp-scatter and contiguous cp sequence-slicing on device — same placement,
no host-side bookkeeping. (Multi-host feeding uses
``jax.make_array_from_process_local_data`` with per-process shards; see
trainer.) Position ids stay absolute and global, as CP requires
(reference dataloader.py:222-233).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional

import numpy as np

from scaletorch_tpu.utils.logger import get_logger

Batch = Dict[str, np.ndarray]


def remap_loader_position(
    position: int,
    *,
    old_samples_per_step: int,
    new_samples_per_step: int,
) -> int:
    """Translate a checkpointed ``loader_position`` (optimizer steps
    consumed) across a dp-degree change (elastic remesh).

    ``position`` counts optimizer steps, and one step consumes
    ``samples_per_step = micro_bs * dp * accum`` sequences — a quantity
    that changes when the dp axis shrinks or grows. The intra-epoch
    sample stream itself is dp-independent (one seeded permutation of
    the full dataset per epoch), so the consumed *region* is
    ``position * old_samples_per_step`` sequences, and the equivalent
    step count under the new geometry is that region divided by the new
    step size — rounded UP, so a partially-covered step batch counts as
    retired and is never re-consumed (double-counting a batch corrupts
    the deterministic trajectory; skipping strictly fewer than one new
    step batch of samples on a non-divisible boundary is logged and
    benign). A shrink to a divisor dp (e.g. dp4 -> dp2) is always exact.
    """
    if old_samples_per_step <= 0 or new_samples_per_step <= 0:
        raise ValueError(
            "samples_per_step must be positive, got "
            f"{old_samples_per_step} -> {new_samples_per_step}"
        )
    if position < 0:
        raise ValueError(f"loader position must be >= 0, got {position}")
    samples = position * old_samples_per_step
    new_position = -(-samples // new_samples_per_step)  # ceil division
    skipped = new_position * new_samples_per_step - samples
    if skipped:
        get_logger().warning(
            f"elastic loader remap: {position} steps x "
            f"{old_samples_per_step} samples does not divide by the new "
            f"step size {new_samples_per_step}; rounding up to step "
            f"{new_position} retires {skipped} extra sample(s) (< 1 step "
            "batch) rather than double-counting a consumed batch"
        )
    return new_position


class MicroBatchDataLoader:
    """Yields per-optimizer-step batches from a [N, seq+1] token array.

    Fault tolerance (resilience layer): each step-batch read runs under
    ``retry_with_backoff`` (``read_retries`` / ``retry_base_delay``) so a
    transiently-flaky storage-backed token array (np.memmap over network
    storage) does not kill the run; a read that stays unreadable —
    deterministic shard corruption — is skipped-and-logged, bounded by
    ``max_skipped_batches``. The loader tracks its absolute stream
    ``position`` (advanced BEFORE each yield, and across skipped
    regions), which the trainer persists as ``loader_position`` in every
    checkpoint — so a crash between fetch and step never double-counts a
    batch, and a restart walks the identical stream with the same
    batches retired.
    """

    def __init__(
        self,
        tokens: np.ndarray,  # [N, seq_len + 1] int32
        micro_batch_size: int,
        gradient_accumulation_steps: int,
        data_parallel_size: int = 1,
        seed: int = 42,
        shuffle: bool = True,
        read_retries: int = 2,
        retry_base_delay: float = 0.05,
        max_skipped_batches: int = 16,
        fault_injector: Optional[Any] = None,
    ) -> None:
        if tokens.ndim != 2:
            raise ValueError(f"tokens must be [N, seq_len+1], got {tokens.shape}")
        self.tokens = tokens
        self.seq_len = tokens.shape[1] - 1
        self.micro_batch_size = micro_batch_size
        self.grad_accum = gradient_accumulation_steps
        self.dp = data_parallel_size
        self.global_batch_size = micro_batch_size * data_parallel_size
        self.samples_per_step = self.global_batch_size * self.grad_accum
        self.seed = seed
        self.shuffle = shuffle
        # A full optimizer-step batch is the minimum unit; the ragged tail of
        # an epoch is always dropped (reference DistributedSampler
        # drop_last=True semantics — partial step batches are not supported).
        if len(tokens) < self.samples_per_step:
            raise ValueError(
                f"dataset has {len(tokens)} sequences < {self.samples_per_step} "
                f"needed per step"
            )
        self.epoch = 0
        self._step_offset = 0  # intra-epoch resume position
        self.position = 0      # absolute stream positions consumed
        self.read_retries = read_retries
        self.retry_base_delay = retry_base_delay
        self.max_skipped_batches = max_skipped_batches
        self.skipped_positions: list[int] = []
        self._injector = fault_injector

    @property
    def tokens_per_step(self) -> int:
        return self.samples_per_step * self.seq_len

    def steps_per_epoch(self) -> int:
        return len(self.tokens) // self.samples_per_step

    def _epoch_order(self) -> np.ndarray:
        if not self.shuffle:
            return np.arange(len(self.tokens))
        # Epoch-dependent seeding = DistributedSampler.set_epoch parity.
        rng = np.random.default_rng(self.seed + self.epoch)
        return rng.permutation(len(self.tokens))

    def set_data_parallel_size(self, data_parallel_size: int) -> None:
        """Elastic remesh hook: adopt a new dp degree in place. Only the
        step GEOMETRY changes (global batch, samples per step); the
        epoch permutation is dp-independent, so the stream itself is
        untouched — the caller re-seats ``position`` via
        ``remap_loader_position`` + ``set_state`` and drops any live
        iterator."""
        if data_parallel_size < 1:
            raise ValueError(
                f"data_parallel_size must be >= 1, got {data_parallel_size}"
            )
        self.dp = data_parallel_size
        self.global_batch_size = self.micro_batch_size * data_parallel_size
        self.samples_per_step = self.global_batch_size * self.grad_accum
        if len(self.tokens) < self.samples_per_step:
            raise ValueError(
                f"dataset has {len(self.tokens)} sequences < "
                f"{self.samples_per_step} needed per step after the dp "
                "change"
            )

    def set_state(self, steps_consumed: int) -> None:
        """Fast-forward to just after ``steps_consumed`` optimizer steps —
        checkpoint-resume parity with the reference's sampler epoch bump +
        restored step counters (reference train.py:195-218). Index-only:
        no data is touched. Live iterators predate the new state — drop
        and re-create them (the trainer does)."""
        spe = self.steps_per_epoch()
        self.epoch = steps_consumed // spe
        self._step_offset = steps_consumed % spe
        self.position = steps_consumed

    def _read_step(self, order: np.ndarray, i: int) -> Optional[Batch]:
        """One step-batch read under retry-with-backoff; None when the
        region stayed unreadable and was skipped-and-logged."""
        from scaletorch_tpu.resilience import retry_with_backoff

        position = self.position

        def read() -> Batch:
            if self._injector is not None \
                    and self._injector.take_bad_read(position):
                raise OSError(
                    f"injected corrupt batch read at stream position "
                    f"{position}"
                )
            idx = order[i * self.samples_per_step
                        : (i + 1) * self.samples_per_step]
            return self._collate(self.tokens[idx])  # [samples, seq+1]

        try:
            return retry_with_backoff(
                read,
                retries=self.read_retries,
                base_delay=self.retry_base_delay,
                retriable=(OSError,),
                describe=f"batch read (stream position {position})",
            )
        except OSError as exc:
            self.skipped_positions.append(position)
            if (self.max_skipped_batches > 0
                    and len(self.skipped_positions)
                    > self.max_skipped_batches):
                raise RuntimeError(
                    f"{len(self.skipped_positions)} unreadable step "
                    f"batches exceed max_skipped_batches="
                    f"{self.max_skipped_batches} — the data source is "
                    "broken, not flaky"
                ) from exc
            get_logger().error(
                f"batch read at stream position {position} unreadable "
                f"after {self.read_retries + 1} attempts ({exc!r}): "
                "skipping the region (it stays retired on restart via "
                "loader_position)"
            )
            return None

    def __iter__(self) -> Iterator[Batch]:
        """Infinite iterator over optimizer-step batches, cycling epochs.

        Bookkeeping advances BEFORE each yield: ``position`` /
        ``_step_offset`` already count a batch when the caller receives
        it, so an exception between fetch and optimizer step — or a
        skip-and-log on an unreadable region — never double-counts a
        batch when the stream is re-iterated or resumed."""
        while True:
            order = self._epoch_order()
            spe = self.steps_per_epoch()
            while self._step_offset < spe:
                i = self._step_offset
                batch = self._read_step(order, i)
                # advance-before-yield (and before the skip `continue`)
                self._step_offset = i + 1
                self.position += 1
                if batch is None:
                    continue  # unreadable region skipped; stream moves on
                yield batch
            self.epoch += 1
            self._step_offset = 0

    def _collate(self, chunk: np.ndarray) -> Batch:
        a, g, s = self.grad_accum, self.global_batch_size, self.seq_len
        inputs = chunk[:, :-1].reshape(a, g, s)
        targets = chunk[:, 1:].reshape(a, g, s)
        # position_ids carry the accumulation axis so the train step's scan
        # can slice every leaf uniformly; each row is absolute 0..seq-1.
        return {
            "input_ids": np.ascontiguousarray(inputs, dtype=np.int32),
            "target_ids": np.ascontiguousarray(targets, dtype=np.int32),
            "position_ids": np.broadcast_to(
                np.arange(s, dtype=np.int32), (a, s)
            ).copy(),
        }


class SyntheticDataLoader:
    """On-host random token stream with the same batch contract — the
    benchmark path (reference benchmarks feed real data; synthetic keeps
    bench.py hermetic)."""

    def __init__(
        self,
        vocab_size: int,
        sequence_length: int,
        micro_batch_size: int,
        gradient_accumulation_steps: int,
        data_parallel_size: int = 1,
        seed: int = 0,
    ) -> None:
        self.vocab_size = vocab_size
        self.seq_len = sequence_length
        self.micro_batch_size = micro_batch_size
        self.grad_accum = gradient_accumulation_steps
        self.dp = data_parallel_size
        self.global_batch_size = micro_batch_size * data_parallel_size
        self.rng = np.random.default_rng(seed)

    def set_data_parallel_size(self, data_parallel_size: int) -> None:
        """Elastic remesh hook — same contract as MicroBatchDataLoader's
        (the synthetic stream has no position to re-seat)."""
        if data_parallel_size < 1:
            raise ValueError(
                f"data_parallel_size must be >= 1, got {data_parallel_size}"
            )
        self.dp = data_parallel_size
        self.global_batch_size = self.micro_batch_size * data_parallel_size

    @property
    def tokens_per_step(self) -> int:
        return self.grad_accum * self.global_batch_size * self.seq_len

    def __iter__(self) -> Iterator[Batch]:
        while True:
            toks = self.rng.integers(
                0,
                self.vocab_size,
                size=(self.grad_accum, self.global_batch_size, self.seq_len + 1),
                dtype=np.int32,
            )
            yield {
                "input_ids": toks[:, :, :-1],
                "target_ids": toks[:, :, 1:],
                "position_ids": np.broadcast_to(
                    np.arange(self.seq_len, dtype=np.int32),
                    (self.grad_accum, self.seq_len),
                ).copy(),
            }
