"""Micro-batch data loading for the SPMD train step.

Parity with reference scaletorch/data/dataloader.py:16-292
(MicroBatchDataLoader): global batch = micro_bs x grad_accum x dp
(:107-109), shifted next-token targets + absolute position ids (:119-233),
seeded shuffling with epoch bump (DistributedSampler parity, :170-186,255-258),
drop_last semantics.

TPU-native difference: the reference's per-rank collate slices the sequence
for this cp_rank and samples for this dp_rank, because every process feeds
only its own device. Under JAX's single-controller SPMD the loader yields
the **global** step batch ``[accum, dp * micro_bs, seq]`` and the jitted
step's input sharding ``P(None, 'dp', 'cp')`` performs exactly that
dp-scatter and contiguous cp sequence-slicing on device — same placement,
no host-side bookkeeping. (Multi-host feeding uses
``jax.make_array_from_process_local_data`` with per-process shards; see
trainer.) Position ids stay absolute and global, as CP requires
(reference dataloader.py:222-233).
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional

import numpy as np

Batch = Dict[str, np.ndarray]


class MicroBatchDataLoader:
    """Yields per-optimizer-step batches from a [N, seq+1] token array."""

    def __init__(
        self,
        tokens: np.ndarray,  # [N, seq_len + 1] int32
        micro_batch_size: int,
        gradient_accumulation_steps: int,
        data_parallel_size: int = 1,
        seed: int = 42,
        shuffle: bool = True,
    ) -> None:
        if tokens.ndim != 2:
            raise ValueError(f"tokens must be [N, seq_len+1], got {tokens.shape}")
        self.tokens = tokens
        self.seq_len = tokens.shape[1] - 1
        self.micro_batch_size = micro_batch_size
        self.grad_accum = gradient_accumulation_steps
        self.dp = data_parallel_size
        self.global_batch_size = micro_batch_size * data_parallel_size
        self.samples_per_step = self.global_batch_size * self.grad_accum
        self.seed = seed
        self.shuffle = shuffle
        # A full optimizer-step batch is the minimum unit; the ragged tail of
        # an epoch is always dropped (reference DistributedSampler
        # drop_last=True semantics — partial step batches are not supported).
        if len(tokens) < self.samples_per_step:
            raise ValueError(
                f"dataset has {len(tokens)} sequences < {self.samples_per_step} "
                f"needed per step"
            )
        self.epoch = 0
        self._step_offset = 0  # intra-epoch resume position

    @property
    def tokens_per_step(self) -> int:
        return self.samples_per_step * self.seq_len

    def steps_per_epoch(self) -> int:
        return len(self.tokens) // self.samples_per_step

    def _epoch_order(self) -> np.ndarray:
        if not self.shuffle:
            return np.arange(len(self.tokens))
        # Epoch-dependent seeding = DistributedSampler.set_epoch parity.
        rng = np.random.default_rng(self.seed + self.epoch)
        return rng.permutation(len(self.tokens))

    def set_state(self, steps_consumed: int) -> None:
        """Fast-forward to just after ``steps_consumed`` optimizer steps —
        checkpoint-resume parity with the reference's sampler epoch bump +
        restored step counters (reference train.py:195-218). Index-only:
        no data is touched."""
        spe = self.steps_per_epoch()
        self.epoch = steps_consumed // spe
        self._step_offset = steps_consumed % spe

    def __iter__(self) -> Iterator[Batch]:
        """Infinite iterator over optimizer-step batches, cycling epochs."""
        while True:
            order = self._epoch_order()
            start = self._step_offset
            self._step_offset = 0
            for i in range(start, self.steps_per_epoch()):
                idx = order[i * self.samples_per_step : (i + 1) * self.samples_per_step]
                chunk = self.tokens[idx]  # [samples, seq+1]
                yield self._collate(chunk)
            self.epoch += 1

    def _collate(self, chunk: np.ndarray) -> Batch:
        a, g, s = self.grad_accum, self.global_batch_size, self.seq_len
        inputs = chunk[:, :-1].reshape(a, g, s)
        targets = chunk[:, 1:].reshape(a, g, s)
        # position_ids carry the accumulation axis so the train step's scan
        # can slice every leaf uniformly; each row is absolute 0..seq-1.
        return {
            "input_ids": np.ascontiguousarray(inputs, dtype=np.int32),
            "target_ids": np.ascontiguousarray(targets, dtype=np.int32),
            "position_ids": np.broadcast_to(
                np.arange(s, dtype=np.int32), (a, s)
            ).copy(),
        }


class SyntheticDataLoader:
    """On-host random token stream with the same batch contract — the
    benchmark path (reference benchmarks feed real data; synthetic keeps
    bench.py hermetic)."""

    def __init__(
        self,
        vocab_size: int,
        sequence_length: int,
        micro_batch_size: int,
        gradient_accumulation_steps: int,
        data_parallel_size: int = 1,
        seed: int = 0,
    ) -> None:
        self.vocab_size = vocab_size
        self.seq_len = sequence_length
        self.micro_batch_size = micro_batch_size
        self.grad_accum = gradient_accumulation_steps
        self.dp = data_parallel_size
        self.global_batch_size = micro_batch_size * data_parallel_size
        self.rng = np.random.default_rng(seed)

    @property
    def tokens_per_step(self) -> int:
        return self.grad_accum * self.global_batch_size * self.seq_len

    def __iter__(self) -> Iterator[Batch]:
        while True:
            toks = self.rng.integers(
                0,
                self.vocab_size,
                size=(self.grad_accum, self.global_batch_size, self.seq_len + 1),
                dtype=np.int32,
            )
            yield {
                "input_ids": toks[:, :, :-1],
                "target_ids": toks[:, :, 1:],
                "position_ids": np.broadcast_to(
                    np.arange(self.seq_len, dtype=np.int32),
                    (self.grad_accum, self.seq_len),
                ).copy(),
            }
