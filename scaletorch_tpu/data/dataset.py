"""Dataset tokenization: strategy registry + concat-chunk default.

Parity with reference scaletorch/data/dataset.py:28-88: a
``register_tokenize_strategy`` registry whose default ``concat_chunk``
strategy concatenates all document tokens and cuts the stream into
``seq_len + 1`` chunks (each yields seq_len inputs + shifted targets), and
a ``DatasetProcessor`` wrapping tokenizer init + HF ``load_dataset`` +
multiprocess ``.map`` tokenization.
"""

from __future__ import annotations

import os
from typing import Callable, Dict

import numpy as np

_STRATEGIES: Dict[str, Callable] = {}


def register_tokenize_strategy(name: str, fn: Callable = None):
    """Register ``strategy(examples, tokenizer, seq_len, text_key) -> dict``.

    The strategy receives a batch of raw examples and returns
    ``{"input_ids": [[seq_len + 1 tokens], ...]}``.
    """

    def _register(f):
        _STRATEGIES[name] = f
        return f

    if fn is not None:
        return _register(fn)
    return _register


@register_tokenize_strategy("concat_chunk")
def concat_chunk(examples, tokenizer, seq_len: int, text_key: str = "text"):
    """Concat every document's tokens (+ eos), cut into seq_len+1 chunks,
    drop the ragged tail (reference dataset.py:64-88)."""
    eos = tokenizer.eos_token_id
    stream: list[int] = []
    for text in examples[text_key]:
        toks = tokenizer(text, add_special_tokens=False)["input_ids"]
        stream.extend(toks)
        if eos is not None:
            stream.append(eos)
    chunk = seq_len + 1
    n = (len(stream) // chunk) * chunk
    chunks = [stream[i : i + chunk] for i in range(0, n, chunk)]
    return {"input_ids": chunks}


def get_tokenize_strategy(name: str) -> Callable:
    if name not in _STRATEGIES:
        raise KeyError(f"unknown tokenize strategy {name!r}; have {sorted(_STRATEGIES)}")
    return _STRATEGIES[name]


class DatasetProcessor:
    """Tokenizer + dataset loading + strategy-driven tokenization
    (reference dataset.py:89+)."""

    def __init__(
        self,
        tokenizer_name_or_path,
        sequence_length: int,
        tokenize_strategy: str = "concat_chunk",
        text_key: str = "text",
        num_proc: int = 4,
        load_retries: int = 2,
        load_retry_base_delay: float = 1.0,
    ) -> None:
        if isinstance(tokenizer_name_or_path, str):
            from transformers import AutoTokenizer

            self.tokenizer = AutoTokenizer.from_pretrained(tokenizer_name_or_path)
        else:
            # an already-constructed tokenizer object (offline / custom)
            self.tokenizer = tokenizer_name_or_path
        self.sequence_length = sequence_length
        self.strategy = get_tokenize_strategy(tokenize_strategy)
        self.text_key = text_key
        self.num_proc = num_proc
        self.load_retries = load_retries
        self.load_retry_base_delay = load_retry_base_delay

    def load(self, dataset_name: str, split: str = "train"):
        """Local json/jsonl path, local dir, or hub name
        (reference pretrain_dataset.py:13-107). Hub/network fetches run
        under retry-with-backoff — on a multi-host pod every worker pulls
        the dataset at startup, and one transient hub hiccup must not
        kill the whole fleet's launch."""
        import datasets as hf_datasets

        from scaletorch_tpu.resilience import retry_with_backoff

        def _load():
            if os.path.isfile(dataset_name) \
                    and dataset_name.endswith((".json", ".jsonl")):
                return hf_datasets.load_dataset(
                    "json", data_files=dataset_name)[split]
            return hf_datasets.load_dataset(dataset_name, split=split)

        return retry_with_backoff(
            _load,
            retries=self.load_retries,
            base_delay=self.load_retry_base_delay,
            retriable=(OSError, ConnectionError),
            describe=f"dataset load ({dataset_name})",
        )

    def tokenize(self, dataset):
        """Map the strategy over the dataset, dropping raw columns."""
        return dataset.map(
            lambda ex: self.strategy(
                ex, self.tokenizer, self.sequence_length, self.text_key
            ),
            batched=True,
            remove_columns=dataset.column_names,
            num_proc=self.num_proc if len(dataset) > 1000 else None,
        )

    def process(self, dataset_name: str, split: str = "train"):
        return self.tokenize(self.load(dataset_name, split))


def chunks_to_array(dataset) -> np.ndarray:
    """Tokenized dataset -> [N, seq_len+1] int32 array."""
    return np.asarray(dataset["input_ids"], dtype=np.int32)
