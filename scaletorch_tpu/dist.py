"""Multi-host runtime bootstrap — the TPU-native ``init_dist``.

Counterpart of reference ``scaletorch/dist/utils.py:78-251`` (``init_dist``
+ per-launcher discovery ``_init_dist_pytorch`` / ``_init_dist_slurm`` /
``_init_dist_mpi``). The torch stack must build NCCL/HCCL process groups
per parallel axis; on TPU all of that collapses into ONE call —
``jax.distributed.initialize`` — after which ``jax.devices()`` spans every
host and the existing mesh/``shard_map`` code is multi-host for free (XLA
routes collectives over ICI within a slice and DCN across slices).

What this module keeps from the reference is the *launcher discovery*
contract (``infer_launcher``, dist/utils.py:144-152): the same process can
be started by torchrun-style env vars, SLURM, or MPI, and finds its
coordinator/rank without code changes. JAX's own cluster detection covers
SLURM/OMPI/TPU-metadata natively; the env launcher additionally accepts
torchrun names (MASTER_ADDR/MASTER_PORT/RANK/WORLD_SIZE) so reference
launch scripts port 1:1.

Data feeding under multi-host SPMD: every process holds the *global* host
batch (deterministic loaders make this free) and ``put_global`` materialises
a global jax.Array by handing each process only its addressable shards —
the role of the reference's per-rank sampler slicing (dataloader.py:170-233).
"""

from __future__ import annotations

import os
from typing import Any, Optional, Sequence

import jax
import numpy as np

# Env names: JAX-native first, torchrun-style fallback (reference
# _init_dist_pytorch reads RANK/WORLD_SIZE/MASTER_*, dist/utils.py:152-165).
from scaletorch_tpu.env import ENV_LAUNCHER_RANK_VARS as _PID_VARS
from scaletorch_tpu.utils.logger import get_logger

_initialized = False

_COORD_VARS = ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS")
_NPROC_VARS = ("JAX_NUM_PROCESSES", "NUM_PROCESSES", "WORLD_SIZE")


def _first_env(names: Sequence[str]) -> Optional[str]:
    for n in names:
        v = os.environ.get(n)
        if v not in (None, ""):
            return v
    return None


def infer_launcher() -> str:
    """Detect how this process was started (reference dist/utils.py:144-152).

    Returns one of 'env' (explicit coordinator env vars, incl. torchrun
    style), 'slurm', 'mpi', or 'none' (single process).
    """
    # 'env' requires a coordinator address: a bare WORLD_SIZE (stale
    # torchrun/SageMaker ambience) must NOT flip a single-process run into
    # a hard "missing coordinator" error.
    if _first_env(_COORD_VARS) or os.environ.get("MASTER_ADDR"):
        return "env"
    if "SLURM_NTASKS" in os.environ and int(os.environ["SLURM_NTASKS"]) > 1:
        return "slurm"
    if "OMPI_COMM_WORLD_SIZE" in os.environ:
        return "mpi"
    return "none"


def _env_coordinator() -> Optional[str]:
    addr = _first_env(_COORD_VARS)
    if addr:
        return addr
    host = os.environ.get("MASTER_ADDR")
    if host:
        port = os.environ.get("MASTER_PORT", "29500")
        return f"{host}:{port}"
    return None


def init_distributed(
    launcher: str = "auto",
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
) -> bool:
    """Initialise the multi-process JAX runtime. Idempotent.

    Returns True when a multi-process runtime is (now) active, False for
    single-process. ``launcher='auto'`` infers from the environment; a
    single-process start is never an error (reference init_dist raises on
    unknown launchers — here 'none' is the benign default because SPMD
    code is identical either way).
    """
    global _initialized
    if _initialized:
        return jax.process_count() > 1
    # Detect an externally-initialised runtime WITHOUT touching the XLA
    # backend (jax.process_count() would initialise it and make a
    # subsequent distributed.initialize impossible).
    try:
        from jax._src.distributed import global_state as _jax_dist_state

        if _jax_dist_state.client is not None:
            _initialized = True
            return jax.process_count() > 1
    except Exception:
        pass

    if launcher == "auto":
        launcher = infer_launcher()
    if launcher == "none":
        return False
    if launcher not in ("env", "slurm", "mpi"):
        raise ValueError(
            f"launcher must be auto|env|slurm|mpi|none, got {launcher!r}"
        )

    # CPU backend (tests / virtual meshes) needs explicit cross-process
    # collectives; gloo is the portable choice. Must be set before backend
    # init. Harmless no-op for the TPU backend, which ignores it.
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        jax.config.update("jax_cpu_collectives_implementation", "gloo")

    kwargs: dict[str, Any] = {}
    if launcher == "env":
        addr = coordinator_address or _env_coordinator()
        nproc = num_processes if num_processes is not None else _first_env(_NPROC_VARS)
        pid = process_id if process_id is not None else _first_env(_PID_VARS)
        if addr is None or nproc is None or pid is None:
            raise ValueError(
                "env launcher needs coordinator_address, num_processes and "
                "process_id (flags, or JAX_COORDINATOR_ADDRESS/"
                "JAX_NUM_PROCESSES/JAX_PROCESS_ID, or torchrun-style "
                "MASTER_ADDR[:MASTER_PORT]/WORLD_SIZE/RANK)"
            )
        kwargs = dict(
            coordinator_address=addr,
            num_processes=int(nproc),
            process_id=int(pid),
        )
        if local_device_ids is not None:
            kwargs["local_device_ids"] = list(local_device_ids)
    # slurm/mpi: jax's ClusterEnv auto-detection (SlurmCluster/OmpiCluster)
    # resolves coordinator + ranks from the scheduler env — the role of
    # reference _init_dist_slurm's scontrol scraping (dist/utils.py:206-251).
    jax.distributed.initialize(**kwargs)
    _initialized = True
    get_logger().info(
        f"distributed runtime up: launcher={launcher} "
        f"process {jax.process_index()}/{jax.process_count()} "
        f"local_devices={jax.local_device_count()} "
        f"global_devices={jax.device_count()}"
    )
    return True


def shutdown_distributed() -> None:
    """Tear down the coordinator link (reference cleanup_dist)."""
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


def is_distributed() -> bool:
    return jax.process_count() > 1


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_main_process() -> bool:
    """Reference ``is_main_process``/rank-0 gating (dist/utils.py role)."""
    return jax.process_index() == 0


def barrier(name: str = "barrier") -> None:
    """Block until every process reaches this point (reference
    torch_dist.barrier role). No-op single-process."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


# ---------------------------------------------------------------------------
# Cross-process OBJECT collectives (reference dist/object_ops.py:26-318 +
# gather_utils.py:24-211). Under single-controller SPMD most result
# collection is moot — every process computes the same globals — but eval
# loops that shard WORK across processes (per-process files, per-host
# generation samples) still need to move arbitrary picklables. The wire
# is pickled bytes -> padded uint8 arrays -> one device all-gather
# (jax.experimental.multihost_utils), the exact role of the reference's
# _object_to_tensor + all_gather (object_ops.py:26-44).
# ---------------------------------------------------------------------------


def _obj_to_u8(obj: Any) -> np.ndarray:
    import pickle

    return np.frombuffer(pickle.dumps(obj), dtype=np.uint8)


def _u8_to_obj(buf: np.ndarray, size: int) -> Any:
    import pickle

    return pickle.loads(bytes(np.asarray(buf[:size], dtype=np.uint8)))


def all_gather_object(obj: Any) -> list:
    """Every process contributes one picklable; every process receives
    ``[obj_0, ..., obj_{P-1}]`` in process order (reference
    all_gather_object, object_ops.py:186-253)."""
    if jax.process_count() == 1:
        return [obj]
    from jax.experimental import multihost_utils

    buf = _obj_to_u8(obj)
    sizes = np.asarray(
        multihost_utils.process_allgather(np.int64(buf.size)))
    cap = int(sizes.max())
    padded = np.zeros(cap, np.uint8)
    padded[: buf.size] = buf
    bufs = np.asarray(multihost_utils.process_allgather(padded))
    return [_u8_to_obj(bufs[p], int(sizes[p]))
            for p in range(jax.process_count())]


def gather_object(obj: Any, dst: int = 0) -> Optional[list]:
    """Gather picklables to process ``dst``; other processes return None
    (reference gather_object, object_ops.py:256-318). The transport is an
    all-gather (XLA collectives have no rooted object gather); only the
    RESULT visibility is rooted, keeping the reference API."""
    out = all_gather_object(obj)
    return out if jax.process_index() == dst else None


def broadcast_object_list(objs: list, src: int = 0) -> list:
    """Replace every element with ``src``'s version (reference
    broadcast_object_list, object_ops.py:117-183)."""
    if jax.process_count() == 1:
        return list(objs)
    # only src's payload matters: non-src processes contribute a tiny
    # placeholder so the padded all-gather moves src's bytes once, not
    # every process's full copy
    mine = list(objs) if jax.process_index() == src else None
    gathered = all_gather_object(mine)
    chosen = gathered[src]
    if len(chosen) != len(objs):
        raise ValueError(
            f"broadcast_object_list: src={src} holds {len(chosen)} objects, "
            f"this process expected {len(objs)}"
        )
    objs[:] = chosen
    return objs


def collect_results(results: list, size: int,
                    device: str = "cpu") -> Optional[list]:
    """Collect per-process result lists to process 0, round-robin
    interleaved and truncated to ``size`` (reference collect_results,
    gather_utils.py:24-211: rank r holds samples r, r+P, r+2P, ... of a
    round-robin sharded eval set). Non-zero processes return None.

    ``device`` is accepted for reference CLI parity; on TPU there is one
    transport (the uint8 all-gather above), so the value is ignored.
    """
    del device  # single transport on TPU
    parts = all_gather_object(list(results))
    if jax.process_index() != 0:
        return None
    interleaved: list = []
    longest = max((len(p) for p in parts), default=0)
    for j in range(longest):
        for p in parts:
            if j < len(p):
                interleaved.append(p[j])
    return interleaved[:size]


def put_global(host_array, sharding) -> jax.Array:
    """Materialise a global array from an identical host copy per process.

    Single-process this is a plain ``device_put``; multi-process each
    process contributes only the shards on its addressable devices
    (``jax.make_array_from_callback`` slices the host copy per device) —
    the multi-host feeding path the reference implements with per-rank
    sampler offsets (dataloader.py:170-233).
    """
    if isinstance(host_array, jax.Array) and not host_array.is_fully_addressable:
        # Already a global multi-process array (e.g. from the streamed HF
        # loader): fetching it to host would crash — and defeat the point.
        if host_array.sharding == sharding:
            return host_array
        return jax.device_put(host_array, sharding)
    if jax.process_count() == 1:
        return jax.device_put(host_array, sharding)
    host_array = np.asarray(host_array)
    return jax.make_array_from_callback(
        host_array.shape, sharding, lambda idx: host_array[idx]
    )
