"""Centralised environment-variable registry.

TPU-native counterpart of the reference's ``scaletorch/env.py:8-29``: a single
place that declares every runtime toggle the framework reads, with defaults,
so models/comms never reach for ``os.environ`` ad hoc.
"""

from __future__ import annotations

import os
from typing import Any, Callable

_REGISTRY: dict[str, tuple[str, Callable[[str], Any]]] = {}


def _as_bool(v: str) -> bool:
    return v.lower() in ("1", "true", "yes", "on")


def register_env(name: str, default: str, parser: Callable[[str], Any] = str) -> None:
    """Declare an environment variable the framework reads."""
    _REGISTRY[name] = (default, parser)


def get_env(name: str) -> Any:
    """Read a registered environment variable, applying default + parser."""
    if name not in _REGISTRY:
        raise KeyError(f"env var {name!r} is not registered; call register_env first")
    default, parser = _REGISTRY[name]
    return parser(os.environ.get(name, default))


def env_snapshot() -> dict[str, Any]:
    """Current values of every registered env var (for logging/diagnostics)."""
    return {k: get_env(k) for k in sorted(_REGISTRY)}


def env_override(name: str, fallback: Any) -> Any:
    """Registered env var when PRESENT — including an explicit 0/empty, so
    a restarted job can CANCEL a config-armed knob without a config edit —
    else the caller's fallback (usually the config field). The single home
    of the present-wins contract shared by every SCALETORCH_TPU_FT_*
    consumer (resilience.FaultInjector, resilience_distributed)."""
    if os.environ.get(name) is not None:
        return get_env(name)
    return fallback


# ---- process-rank discovery (shared by dist.py and logger.py) ---------------
# The first three are the explicit 'env' launcher contract
# (dist.init_distributed); the scheduler-set tail is only a pre-backend-init
# fallback for log gating (logger._process_index_noinit).
ENV_LAUNCHER_RANK_VARS: tuple[str, ...] = ("JAX_PROCESS_ID", "PROCESS_ID", "RANK")
RANK_DISCOVERY_VARS: tuple[str, ...] = ENV_LAUNCHER_RANK_VARS + (
    "SLURM_PROCID",
    "OMPI_COMM_WORLD_RANK",
)

# ---- core toggles (parity with reference scaletorch/env.py) -----------------
register_env("FLASH_ATTEN", "1", _as_bool)          # use pallas flash attention
register_env("CONTEXT_PARALLEL", "0", _as_bool)     # ring attention enabled
register_env("SEQUENCE_PARALLEL", "0", _as_bool)    # Megatron-style SP on tp axis
register_env("VERBOSE", "0", _as_bool)              # chatty comms logging
register_env("DTYPE", "bfloat16", str)              # compute dtype
# TPU-specific additions
register_env("SCALETORCH_TPU_DEVICE_FLOPS", "", str)  # peak-FLOPS override
register_env("SCALETORCH_TPU_MATMUL_PRECISION", "", str)
register_env("SCALETORCH_TPU_DISABLE_PALLAS", "0", _as_bool)  # force XLA fallbacks
# Force the Pallas kernels on when local-device sniffing can't see the TPU:
# AOT compile-only sessions (tools/aot_memory.py) have no local devices at
# all, and remote-execution PJRT plugins may report a tunnel platform name.
register_env("SCALETORCH_TPU_FORCE_PALLAS", "0", _as_bool)
# Context-parallel sequence layout: 'contiguous' or 'zigzag' (balanced
# causal work per ring rank; needs the loader's zigzag token order —
# parallel/zigzag.py). Read by the 'ring' backend at trace time.
register_env("SCALETORCH_TPU_CP_LAYOUT", "contiguous", str)
# Sequence-chunk length for the fused LM-head + cross-entropy (bounds the
# live fp32 [B, C, V/tp] logits transient; halve on HBM-edge configs).
register_env("SCALETORCH_TPU_CE_CHUNK", "1024", int)
# Grouped-MLP Pallas kernel for MoE expert compute (ops/pallas/
# grouped_mlp.py): skips capacity slots past each expert's fill count.
# Default OFF until measured faster than the batched einsum on real
# chips (the einsum is already MXU-dense; the win is the padding skip).
register_env("SCALETORCH_TPU_GROUPED_MLP_KERNEL", "0", _as_bool)
# Flash-kernel tile sizes (ops/pallas/flash.py). The defaults are sound
# for d=64..128 on v5e VMEM; tools/optimize_mfu.py --flash-blocks sweeps
# these on the actual chip (block choice is a measured property, not a
# host-side heuristic).
register_env("SCALETORCH_TPU_FLASH_BLOCK_Q", "512", int)
register_env("SCALETORCH_TPU_FLASH_BLOCK_KV", "512", int)
# Paged-decode attention (ops/pallas/paged_attention.py): 1 (default)
# lets single-token decode on a TPU backend take the Pallas kernel; 0
# forces the lax gather fallback everywhere (the bit-parity oracle).
register_env("SCALETORCH_TPU_PAGED_KERNEL", "1", _as_bool)

# Fault-injection hooks (resilience.FaultInjector): 0 = off. Env overrides
# the ft_* config fields so a running job can be drilled without a config
# edit (e.g. SCALETORCH_TPU_FT_SIGTERM_STEP=100 simulates preemption).
register_env("SCALETORCH_TPU_FT_NAN_STEP", "0", int)
register_env("SCALETORCH_TPU_FT_FAIL_SAVES", "0", int)
register_env("SCALETORCH_TPU_FT_SIGTERM_STEP", "0", int)
# Telemetry drill: stall one optimizer step at the boundary so the
# slow-step detector (telemetry/profiling.py) arms a profiler window.
register_env("SCALETORCH_TPU_FT_SLOW_STEP_STEP", "0", int)
register_env("SCALETORCH_TPU_FT_SLOW_STEP_SECONDS", "0.5", float)
# Multi-host resilience (resilience_distributed.py): restrict the SIGTERM
# drill to one host, inject a step-boundary stall, corrupt one data-stream
# read, tune the hang watchdog, and toggle cross-host decision
# coordination without a config edit.
register_env("SCALETORCH_TPU_FT_SIGTERM_HOST", "-1", int)
register_env("SCALETORCH_TPU_FT_HANG_STEP", "0", int)
register_env("SCALETORCH_TPU_FT_BAD_BATCH_STEP", "0", int)
register_env("SCALETORCH_TPU_FT_HANG_TIMEOUT", "0", float)
register_env("SCALETORCH_TPU_FT_COORDINATE", "1", _as_bool)
# Elastic drills (resilience_distributed.ElasticCoordinator): hard-kill
# one host after step k (survivors remesh and continue), or stall one
# host past the elastic epoch-bus deadline (the fleet evicts it and it
# must park-and-rejoin). KILL_HOST selects the target rank for both.
register_env("SCALETORCH_TPU_FT_KILL_HOST_STEP", "0", int)
register_env("SCALETORCH_TPU_FT_KILL_HOST", "-1", int)
register_env("SCALETORCH_TPU_FT_HOST_HANG_ELASTIC", "0", int)
# Serving fault injection (inference/resilience.ServingFaultInjector):
# same present-wins contract over the ft_serve_* config fields; steps are
# 1-based decode steps of the engine's lifetime.
register_env("SCALETORCH_TPU_FT_SERVE_NAN_STEP", "0", int)
register_env("SCALETORCH_TPU_FT_SERVE_NAN_SLOT", "0", int)
register_env("SCALETORCH_TPU_FT_SERVE_SLOW_STEP", "0", int)
register_env("SCALETORCH_TPU_FT_SERVE_SLOW_SECONDS", "30", float)
register_env("SCALETORCH_TPU_FT_SERVE_SUBMIT_STORM_STEP", "0", int)
register_env("SCALETORCH_TPU_FT_SERVE_SUBMIT_STORM_COUNT", "8", int)
register_env("SCALETORCH_TPU_FT_SERVE_DEADLINE_STORM_STEP", "0", int)
# Gateway fault injection (serving/gateway.py, same present-wins contract
# over the ft_gw_* config fields; the counting unit is 1-based HTTP
# requests — tenant storm at arrival k, replica-down at dispatch k).
register_env("SCALETORCH_TPU_FT_GW_TENANT_STORM_AT", "0", int)
register_env("SCALETORCH_TPU_FT_GW_TENANT_STORM_COUNT", "8", int)
register_env("SCALETORCH_TPU_FT_GW_REPLICA_DOWN_AT", "0", int)
register_env("SCALETORCH_TPU_FT_GW_REPLICA_CRASH_AT", "0", int)
register_env("SCALETORCH_TPU_FT_GW_REPLICA_HANG_AT", "0", int)
# Warm-rejoin drills (serving/remote.py donor side; the counting unit is
# 1-based warm-transfer chunks on the /warm stream).
register_env("SCALETORCH_TPU_FT_GW_WARM_DONOR_CRASH_AT", "0", int)
register_env("SCALETORCH_TPU_FT_GW_WARM_CORRUPT_CHUNK_AT", "0", int)
# Telemetry (scaletorch_tpu/telemetry/): present-wins over the config
# fields (an explicitly EMPTY dir cancels a config-armed telemetry run).
register_env("SCALETORCH_TPU_TELEMETRY_DIR", "", str)
register_env("SCALETORCH_TPU_PROFILE_STEPS", "", str)
