"""TPU-native inference: KV-cache decode, continuous batching, sharded serving.

The serving half of the framework (ROADMAP north star: "serves heavy
traffic from millions of users"), reusing the training stack's mesh, TP
sharding specs, and attention math:

  * ``kv_cache``  — per-layer KV caches in the models' scan layout:
    the dense per-slot ``[L, B, Hkv, S_max, D]`` buffers, the MLA
    latent-only cache, and the PAGED layout — a global pool of
    fixed-size pages ``[L, n_pages, Hkv, page_size, D]`` with per-slot
    page tables, a host-side ``PageAllocator`` (free list + refcounts)
    and a ``RadixPrefixCache`` sharing page-aligned prompt prefixes
    across requests; all head-sharded with the existing TP
    NamedSharding specs.
  * ``decode``    — the jitted steps (full-prompt prefill, single-
    token decode, dense and paged variants) over the models'
    cache-aware forwards; static shapes, donated cache buffers, two
    compiles total per layout.
  * ``sampling``  — greedy / temperature / top-k / top-p with per-slot
    PRNG keys.
  * ``engine``    — continuous batching over a fixed-slot batch: admit
    queued requests into freed slots between decode steps (the jitted
    step never retraces), engine metrics riding the monitor plumbing.
  * ``disagg``    — disaggregated prefill/decode serving: MPMD phase
    slices (two meshes over disjoint device subsets, one jitted
    program each) with page-ownership handoff between two allocators
    through a ``PageHandoffChannel``; slice sizing from the CI-pinned
    per-phase HBM rows.
  * ``resilience`` — serving fault tolerance: the terminal-outcome
    taxonomy (ok / timeout / shed / rejected / quarantined / aborted),
    bounded admission + load shedding, non-finite quarantine, graceful
    drain, the serving stall watchdog (exit code 44), and the
    ``ServingFaultInjector`` driving hermetic end-to-end drills.
"""

from scaletorch_tpu.inference.kv_cache import (  # noqa: F401
    KVCache,
    MLACache,
    PageAllocator,
    PagedKVCache,
    PagedKVIO,
    RadixPrefixCache,
    cache_nbytes,
    init_kv_cache,
    init_mla_cache,
    init_paged_kv_cache,
    kv_cache_bytes,
    kv_cache_shape,
    kv_cache_shardings,
    kv_cache_specs,
    paged_kv_cache_shape,
    paged_kv_cache_shardings,
    paged_kv_cache_specs,
)
from scaletorch_tpu.inference.sampling import (  # noqa: F401
    SamplingParams,
    sample,
    sample_one,
)
from scaletorch_tpu.inference.decode import (  # noqa: F401
    make_decode_step,
    make_fill_slots_step,
    make_paged_decode_step,
    make_paged_prefill_step,
    make_prefill_step,
    resolve_forward_cached,
)
from scaletorch_tpu.inference.resilience import (  # noqa: F401
    SERVING_STALL_EXIT_CODE,
    TERMINAL_OUTCOMES,
    EngineDraining,
    ServingFaultInjector,
    make_serving_watchdog,
)
from scaletorch_tpu.inference.engine import (  # noqa: F401
    EngineMetrics,
    InferenceEngine,
    Request,
    RequestResult,
)
from scaletorch_tpu.inference.disagg import (  # noqa: F401
    DisaggMetrics,
    DisaggregatedEngine,
    HandoffError,
    PageHandoffChannel,
    parse_disagg_spec,
    plan_slice_split,
)
