"""The two jitted engine steps: full-prompt prefill and one-token decode.

Static shapes everywhere — the engine compiles each step exactly once
per run, however many requests flow through it:

  * ``prefill``: a full-sequence causal forward over the fixed
    ``[B, P_max]`` prompt buffer that also writes cache positions
    [0, P_max) for the slots named by ``write_mask`` (live slots'
    cache bytes are untouched), returns the first sampled token per
    slot. Admitting a request into a freed slot is "set its row of the
    buffer, flip its mask bit" — no new trace.
  * ``decode``: one token per slot at per-slot absolute positions,
    RoPE at the absolute position, ``lax.dynamic_update_slice`` cache
    append, sample. Cache buffers are DONATED — XLA appends in place
    instead of copying the whole cache every token.

Both lower onto the models' cache-aware forwards
(models/llama.py forward_cached & family), resolved per config by
``resolve_forward_cached``.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from scaletorch_tpu.inference.kv_cache import KVCache
from scaletorch_tpu.inference.sampling import (
    SamplingParams,
    finite_mask,
    sample,
    slot_keys,
)


def _resolve_donate(donate_cache: Optional[bool]) -> bool:
    """None = donate wherever the backend honours it (TPU/GPU); the CPU
    runtime ignores donation and warns per call, so skip it there."""
    if donate_cache is not None:
        return donate_cache
    return jax.default_backend() != "cpu"


def resolve_forward_cached(cfg) -> Callable:
    """The cache-aware forward for a model config: Qwen3-MoE and GPT-MoE
    have their own cached forwards; every other LlamaConfig subclass
    (Llama, Qwen3) shares the Llama one."""
    from scaletorch_tpu.models.gpt_moe import GPTMoEConfig
    from scaletorch_tpu.models.llama import LlamaConfig
    from scaletorch_tpu.models.qwen3_moe import Qwen3MoEConfig

    if isinstance(cfg, Qwen3MoEConfig):
        from scaletorch_tpu.models import qwen3_moe

        return qwen3_moe.forward_cached
    if isinstance(cfg, LlamaConfig):
        from scaletorch_tpu.models import llama

        return llama.forward_cached
    if isinstance(cfg, GPTMoEConfig):
        from scaletorch_tpu.models import gpt_moe

        return gpt_moe.forward_cached
    raise TypeError(
        f"no cache-aware forward known for config {type(cfg).__name__}"
    )


def make_prefill_step(
    cfg,
    sampling: SamplingParams,
    *,
    forward_fn: Optional[Callable] = None,
    donate_cache: Optional[bool] = None,
) -> Callable:
    """Build the jitted prefill step.

    prefill(params, tokens [B, P], lengths [B], write_mask [B] bool,
            cache, base_keys [B, 2])
      -> (first_token [B] i32, last_logits [B, V] f32, finite [B] bool,
          new_cache)

    Runs the full causal forward over the whole fixed buffer (positions
    [0, P) for every slot), writes cache [0, P) for masked slots only,
    reads each slot's logits at ``lengths - 1`` and samples its first
    token. ``finite`` flags the slots whose sampled-from logits are all
    finite (``sampling.finite_mask``) — the engine quarantines a False
    slot instead of emitting its garbage sample. Anything the buffer
    holds beyond a slot's length writes garbage K/V above the slot's
    live region — invisible, because the j <= p attention mask never
    reaches past the current position and decode overwrites position p
    before attending to it.
    """
    fwd = forward_fn or resolve_forward_cached(cfg)

    def prefill(params, tokens, lengths, write_mask, cache, base_keys):
        b, p = tokens.shape
        positions = jnp.broadcast_to(
            jnp.arange(p, dtype=jnp.int32), (b, p))
        logits, new_cache = fwd(
            params, tokens, cfg, tuple(cache),
            positions=positions, write_mask=write_mask,
        )
        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1
        )[:, 0, :]
        keys = slot_keys(base_keys, lengths - 1)
        first = sample(last, keys, sampling)
        return (first, last.astype(jnp.float32), finite_mask(last),
                KVCache(*new_cache))

    return jax.jit(
        prefill, donate_argnums=(4,) if _resolve_donate(donate_cache) else ()
    )


def make_decode_step(
    cfg,
    sampling: SamplingParams,
    *,
    forward_fn: Optional[Callable] = None,
    donate_cache: Optional[bool] = None,
) -> Callable:
    """Build the jitted single-token decode step.

    decode(params, tokens [B] i32, positions [B] i32, active [B] bool,
           cache, base_keys [B, 2])
      -> (next_token [B] i32, logits [B, V] f32, finite [B] bool,
          new_cache)

    Feeds each slot's current token at its absolute position (RoPE at
    that position), appends K/V at the position for ACTIVE slots only,
    and samples the next token with the slot's (seed, position) key.
    ``finite`` is the in-step non-finite guard (``sampling.finite_mask``
    over the step logits): a False slot carries NaN/Inf numerics — the
    engine retires it as ``quarantined`` and never emits its sample.
    Inactive slots compute garbage that goes nowhere — their mask bit
    keeps their cache bytes intact and the engine ignores their sample.
    """
    fwd = forward_fn or resolve_forward_cached(cfg)

    def decode(params, tokens, positions, active, cache, base_keys):
        logits, new_cache = fwd(
            params, tokens[:, None], cfg, tuple(cache),
            positions=positions[:, None], write_mask=active,
        )
        step_logits = logits[:, 0, :]
        keys = slot_keys(base_keys, positions)
        nxt = sample(step_logits, keys, sampling)
        return (nxt, step_logits.astype(jnp.float32),
                finite_mask(step_logits), KVCache(*new_cache))

    return jax.jit(
        decode, donate_argnums=(4,) if _resolve_donate(donate_cache) else ()
    )


def make_fill_slots_step(*, donate_cache: Optional[bool] = None) -> Callable:
    """Build the jitted masked fill over axis 1 of the stacked cache.

    fill_slots(cache, mask bool, value scalar) -> cache with every
    masked index's lines along axis 1 set to ``value``; unmasked bytes
    pass through bit-identical. Axis 1 is the SLOT axis of the dense
    [L, B, Hkv, S_max, D] buffers and the PAGE axis of the paged
    [L, n_pages, Hkv, page_size, D] pools — the same compiled step
    serves both layouts (the engine clears whole slots dense, whole
    pages paged).

    One compile serves the scalar consumers — quarantine hygiene
    (value 0: a retired poison slot's NaN K/V must not outlive the
    request) and fault injection (value NaN: poison a slot's cache
    lines so its next decode step goes non-finite) — because the mask
    and the fill value are data, never shapes. ``value`` may also be a
    cache-shaped tuple (one buffer per cache field): the warm-rejoin
    import writes transferred page CONTENTS through this same step —
    masked pages take the tuple's bytes, unmasked pages pass through
    bit-identical. That is a second argument STRUCTURE, hence a second
    specialization of this function only; the decode/prefill entries
    the deep-tier audit pins never retrace. The cache is donated like
    the engine steps, so XLA rewrites the masked lanes in place.
    """

    def fill_slots(cache, mask, value):
        vals = tuple(value) if isinstance(value, tuple) \
            else (value,) * len(cache)

        def fill(buf, val):
            m = mask.reshape((1, mask.shape[0]) + (1,) * (buf.ndim - 2))
            return jnp.where(m, jnp.asarray(val, buf.dtype), buf)

        return type(cache)(*(fill(buf, val)
                             for buf, val in zip(cache, vals)))

    return jax.jit(
        fill_slots,
        donate_argnums=(0,) if _resolve_donate(donate_cache) else (),
    )


# ---------------------------------------------------------------------------
# paged-cache steps (ISSUE 10)
# ---------------------------------------------------------------------------
def make_paged_prefill_step(
    cfg,
    sampling: SamplingParams,
    *,
    page_size: int,
    seq_limit: Optional[int] = None,
    forward_fn: Optional[Callable] = None,
    donate_cache: Optional[bool] = None,
) -> Callable:
    """Build the jitted paged prefill step.

    prefill(params, tokens [B, P], tail_lens [B], starts [B],
            write_mask [B] bool, page_tables [B, max_pages] i32,
            pool (PagedKVCache), base_keys [B, 2])
      -> (first_token [B] i32, last_logits [B, V] f32, finite [B] bool,
          new_pool)

    The paged twist on ``make_prefill_step``: each admitted slot
    prefills only its NON-SHARED prompt tail. ``starts`` is the
    page-aligned count of tokens already cached via a radix prefix hit
    (0 without one); the tail tokens sit at buffer rows [0, tail_len)
    and run at absolute positions ``starts + row`` — their attention
    reads the shared prefix pages straight out of the pool through the
    page table, so the shared positions cost ZERO forward compute.
    Writes land in the slot's own pages only (prefix sharing is
    page-aligned and shared pages are frozen); rows past ``tail_len``
    write garbage into the slot's own later pages or the TRASH page,
    invisible for the same reason the dense buffer's garbage is. The
    first token samples from the logits at row ``tail_len - 1`` with
    the slot's (seed, prompt_len - 1) key — bit-identical to the dense
    engine's first sample.
    """
    fwd = forward_fn or resolve_forward_cached(cfg)

    def prefill(params, tokens, tail_lens, starts, write_mask,
                page_tables, pool, base_keys):
        from scaletorch_tpu.inference.kv_cache import PagedKVCache, PagedKVIO

        b, p = tokens.shape
        positions = starts[:, None] + jnp.broadcast_to(
            jnp.arange(p, dtype=jnp.int32), (b, p))
        kv_io = PagedKVIO(page_tables, page_size, seq_limit=seq_limit)
        logits, new_pool = fwd(
            params, tokens, cfg, tuple(pool),
            positions=positions, write_mask=write_mask, kv_io=kv_io,
        )
        last = jnp.take_along_axis(
            logits, (tail_lens - 1)[:, None, None], axis=1
        )[:, 0, :]
        keys = slot_keys(base_keys, starts + tail_lens - 1)
        first = sample(last, keys, sampling)
        return (first, last.astype(jnp.float32), finite_mask(last),
                PagedKVCache(*new_pool))

    return jax.jit(
        prefill, donate_argnums=(6,) if _resolve_donate(donate_cache) else ()
    )


def make_paged_decode_step(
    cfg,
    sampling: SamplingParams,
    *,
    page_size: int,
    seq_limit: Optional[int] = None,
    forward_fn: Optional[Callable] = None,
    donate_cache: Optional[bool] = None,
) -> Callable:
    """Build the jitted paged single-token decode step.

    decode(params, tokens [B] i32, positions [B] i32, active [B] bool,
           page_tables [B, max_pages] i32, pool (PagedKVCache),
           base_keys [B, 2])
      -> (next_token [B] i32, logits [B, V] f32, finite [B] bool,
          new_pool)

    Identical contract to ``make_decode_step`` with the cache reads
    routed through the page table: the K/V append is a scatter into the
    slot's current page and attention is a gather over its table (the
    Pallas paged-decode kernel on TPU, the lax gather fallback on
    CPU/interpret/old-jax — ops/pallas/paged_attention.py). Page-table
    contents are DATA: admissions, prefix hits, quarantine clears, and
    frees all mutate tables host-side and this one compile serves them
    all.
    """
    fwd = forward_fn or resolve_forward_cached(cfg)

    def decode(params, tokens, positions, active, page_tables, pool,
               base_keys):
        from scaletorch_tpu.inference.kv_cache import PagedKVCache, PagedKVIO

        kv_io = PagedKVIO(page_tables, page_size, seq_limit=seq_limit)
        logits, new_pool = fwd(
            params, tokens[:, None], cfg, tuple(pool),
            positions=positions[:, None], write_mask=active, kv_io=kv_io,
        )
        step_logits = logits[:, 0, :]
        keys = slot_keys(base_keys, positions)
        nxt = sample(step_logits, keys, sampling)
        return (nxt, step_logits.astype(jnp.float32),
                finite_mask(step_logits), PagedKVCache(*new_pool))

    return jax.jit(
        decode, donate_argnums=(5,) if _resolve_donate(donate_cache) else ()
    )


def teacher_forced_decode_paged(
    params,
    cfg,
    tokens: jax.Array,
    *,
    page_size: int,
    max_seq: Optional[int] = None,
    prefill_len: int = 1,
    forward_fn: Optional[Callable] = None,
    dtype=None,
) -> jax.Array:
    """Paged twin of ``teacher_forced_decode``: the same prefill-then-
    teacher-forced-decode schedule run against a page pool through an
    identity page table (slot ``b`` owns pages ``b*max_pages+1 ..``,
    page 0 reserved as TRASH). Returns [B, S, V] logits — the parity
    oracle proving the paged read/write path is positionally identical
    to the dense cache, layer by layer, token by token."""
    import numpy as np

    from scaletorch_tpu.inference.kv_cache import (
        PagedKVIO,
        ceil_div,
        init_paged_kv_cache,
    )

    fwd = forward_fn or resolve_forward_cached(cfg)
    b, s = tokens.shape
    s_max = max_seq or s
    max_pages = ceil_div(s_max, page_size)
    pool = init_paged_kv_cache(
        cfg, b * max_pages + 1, page_size,
        dtype=dtype or getattr(cfg, "dtype", None))
    tables = (np.arange(b * max_pages, dtype=np.int32) + 1).reshape(
        b, max_pages)
    kv_io = PagedKVIO(jnp.asarray(tables), page_size, seq_limit=s_max)
    p = prefill_len
    positions = jnp.broadcast_to(jnp.arange(p, dtype=jnp.int32), (b, p))
    logits_p, pool = fwd(params, tokens[:, :p], cfg, tuple(pool),
                         positions=positions, kv_io=kv_io)
    chunks = [logits_p]
    for t in range(p, s):
        logits_t, pool = fwd(
            params, tokens[:, t:t + 1], cfg, tuple(pool),
            positions=jnp.full((b, 1), t, jnp.int32), kv_io=kv_io,
        )
        chunks.append(logits_t)
    return jnp.concatenate(chunks, axis=1)


def _audit_cfg_and_cache(compute_dtype: str = "fp32"):
    """Shared tiny setup for the inference audit targets below.
    ``compute_dtype`` selects the activation/cache dtype so the memory
    tier's ST1003 injection tests can build a bf16-contracted entry;
    the manifest default stays fp32 (the CPU-mesh numerics the parity
    oracles attest)."""
    from scaletorch_tpu.inference.kv_cache import init_kv_cache
    from scaletorch_tpu.models.llama import LlamaConfig, init_params

    dt = jnp.bfloat16 if compute_dtype in ("bf16", "bfloat16") \
        else jnp.float32
    cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=256,
        dtype=dt, param_dtype=jnp.float32,
    )
    b, s_max = 2, 32
    params = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg))
    cache = jax.eval_shape(
        lambda: init_kv_cache(cfg, b, s_max, dtype=dt))
    base_keys = jax.ShapeDtypeStruct((b, 2), jnp.uint32)
    return cfg, params, cache, base_keys, b, s_max


def audit_entry_prefill():
    """Deep-tier audit target (analysis/jaxpr_audit.py): the jitted
    prefill step on one device. Contract: cache donation survives
    lowering (``donate_cache=True`` — the CPU default skips donation,
    which is exactly what the audit must not silently accept), and the
    single-device step compiles to ZERO collectives — any collective
    that appears is unbudgeted by definition (tools/comm_budget.json
    records an empty set for this entry).

    Memory-tier contract (analysis/memory.py): the donated cache's
    bytes show up as input/output alias savings (``donated_min_mb`` —
    ST1002), and the engine's ``kv_cache_bytes`` for the dense layout
    matches the compiled cache buffers (``kv_cache`` — ST1005). Pinned
    here, NOT derived from the built objects, so a sizing drift fails
    the gate instead of relaxing it."""
    from scaletorch_tpu.inference.kv_cache import kv_cache_bytes

    cfg, params, cache, base_keys, b, s_max = _audit_cfg_and_cache()
    fn = make_prefill_step(
        cfg, SamplingParams(temperature=0.0), donate_cache=True)
    args = (
        params,
        jax.ShapeDtypeStruct((b, s_max), jnp.int32),   # tokens
        jax.ShapeDtypeStruct((b,), jnp.int32),         # lengths
        jax.ShapeDtypeStruct((b,), jnp.bool_),         # write_mask
        cache,
        base_keys,
    )
    cache_mb = kv_cache_bytes(cfg, b, s_max, jnp.float32) / 1e6
    return {
        "name": "prefill_step",
        "file": "scaletorch_tpu/inference/decode.py",
        "fn": fn,
        "args": args,
        "min_devices": 1,
        "quantized_axis": None,
        "expect_donation": True,
        "hoisted_axes": (),
        "max_collective_result_mb": 1.0,
        "compute_dtype": "fp32",
        "donated_min_mb": round(0.9 * cache_mb, 4),
        "kv_cache": {
            "cfg": cfg, "layout": "dense", "batch": b, "max_seq": s_max,
            "dtype": jnp.float32, "arg_index": 4,
        },
    }


def audit_entry_decode(
    compute_dtype: str = "fp32", fp32_residual: bool = False
):
    """Deep-tier audit target: the jitted one-token decode step on one
    device (same contract as ``audit_entry_prefill``).

    The kwargs exist so the memory-tier tests can inject exactly the
    ST1003 regression: ``compute_dtype="bf16"`` builds the
    bf16-contracted entry, ``fp32_residual=True`` routes the cache
    through a large fp32 round-trip in the forward — the accidental
    upcast the precision-leak check must attribute to its source line.
    The manifest build stays fp32 (check inert, like the train steps).
    """
    from scaletorch_tpu.inference.kv_cache import kv_cache_bytes

    cfg, params, cache, base_keys, b, s_max = \
        _audit_cfg_and_cache(compute_dtype)
    forward_fn = None
    if fp32_residual:
        base_fwd = resolve_forward_cached(cfg)

        def forward_fn(p, tokens, c, kv, **kw):
            logits, new_kv = base_fwd(p, tokens, c, kv, **kw)
            # the injected leak: a full-cache fp32 round trip
            new_kv = jax.tree.map(
                lambda x: (x.astype(jnp.float32) + 0.0).astype(x.dtype),
                new_kv,
            )
            return logits, new_kv

    fn = make_decode_step(
        cfg, SamplingParams(temperature=0.0), forward_fn=forward_fn,
        donate_cache=True)
    args = (
        params,
        jax.ShapeDtypeStruct((b,), jnp.int32),         # tokens
        jax.ShapeDtypeStruct((b,), jnp.int32),         # positions
        jax.ShapeDtypeStruct((b,), jnp.bool_),         # active
        cache,
        base_keys,
    )
    cache_dt = cache.k.dtype
    cache_mb = kv_cache_bytes(cfg, b, s_max, cache_dt) / 1e6
    return {
        "name": "decode_step",
        "file": "scaletorch_tpu/inference/decode.py",
        "fn": fn,
        "args": args,
        "min_devices": 1,
        "quantized_axis": None,
        "expect_donation": True,
        "hoisted_axes": (),
        "max_collective_result_mb": 1.0,
        "compute_dtype": compute_dtype,
        # one cache buffer (k or v) counts as "large" — the smallest
        # fp32 intermediate the leak injection materialises
        "fp32_large_elems": 2048,
        "donated_min_mb": round(0.9 * cache_mb, 4),
        "kv_cache": {
            "cfg": cfg, "layout": "dense", "batch": b, "max_seq": s_max,
            "dtype": cache_dt, "arg_index": 4,
        },
    }


def audit_entry_paged_decode(pool_pages: Optional[int] = None):
    """Deep-tier audit target: the jitted paged one-token decode step on
    one device. Contract: donation of the PAGE POOL survives lowering
    (the pool is the whole serving cache — losing the alias doubles
    serving HBM per step) and the single-device step compiles to ZERO
    collectives (empty budget row in tools/comm_budget.json, like the
    dense steps).

    Memory-tier contract: the ``kv_cache`` sizing is pinned to the
    DEFAULT pool (``b * max_pages + 1`` pages, the dense-equivalent +
    trash page) regardless of ``pool_pages`` — the kwarg exists so the
    ST1005 tests can build a shrunken pool and prove the gate catches
    the engine/compiled-bytes drift, exactly the PR 6 injection style.
    """
    from scaletorch_tpu.inference.kv_cache import (
        init_paged_kv_cache,
        kv_cache_bytes,
    )

    cfg, params, _, base_keys, b, s_max = _audit_cfg_and_cache()
    page_size = 8
    max_pages = s_max // page_size
    num_pages = b * max_pages + 1
    pool = jax.eval_shape(
        lambda: init_paged_kv_cache(
            cfg, pool_pages if pool_pages is not None else num_pages,
            page_size, dtype=jnp.float32))
    fn = make_paged_decode_step(
        cfg, SamplingParams(temperature=0.0), page_size=page_size,
        seq_limit=s_max, donate_cache=True)
    args = (
        params,
        jax.ShapeDtypeStruct((b,), jnp.int32),             # tokens
        jax.ShapeDtypeStruct((b,), jnp.int32),             # positions
        jax.ShapeDtypeStruct((b,), jnp.bool_),             # active
        jax.ShapeDtypeStruct((b, max_pages), jnp.int32),   # page tables
        pool,
        base_keys,
    )
    pool_mb = kv_cache_bytes(
        cfg, b, s_max, jnp.float32, layout="paged", page_size=page_size,
        num_pages=num_pages) / 1e6
    return {
        "name": "paged_decode_step",
        "file": "scaletorch_tpu/inference/decode.py",
        "fn": fn,
        "args": args,
        "min_devices": 1,
        "quantized_axis": None,
        "expect_donation": True,
        "hoisted_axes": (),
        "max_collective_result_mb": 1.0,
        "compute_dtype": "fp32",
        "donated_min_mb": round(0.9 * pool_mb, 4),
        "kv_cache": {
            "cfg": cfg, "layout": "paged", "batch": b, "max_seq": s_max,
            "dtype": jnp.float32, "page_size": page_size,
            "num_pages": num_pages, "arg_index": 5,
        },
    }


def teacher_forced_decode(
    params,
    cfg,
    tokens: jax.Array,
    *,
    max_seq: Optional[int] = None,
    prefill_len: int = 1,
    forward_fn: Optional[Callable] = None,
    dtype=None,
) -> jax.Array:
    """Reference harness: prefill the first ``prefill_len`` tokens, then
    decode the rest one at a time with the GROUND-TRUTH token at each
    step (no sampling). Returns [B, S, V] logits position-aligned with
    the full-sequence training forward — the parity oracle the engine
    tests assert against (ISSUE 4 acceptance: prefill+decode logit
    parity under teacher forcing).
    """
    from scaletorch_tpu.inference.kv_cache import init_kv_cache

    fwd = forward_fn or resolve_forward_cached(cfg)
    b, s = tokens.shape
    cache = init_kv_cache(cfg, b, max_seq or s,
                          dtype=dtype or getattr(cfg, "dtype", None))
    p = prefill_len
    positions = jnp.broadcast_to(jnp.arange(p, dtype=jnp.int32), (b, p))
    logits_p, cache = fwd(params, tokens[:, :p], cfg, tuple(cache),
                          positions=positions)
    chunks = [logits_p]
    for t in range(p, s):
        logits_t, cache = fwd(
            params, tokens[:, t:t + 1], cfg, tuple(cache),
            positions=jnp.full((b, 1), t, jnp.int32),
        )
        chunks.append(logits_t)
    return jnp.concatenate(chunks, axis=1)
