"""The two jitted engine steps: full-prompt prefill and one-token decode.

Static shapes everywhere — the engine compiles each step exactly once
per run, however many requests flow through it:

  * ``prefill``: a full-sequence causal forward over the fixed
    ``[B, P_max]`` prompt buffer that also writes cache positions
    [0, P_max) for the slots named by ``write_mask`` (live slots'
    cache bytes are untouched), returns the first sampled token per
    slot. Admitting a request into a freed slot is "set its row of the
    buffer, flip its mask bit" — no new trace.
  * ``decode``: one token per slot at per-slot absolute positions,
    RoPE at the absolute position, ``lax.dynamic_update_slice`` cache
    append, sample. Cache buffers are DONATED — XLA appends in place
    instead of copying the whole cache every token.

Both lower onto the models' cache-aware forwards
(models/llama.py forward_cached & family), resolved per config by
``resolve_forward_cached``.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from scaletorch_tpu.inference.kv_cache import KVCache
from scaletorch_tpu.inference.sampling import (
    SamplingParams,
    finite_mask,
    sample,
    slot_keys,
)


def _resolve_donate(donate_cache: Optional[bool]) -> bool:
    """None = donate wherever the backend honours it (TPU/GPU); the CPU
    runtime ignores donation and warns per call, so skip it there."""
    if donate_cache is not None:
        return donate_cache
    return jax.default_backend() != "cpu"


def resolve_forward_cached(cfg) -> Callable:
    """The cache-aware forward for a model config: Qwen3-MoE and GPT-MoE
    have their own cached forwards; every other LlamaConfig subclass
    (Llama, Qwen3) shares the Llama one."""
    from scaletorch_tpu.models.gpt_moe import GPTMoEConfig
    from scaletorch_tpu.models.llama import LlamaConfig
    from scaletorch_tpu.models.qwen3_moe import Qwen3MoEConfig

    if isinstance(cfg, Qwen3MoEConfig):
        from scaletorch_tpu.models import qwen3_moe

        return qwen3_moe.forward_cached
    if isinstance(cfg, LlamaConfig):
        from scaletorch_tpu.models import llama

        return llama.forward_cached
    if isinstance(cfg, GPTMoEConfig):
        from scaletorch_tpu.models import gpt_moe

        return gpt_moe.forward_cached
    raise TypeError(
        f"no cache-aware forward known for config {type(cfg).__name__}"
    )


def make_prefill_step(
    cfg,
    sampling: SamplingParams,
    *,
    forward_fn: Optional[Callable] = None,
    donate_cache: Optional[bool] = None,
) -> Callable:
    """Build the jitted prefill step.

    prefill(params, tokens [B, P], lengths [B], write_mask [B] bool,
            cache, base_keys [B, 2])
      -> (first_token [B] i32, last_logits [B, V] f32, finite [B] bool,
          new_cache)

    Runs the full causal forward over the whole fixed buffer (positions
    [0, P) for every slot), writes cache [0, P) for masked slots only,
    reads each slot's logits at ``lengths - 1`` and samples its first
    token. ``finite`` flags the slots whose sampled-from logits are all
    finite (``sampling.finite_mask``) — the engine quarantines a False
    slot instead of emitting its garbage sample. Anything the buffer
    holds beyond a slot's length writes garbage K/V above the slot's
    live region — invisible, because the j <= p attention mask never
    reaches past the current position and decode overwrites position p
    before attending to it.
    """
    fwd = forward_fn or resolve_forward_cached(cfg)

    def prefill(params, tokens, lengths, write_mask, cache, base_keys):
        b, p = tokens.shape
        positions = jnp.broadcast_to(
            jnp.arange(p, dtype=jnp.int32), (b, p))
        logits, new_cache = fwd(
            params, tokens, cfg, tuple(cache),
            positions=positions, write_mask=write_mask,
        )
        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1
        )[:, 0, :]
        keys = slot_keys(base_keys, lengths - 1)
        first = sample(last, keys, sampling)
        return (first, last.astype(jnp.float32), finite_mask(last),
                KVCache(*new_cache))

    return jax.jit(
        prefill, donate_argnums=(4,) if _resolve_donate(donate_cache) else ()
    )


def make_decode_step(
    cfg,
    sampling: SamplingParams,
    *,
    forward_fn: Optional[Callable] = None,
    donate_cache: Optional[bool] = None,
) -> Callable:
    """Build the jitted single-token decode step.

    decode(params, tokens [B] i32, positions [B] i32, active [B] bool,
           cache, base_keys [B, 2])
      -> (next_token [B] i32, logits [B, V] f32, finite [B] bool,
          new_cache)

    Feeds each slot's current token at its absolute position (RoPE at
    that position), appends K/V at the position for ACTIVE slots only,
    and samples the next token with the slot's (seed, position) key.
    ``finite`` is the in-step non-finite guard (``sampling.finite_mask``
    over the step logits): a False slot carries NaN/Inf numerics — the
    engine retires it as ``quarantined`` and never emits its sample.
    Inactive slots compute garbage that goes nowhere — their mask bit
    keeps their cache bytes intact and the engine ignores their sample.
    """
    fwd = forward_fn or resolve_forward_cached(cfg)

    def decode(params, tokens, positions, active, cache, base_keys):
        logits, new_cache = fwd(
            params, tokens[:, None], cfg, tuple(cache),
            positions=positions[:, None], write_mask=active,
        )
        step_logits = logits[:, 0, :]
        keys = slot_keys(base_keys, positions)
        nxt = sample(step_logits, keys, sampling)
        return (nxt, step_logits.astype(jnp.float32),
                finite_mask(step_logits), KVCache(*new_cache))

    return jax.jit(
        decode, donate_argnums=(4,) if _resolve_donate(donate_cache) else ()
    )


def make_fill_slots_step(*, donate_cache: Optional[bool] = None) -> Callable:
    """Build the jitted masked slot-fill over the stacked KV cache.

    fill_slots(cache, mask [B] bool, value scalar) -> cache with every
    masked slot's cache lines set to ``value`` along the batch axis
    (axis 1 of the [L, B, Hkv, S_max, D] buffers); unmasked slots' bytes
    pass through bit-identical.

    One compile serves both consumers — quarantine hygiene (value 0:
    a retired poison slot's NaN K/V must not outlive the request) and
    fault injection (value NaN: poison one slot's cache so its next
    decode step goes non-finite) — because the mask and the fill value
    are data, never shapes. The cache is donated like the engine steps,
    so XLA rewrites the masked lanes in place.
    """

    def fill_slots(cache, mask, value):
        def fill(buf):
            m = mask.reshape((1, mask.shape[0]) + (1,) * (buf.ndim - 2))
            return jnp.where(m, jnp.asarray(value, buf.dtype), buf)

        return KVCache(*(fill(buf) for buf in cache))

    return jax.jit(
        fill_slots,
        donate_argnums=(0,) if _resolve_donate(donate_cache) else (),
    )


def _audit_cfg_and_cache():
    """Shared tiny setup for the two inference audit targets below."""
    from scaletorch_tpu.inference.kv_cache import init_kv_cache
    from scaletorch_tpu.models.llama import LlamaConfig, init_params

    cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=256,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    b, s_max = 2, 32
    params = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg))
    cache = jax.eval_shape(
        lambda: init_kv_cache(cfg, b, s_max, dtype=jnp.float32))
    base_keys = jax.ShapeDtypeStruct((b, 2), jnp.uint32)
    return cfg, params, cache, base_keys, b, s_max


def audit_entry_prefill():
    """Deep-tier audit target (analysis/jaxpr_audit.py): the jitted
    prefill step on one device. Contract: cache donation survives
    lowering (``donate_cache=True`` — the CPU default skips donation,
    which is exactly what the audit must not silently accept), and the
    single-device step compiles to ZERO collectives — any collective
    that appears is unbudgeted by definition (tools/comm_budget.json
    records an empty set for this entry)."""
    cfg, params, cache, base_keys, b, s_max = _audit_cfg_and_cache()
    fn = make_prefill_step(
        cfg, SamplingParams(temperature=0.0), donate_cache=True)
    args = (
        params,
        jax.ShapeDtypeStruct((b, s_max), jnp.int32),   # tokens
        jax.ShapeDtypeStruct((b,), jnp.int32),         # lengths
        jax.ShapeDtypeStruct((b,), jnp.bool_),         # write_mask
        cache,
        base_keys,
    )
    return {
        "name": "prefill_step",
        "file": "scaletorch_tpu/inference/decode.py",
        "fn": fn,
        "args": args,
        "min_devices": 1,
        "quantized_axis": None,
        "expect_donation": True,
        "hoisted_axes": (),
        "max_collective_result_mb": 1.0,
    }


def audit_entry_decode():
    """Deep-tier audit target: the jitted one-token decode step on one
    device (same contract as ``audit_entry_prefill``)."""
    cfg, params, cache, base_keys, b, _ = _audit_cfg_and_cache()
    fn = make_decode_step(
        cfg, SamplingParams(temperature=0.0), donate_cache=True)
    args = (
        params,
        jax.ShapeDtypeStruct((b,), jnp.int32),         # tokens
        jax.ShapeDtypeStruct((b,), jnp.int32),         # positions
        jax.ShapeDtypeStruct((b,), jnp.bool_),         # active
        cache,
        base_keys,
    )
    return {
        "name": "decode_step",
        "file": "scaletorch_tpu/inference/decode.py",
        "fn": fn,
        "args": args,
        "min_devices": 1,
        "quantized_axis": None,
        "expect_donation": True,
        "hoisted_axes": (),
        "max_collective_result_mb": 1.0,
    }


def teacher_forced_decode(
    params,
    cfg,
    tokens: jax.Array,
    *,
    max_seq: Optional[int] = None,
    prefill_len: int = 1,
    forward_fn: Optional[Callable] = None,
    dtype=None,
) -> jax.Array:
    """Reference harness: prefill the first ``prefill_len`` tokens, then
    decode the rest one at a time with the GROUND-TRUTH token at each
    step (no sampling). Returns [B, S, V] logits position-aligned with
    the full-sequence training forward — the parity oracle the engine
    tests assert against (ISSUE 4 acceptance: prefill+decode logit
    parity under teacher forcing).
    """
    from scaletorch_tpu.inference.kv_cache import init_kv_cache

    fwd = forward_fn or resolve_forward_cached(cfg)
    b, s = tokens.shape
    cache = init_kv_cache(cfg, b, max_seq or s,
                          dtype=dtype or getattr(cfg, "dtype", None))
    p = prefill_len
    positions = jnp.broadcast_to(jnp.arange(p, dtype=jnp.int32), (b, p))
    logits_p, cache = fwd(params, tokens[:, :p], cfg, tuple(cache),
                          positions=positions)
    chunks = [logits_p]
    for t in range(p, s):
        logits_t, cache = fwd(
            params, tokens[:, t:t + 1], cfg, tuple(cache),
            positions=jnp.full((b, 1), t, jnp.int32),
        )
        chunks.append(logits_t)
    return jnp.concatenate(chunks, axis=1)
