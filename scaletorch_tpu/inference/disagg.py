"""Disaggregated prefill/decode serving: MPMD phase slices with
page-ownership handoff.

Prefill is compute-bound, decode is bandwidth-bound — one SPMD program
over both phases wastes whichever resource the current phase doesn't
need. ``DisaggregatedEngine`` splits the device fleet into a PREFILL
slice and a DECODE slice (two ``Mesh``es over disjoint device subsets)
and runs one jitted program per phase: the paged prefill step only ever
sees prefill-slice operands, the paged decode step only decode-slice
operands, so the one-compile discipline holds on BOTH programs
(``prefill_compile_count == 1`` and ``decode_compile_count == 1``
across admissions, handoffs and quarantines — jit follows committed
operand placement, it never retraces for it).

The page is the handoff unit (PR 10) and ownership crosses slices
through TWO ``PageAllocator``s, all-or-nothing per request:

  submit -> queue -> [prefill slice] prefill pool pages, full-prompt
  prefill, FIRST token emitted -> handoff queue -> [wire] only the
  filled prompt pages move (``PageHandoffChannel`` — ``jax.device_put``
  on the CPU simulation path, the same seam an ICI transfer slots
  into) -> [decode slice] decode pool pages reserved (radix prefix
  shared pages retained, not re-transferred), contents scattered in,
  prompt prefix registered FROZEN in the decode-side radix tree,
  decode slot bound -> prefill pages released.

A request that dies mid-handoff (deadline, cancel, transport fault)
ends in exactly ONE of the six terminal outcomes and leaks zero pages
on either pool: the decode-side reservation rolls back whole and the
prefill-side pages release through the same funnel — both allocators'
``check_conservation`` stay green under randomized
admit/handoff/retire/quarantine/abort schedules (the tests' oracle).

Greedy outputs are BIT-IDENTICAL to the colocated paged engine: per
request, the forward is row-independent, the prefill computes the same
K/V from the same (tokens, positions, params), and the page copy is
bitwise — scheduling differences cannot change a token. The colocated
engine is therefore the standing parity oracle (tests, bench row,
gateway smoke).

Slice sizing reads the per-program HBM rows the memory tier pins in
``tools/hbm_budget.json`` (``prefill_step`` vs ``paged_decode_step``):
``plan_slice_split`` splits the fleet proportional to per-phase peak
memory, which on the 8-virtual-device CPU mesh lands on 4+4. An
explicit ``"prefill:decode"`` spec overrides.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# ---- spec parsing / slice planning (pure host, importable cheaply) ----

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
DEFAULT_HBM_BUDGET = os.path.join(_REPO_ROOT, "tools", "hbm_budget.json")


def parse_disagg_spec(spec: Any) -> Optional[Tuple[int, int]]:
    """``"P:D"`` -> ``(P, D)`` device counts; ``""``/``"auto"`` -> None
    (budget-driven sizing via ``plan_slice_split``). The single grammar
    home for ``scripts/serve.py --disagg`` and
    ``config.ServingArguments.serve_disagg``."""
    s = str(spec).strip().lower()
    if s in ("", "auto", "none"):
        return None
    parts = s.split(":")
    err = (f"disagg spec must be 'prefill:decode' device counts "
           f"(e.g. '4:4') or 'auto', got {spec!r}")
    if len(parts) != 2:
        raise ValueError(err)
    try:
        n_p, n_d = int(parts[0]), int(parts[1])
    except ValueError:
        raise ValueError(err) from None
    if n_p < 1 or n_d < 1:
        raise ValueError(
            f"each slice needs >= 1 device, got {spec!r}")
    return n_p, n_d


def _budget_peak(entries: Dict[str, Any], *names: str) -> Optional[float]:
    for name in names:
        try:
            return float(entries[name]["peak_mb"])
        except (KeyError, ValueError, TypeError):
            continue
    return None


def plan_slice_split(
    num_devices: int,
    *,
    budget_path: Optional[str] = None,
) -> Tuple[int, int]:
    """Size the two slices from the CI-attested per-phase HBM rows:
    devices split proportional to ``peak_mb`` of the prefill-slice vs
    decode-slice programs (the ``disagg_*`` rows the manifest entries
    below pin; the colocated ``prefill_step``/``paged_decode_step``
    rows are the fallback), each slice getting at least one device. A
    missing or unreadable budget falls back to an even split — sizing
    degrades, correctness doesn't."""
    if num_devices < 2:
        raise ValueError(
            f"disaggregation needs >= 2 devices (one per slice), "
            f"got {num_devices}")
    w_p = w_d = 1.0
    path = budget_path or DEFAULT_HBM_BUDGET
    try:
        with open(path) as f:
            entries = json.load(f)["entries"]
    except (OSError, ValueError):
        entries = {}
    w_p = _budget_peak(entries, "disagg_prefill_slice",
                       "prefill_step") or 1.0
    w_d = _budget_peak(entries, "disagg_decode_slice",
                       "paged_decode_step") or 1.0
    n_p = int(round(num_devices * w_p / (w_p + w_d)))
    n_p = max(1, min(num_devices - 1, n_p))
    return n_p, num_devices - n_p


# jax-dependent imports AFTER the pure helpers: config-time callers of
# `parse_disagg_spec` go through a lazy import, everything below is the
# engine half
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from scaletorch_tpu.inference.engine import (  # noqa: E402
    EngineMetrics,
    InferenceEngine,
    Request,
)
from scaletorch_tpu.inference.kv_cache import (  # noqa: E402
    TRASH_PAGE,
    PageAllocator,
    ceil_div,
    init_paged_kv_cache,
)
from scaletorch_tpu.telemetry.histogram import LogHistogram  # noqa: E402
from scaletorch_tpu.utils.logger import get_logger  # noqa: E402

logger = get_logger()


class HandoffError(RuntimeError):
    """A page transfer failed in flight (injected in drills; a real ICI
    transport fault on hardware). The engine converts it into exactly
    one ``aborted`` terminal result with both pools conserved."""


class PageHandoffChannel:
    """Moves filled K/V pages from the prefill slice to the decode
    slice.

    ``transfer`` gathers the source pages on the prefill slice (an
    eager device-side take — the host never sees the bytes) and commits
    them to the decode slice's placement with ``jax.device_put``. On
    the CPU simulation mesh that is a buffer copy; on hardware the SAME
    call lowers to an ICI device-to-device transfer — this seam is the
    only line that changes for a real fabric. Byte/page accounting and
    the fault-injection hook live here so drills and gauges share one
    counter set."""

    def __init__(self, dst_sharding: Optional[Any] = None) -> None:
        self.dst_sharding = dst_sharding
        self.transfers = 0
        self.pages_transferred = 0
        self.bytes_transferred = 0
        self.failures = 0
        self._fail_next = 0

    def fail_next(self, n: int = 1) -> None:
        """Drill hook: the next ``n`` transfers raise ``HandoffError``
        (the mid-handoff crash the conservation tests interleave)."""
        self._fail_next += n

    def transfer(self, src_cache, src_pages: List[int]):
        """Returns ``(k_pages, v_pages, nbytes)`` with both page blocks
        committed to ``dst_sharding`` — shape [L, n, H_kv, page, D]."""
        if self._fail_next > 0:
            self._fail_next -= 1
            self.failures += 1
            raise HandoffError("injected handoff transport fault")
        idx = jnp.asarray(np.asarray(src_pages, np.int32))
        k = src_cache.k[:, idx]
        v = src_cache.v[:, idx]
        if self.dst_sharding is not None:
            k = jax.device_put(k, self.dst_sharding)
            v = jax.device_put(v, self.dst_sharding)
        nbytes = int(k.nbytes + v.nbytes)
        self.transfers += 1
        self.pages_transferred += len(src_pages)
        self.bytes_transferred += nbytes
        return k, v, nbytes


@dataclass
class DisaggMetrics(EngineMetrics):
    """EngineMetrics plus the per-slice health the phase split creates:
    slice sizes, the prefill pool's occupancy (the decode pool rides the
    base gauges), handoff counters/bytes and per-slice busy fractions
    (host wall attributed to each slice's program over the metrics
    window). ``snapshot()`` stays flat numeric, so every key reaches
    /metrics as an ``engine_*`` gauge and JSONL consumers unchanged."""

    prefill_slice_devices: int = 0
    decode_slice_devices: int = 0
    prefill_pages_in_use: int = 0
    prefill_pool_free: int = 0
    handoffs: int = 0
    handoff_failures: int = 0
    pages_handed_off: int = 0
    handoff_bytes: int = 0
    prefill_busy_s: float = 0.0
    decode_busy_s: float = 0.0

    def __post_init__(self) -> None:
        # handoff latency (prefill-done -> decode-slot bound): queueing
        # for a free slot/pages plus the wire
        self.hist["handoff"] = LogHistogram()

    def busy_fractions(self) -> Tuple[float, float]:
        dt = time.monotonic() - self._window_start
        if dt <= 0:
            return 0.0, 0.0
        return (min(1.0, self.prefill_busy_s / dt),
                min(1.0, self.decode_busy_s / dt))

    def reset_window(self) -> None:
        super().reset_window()
        self.prefill_busy_s = 0.0
        self.decode_busy_s = 0.0

    def snapshot(self) -> Dict[str, float]:
        snap = super().snapshot()
        busy_p, busy_d = self.busy_fractions()
        snap.update({
            "prefill_slice_devices": self.prefill_slice_devices,
            "decode_slice_devices": self.decode_slice_devices,
            "prefill_pages_in_use": self.prefill_pages_in_use,
            "prefill_pool_free": self.prefill_pool_free,
            "handoffs": self.handoffs,
            "handoff_failures": self.handoff_failures,
            "pages_handed_off": self.pages_handed_off,
            "handoff_bytes": self.handoff_bytes,
            "prefill_slice_busy_fraction": busy_p,
            "decode_slice_busy_fraction": busy_d,
        })
        return snap


class _PendingHandoff:
    """A request between phases: prefilled (first token already emitted
    to the stream), holding prefill-pool pages, waiting for a decode
    slot + decode-pool pages."""

    __slots__ = ("req", "pages", "first_token", "prefill_s",
                 "first_token_t", "ready_t")

    def __init__(self, req: Request, pages: List[int], first_token: int,
                 prefill_s: float, first_token_t: float,
                 ready_t: float) -> None:
        self.req = req
        self.pages = pages
        self.first_token = first_token
        self.prefill_s = prefill_s
        self.first_token_t = first_token_t
        self.ready_t = ready_t


class DisaggregatedEngine(InferenceEngine):
    """The colocated paged engine with its prefill phase lifted onto a
    separate device slice.

    The base class remains the DECODE side unchanged: pool, allocator,
    radix tree, page tables, slots, the jitted decode step and the tick
    loop — ``step()`` is inherited, only the admission hooks
    (``_admit`` / ``_expire`` / ``cancel`` / ``_abort_pending``) are
    reinterpreted as the phase scheduler:

      1. handoff sweep — bind prefilled requests into free decode slots
         by decode-pool budget (FIFO; all-or-nothing reservation);
      2. prefill admission — admit queued requests into the prefill
         slice by PREFILL-pool budget, one batched prefill call, first
         tokens emitted (or poison prompts quarantined) right here;
      3. second handoff sweep — a request prefilled this tick can reach
         a decode slot the same tick, matching the colocated engine's
         admit-then-decode cadence.

    Parameters beyond ``InferenceEngine``: ``devices`` (default the
    whole fleet), ``disagg_split`` (``(P, D)`` tuple, ``"P:D"`` string,
    or None = ``plan_slice_split`` over ``budget_path``),
    ``prefill_pool_pages`` (prefill-side scratch pool; default sizes
    ``max_slots`` full prompts + trash page) and ``channel`` (a
    ``PageHandoffChannel``, injectable for drills)."""

    def __init__(self, params, cfg, *,
                 devices: Optional[List[Any]] = None,
                 disagg_split: Any = None,
                 budget_path: Optional[str] = None,
                 prefill_pool_pages: Optional[int] = None,
                 channel: Optional[PageHandoffChannel] = None,
                 **kw) -> None:
        layout = kw.setdefault("cache_layout", "paged")
        if layout != "paged":
            raise ValueError(
                "DisaggregatedEngine requires cache_layout='paged' — "
                "the page is the handoff unit")
        if kw.get("mesh") is not None:
            raise ValueError(
                "DisaggregatedEngine owns its slice meshes; pass "
                "devices/disagg_split instead of mesh")
        devs = list(devices) if devices is not None else list(jax.devices())
        if isinstance(disagg_split, str):
            disagg_split = parse_disagg_spec(disagg_split)
        if disagg_split is None:
            disagg_split = plan_slice_split(
                len(devs), budget_path=budget_path)
        n_p, n_d = disagg_split
        if n_p < 1 or n_d < 1:
            raise ValueError(
                f"each slice needs >= 1 device, got {n_p}:{n_d}")
        if n_p + n_d > len(devs):
            raise ValueError(
                f"slice spec {n_p}:{n_d} needs {n_p + n_d} devices but "
                f"only {len(devs)} are visible")
        prefill_devs = devs[:n_p]
        decode_devs = devs[n_p:n_p + n_d]

        super().__init__(params, cfg, **kw)

        # two disjoint 1-D meshes; replicated placement per slice (the
        # CPU simulation shape — TP within a slice layers on via the
        # kv_cache sharding helpers once slices grow past one program
        # copy)
        self.prefill_mesh = Mesh(np.array(prefill_devs), ("slice",))
        self.decode_mesh = Mesh(np.array(decode_devs), ("slice",))
        self._prefill_place = NamedSharding(self.prefill_mesh, P())
        self._decode_place = NamedSharding(self.decode_mesh, P())
        # MPMD placement: decode program state on the decode slice, a
        # second param copy + scratch pool on the prefill slice. jit
        # follows committed operands — each program compiles once for
        # its slice and never again.
        self.params = jax.device_put(self.params, self._decode_place)
        self.cache = jax.device_put(self.cache, self._decode_place)
        self._params_prefill = jax.device_put(params, self._prefill_place)

        # prefill-side scratch pool: PROMPT pages only — a request's
        # generation pages exist solely on the decode side
        prompt_pages_max = ceil_div(self.prefill_len, self.page_size)
        if prefill_pool_pages is None:
            prefill_pool_pages = self.max_slots * prompt_pages_max + 1
        if prefill_pool_pages < prompt_pages_max + 1:
            raise ValueError(
                f"prefill_pool_pages {prefill_pool_pages} cannot hold "
                f"one max-length prompt ({prompt_pages_max} pages + "
                f"trash page)")
        self.prefill_num_pages = prefill_pool_pages
        self.prefill_cache = init_paged_kv_cache(
            cfg, prefill_pool_pages, self.page_size,
            dtype=self.cache.k.dtype, sharding=self._prefill_place)
        self.prefill_allocator = PageAllocator(prefill_pool_pages)
        self._prefill_keys = np.zeros((self.max_slots, 2), np.uint32)
        self._handoff: deque[_PendingHandoff] = deque()
        self.channel = channel if channel is not None \
            else PageHandoffChannel(self._decode_place)
        if self.channel.dst_sharding is None:
            self.channel.dst_sharding = self._decode_place

        # decode busy attribution: wrap the jitted step, keep the
        # compiled callable reachable for the compile-count attestation
        self._decode_jit = self._decode

        def _timed_decode(*args):
            t0 = time.monotonic()
            out = self._decode_jit(*args)
            # the tick loop syncs on these outputs immediately after
            # (np.asarray on the sampled tokens), so blocking here just
            # moves that sync inside the busy window
            jax.block_until_ready(out[0])
            self.metrics.decode_busy_s += time.monotonic() - t0
            return out

        self._decode = _timed_decode

        metrics = DisaggMetrics(num_slots=self.max_slots)
        metrics.prefill_slice_devices = n_p
        metrics.decode_slice_devices = n_d
        self.metrics = metrics
        self._update_page_gauges()
        self._exported_key = self._export_key()
        logger.info(
            "disaggregated engine: prefill slice %d device(s) "
            "(%d-page pool), decode slice %d device(s) (%d-page pool)",
            n_p, prefill_pool_pages, n_d, self.num_pages)

    # ---- compile accounting (wrapper-aware) --------------------------
    @property
    def decode_compile_count(self) -> int:
        return self._decode_jit._cache_size()

    # ---- conservation (both pools) -----------------------------------
    def check_conservation(self) -> None:
        """Green iff NEITHER pool leaked: free + allocated == capacity
        and positive refcounts on both allocators."""
        self.allocator.check_conservation()
        self.prefill_allocator.check_conservation()

    @property
    def pending(self) -> int:
        return (len(self._queue) + len(self._handoff)
                + sum(s.active for s in self._slots))

    def _update_page_gauges(self) -> None:
        super()._update_page_gauges()
        alloc = getattr(self, "prefill_allocator", None)
        if alloc is not None and isinstance(self.metrics, DisaggMetrics):
            self.metrics.prefill_pages_in_use = alloc.used_count
            self.metrics.prefill_pool_free = alloc.free_count

    # ---- phase scheduler ---------------------------------------------
    def _admit(self) -> None:
        with self._span("handoff", pending=len(self._handoff)):
            self._handoff_sweep(time.monotonic())
        self._prefill_admit()
        if self._handoff:
            # same-tick pipeline: a request prefilled above reaches a
            # decode slot before this tick's decode step, exactly the
            # colocated admit-then-decode cadence
            with self._span("handoff", pending=len(self._handoff)):
                self._handoff_sweep(time.monotonic())

    def _expire(self, now: float) -> None:
        super()._expire(now)
        if self._handoff:
            kept: deque[_PendingHandoff] = deque()
            for h in self._handoff:
                if (h.req.deadline is not None
                        and now >= h.req.deadline):
                    self._drop_handoff(
                        h, "timeout",
                        detail="deadline exceeded awaiting handoff",
                        now=now)
                else:
                    kept.append(h)
            self._handoff = kept

    def cancel(self, request_id: int, *,
               detail: str = "cancelled by client") -> bool:
        now = time.monotonic()
        for h in self._handoff:
            if h.req.request_id == request_id:
                self._handoff.remove(h)
                self._drop_handoff(h, "aborted", detail=detail, now=now)
                return True
        return super().cancel(request_id, detail=detail)

    def _abort_pending(self, detail: str) -> None:
        now = time.monotonic()
        while self._handoff:
            self._drop_handoff(
                self._handoff.popleft(), "aborted", detail=detail,
                now=now)
        super()._abort_pending(detail)

    def _drop_handoff(self, h: _PendingHandoff, outcome: str, *,
                      detail: str, now: float) -> None:
        """Mid-handoff death: release the prefill-side pages and record
        the request's single terminal result (its already-streamed first
        token attached). The decode side holds nothing yet — exactly one
        outcome, zero leaks on either pool."""
        for p in h.pages:
            self.prefill_allocator.release(p)
        self._req_event("e", h.req, "req.handoff", outcome=outcome)
        self._finalize(
            h.req, outcome, tokens=[h.first_token], detail=detail,
            ttft_t=h.first_token_t, prefill_s=h.prefill_s, now=now)
        self._update_page_gauges()

    # ---- phase 1: prefill slice --------------------------------------
    def _prefill_admit(self) -> None:
        """Admit queued requests into the prefill slice by PREFILL-pool
        budget — one batched prefill call for everything admitted this
        tick, first tokens emitted (streamed) straight from the slice,
        poison prompts quarantined with their pool lines cleared."""
        if not self._queue:
            return
        b = self.max_slots
        admitted: List[Tuple[int, Request, List[int]]] = []
        tokens = np.zeros((b, self.prefill_len), np.int32)
        tail_lens = np.ones(b, np.int32)
        starts = np.zeros(b, np.int32)
        write_mask = np.zeros(b, bool)
        tables = np.full((b, self._pages_per_slot), TRASH_PAGE, np.int32)
        row = 0
        while row < b and self._queue:
            req = self._queue[0]
            n_pages = ceil_div(len(req.prompt), self.page_size)
            pages = self.prefill_allocator.alloc(n_pages)
            if pages is None:
                break  # prefill-pool budget: head of the line waits
            self._queue.popleft()
            req.admit_time = time.monotonic()
            self.metrics.hist["queue_wait"].observe(
                req.admit_time - req.submit_time)
            self._req_event("e", req, "req.queued")
            self._req_event("n", req, "req.admitted", slot=row,
                            slice="prefill")
            self.metrics.requests_admitted += 1
            tokens[row, :len(req.prompt)] = req.prompt
            tail_lens[row] = len(req.prompt)
            write_mask[row] = True
            tables[row, :n_pages] = pages
            self._prefill_keys[row] = np.asarray(
                jax.random.PRNGKey(req.seed), np.uint32)
            admitted.append((row, req, pages))
            row += 1
        if not admitted:
            return
        t0 = time.monotonic()
        for _, req, _ in admitted:
            self._req_event("b", req, "req.prefill", slice="prefill")
        with self._span("prefill", admitted=len(admitted),
                        slice="prefill"):
            first, _logits, finite, self.prefill_cache = self._prefill(
                self._params_prefill, jnp.asarray(tokens),
                jnp.asarray(tail_lens), jnp.asarray(starts),
                jnp.asarray(write_mask), jnp.asarray(tables),
                self.prefill_cache, jnp.asarray(self._prefill_keys))
        self.metrics.prefill_calls += 1
        first = np.asarray(first)
        finite = np.asarray(finite)
        now = time.monotonic()
        prefill_s = now - t0
        self.metrics.prefill_busy_s += prefill_s
        poison_mask = np.zeros(self.prefill_num_pages, bool)
        poisoned: List[Tuple[Request, List[int]]] = []
        for row, req, pages in admitted:
            self.metrics.hist["prefill"].observe(prefill_s)
            self._req_event("e", req, "req.prefill")
            if not finite[row]:
                poison_mask[pages] = True
                poisoned.append((req, pages))
                continue
            self._finish_prefill(req, pages, int(first[row]),
                                 prefill_s, now)
        if poisoned:
            # the NaN K/V must not outlive the request on THIS pool
            # either — same masked clear quarantine uses on the decode
            # pool, compiled once per pool shape
            self.prefill_cache = self._fill_slots(
                self.prefill_cache, jnp.asarray(poison_mask),
                jnp.asarray(0.0, jnp.float32))
            for req, pages in poisoned:
                for p in pages:
                    self.prefill_allocator.release(p)
                self._finalize(
                    req, "quarantined", tokens=[],
                    detail="non-finite logits at prefill",
                    prefill_s=prefill_s, now=now)
        self._update_page_gauges()
        self.metrics.queue_depth = len(self._queue)

    def _finish_prefill(self, req: Request, pages: List[int],
                        token: int, prefill_s: float,
                        now: float) -> None:
        """Healthy prefill: stream the first token, then either finish
        the request outright (stop condition at token one — no decode
        phase needed) or queue it for handoff."""
        self.metrics.tokens_generated += 1
        self.metrics._window_tokens += 1
        self.metrics.record_ttft(now - req.submit_time)
        if self.on_tokens is not None:
            try:
                self.on_tokens(-1, req.request_id, [token])
            except Exception:
                logger.exception(
                    "on_tokens hook raised; disarming the hook")
                self.on_tokens = None
        reason = None
        if req.eos_id is not None and token == req.eos_id:
            reason = "eos"
        elif req.max_new_tokens <= 1:
            reason = "length"
        elif len(req.prompt) + 1 >= self.max_seq:
            reason = "max_seq"
        if reason is not None:
            for p in pages:
                self.prefill_allocator.release(p)
            self._finalize(req, "ok", tokens=[token], reason=reason,
                           ttft_t=now, prefill_s=prefill_s, now=now)
            return
        self._handoff.append(_PendingHandoff(
            req, pages, token, prefill_s, now, now))
        self._req_event("b", req, "req.handoff")

    # ---- phase 2: the wire -------------------------------------------
    def _handoff_sweep(self, now: float) -> None:
        """Bind prefilled requests into free decode slots, FIFO. The
        head blocks on decode-pool budget (pages free as slots retire);
        a transport fault finalizes the head and the sweep continues."""
        while self._handoff:
            free = [i for i, s in enumerate(self._slots) if not s.active]
            if not free:
                return
            status = self._try_handoff(free[0], self._handoff[0], now)
            if status == "wait":
                return
            self._handoff.popleft()

    def _try_handoff(self, i: int, h: _PendingHandoff,
                     now: float) -> str:
        """All-or-nothing ownership flip for one request: reserve on the
        decode pool (radix prefix shared, rest allocated — identical
        math to colocated admission), move only the NON-SHARED prompt
        pages over the wire, register the prompt prefix frozen in the
        decode radix, bind the slot, release the prefill pages. Any
        failure rolls the decode-side reservation back whole. Returns
        'done' | 'wait' | 'failed'."""
        req = h.req
        plen = len(req.prompt)
        ps = self.page_size
        reserved = self._reserve_pages(req)
        if reserved is None:
            return "wait"
        shared, pages = reserved
        n_shared = shared // ps
        prompt_pages = ceil_div(plen, ps)
        src = h.pages[n_shared:prompt_pages]
        dst = pages[n_shared:prompt_pages]
        try:
            k_pages, v_pages, nbytes = self.channel.transfer(
                self.prefill_cache, src)
        except HandoffError as exc:
            for p in pages:
                self.allocator.release(p)
            for p in h.pages:
                self.prefill_allocator.release(p)
            self.metrics.handoff_failures += 1
            self._req_event("e", req, "req.handoff", error=str(exc))
            self._finalize(
                req, "aborted", tokens=[h.first_token],
                detail=f"page handoff failed: {exc}",
                ttft_t=h.first_token_t, prefill_s=h.prefill_s, now=now)
            self._update_page_gauges()
            return "failed"
        # scatter the transferred pages into the decode pool (eager
        # update on the committed pool — on hardware this becomes the
        # donated in-place write the ICI transfer lands into)
        dst_idx = jnp.asarray(np.asarray(dst, np.int32))
        self.cache = type(self.cache)(
            self.cache.k.at[:, dst_idx].set(k_pages),
            self.cache.v.at[:, dst_idx].set(v_pages))
        # destination registered before the source releases: the pages
        # are never owned by zero allocators
        slot = self._slots[i]
        slot.request = req
        slot.tokens = list(req.prompt) + [h.first_token]
        slot.position = plen
        slot.generated = 1
        slot.first_token_t = h.first_token_t
        slot.last_token_t = h.first_token_t
        slot.prefill_s = h.prefill_s
        slot.prefix_hit = shared > 0
        self._slot_pages[i] = pages
        self._slot_frozen[i] = n_shared
        self._tables[i, :] = TRASH_PAGE
        self._tables[i, :len(pages)] = pages
        self._tables_dev = None
        self._base_keys[i] = np.asarray(
            jax.random.PRNGKey(req.seed), np.uint32)
        if shared:
            self.metrics.prefix_hits += 1
        if self.radix is not None:
            # the page-aligned prompt prefix was written once by a
            # healthy prefill and is immutable from here on: register
            # it frozen (shareable, exempt from quarantine clears,
            # evictable at refcount zero like any chain)
            frozen = (plen // ps) * ps
            if frozen:
                n = frozen // ps
                self.radix.insert(req.prompt[:frozen],
                                  [int(p) for p in pages[:n]])
                self._slot_frozen[i] = n
        for p in h.pages:
            self.prefill_allocator.release(p)
        done = time.monotonic()
        self.metrics.handoffs += 1
        self.metrics.pages_handed_off += len(src)
        self.metrics.handoff_bytes += nbytes
        self.metrics.hist["handoff"].observe(done - h.ready_t)
        self._req_event("e", req, "req.handoff", pages=len(src),
                        shared_tokens=shared)
        self._req_event("b", req, "req.decode", slot=i, slice="decode")
        self._update_page_gauges()
        return "done"

    # ---- export ------------------------------------------------------
    def _export_snapshot(self) -> None:
        made_progress = self._export_key() != self._exported_key
        super()._export_snapshot()
        if made_progress and self.exporter is not None:
            m = self.metrics
            busy_p, busy_d = m.busy_fractions()
            self.exporter.emit("disagg", {
                "prefill_slice_devices": m.prefill_slice_devices,
                "decode_slice_devices": m.decode_slice_devices,
                "handoffs": m.handoffs,
                "handoff_failures": m.handoff_failures,
                "pages_handed_off": m.pages_handed_off,
                "handoff_bytes": m.handoff_bytes,
                "prefill_pages_in_use": m.prefill_pages_in_use,
                "prefill_pool_free": m.prefill_pool_free,
                "prefill_slice_busy_fraction": busy_p,
                "decode_slice_busy_fraction": busy_d,
            })


# ---- jaxlint deep/memory-tier audit targets --------------------------


def audit_entry_prefill_slice():
    """Deep-tier audit target: the PREFILL slice's single program — the
    jitted paged prefill step exactly as the disaggregated engine calls
    it (full-prompt prefill into a prompt-pages pool). Contract: pool
    donation survives lowering (``donate_cache=True`` — ST702/ST1002)
    and the single-device program compiles to ZERO collectives (the
    comm budget pins an empty row; slice-internal TP would add axes
    here, cross-slice traffic rides the handoff channel, never a
    collective). Memory tier: the pinned ``kv_cache`` geometry must
    match the compiled pool buffer (ST1005) — the per-phase ``peak_mb``
    row this writes into ``tools/hbm_budget.json`` is what
    ``plan_slice_split`` sizes the prefill slice by."""
    from scaletorch_tpu.inference.decode import (
        _audit_cfg_and_cache,
        make_paged_prefill_step,
    )
    from scaletorch_tpu.inference.kv_cache import kv_cache_bytes
    from scaletorch_tpu.inference.sampling import SamplingParams

    cfg, params, _, base_keys, b, s_max = _audit_cfg_and_cache()
    page_size = 8
    max_pages = s_max // page_size
    num_pages = b * max_pages + 1
    pool = jax.eval_shape(
        lambda: init_paged_kv_cache(
            cfg, num_pages, page_size, dtype=jnp.float32))
    fn = make_paged_prefill_step(
        cfg, SamplingParams(temperature=0.0), page_size=page_size,
        seq_limit=s_max, donate_cache=True)
    args = (
        params,
        jax.ShapeDtypeStruct((b, s_max), jnp.int32),       # tokens
        jax.ShapeDtypeStruct((b,), jnp.int32),             # tail_lens
        jax.ShapeDtypeStruct((b,), jnp.int32),             # starts
        jax.ShapeDtypeStruct((b,), jnp.bool_),             # write_mask
        jax.ShapeDtypeStruct((b, max_pages), jnp.int32),   # page tables
        pool,
        base_keys,
    )
    pool_mb = kv_cache_bytes(
        cfg, b, s_max, jnp.float32, layout="paged", page_size=page_size,
        num_pages=num_pages) / 1e6
    return {
        "name": "disagg_prefill_slice",
        "file": "scaletorch_tpu/inference/disagg.py",
        "fn": fn,
        "args": args,
        "min_devices": 1,
        "quantized_axis": None,
        "expect_donation": True,
        "hoisted_axes": (),
        "max_collective_result_mb": 1.0,
        "compute_dtype": "fp32",
        "donated_min_mb": round(0.9 * pool_mb, 4),
        "kv_cache": {
            "cfg": cfg, "layout": "paged", "batch": b, "max_seq": s_max,
            "dtype": jnp.float32, "page_size": page_size,
            "num_pages": num_pages, "arg_index": 6,
        },
    }


def audit_entry_decode_slice():
    """Deep-tier audit target: the DECODE slice's single program — the
    same jitted paged decode step the colocated engine runs (the slice
    changes placement, never the program), attested under the disagg
    name so its ``peak_mb`` row sizes the decode slice in
    ``plan_slice_split`` and a drift in EITHER phase's footprint moves
    the CI-pinned split, not a hand-edited constant."""
    from scaletorch_tpu.inference.decode import audit_entry_paged_decode

    entry = audit_entry_paged_decode()
    entry["name"] = "disagg_decode_slice"
    entry["file"] = "scaletorch_tpu/inference/disagg.py"
    return entry
