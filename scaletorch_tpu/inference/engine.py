"""Continuous-batching inference engine over a fixed-slot batch.

The serving loop the ROADMAP's "heavy traffic" story needs, shaped for
TPU execution discipline:

  * a FIXED number of slots (the decode batch) and a FIXED maximum
    sequence length — every device buffer keeps its shape for the whole
    engine lifetime, so the two jitted steps (prefill / decode,
    inference/decode.py) compile exactly once each;
  * per-slot lengths and stop state live on the HOST; between decode
    steps the engine admits queued requests into freed slots by writing
    their row of the prompt buffer and flipping their ``write_mask``
    bit — data changes, shapes don't, nothing retraces;
  * the KV cache is donated through every step (XLA appends in place);
    with a mesh it is head-sharded over ``tp`` via the same specs the
    training params use (kv_cache_specs), and the steps run GSPMD.

Metrics ride the existing plumbing: ``EngineMetrics`` keeps the
counters/gauges (tokens/s, time-to-first-token, queue depth, slot
occupancy) and can sample them into a ``SystemMonitor`` ring buffer
(utils/monitor.py) so a serving process's tail is diagnosable exactly
like a training run's.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from scaletorch_tpu.inference.decode import (
    make_decode_step,
    make_prefill_step,
)
from scaletorch_tpu.inference.kv_cache import (
    init_kv_cache,
    kv_cache_bytes,
)
from scaletorch_tpu.inference.sampling import SamplingParams
from scaletorch_tpu.utils.logger import get_logger

logger = get_logger(__name__)


@dataclass
class Request:
    """One generation request. ``eos_id`` stops the slot early;
    ``max_new_tokens`` always bounds it; the engine's ``max_seq`` caps
    prompt + generation regardless."""

    request_id: int
    prompt: List[int]
    max_new_tokens: int = 64
    eos_id: Optional[int] = None
    seed: int = 0
    submit_time: float = field(default_factory=time.monotonic)


@dataclass
class RequestResult:
    request_id: int
    prompt: List[int]
    tokens: List[int]               # generated tokens (prompt excluded)
    finish_reason: str              # 'eos' | 'length' | 'max_seq'
    ttft_s: Optional[float] = None  # submit -> first generated token
    latency_s: Optional[float] = None


@dataclass
class EngineMetrics:
    """Serving health counters/gauges. ``snapshot()`` is flat numeric —
    ready for a MetricsLogger line or a SystemMonitor ring-buffer record
    (``monitor.sample(counters=metrics.snapshot())``)."""

    requests_submitted: int = 0
    requests_completed: int = 0
    tokens_generated: int = 0
    prefill_calls: int = 0
    decode_steps: int = 0
    queue_depth: int = 0
    active_slots: int = 0
    num_slots: int = 0
    ttft_sum_s: float = 0.0
    ttft_count: int = 0
    _window_start: float = field(default_factory=time.monotonic)
    _window_tokens: int = 0

    def record_ttft(self, ttft_s: float) -> None:
        self.ttft_sum_s += ttft_s
        self.ttft_count += 1

    def tokens_per_second(self) -> float:
        dt = time.monotonic() - self._window_start
        return self._window_tokens / dt if dt > 0 else 0.0

    def reset_window(self) -> None:
        self._window_start = time.monotonic()
        self._window_tokens = 0

    def snapshot(self) -> Dict[str, float]:
        return {
            "requests_submitted": self.requests_submitted,
            "requests_completed": self.requests_completed,
            "tokens_generated": self.tokens_generated,
            "prefill_calls": self.prefill_calls,
            "decode_steps": self.decode_steps,
            "queue_depth": self.queue_depth,
            "slot_occupancy": (
                self.active_slots / self.num_slots if self.num_slots else 0.0
            ),
            "tokens_per_second": self.tokens_per_second(),
            "mean_ttft_s": (
                self.ttft_sum_s / self.ttft_count if self.ttft_count else 0.0
            ),
        }


class _Slot:
    """Host-side state of one decode slot."""

    __slots__ = ("request", "tokens", "position", "generated", "first_token_t")

    def __init__(self) -> None:
        self.request: Optional[Request] = None
        self.tokens: List[int] = []
        self.position = 0        # absolute position of the NEXT token to feed
        self.generated = 0
        self.first_token_t: Optional[float] = None

    @property
    def active(self) -> bool:
        return self.request is not None


class InferenceEngine:
    """KV-cache decode with continuous batching.

    Parameters
    ----------
    params, cfg : the model tree and its config (any Llama-family or
        GPT-MoE config; ``resolve_forward_cached`` picks the forward).
        For sharded serving pass params already placed with their
        NamedShardings (utils/hf_interop.load_hf_params(shardings=...)
        feeds this directly).
    max_slots : decode batch size B (fixed).
    max_seq : cache length S_max (prompt + generation cap per slot).
    prefill_len : static prompt-buffer length P_max (default
        ``max_seq``); prompts longer than this are rejected.
    sampling : engine-wide sampling knobs (static, baked into the
        compiled steps).
    mesh / tp_axis / batch_axis : optional — shard the cache over the
        mesh (KV heads over ``tp_axis``, slots over ``batch_axis``).
    monitor : optional SystemMonitor; ``step()`` samples the metrics
        snapshot into its ring buffer every ``monitor_every`` steps.
    """

    def __init__(
        self,
        params: Any,
        cfg: Any,
        *,
        max_slots: int = 4,
        max_seq: int = 512,
        prefill_len: Optional[int] = None,
        sampling: SamplingParams = SamplingParams(),
        cache_dtype: Any = None,
        mesh: Any = None,
        tp_axis: str = "tp",
        batch_axis: Optional[str] = None,
        donate_cache: Optional[bool] = None,
        monitor: Any = None,
        monitor_every: int = 16,
    ) -> None:
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if max_seq < 2:
            raise ValueError(f"max_seq must be >= 2, got {max_seq}")
        self.params = params
        self.cfg = cfg
        self.max_slots = max_slots
        self.max_seq = max_seq
        self.prefill_len = prefill_len or max_seq
        if self.prefill_len > max_seq:
            raise ValueError(
                f"prefill_len {self.prefill_len} exceeds max_seq {max_seq}"
            )
        self.sampling = sampling
        self.monitor = monitor
        self.monitor_every = monitor_every

        sharding = None
        if mesh is not None:
            from scaletorch_tpu.inference.kv_cache import kv_cache_shardings

            sharding = kv_cache_shardings(
                mesh, tp_axis=tp_axis, batch_axis=batch_axis)
        self.cache = init_kv_cache(
            cfg, max_slots, max_seq, dtype=cache_dtype, sharding=sharding)
        logger.info(
            "inference engine: %d slots x %d positions, cache %.1f MiB%s",
            max_slots, max_seq,
            kv_cache_bytes(cfg, max_slots, max_seq,
                           dtype=cache_dtype) / 2**20,
            f", sharded over {mesh.axis_names}" if mesh is not None else "",
        )

        self._prefill = make_prefill_step(
            cfg, sampling, donate_cache=donate_cache)
        self._decode = make_decode_step(
            cfg, sampling, donate_cache=donate_cache)

        self._slots = [_Slot() for _ in range(max_slots)]
        self._queue: deque[Request] = deque()
        self._results: Dict[int, RequestResult] = {}
        self._ids = itertools.count()
        self._base_keys = np.zeros((max_slots, 2), np.uint32)
        self.metrics = EngineMetrics(num_slots=max_slots)

    # ---- compile accounting (the no-retrace contract) --------------------
    @property
    def decode_compile_count(self) -> int:
        return self._decode._cache_size()

    @property
    def prefill_compile_count(self) -> int:
        return self._prefill._cache_size()

    # ---- request lifecycle ----------------------------------------------
    def submit(
        self,
        prompt: List[int],
        *,
        max_new_tokens: int = 64,
        eos_id: Optional[int] = None,
        seed: int = 0,
    ) -> int:
        """Queue a request; returns its id. Admission happens inside
        ``step()`` when a slot frees up."""
        if not prompt:
            raise ValueError("prompt must contain at least one token")
        if len(prompt) > self.prefill_len:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the engine's static "
                f"prefill buffer ({self.prefill_len}); re-create the engine "
                "with a larger prefill_len/max_seq"
            )
        if len(prompt) >= self.max_seq:
            raise ValueError(
                f"prompt length {len(prompt)} leaves no room to generate "
                f"within max_seq {self.max_seq}"
            )
        req = Request(
            request_id=next(self._ids), prompt=list(prompt),
            max_new_tokens=max_new_tokens, eos_id=eos_id, seed=seed,
        )
        self._queue.append(req)
        self.metrics.requests_submitted += 1
        self.metrics.queue_depth = len(self._queue)
        return req.request_id

    def _admit(self) -> None:
        """Move queued requests into free slots and prefill them — ONE
        batched prefill call regardless of how many were admitted."""
        free = [i for i, s in enumerate(self._slots) if not s.active]
        if not free or not self._queue:
            return
        admitted: List[int] = []
        tokens = np.zeros((self.max_slots, self.prefill_len), np.int32)
        lengths = np.ones(self.max_slots, np.int32)
        write_mask = np.zeros(self.max_slots, bool)
        for i in free:
            if not self._queue:
                break
            req = self._queue.popleft()
            slot = self._slots[i]
            slot.request = req
            slot.tokens = list(req.prompt)
            slot.position = len(req.prompt)
            slot.generated = 0
            slot.first_token_t = None
            tokens[i, : len(req.prompt)] = req.prompt
            lengths[i] = len(req.prompt)
            write_mask[i] = True
            self._base_keys[i] = np.asarray(
                jax.random.PRNGKey(req.seed), np.uint32)
            admitted.append(i)
        first, _logits, self.cache = self._prefill(
            self.params, jnp.asarray(tokens), jnp.asarray(lengths),
            jnp.asarray(write_mask), self.cache, jnp.asarray(self._base_keys),
        )
        self.metrics.prefill_calls += 1
        now = time.monotonic()
        first = np.asarray(first)
        for i in admitted:
            slot = self._slots[i]
            self._emit(i, int(first[i]), now)
        self.metrics.queue_depth = len(self._queue)

    def _emit(self, i: int, token: int, now: float) -> None:
        """Record one generated token for slot i; retire the slot when a
        stop condition hits."""
        slot = self._slots[i]
        req = slot.request
        slot.tokens.append(token)
        slot.generated += 1
        self.metrics.tokens_generated += 1
        self.metrics._window_tokens += 1
        if slot.first_token_t is None:
            slot.first_token_t = now
            self.metrics.record_ttft(now - req.submit_time)

        reason = None
        if req.eos_id is not None and token == req.eos_id:
            reason = "eos"
        elif slot.generated >= req.max_new_tokens:
            reason = "length"
        elif slot.position + slot.generated >= self.max_seq:
            # continuing would feed a token at position >= max_seq —
            # past the end of the cache
            reason = "max_seq"
        if reason is not None:
            self._results[req.request_id] = RequestResult(
                request_id=req.request_id,
                prompt=req.prompt,
                tokens=slot.tokens[len(req.prompt):],
                finish_reason=reason,
                ttft_s=slot.first_token_t - req.submit_time,
                latency_s=now - req.submit_time,
            )
            self.metrics.requests_completed += 1
            slot.request = None
            slot.tokens = []

    def step(self) -> List[RequestResult]:
        """One engine tick: admit into freed slots (prefill), then one
        decode step for the active slots. Returns results finished this
        tick."""
        before = {r for r in self._results}
        self._admit()
        active_idx = [i for i, s in enumerate(self._slots) if s.active]
        if active_idx:
            tokens = np.zeros(self.max_slots, np.int32)
            positions = np.zeros(self.max_slots, np.int32)
            active = np.zeros(self.max_slots, bool)
            for i in active_idx:
                slot = self._slots[i]
                # feed the last emitted token at its absolute position:
                # the prompt occupies [0, len), generated token g sits at
                # len + g - 1
                tokens[i] = slot.tokens[-1]
                positions[i] = slot.position + slot.generated - 1
                active[i] = True
            nxt, _logits, self.cache = self._decode(
                self.params, jnp.asarray(tokens), jnp.asarray(positions),
                jnp.asarray(active), self.cache,
                jnp.asarray(self._base_keys),
            )
            self.metrics.decode_steps += 1
            nxt = np.asarray(nxt)
            now = time.monotonic()
            for i in active_idx:
                self._emit(i, int(nxt[i]), now)
        self.metrics.active_slots = sum(s.active for s in self._slots)
        self.metrics.queue_depth = len(self._queue)
        if (
            self.monitor is not None
            and self.metrics.decode_steps % self.monitor_every == 0
        ):
            self.monitor.sample(counters=self.metrics.snapshot())
        return [self._results[r] for r in self._results if r not in before]

    @property
    def pending(self) -> int:
        return len(self._queue) + sum(s.active for s in self._slots)

    def run(self, max_steps: int = 100_000) -> Dict[int, RequestResult]:
        """Drive ``step()`` until queue and slots drain; returns all
        results by request id."""
        steps = 0
        while self.pending and steps < max_steps:
            self.step()
            steps += 1
        if self.pending:
            raise RuntimeError(
                f"engine did not drain within {max_steps} steps "
                f"({self.pending} requests still in flight)"
            )
        return dict(self._results)

    def result(self, request_id: int) -> Optional[RequestResult]:
        return self._results.get(request_id)
