"""Continuous-batching inference engine over a fixed-slot batch.

The serving loop the ROADMAP's "heavy traffic" story needs, shaped for
TPU execution discipline:

  * a FIXED number of slots (the decode batch) and a FIXED maximum
    sequence length — every device buffer keeps its shape for the whole
    engine lifetime, so the two jitted steps (prefill / decode,
    inference/decode.py) compile exactly once each;
  * per-slot lengths and stop state live on the HOST; between decode
    steps the engine admits queued requests into freed slots by writing
    their row of the prompt buffer and flipping their ``write_mask``
    bit — data changes, shapes don't, nothing retraces;
  * the KV cache is donated through every step (XLA appends in place);
    with a mesh it is head-sharded over ``tp`` via the same specs the
    training params use (kv_cache_specs), and the steps run GSPMD;
  * ``cache_layout="paged"`` swaps the dense per-slot buffers for a
    global page pool + per-slot page tables (kv_cache.PagedKVCache):
    admission becomes page-budget-aware (HBM scales with tokens cached,
    not B x S_max), a radix tree shares page-aligned prompt prefixes
    across requests (refcounted, copy-on-write at the page boundary),
    and decode attention gathers through the table (the Pallas kernel
    in ops/pallas/paged_attention.py on TPU, the lax fallback
    elsewhere) — greedy outputs stay bit-identical to the dense layout
    and the tables are data, so the one-compile discipline survives
    admissions, prefix hits, quarantine page-clears, and frees.

Serving-grade fault tolerance (inference/resilience.py) rides the same
discipline: every submitted request ends in exactly one terminal
``outcome`` (ok / timeout / shed / rejected / quarantined / aborted),
admission is bounded (``queue_capacity`` sheds oldest-first), per-request
TTL deadlines are checked at admission and every decode step, a slot
whose logits go non-finite is quarantined (cache lines mask-cleared, the
other slots keep serving, nothing retraces), and ``drain()`` stops
admissions and finishes the in-flight work — wired to the training
stack's ``PreemptionHandler`` for SIGTERM and to ``HangWatchdog`` via
``make_serving_watchdog`` for stalled steps.

Metrics ride the existing plumbing: ``EngineMetrics`` keeps the
counters/gauges (tokens/s, time-to-first-token, queue depth, slot
occupancy, per-outcome counters, deadline-miss/quarantine rates) and can
sample them into a ``SystemMonitor`` ring buffer (utils/monitor.py) so a
serving process's tail is diagnosable exactly like a training run's.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from scaletorch_tpu.inference.decode import (
    make_decode_step,
    make_fill_slots_step,
    make_paged_decode_step,
    make_paged_prefill_step,
    make_prefill_step,
)
from scaletorch_tpu.inference.kv_cache import (
    PageAllocator,
    RadixPrefixCache,
    TRASH_PAGE,
    ceil_div,
    init_kv_cache,
    init_paged_kv_cache,
    kv_cache_bytes,
)
from scaletorch_tpu.inference.resilience import (
    TERMINAL_OUTCOMES,
    EngineDraining,
    ServingFaultInjector,
)
from scaletorch_tpu.inference.sampling import SamplingParams
from scaletorch_tpu.telemetry.histogram import LogHistogram
from scaletorch_tpu.telemetry.spans import NOOP_SPAN
from scaletorch_tpu.utils.logger import get_logger

logger = get_logger(__name__)


@dataclass
class Request:
    """One generation request. ``eos_id`` stops the slot early;
    ``max_new_tokens`` always bounds it; the engine's ``max_seq`` caps
    prompt + generation regardless. ``deadline`` (absolute monotonic
    time, or None) retires the request with ``timeout`` wherever it is
    — queued or mid-decode — once passed. ``trace_id`` is the W3C
    trace-context id the gateway threaded in (None = untraced): it
    keys the request's lifecycle spans on the tracer's async track.
    ``admit_time`` is stamped when the request enters a slot —
    ``queue_wait_s`` on the result derives from it."""

    request_id: int
    prompt: List[int]
    max_new_tokens: int = 64
    eos_id: Optional[int] = None
    seed: int = 0
    submit_time: float = field(default_factory=time.monotonic)
    deadline: Optional[float] = None
    trace_id: Optional[str] = None
    admit_time: Optional[float] = None


@dataclass
class RequestResult:
    """The single terminal record of a request. ``outcome`` is one of
    ``TERMINAL_OUTCOMES``; ``finish_reason`` refines an ``ok`` outcome
    ('eos' | 'length' | 'max_seq') and repeats the outcome otherwise.
    Non-ok outcomes carry whatever tokens were generated before the
    fault (``tokens``) plus a human-readable ``detail``."""

    request_id: int
    prompt: List[int]
    tokens: List[int]               # generated tokens (prompt excluded)
    finish_reason: str              # 'eos' | 'length' | 'max_seq' | outcome
    outcome: str = "ok"             # one of TERMINAL_OUTCOMES
    detail: Optional[str] = None    # non-ok outcomes: what happened
    ttft_s: Optional[float] = None  # submit -> first generated token
    latency_s: Optional[float] = None
    # request-scoped latency attribution (additive; the gateway's
    # access records and per-tenant histograms read these):
    queue_wait_s: Optional[float] = None   # submit -> slot admission
    prefill_s: Optional[float] = None      # its admission's prefill wall
    prefix_hit: bool = False               # radix prefix pages shared
    trace_id: Optional[str] = None


@dataclass
class EngineMetrics:
    """Serving health counters/gauges. ``snapshot()`` is flat numeric —
    ready for a MetricsLogger line or a SystemMonitor ring-buffer record
    (``monitor.sample(counters=metrics.snapshot())``) — and lands in
    serving crash reports via ``make_serving_watchdog``. The per-outcome
    counters satisfy the conservation invariant
    ``requests_submitted == sum(requests_<outcome>)`` once the engine
    is drained."""

    requests_submitted: int = 0
    requests_completed: int = 0     # ok outcomes only
    requests_admitted: int = 0      # entered a slot (prefilled)
    tokens_generated: int = 0
    prefill_calls: int = 0
    decode_steps: int = 0
    queue_depth: int = 0
    active_slots: int = 0
    num_slots: int = 0
    # paged-cache gauges/counters (zero on the dense layout): pool
    # occupancy plus the radix prefix-cache's yield — an admission whose
    # prompt head was already cached is a ``prefix_hit`` and its shared
    # tokens (never re-prefilled) accumulate in ``prefill_tokens_saved``
    pages_in_use: int = 0
    page_pool_free: int = 0
    prefix_hits: int = 0
    prefill_tokens_saved: int = 0
    # warm-rejoin accounting: ``prefix_pages`` gauges the radix tree's
    # registered page count (the donor-selection signal the gateway
    # ranks peers by); ``warm_pages_total`` counts pages this engine
    # imported from peers since boot
    prefix_pages: int = 0
    warm_pages_total: int = 0
    ttft_sum_s: float = 0.0
    ttft_count: int = 0
    outcomes: Dict[str, int] = field(
        default_factory=lambda: {o: 0 for o in TERMINAL_OUTCOMES})
    # request-scoped latency distributions (telemetry/histogram.py):
    # one log-bucketed histogram per metric, fed on the host paths that
    # already exist (no device sync) — mean_ttft_s above is the legacy
    # running mean, these are where the tails live. ``snapshot()``
    # stays flat numeric; readers wanting distributions use
    # ``histogram_state()`` (live snapshots, replica aggregation).
    hist: Dict[str, LogHistogram] = field(default_factory=lambda: {
        name: LogHistogram()
        for name in ("ttft", "tpot", "queue_wait", "prefill", "e2e")})
    _window_start: float = field(default_factory=time.monotonic)
    _window_tokens: int = 0

    def record_ttft(self, ttft_s: float) -> None:
        self.ttft_sum_s += ttft_s
        self.ttft_count += 1
        self.hist["ttft"].observe(ttft_s)

    def histogram_state(self) -> Dict[str, Dict]:
        """Sparse JSON form of every latency histogram (the
        ``latency_histograms`` JSONL record shape, unlabeled)."""
        return {name: h.to_dict() for name, h in self.hist.items()
                if h.count}

    def record_outcome(self, outcome: str) -> None:
        self.outcomes[outcome] += 1
        if outcome == "ok":
            self.requests_completed += 1

    def tokens_per_second(self) -> float:
        dt = time.monotonic() - self._window_start
        return self._window_tokens / dt if dt > 0 else 0.0

    def reset_window(self) -> None:
        self._window_start = time.monotonic()
        self._window_tokens = 0

    def snapshot(self) -> Dict[str, float]:
        terminal = sum(self.outcomes.values())
        snap = {
            "requests_submitted": self.requests_submitted,
            "requests_completed": self.requests_completed,
            "tokens_generated": self.tokens_generated,
            "prefill_calls": self.prefill_calls,
            "decode_steps": self.decode_steps,
            "queue_depth": self.queue_depth,
            "num_slots": self.num_slots,
            "slot_occupancy": (
                self.active_slots / self.num_slots if self.num_slots else 0.0
            ),
            "tokens_per_second": self.tokens_per_second(),
            "mean_ttft_s": (
                self.ttft_sum_s / self.ttft_count if self.ttft_count else 0.0
            ),
            "deadline_miss_rate": (
                self.outcomes["timeout"] / terminal if terminal else 0.0
            ),
            "quarantine_rate": (
                self.outcomes["quarantined"] / terminal if terminal else 0.0
            ),
            "pages_in_use": self.pages_in_use,
            "page_pool_free": self.page_pool_free,
            "prefix_hit_rate": (
                self.prefix_hits / self.requests_admitted
                if self.requests_admitted else 0.0
            ),
            "prefill_tokens_saved": self.prefill_tokens_saved,
            "prefix_pages": self.prefix_pages,
            "warm_pages_total": self.warm_pages_total,
        }
        for outcome, count in self.outcomes.items():
            snap[f"requests_{outcome}"] = count
        return snap


class _Slot:
    """Host-side state of one decode slot."""

    __slots__ = ("request", "tokens", "position", "generated",
                 "first_token_t", "last_token_t", "prefill_s", "prefix_hit")

    def __init__(self) -> None:
        self.request: Optional[Request] = None
        self.tokens: List[int] = []
        self.position = 0        # absolute position of the NEXT token to feed
        self.generated = 0
        self.first_token_t: Optional[float] = None
        self.last_token_t: Optional[float] = None  # TPOT inter-arrival
        self.prefill_s: Optional[float] = None     # its admission's prefill
        self.prefix_hit = False                    # radix pages shared

    @property
    def active(self) -> bool:
        return self.request is not None


class InferenceEngine:
    """KV-cache decode with continuous batching.

    Parameters
    ----------
    params, cfg : the model tree and its config (any Llama-family or
        GPT-MoE config; ``resolve_forward_cached`` picks the forward).
        For sharded serving pass params already placed with their
        NamedShardings (utils/hf_interop.load_hf_params(shardings=...)
        feeds this directly).
    max_slots : decode batch size B (fixed).
    max_seq : cache length S_max (prompt + generation cap per slot).
    prefill_len : static prompt-buffer length P_max (default
        ``max_seq``); prompts longer than this are rejected.
    sampling : engine-wide sampling knobs (static, baked into the
        compiled steps).
    cache_layout : ``"dense"`` (default, per-slot [L,B,Hkv,S_max,D]
        buffers) or ``"paged"`` — a global pool of fixed-size pages
        [L,n_pages,Hkv,page_size,D] plus per-slot page tables. Paged,
        admission is PAGE-BUDGET-aware: a request is admitted when the
        pool can cover ``min(prompt + max_new_tokens, max_seq)`` tokens
        of pages (minus any radix prefix hit), not when a slot index
        frees up — HBM scales with tokens actually cached, and
        concurrency with the pool, not with ``B × S_max``.
    page_size : tokens per page (paged layout only).
    num_pages : pool size including the reserved TRASH page. None sizes
        the dense-equivalent pool (``max_slots * ceil(max_seq /
        page_size) + 1``); smaller pools trade concurrency for HBM.
    prefix_cache : paged only — keep a radix tree over page-aligned
        token prefixes so a request whose prompt head is already cached
        shares those pages (refcounted, copy-on-write at the page
        boundary) and prefills only its tail.
    mesh / tp_axis / batch_axis : optional — shard the cache over the
        mesh (KV heads over ``tp_axis``, slots over ``batch_axis``;
        the paged pool shards KV heads the same way, ``batch_axis``
        is dense-only — pages are not slot-aligned).
    monitor : optional SystemMonitor; ``step()`` samples the metrics
        snapshot into its ring buffer every ``monitor_every`` steps.
    tracer : optional ``telemetry.SpanTracer``; each tick records
        ``tick`` / ``admission`` / ``prefill`` / ``decode`` spans (host
        dispatch time — never a device sync; the vocabulary matches the
        serving watchdog's beat phases). None = one branch per site.
    exporter : optional ``telemetry.TelemetryExporter``; metrics
        snapshots ride the same schema-versioned JSONL stream the
        trainer's step records use (kind ``engine_metrics``) on the
        ``monitor_every`` cadence and at drain/run exit — durable
        serving metrics, not just the in-memory ring buffer.
    queue_capacity : bounded admission — with more than this many
        requests queued, the OLDEST queued request is shed (terminal
        outcome ``shed``). 0 (default) keeps the queue unbounded.
    default_ttl_s : deadline applied to requests submitted without an
        explicit ``ttl_s`` (0 = no deadline). Expired requests end as
        ``timeout``, queued or mid-decode.
    strict_submit : True (default) preserves raise-on-invalid
        ``submit()``; False converts validation failures into a
        structured ``rejected`` terminal result so one malformed
        request cannot kill a server loop.
    forward_fn : optional override of the model's cache-aware forward
        (tests use it to simulate content-dependent poison requests).
    injector : optional ``ServingFaultInjector`` driving hermetic
        fault drills (NaN logits, slow decode, submit/deadline storms).
    preemption : optional ``resilience.PreemptionHandler``; ``run()``
        polls it each tick and responds to SIGTERM by draining.
    watchdog : optional ``HangWatchdog`` (see ``make_serving_watchdog``);
        ``step()`` beats it so a stalled tick fires the serving
        crash-report path.
    on_tokens : optional ``(slot, request_id, token_ids)`` callback
        invoked from ``step()`` with each slot's newly sampled tokens
        the moment they exist on the host — PUSH, not poll, so a
        streaming bridge (serving/gateway.py) never waits on terminal
        results to forward tokens. Host-side only: the hook sees tokens
        after the device->host transfer the engine already performs, so
        attaching it adds zero retraces (``decode_compile_count`` stays
        1). Concatenating every ``token_ids`` delivered for a request
        reproduces its final ``RequestResult.tokens`` bit-exactly. A
        raising hook is logged and disarmed, never fatal to serving.
    """

    def __init__(
        self,
        params: Any,
        cfg: Any,
        *,
        max_slots: int = 4,
        max_seq: int = 512,
        prefill_len: Optional[int] = None,
        sampling: SamplingParams = SamplingParams(),
        cache_dtype: Any = None,
        cache_layout: str = "dense",
        page_size: int = 16,
        num_pages: Optional[int] = None,
        prefix_cache: bool = True,
        mesh: Any = None,
        tp_axis: str = "tp",
        batch_axis: Optional[str] = None,
        donate_cache: Optional[bool] = None,
        monitor: Any = None,
        monitor_every: int = 16,
        tracer: Any = None,
        exporter: Any = None,
        queue_capacity: int = 0,
        default_ttl_s: float = 0.0,
        strict_submit: bool = True,
        forward_fn: Optional[Callable] = None,
        injector: Optional[ServingFaultInjector] = None,
        preemption: Any = None,
        watchdog: Any = None,
        on_tokens: Optional[Callable[[int, int, List[int]], None]] = None,
    ) -> None:
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if max_seq < 2:
            raise ValueError(f"max_seq must be >= 2, got {max_seq}")
        if queue_capacity < 0:
            raise ValueError(
                f"queue_capacity must be >= 0 (0 = unbounded), "
                f"got {queue_capacity}"
            )
        if default_ttl_s < 0:
            raise ValueError(
                f"default_ttl_s must be >= 0 (0 = no deadline), "
                f"got {default_ttl_s}"
            )
        self.params = params
        self.cfg = cfg
        self.max_slots = max_slots
        self.max_seq = max_seq
        self.prefill_len = prefill_len or max_seq
        if self.prefill_len > max_seq:
            raise ValueError(
                f"prefill_len {self.prefill_len} exceeds max_seq {max_seq}"
            )
        self.sampling = sampling
        self.monitor = monitor
        self.monitor_every = monitor_every
        self.tracer = tracer
        self.exporter = exporter
        self.queue_capacity = queue_capacity
        self.default_ttl_s = default_ttl_s
        self.strict_submit = strict_submit
        self.injector = injector
        self.preemption = preemption
        self.watchdog = watchdog
        self.on_tokens = on_tokens

        if cache_layout not in ("dense", "paged"):
            raise ValueError(
                f"cache_layout must be 'dense' or 'paged', "
                f"got {cache_layout!r}"
            )
        self.cache_layout = cache_layout
        self._paged = cache_layout == "paged"
        if self._paged and page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.page_size = page_size
        self._pages_per_slot = (
            ceil_div(max_seq, page_size) if self._paged else 0)
        if num_pages is None and self._paged:
            num_pages = max_slots * self._pages_per_slot + 1
        self.num_pages = num_pages

        if self._paged:
            from scaletorch_tpu.inference.kv_cache import (
                paged_kv_cache_shardings,
            )

            sharding = (
                paged_kv_cache_shardings(mesh, tp_axis=tp_axis)
                if mesh is not None else None
            )
            self.cache = init_paged_kv_cache(
                cfg, num_pages, page_size, dtype=cache_dtype,
                sharding=sharding)
            self.allocator = PageAllocator(num_pages)
            self.radix = (
                RadixPrefixCache(
                    page_size, self.allocator.retain, self.allocator.release,
                    self.allocator.refcount,
                ) if prefix_cache else None
            )
            # per-slot page table (host copy; reaches the device as data
            # every step), the pages each slot holds a reference on
            # (shared prefix pages first, own pages after), and how many
            # leading table entries are FROZEN — shared or
            # radix-registered, so exempt from quarantine clears/pokes
            self._tables = np.full(
                (max_slots, self._pages_per_slot), TRASH_PAGE, np.int32)
            # device copy of the tables, re-uploaded only after a host
            # write (admission/retire) — the decode hot loop reads it
            # every tick and must not pay a H2D transfer per token
            self._tables_dev = None
            self._slot_pages: List[List[int]] = [[] for _ in range(max_slots)]
            self._slot_frozen = [0] * max_slots
            cache_mib = kv_cache_bytes(
                cfg, max_slots, max_seq, dtype=cache_dtype, layout="paged",
                page_size=page_size, num_pages=num_pages) / 2**20
            logger.info(
                "inference engine: %d slots over %d pages x %d tokens, "
                "pool %.1f MiB%s%s",
                max_slots, num_pages, page_size, cache_mib,
                ", prefix cache on" if prefix_cache else "",
                f", sharded over {mesh.axis_names}" if mesh is not None
                else "",
            )
        else:
            sharding = None
            if mesh is not None:
                from scaletorch_tpu.inference.kv_cache import (
                    kv_cache_shardings,
                )

                sharding = kv_cache_shardings(
                    mesh, tp_axis=tp_axis, batch_axis=batch_axis)
            self.cache = init_kv_cache(
                cfg, max_slots, max_seq, dtype=cache_dtype, sharding=sharding)
            self.allocator = None
            self.radix = None
            logger.info(
                "inference engine: %d slots x %d positions, cache %.1f "
                "MiB%s",
                max_slots, max_seq,
                kv_cache_bytes(cfg, max_slots, max_seq,
                               dtype=cache_dtype) / 2**20,
                f", sharded over {mesh.axis_names}" if mesh is not None
                else "",
            )

        if self._paged:
            self._prefill = make_paged_prefill_step(
                cfg, sampling, page_size=page_size, seq_limit=max_seq,
                forward_fn=forward_fn, donate_cache=donate_cache)
            self._decode = make_paged_decode_step(
                cfg, sampling, page_size=page_size, seq_limit=max_seq,
                forward_fn=forward_fn, donate_cache=donate_cache)
        else:
            self._prefill = make_prefill_step(
                cfg, sampling, forward_fn=forward_fn,
                donate_cache=donate_cache)
            self._decode = make_decode_step(
                cfg, sampling, forward_fn=forward_fn,
                donate_cache=donate_cache)
        self._fill_slots = make_fill_slots_step(donate_cache=donate_cache)

        self._slots = [_Slot() for _ in range(max_slots)]
        self._queue: deque[Request] = deque()
        self._results: Dict[int, RequestResult] = {}
        self._finished_tick: List[RequestResult] = []
        self._ids = itertools.count()
        self._base_keys = np.zeros((max_slots, 2), np.uint32)
        self._draining = False
        self.metrics = EngineMetrics(num_slots=max_slots)
        if self._paged:
            self._update_page_gauges()
        # progress fingerprint of the last JSONL export: an idle engine
        # polled at a cadence multiple (or a drain() straight after
        # run()) must not append duplicate records — but any outcome
        # movement (e.g. a queued request timing out on an idle tick)
        # still must
        self._exported_key = self._export_key()

    def _update_page_gauges(self) -> None:
        self.metrics.pages_in_use = self.allocator.used_count
        self.metrics.page_pool_free = self.allocator.free_count
        self.metrics.prefix_pages = (
            len(self.radix) if self.radix is not None else 0)

    def _tables_device(self):
        """The page tables as a device array, uploaded once per host
        mutation rather than once per decode tick."""
        if self._tables_dev is None:
            self._tables_dev = jnp.asarray(self._tables)
        return self._tables_dev

    def _request_pages(self, prompt_len: int, max_new_tokens: int) -> int:
        """Worst-case pages a request reserves: every position it can
        write — prompt plus generation, capped by ``max_seq`` (the
        engine retires at the cap before feeding past it)."""
        total = min(prompt_len + max_new_tokens, self.max_seq)
        return ceil_div(total, self.page_size)

    def _span(self, name: str, **args):
        """Telemetry span when a tracer is attached, shared no-op
        otherwise (one branch; spans time HOST dispatch, never a device
        sync — the telemetry/spans.py contract)."""
        if self.tracer is None:
            return NOOP_SPAN
        return self.tracer.span(name, **args)

    def _req_event(self, ph: str, req: Request, name: str, **args) -> None:
        """Request-scoped async span event (``ph`` in 'b'/'e'/'n') on
        the request's trace_id track — one branch when untraced or the
        tracer is off. The lifecycle vocabulary (req.queued /
        req.admitted / req.prefill / req.decode / req.finalize) shares
        the tick loop's phase names, so one Perfetto load correlates a
        request's track with the per-thread phase spans by eye AND by
        trace_id."""
        if self.tracer is None or req.trace_id is None:
            return
        self.tracer.async_event(ph, name, req.trace_id, **args)

    def _export_key(self):
        """Progress fingerprint for JSONL export dedup (counters only —
        snapshot() itself has wall-clock-derived rates that differ on
        every call)."""
        return (
            self.metrics.decode_steps,
            self.metrics.requests_submitted,
            tuple(sorted(self.metrics.outcomes.items())),
        )

    def _export_snapshot(self) -> None:
        """Append a metrics record to the JSONL stream iff progress was
        made since the last export."""
        key = self._export_key()
        if key == self._exported_key:
            return
        self._exported_key = key
        self.exporter.emit("engine_metrics", self.metrics.snapshot())

    # ---- compile accounting (the no-retrace contract) --------------------
    @property
    def decode_compile_count(self) -> int:
        return self._decode._cache_size()

    @property
    def prefill_compile_count(self) -> int:
        return self._prefill._cache_size()

    @property
    def draining(self) -> bool:
        return self._draining

    # ---- request lifecycle ----------------------------------------------
    def submit(
        self,
        prompt: List[int],
        *,
        max_new_tokens: int = 64,
        eos_id: Optional[int] = None,
        seed: int = 0,
        ttl_s: Optional[float] = None,
        trace_id: Optional[str] = None,
    ) -> int:
        """Queue a request; returns its id. Admission happens inside
        ``step()`` when a slot frees up.

        ``ttl_s`` sets this request's deadline (None = engine
        ``default_ttl_s``; <= 0 = no deadline). ``trace_id`` (a W3C
        trace-context id, threaded in by the serving gateway) keys this
        request's lifecycle spans on the tracer's async track and rides
        the terminal result. Invalid submissions
        raise (``strict_submit=True``, the default) or end as a
        ``rejected`` terminal result; submitting into a draining engine
        raises ``EngineDraining`` / rejects the same way. A full queue
        (``queue_capacity``) sheds the OLDEST queued request to make
        room — under overload the freshest work survives, and the shed
        request gets a ``shed`` terminal result instead of silently
        rotting in an unbounded queue.
        """
        err = None
        if self._draining:
            err = "engine is draining: admissions are stopped"
        elif not prompt:
            err = "prompt must contain at least one token"
        elif len(prompt) > self.prefill_len:
            err = (
                f"prompt length {len(prompt)} exceeds the engine's static "
                f"prefill buffer ({self.prefill_len}); re-create the engine "
                "with a larger prefill_len/max_seq"
            )
        elif len(prompt) >= self.max_seq:
            err = (
                f"prompt length {len(prompt)} leaves no room to generate "
                f"within max_seq {self.max_seq}"
            )
        elif (self._paged and self._request_pages(len(prompt), max_new_tokens)
                > self.allocator.capacity):
            err = (
                f"request needs {self._request_pages(len(prompt), max_new_tokens)} "
                f"pages but the pool's capacity is {self.allocator.capacity}; "
                "re-create the engine with more num_pages or cap "
                "max_new_tokens"
            )
        if err is not None and self.strict_submit:
            raise EngineDraining(err) if self._draining else ValueError(err)
        req = Request(
            request_id=next(self._ids), prompt=list(prompt),
            max_new_tokens=max_new_tokens, eos_id=eos_id, seed=seed,
            trace_id=trace_id,
        )
        ttl = self.default_ttl_s if ttl_s is None else ttl_s
        if ttl and ttl > 0:
            req.deadline = req.submit_time + ttl
        self.metrics.requests_submitted += 1
        self._req_event("b", req, "request", request_id=req.request_id)
        self._req_event("b", req, "req.queued")
        if err is not None:
            self._finalize(req, "rejected", tokens=[], detail=err,
                           now=time.monotonic())
            return req.request_id
        self._queue.append(req)
        while self.queue_capacity and len(self._queue) > self.queue_capacity:
            shed = self._queue.popleft()
            self._finalize(
                shed, "shed", tokens=[],
                detail=(f"queue exceeded capacity {self.queue_capacity}; "
                        "oldest request shed"),
                now=time.monotonic(),
            )
        self.metrics.queue_depth = len(self._queue)
        return req.request_id

    def _finalize(
        self,
        req: Request,
        outcome: str,
        *,
        tokens: List[int],
        reason: Optional[str] = None,
        detail: Optional[str] = None,
        ttft_t: Optional[float] = None,
        prefill_s: Optional[float] = None,
        prefix_hit: bool = False,
        now: float,
    ) -> None:
        """Record the single terminal result of ``req``. Every request
        path funnels through here, so the conservation invariant
        (submitted == sum over outcomes) holds by construction — and so
        do the request's lifecycle-span close and its e2e-latency
        histogram observation."""
        latency = now - req.submit_time
        queue_wait = (req.admit_time - req.submit_time
                      if req.admit_time is not None else None)
        self._results[req.request_id] = RequestResult(
            request_id=req.request_id,
            prompt=req.prompt,
            tokens=tokens,
            finish_reason=reason or outcome,
            outcome=outcome,
            detail=detail,
            ttft_s=(ttft_t - req.submit_time) if ttft_t is not None else None,
            latency_s=latency,
            queue_wait_s=queue_wait,
            prefill_s=prefill_s,
            prefix_hit=prefix_hit,
            trace_id=req.trace_id,
        )
        if req.admit_time is not None and outcome in ("ok", "timeout"):
            # only SERVED requests feed the e2e histogram (the same
            # outcome set as serving/slo.py's LATENCY_OUTCOMES, not
            # imported — serving sits above inference): an instant
            # reject's near-zero latency and a client-cancelled slot's
            # truncated one would both drag the tail estimate down
            # exactly when overload makes served traffic slowest
            self.metrics.hist["e2e"].observe(latency)
        self._req_event(
            "e", req, "req.decode" if req.admit_time is not None
            else "req.queued")
        self._req_event("n", req, "req.finalize", outcome=outcome,
                        finish_reason=reason or outcome)
        self._req_event("e", req, "request", outcome=outcome)
        self._finished_tick.append(self._results[req.request_id])
        self.metrics.record_outcome(outcome)
        if outcome != "ok":
            logger.warning(
                "request %d -> %s%s", req.request_id, outcome,
                f" ({detail})" if detail else "",
            )

    def _retire_slot(
        self,
        i: int,
        outcome: str,
        *,
        reason: Optional[str] = None,
        detail: Optional[str] = None,
        now: float,
    ) -> None:
        """Terminal-result a slot's request (partial tokens attached)
        and free the slot."""
        slot = self._slots[i]
        req = slot.request
        self._finalize(
            req, outcome, tokens=slot.tokens[len(req.prompt):],
            reason=reason, detail=detail, ttft_t=slot.first_token_t,
            prefill_s=slot.prefill_s, prefix_hit=slot.prefix_hit, now=now,
        )
        slot.request = None
        slot.tokens = []
        if self._paged:
            # drop the slot's references; pages shared with live slots or
            # pinned by the radix tree survive (refcount > 1), the rest
            # return to the free list
            for p in self._slot_pages[i]:
                self.allocator.release(p)
            self._slot_pages[i] = []
            self._slot_frozen[i] = 0
            self._tables[i, :] = TRASH_PAGE
            self._tables_dev = None
            self._update_page_gauges()

    def _expire(self, now: float) -> None:
        """Deadline sweep: retire queued and mid-decode requests whose
        deadline has passed with a ``timeout`` terminal result. Runs at
        every tick — admission control AND each decode step see fresh
        deadline state."""
        if self._queue:
            kept: deque[Request] = deque()
            for req in self._queue:
                if req.deadline is not None and now >= req.deadline:
                    self._finalize(
                        req, "timeout", tokens=[],
                        detail="deadline exceeded before admission", now=now)
                else:
                    kept.append(req)
            self._queue = kept
            self.metrics.queue_depth = len(self._queue)
        for i, slot in enumerate(self._slots):
            if (slot.active and slot.request.deadline is not None
                    and now >= slot.request.deadline):
                self._retire_slot(
                    i, "timeout", detail="deadline exceeded mid-decode",
                    now=now)

    def _quarantine(self, indices: List[int], now: float, where: str) -> None:
        """Retire poisoned slots (non-finite logits) and mask-clear their
        cache lines so the NaN K/V cannot outlive the request. The clear
        is one jitted masked fill over the whole cache — data-only, so
        the decode step's single compile survives the fault. Paged, the
        mask covers the slot's MUTABLE pages only (own pages past the
        frozen prefix): frozen pages are immutable since registration —
        written once by a healthy prefill — so the NaN cannot live there,
        and clearing them would corrupt the slots sharing them."""
        if self._paged:
            mask = np.zeros(self.num_pages, bool)
            for i in indices:
                mutable = self._slot_pages[i][self._slot_frozen[i]:]
                mask[mutable] = True
                self._retire_slot(
                    i, "quarantined",
                    detail=f"non-finite logits at {where}", now=now)
        else:
            mask = np.zeros(self.max_slots, bool)
            for i in indices:
                self._retire_slot(
                    i, "quarantined",
                    detail=f"non-finite logits at {where}", now=now)
                mask[i] = True
        self.cache = self._fill_slots(
            self.cache, jnp.asarray(mask), jnp.asarray(0.0, jnp.float32))

    def _poison_slot(self, slot_idx: int) -> None:
        """Fault injection: NaN-fill one slot's cache lines so its next
        decode step produces non-finite logits (same masked fill the
        quarantine clear uses — one compile serves both)."""
        active = [i for i, s in enumerate(self._slots) if s.active]
        if not active:
            logger.warning(
                "fault injection: no active slot to poison; skipping")
            return
        if slot_idx not in active:
            slot_idx = active[0]
        if self._paged:
            # NaN the slot's mutable pages only — frozen prefix pages may
            # be shared, and poisoning them would fault the neighbours
            # the drill asserts are unaffected. (With a page-aligned
            # prompt the poke surfaces from the second decode on: until
            # then the only mutable lane is overwritten fresh each step.)
            mask = np.zeros(self.num_pages, bool)
            mutable = self._slot_pages[slot_idx][self._slot_frozen[slot_idx]:]
            mask[mutable] = True
        else:
            mask = np.zeros(self.max_slots, bool)
            mask[slot_idx] = True
        self.cache = self._fill_slots(
            self.cache, jnp.asarray(mask),
            jnp.asarray(float("nan"), jnp.float32))

    # ---- warm rejoin: peer-to-peer prefix state exchange -----------------
    #
    # A restarted replica rejoins with an empty radix tree; these three
    # methods are the engine half of warming it from a live peer. The
    # donor side (`export_prefix_map` / `export_prefix_pages`) is a pure
    # read plus a refcount-retained host copy — donor conservation is
    # untouched and the wire streams from host memory, so a slow
    # recipient can never pin (or evict) donor pool pages. The recipient
    # side (`import_prefix_pages`) allocates pool pages, writes the
    # transferred bytes through the SAME jitted fill step quarantine
    # uses (a cache-shaped value is a new argument structure of
    # `fill_slots` only — `decode_compile_count == 1` holds through
    # warming), registers the chains frozen-from-birth (the tree holds
    # the single reference, so a warmed page is evictable-at-zero like
    # any cached prefix), and releases every allocation in a `finally`
    # so an interrupted import leaves the allocator conservation oracle
    # green.

    def export_prefix_map(self) -> Dict[str, Any]:
        """Snapshot the radix tree for a warming peer: root-to-leaf
        token chains with their page ids, plus per-page refcount/frozen
        state. Engine-thread only (worker inbox)."""
        if not self._paged or self.radix is None:
            return {"page_size": self.page_size if self._paged else None,
                    "chains": [], "pages": {}}
        return {
            "page_size": self.page_size,
            "dtype": str(self.cache.k.dtype),
            "page_shape": ([int(self.cache.k.shape[0])]
                           + [int(d) for d in self.cache.k.shape[2:]]),
            "chains": [
                {"tokens": [int(t) for t in tokens],
                 "pages": [int(p) for p in pages]}
                for tokens, pages in self.radix.chains()],
            "pages": {
                int(p): {"refcount": self.allocator.refcount(p),
                         "frozen": True}
                for p in self.radix.registered_pages()},
            "capacity": self.allocator.capacity,
            "free": self.allocator.free_count,
        }

    def export_prefix_pages(
        self, pages: Sequence[int]
    ) -> Tuple[Dict[str, Any], Dict[int, Tuple[bytes, bytes]]]:
        """Copy the requested FROZEN pages' K/V bytes to host memory.

        Only radix-registered pages ship (anything else is mutable slot
        state); each is refcount-retained across the device->host copy
        and released immediately after, so the donor keeps serving and
        its conservation invariant never moves. Returns ``(meta,
        {page: (k_bytes, v_bytes)})``; requested pages no longer frozen
        are simply absent (the wire sends a zero-content frame)."""
        meta: Dict[str, Any] = {
            "dtype": str(self.cache.k.dtype) if self._paged else None,
            "page_shape": ([int(self.cache.k.shape[0])]
                           + [int(d) for d in self.cache.k.shape[2:]])
            if self._paged else [],
            "page_size": self.page_size if self._paged else None,
        }
        contents: Dict[int, Tuple[bytes, bytes]] = {}
        if not self._paged or self.radix is None:
            return meta, contents
        frozen = set(self.radix.registered_pages())
        valid = [int(p) for p in pages if int(p) in frozen]
        if not valid:
            return meta, contents
        for p in valid:
            self.allocator.retain(p)
        try:
            idx = jnp.asarray(np.asarray(valid, np.int32))
            k_host = np.asarray(self.cache.k[:, idx])
            v_host = np.asarray(self.cache.v[:, idx])
        finally:
            for p in valid:
                self.allocator.release(p)
        for i, p in enumerate(valid):
            contents[p] = (k_host[:, i].tobytes(), v_host[:, i].tobytes())
        return meta, contents

    def import_prefix_pages(
        self,
        chains: Sequence[Tuple[Sequence[int], Sequence[int]]],
        contents: Dict[int, Tuple[bytes, bytes]],
        *,
        dtype: Optional[str],
        page_shape: Sequence[int],
        page_size: Optional[int],
    ) -> Dict[str, Any]:
        """Install transferred donor pages into this engine's pool and
        radix tree. ``chains`` holds donor ``(tokens, donor_pages)``
        paths; ``contents`` maps donor page id -> ``(k, v)`` bytes —
        a chain whose page bytes are missing (dropped chunk, snapped
        stream) keeps its valid PREFIX and sheds the tail, so a partial
        transfer still warms what arrived intact. Returns ``{"pages":
        new_radix_pages, "chains": [registered token lists]}``."""
        result: Dict[str, Any] = {"pages": 0, "chains": []}
        if not self._paged or self.radix is None:
            return result
        expected_shape = tuple(
            [int(self.cache.k.shape[0])]
            + [int(d) for d in self.cache.k.shape[2:]])
        if (page_size != self.page_size
                or str(dtype) != str(self.cache.k.dtype)
                or tuple(int(d) for d in page_shape) != expected_shape):
            logger.warning(
                "warm import skipped: peer pool is incompatible "
                "(page_size=%s dtype=%s shape=%s vs local %s/%s/%s)",
                page_size, dtype, tuple(page_shape),
                self.page_size, self.cache.k.dtype, expected_shape)
            return result
        page_nbytes = int(np.prod(expected_shape)
                          * np.dtype(self.cache.k.dtype).itemsize)
        imported: Dict[int, int] = {}       # donor page -> local page
        newly_allocated: List[int] = []
        planned: List[Tuple[List[int], List[int]]] = []
        try:
            for tokens, donor_pages in chains:
                local: List[int] = []
                for dp in donor_pages:
                    dp = int(dp)
                    lp = imported.get(dp)
                    if lp is None:
                        data = contents.get(dp)
                        if (data is None or len(data[0]) != page_nbytes
                                or len(data[1]) != page_nbytes):
                            break  # chunk never arrived: keep the prefix
                        got = self.allocator.alloc(1)
                        if got is None:
                            break  # pool pressure: warm what fits
                        lp = got[0]
                        imported[dp] = lp
                        newly_allocated.append(lp)
                    local.append(lp)
                if local:
                    planned.append((
                        [int(t) for t in
                         tokens[:len(local) * self.page_size]], local))
            if imported:
                self._write_imported_pages(imported, contents)
                created = 0
                for tokens, local in planned:
                    created += self.radix.insert(tokens, local)
                self.metrics.warm_pages_total += created
                result["pages"] = created
                result["chains"] = [tokens for tokens, _ in planned]
        finally:
            # drop our allocation reference on every imported page:
            # registered ones fall to the tree's single reference
            # (frozen-from-birth, evictable at zero slot refs like any
            # cached prefix); duplicates of chunks the tree already held
            # — and everything, if the import was interrupted before
            # insert — free immediately, so the conservation oracle
            # passes after an aborted transfer
            for lp in newly_allocated:
                self.allocator.release(lp)
            self._update_page_gauges()
        return result

    def _write_imported_pages(
        self, imported: Dict[int, int],
        contents: Dict[int, Tuple[bytes, bytes]],
    ) -> None:
        """One masked fill writes every imported page's bytes into the
        pool — the same audited `fill_slots` compile quarantine rides,
        fed a cache-shaped value instead of a scalar."""
        mask = np.zeros(self.num_pages, bool)
        vk = np.zeros(self.cache.k.shape, self.cache.k.dtype)
        vv = np.zeros(self.cache.v.shape, self.cache.v.dtype)
        shape = tuple([vk.shape[0]] + list(vk.shape[2:]))
        for dp, lp in imported.items():
            kb, vb = contents[dp]
            vk[:, lp] = np.frombuffer(kb, vk.dtype).reshape(shape)
            vv[:, lp] = np.frombuffer(vb, vv.dtype).reshape(shape)
            mask[lp] = True
        self.cache = self._fill_slots(
            self.cache, jnp.asarray(mask),
            type(self.cache)(jnp.asarray(vk), jnp.asarray(vv)))

    def _admit(self) -> None:
        """Move queued requests into free slots and prefill them — ONE
        batched prefill call regardless of how many were admitted. A
        slot whose prefill logits are non-finite (poison prompt) is
        quarantined immediately; the other admitted slots proceed."""
        if self._paged:
            self._admit_paged()
        else:
            self._admit_dense()

    def _bind_slot(self, i: int, req: Request) -> None:
        slot = self._slots[i]
        slot.request = req
        slot.tokens = list(req.prompt)
        slot.position = len(req.prompt)
        slot.generated = 0
        slot.first_token_t = None
        slot.last_token_t = None
        slot.prefill_s = None
        slot.prefix_hit = False
        req.admit_time = time.monotonic()
        self.metrics.hist["queue_wait"].observe(
            req.admit_time - req.submit_time)
        self._req_event("e", req, "req.queued")
        self._req_event("n", req, "req.admitted", slot=i)
        self._base_keys[i] = np.asarray(
            jax.random.PRNGKey(req.seed), np.uint32)
        self.metrics.requests_admitted += 1

    def _admit_dense(self) -> None:
        free = [i for i, s in enumerate(self._slots) if not s.active]
        if not free or not self._queue:
            return
        admitted: List[int] = []
        tokens = np.zeros((self.max_slots, self.prefill_len), np.int32)
        lengths = np.ones(self.max_slots, np.int32)
        write_mask = np.zeros(self.max_slots, bool)
        for i in free:
            if not self._queue:
                break
            req = self._queue.popleft()
            self._bind_slot(i, req)
            tokens[i, : len(req.prompt)] = req.prompt
            lengths[i] = len(req.prompt)
            write_mask[i] = True
            admitted.append(i)
        t0 = time.monotonic()
        for i in admitted:
            self._req_event("b", self._slots[i].request, "req.prefill")
        with self._span("prefill", admitted=len(admitted)):
            first, _logits, finite, self.cache = self._prefill(
                self.params, jnp.asarray(tokens), jnp.asarray(lengths),
                jnp.asarray(write_mask), self.cache,
                jnp.asarray(self._base_keys),
            )
        self.metrics.prefill_calls += 1
        first = np.asarray(first)
        finite = np.asarray(finite)
        now = time.monotonic()
        self._note_prefill(admitted, now - t0)
        poisoned = [i for i in admitted if not finite[i]]
        if poisoned:
            self._quarantine(poisoned, now, where="prefill")
        for i in admitted:
            if finite[i]:
                self._emit(i, int(first[i]), now)
        self.metrics.queue_depth = len(self._queue)

    def _note_prefill(self, admitted: List[int], prefill_s: float) -> None:
        """Attribute one batched prefill's wall time to every request it
        admitted (they shared the call), close their ``req.prefill``
        spans and open ``req.decode`` — BEFORE any quarantine retires a
        poisoned slot, so every begun span gets its end."""
        for i in admitted:
            slot = self._slots[i]
            slot.prefill_s = prefill_s
            self.metrics.hist["prefill"].observe(prefill_s)
            self._req_event("e", slot.request, "req.prefill")
            self._req_event("b", slot.request, "req.decode")

    def _reserve_pages(self, req: Request):
        """Try to reserve the pages one request needs: radix-match its
        prompt, retain the shared prefix pages, allocate the rest
        (evicting unpinned radix leaves when the free list runs short).
        Returns (shared_tokens, page_list) or None when the pool cannot
        cover the request right now — pages free as slots retire, so the
        request just waits at the head of the queue (FIFO)."""
        plen = len(req.prompt)
        ps = self.page_size
        total_pages = self._request_pages(plen, req.max_new_tokens)
        shared = 0
        shared_pages: List[int] = []
        if self.radix is not None:
            matched, pages = self.radix.match(req.prompt)
            # never share the whole prompt: the first token samples from
            # the logits at prompt_len - 1, so at least one tail token
            # must run through prefill
            shared = min(matched, ((plen - 1) // ps) * ps)
            shared_pages = pages[: shared // ps]
            for p in shared_pages:
                self.allocator.retain(p)
        own_needed = total_pages - len(shared_pages)
        own = self.allocator.alloc(own_needed)
        if own is None and self.radix is not None:
            self.radix.evict(own_needed - self.allocator.free_count)
            own = self.allocator.alloc(own_needed)
        if own is None:
            for p in shared_pages:
                self.allocator.release(p)
            return None
        return shared, shared_pages + own

    def _admit_paged(self) -> None:
        free = [i for i, s in enumerate(self._slots) if not s.active]
        if not free or not self._queue:
            return
        admitted: List[int] = []
        tokens = np.zeros((self.max_slots, self.prefill_len), np.int32)
        tail_lens = np.ones(self.max_slots, np.int32)
        starts = np.zeros(self.max_slots, np.int32)
        write_mask = np.zeros(self.max_slots, bool)
        for i in free:
            if not self._queue:
                break
            reserved = self._reserve_pages(self._queue[0])
            if reserved is None:
                break  # page budget exhausted: head of the line waits
            req = self._queue.popleft()
            shared, pages = reserved
            self._bind_slot(i, req)
            self._slot_pages[i] = pages
            self._slot_frozen[i] = shared // self.page_size
            self._tables[i, :] = TRASH_PAGE
            self._tables[i, : len(pages)] = pages
            self._tables_dev = None
            tail = req.prompt[shared:]
            tokens[i, : len(tail)] = tail
            tail_lens[i] = len(tail)
            starts[i] = shared
            write_mask[i] = True
            if shared:
                self.metrics.prefix_hits += 1
                self.metrics.prefill_tokens_saved += shared
                self._slots[i].prefix_hit = True
            admitted.append(i)
        if not admitted:
            return
        t0 = time.monotonic()
        for i in admitted:
            self._req_event("b", self._slots[i].request, "req.prefill",
                            prefix_hit=self._slots[i].prefix_hit)
        with self._span("prefill", admitted=len(admitted)):
            first, _logits, finite, self.cache = self._prefill(
                self.params, jnp.asarray(tokens), jnp.asarray(tail_lens),
                jnp.asarray(starts), jnp.asarray(write_mask),
                self._tables_device(), self.cache,
                jnp.asarray(self._base_keys),
            )
        self.metrics.prefill_calls += 1
        first = np.asarray(first)
        finite = np.asarray(finite)
        now = time.monotonic()
        self._note_prefill(admitted, now - t0)
        poisoned = [i for i in admitted if not finite[i]]
        if poisoned:
            # skip radix registration for poison prompts — their pages
            # hold non-finite K/V and must never be shared
            self._quarantine(poisoned, now, where="prefill")
        for i in admitted:
            if not finite[i]:
                continue
            if self.radix is not None:
                slot = self._slots[i]
                plen = len(slot.request.prompt)
                frozen = (plen // self.page_size) * self.page_size
                if frozen:
                    n = frozen // self.page_size
                    self.radix.insert(
                        slot.request.prompt[:frozen],
                        [int(p) for p in self._tables[i, :n]],
                    )
                    # the fully-written prompt pages are immutable from
                    # here on — exempt from quarantine clears and
                    # shareable by later admissions
                    self._slot_frozen[i] = n
            self._emit(i, int(first[i]), now)
        self._update_page_gauges()
        self.metrics.queue_depth = len(self._queue)

    def _emit(self, i: int, token: int, now: float) -> None:
        """Record one generated token for slot i; retire the slot when a
        stop condition hits."""
        slot = self._slots[i]
        req = slot.request
        slot.tokens.append(token)
        slot.generated += 1
        self.metrics.tokens_generated += 1
        self.metrics._window_tokens += 1
        if slot.first_token_t is None:
            slot.first_token_t = now
            self.metrics.record_ttft(now - req.submit_time)
        else:
            # per-token inter-arrival (TPOT): decode cadence as the
            # client experiences it, first token (prefill) excluded
            self.metrics.hist["tpot"].observe(now - slot.last_token_t)
        slot.last_token_t = now
        if self.on_tokens is not None:
            # push the newly sampled token to the streaming bridge BEFORE
            # any stop condition retires the slot — the stream sees every
            # token, then the terminal result. A raising hook is disarmed
            # (logged), never fatal: one bad consumer must not take the
            # whole decode batch down.
            try:
                self.on_tokens(i, req.request_id, [token])
            except Exception:
                logger.exception(
                    "on_tokens hook raised; disarming the hook")
                self.on_tokens = None

        reason = None
        if req.eos_id is not None and token == req.eos_id:
            reason = "eos"
        elif slot.generated >= req.max_new_tokens:
            reason = "length"
        elif slot.position + slot.generated >= self.max_seq:
            # continuing would feed a token at position >= max_seq —
            # past the end of the cache
            reason = "max_seq"
        if reason is not None:
            self._retire_slot(i, "ok", reason=reason, now=now)

    def step(self) -> List[RequestResult]:
        """One engine tick: deadline sweep, admit into freed slots
        (prefill), then one decode step for the active slots — with the
        slots whose logits went non-finite quarantined instead of
        emitting. Returns every result that reached its terminal outcome
        since the PREVIOUS ``step()`` returned — including requests
        finalized between ticks (a ``shed``/``rejected`` recorded inside
        ``submit()``, a ``cancel()``), so a push-delivery bridge sees
        each terminal result exactly once. With a tracer attached the
        tick records ``tick`` / ``admission`` / ``prefill`` / ``decode``
        spans."""
        tick = self.metrics.decode_steps + 1  # the decode step this tick runs
        if self.watchdog is not None:
            self.watchdog.beat(step=self.metrics.decode_steps,
                               phase="serve-step")
        inj = self.injector
        if inj is not None:
            storm = inj.take_submit_storm(tick) if not self._draining else 0
            for _ in range(storm):
                self.submit([1], max_new_tokens=1)
            if inj.take_deadline_storm(tick):
                past = time.monotonic() - 1.0
                for req in self._queue:
                    req.deadline = past
                for s in self._slots:
                    if s.active:
                        s.request.deadline = past
        with self._span("tick", tick=tick):
            with self._span("admission"):
                self._expire(time.monotonic())
                self._admit()
            active_idx = [i for i, s in enumerate(self._slots) if s.active]
            if active_idx:
                if inj is not None:
                    poison = inj.take_nan_logits(tick)
                    if poison is not None:
                        self._poison_slot(poison)
                    stall = inj.take_slow_decode(tick)
                    if stall > 0:
                        time.sleep(stall)
                tokens = np.zeros(self.max_slots, np.int32)
                positions = np.zeros(self.max_slots, np.int32)
                active = np.zeros(self.max_slots, bool)
                for i in active_idx:
                    slot = self._slots[i]
                    # feed the last emitted token at its absolute position:
                    # the prompt occupies [0, len), generated token g sits at
                    # len + g - 1
                    tokens[i] = slot.tokens[-1]
                    positions[i] = slot.position + slot.generated - 1
                    active[i] = True
                # the paged step takes the page tables between the slot
                # mask and the cache; the dense signature is otherwise
                # identical
                tables = (
                    (self._tables_device(),) if self._paged else ())
                with self._span("decode", active=len(active_idx)):
                    nxt, _logits, finite, self.cache = self._decode(
                        self.params, jnp.asarray(tokens),
                        jnp.asarray(positions), jnp.asarray(active),
                        *tables, self.cache,
                        jnp.asarray(self._base_keys),
                    )
                self.metrics.decode_steps += 1
                nxt = np.asarray(nxt)
                finite = np.asarray(finite)
                now = time.monotonic()
                poisoned = [i for i in active_idx if not finite[i]]
                if poisoned:
                    self._quarantine(poisoned, now, where="decode")
                for i in active_idx:
                    if finite[i]:
                        self._emit(i, int(nxt[i]), now)
        self.metrics.active_slots = sum(s.active for s in self._slots)
        self.metrics.queue_depth = len(self._queue)
        if (
            (self.monitor is not None or self.exporter is not None)
            and self.metrics.decode_steps % self.monitor_every == 0
        ):
            if self.monitor is not None:
                self.monitor.sample(counters=self.metrics.snapshot())
            if self.exporter is not None:
                # idle ticks keep the progress fingerprint unchanged —
                # only movement appends to the durable stream (the ring
                # buffer above is bounded, the file is not)
                self._export_snapshot()
        finished, self._finished_tick = self._finished_tick, []
        return finished

    def tick(self) -> List[RequestResult]:
        """Single-step driving alias for ``step()`` — the vocabulary the
        serving bridge (serving/gateway.py) uses: one tick = one
        admission sweep + one decode step."""
        return self.step()

    @property
    def pending(self) -> int:
        return len(self._queue) + sum(s.active for s in self._slots)

    def cancel(self, request_id: int, *,
               detail: str = "cancelled by client") -> bool:
        """Abort one in-flight request — queued or mid-decode — with an
        ``aborted`` terminal result (partial tokens attached, pages
        released through the allocator). The serving gateway calls this
        when a client disconnects mid-stream: the slot frees for the
        next admission instead of decoding for a closed socket. Returns
        False when the id is unknown or already terminal."""
        now = time.monotonic()
        for idx, req in enumerate(self._queue):
            if req.request_id == request_id:
                del self._queue[idx]
                self._finalize(req, "aborted", tokens=[], detail=detail,
                               now=now)
                self.metrics.queue_depth = len(self._queue)
                return True
        for i, slot in enumerate(self._slots):
            if slot.active and slot.request.request_id == request_id:
                self._retire_slot(i, "aborted", detail=detail, now=now)
                self.metrics.active_slots = sum(
                    s.active for s in self._slots)
                return True
        return False

    def stop_admissions(self) -> None:
        """Enter the draining state WITHOUT running the tick loop:
        ``submit()`` now raises ``EngineDraining`` / rejects, while
        queued and admitted requests keep flowing through ``step()``.
        The blocking ``drain()`` composes this with its own loop; a
        streaming bridge that owns the tick loop (and must keep
        delivering per-tick tokens/results during shutdown) calls this
        and keeps ticking until ``pending`` reaches zero. Idempotent."""
        self._draining = True

    def _abort_pending(self, detail: str) -> None:
        """Terminal-result every in-flight request as ``aborted``
        (partial tokens attached for admitted slots) — completed work is
        never discarded, and no slot stays active past its request's
        terminal result."""
        now = time.monotonic()
        while self._queue:
            self._finalize(self._queue.popleft(), "aborted", tokens=[],
                           detail=detail, now=now)
        for i, slot in enumerate(self._slots):
            if slot.active:
                self._retire_slot(i, "aborted", detail=detail, now=now)
        self.metrics.queue_depth = 0
        self.metrics.active_slots = 0

    def run(self, max_steps: int = 100_000) -> Dict[int, RequestResult]:
        """Drive ``step()`` until queue and slots drain; returns all
        results by request id. On ``max_steps`` exhaustion the completed
        results are RETURNED (never discarded) and the unfinished
        requests end as ``aborted`` with their partial tokens. A pending
        preemption request (SIGTERM via the ``preemption`` handler)
        switches to ``drain()``: admissions stop, in-flight requests
        finish, and the engine returns cleanly."""
        steps = 0
        while self.pending and steps < max_steps:
            if self.preemption is not None and self.preemption.requested:
                logger.warning(
                    "preemption requested (signal %s): draining the engine",
                    self.preemption.signum,
                )
                self.drain(max_steps=max_steps - steps)
                return dict(self._results)
            self.step()
            steps += 1
        if self.pending:
            logger.warning(
                "engine did not drain within %d steps: aborting %d "
                "in-flight requests (completed results are returned)",
                max_steps, self.pending,
            )
            self._abort_pending(f"run(max_steps={max_steps}) exhausted")
        if self.exporter is not None:
            # final snapshot: a short-lived run must leave its terminal
            # counters on the durable stream even between cadence points
            # (deduped — ending exactly on a cadence step appends once)
            self._export_snapshot()
        return dict(self._results)

    def drain(
        self,
        *,
        max_steps: int = 100_000,
        finish_queued: bool = False,
    ) -> Dict[int, RequestResult]:
        """Graceful shutdown: stop admissions (``submit()`` now raises
        ``EngineDraining`` / returns ``rejected``), finish the in-flight
        (admitted) requests, and flush all results. Queued-but-never-
        admitted requests are ``aborted`` immediately unless
        ``finish_queued`` — a SIGTERM grace period has no room for
        unbounded queue depth. Anything still unfinished after
        ``max_steps`` is ``aborted`` with partials attached. Idempotent."""
        self.stop_admissions()
        if not finish_queued:
            now = time.monotonic()
            while self._queue:
                self._finalize(
                    self._queue.popleft(), "aborted", tokens=[],
                    detail="drain: not yet admitted", now=now)
            self.metrics.queue_depth = 0
        steps = 0
        while self.pending and steps < max_steps:
            self.step()
            steps += 1
        if self.pending:
            self._abort_pending(f"drain(max_steps={max_steps}) exhausted")
        if self.exporter is not None:
            self._export_snapshot()
        return dict(self._results)

    def result(self, request_id: int) -> Optional[RequestResult]:
        return self._results.get(request_id)

    def pop_result(self, request_id: int) -> Optional[RequestResult]:
        """Remove and return a terminal result (None when absent or not
        yet terminal). The engine retains every terminal record for
        ``result()``/``run()`` otherwise — unbounded over a long-running
        server's lifetime, so a serving loop should pop each result once
        it has been delivered."""
        return self._results.pop(request_id, None)
