"""KV-cache containers in the models' stacked-scan layout.

The decode engine keeps one cache buffer pair per model: keys and values
``[L, B, Hkv, S_max, D]`` with the layer axis leading — the same stacked
layout the training params use, so the cached forward scans layers and
cache slices together (models/llama.py forward_cached) and compile time
stays O(1) in depth.

Sharding reuses the training stack's TP placement: K/V projections are
column-parallel over ``tp`` (tensor_parallel.llama_param_specs), so the
cache shards its KV-head axis over the same ``tp`` mesh axis —
``kv_cache_specs`` is the cache-side counterpart of llama_param_specs.
Slots (the engine's batch axis) can additionally shard over ``dp`` for
throughput serving. Placement is declarative (NamedSharding +
device_put); the jitted steps run GSPMD — no shard_map needed, so the
serving path works on any jax new enough for NamedSharding.

MLA models cache only the low-rank latent (``MLACache``,
[B, S_max, kv_rank]) and re-expand K/V per step — the trade the variant
documents (models/attention/variants.py MultiHeadLatentAttention).

Paged layout (ISSUE 10): ``PagedKVCache`` replaces the dense per-slot
buffers with a global pool of fixed-size pages
``[L, n_pages, Hkv, page_size, D]`` plus per-slot page tables
(``[B, max_pages]`` int32, TRASH_PAGE-padded). Slots reserve only the
pages their request can actually touch — HBM scales with tokens cached,
not ``B × S_max`` — and requests sharing a token prefix share pages:
``PageAllocator`` (host-side free list + refcounts) and
``RadixPrefixCache`` (page-granular radix tree over token chunks) keep
the bookkeeping; ``PagedKVIO`` adapts the models' cache-aware forwards
to the paged pool (ops/pallas/paged_attention.py holds the gather /
scatter primitives and the Pallas decode kernel). Sharding mirrors the
dense layout: the KV-head axis over the same ``tp`` mesh axis
(``paged_kv_cache_specs``).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from scaletorch_tpu.ops.pallas.paged_attention import (
    TRASH_PAGE,
    paged_attention,
    paged_write_kv,
)


class KVCache(NamedTuple):
    """Stacked per-layer cache buffers, each [L, B, Hkv, S_max, D].

    A NamedTuple so it is a pytree (jit/donate/scan-friendly) and
    unpacks as the plain ``(k, v)`` pair the models' cache-aware
    forwards consume.
    """

    k: jax.Array
    v: jax.Array


class MLACache(NamedTuple):
    """Latent-only cache [B, S_max, kv_rank] for MLA attention."""

    latent: jax.Array


def kv_cache_shape(cfg, batch: int, max_seq: int) -> Tuple[int, ...]:
    """[L, B, Hkv, S_max, D] for a Llama-family config, or
    [L, B, H, S_max, D] for GPT-MoE (full per-head K/V)."""
    if hasattr(cfg, "num_key_value_heads"):  # Llama / Qwen3 / Qwen3-MoE
        return (cfg.num_hidden_layers, batch, cfg.num_key_value_heads,
                max_seq, cfg.actual_head_dim)
    if hasattr(cfg, "n_layer"):  # GPTMoEConfig
        return (cfg.n_layer, batch, cfg.n_head, max_seq, cfg.head_dim)
    raise TypeError(f"no KV-cache layout known for config {type(cfg).__name__}")


def kv_cache_bytes(
    cfg,
    batch: int,
    max_seq: int,
    dtype: Any = None,
    *,
    layout: str = "dense",
    page_size: Optional[int] = None,
    num_pages: Optional[int] = None,
) -> int:
    """Total cache footprint (both buffers) — the capacity-planning number
    the engine logs at startup and the bench HBM column reports.

    Layout-aware: ``dense`` is the per-slot ``[L, B, Hkv, S_max, D]``
    pair (``batch × max_seq`` positions reserved whether used or not);
    ``paged`` is the page pool ``[L, n_pages, Hkv, page_size, D]`` pair —
    pass ``page_size`` and ``num_pages`` (``batch``/``max_seq`` then only
    size the default pool when ``num_pages`` is None: the
    dense-equivalent ``batch * ceil(max_seq / page_size)`` + trash).
    """
    if layout == "paged":
        if not page_size or page_size < 1:
            raise ValueError(
                f"paged layout needs page_size >= 1, got {page_size}")
        if num_pages is None:
            num_pages = batch * ceil_div(max_seq, page_size) + 1
        shape = paged_kv_cache_shape(cfg, num_pages, page_size)
    elif layout == "dense":
        shape = kv_cache_shape(cfg, batch, max_seq)
    else:
        raise ValueError(f"unknown cache layout {layout!r}")
    dt = jnp.dtype(dtype or getattr(cfg, "dtype", jnp.bfloat16))
    n = 1
    for d in shape:
        n *= d
    return 2 * n * dt.itemsize


def cache_nbytes(cache: Any) -> int:
    """Actual bytes of a cache pytree (arrays OR ShapeDtypeStructs) —
    the measured twin of :func:`kv_cache_bytes`. The jaxlint memory
    tier's ST1005 check (analysis/memory.py) and the quick-tier
    cross-check tests compare the two so bench_decode's HBM column and
    the engine's page-budget admission math can never drift from what
    XLA actually allocates."""
    from scaletorch_tpu.utils.misc import tree_bytes

    return tree_bytes(cache)


def init_kv_cache(
    cfg,
    batch: int,
    max_seq: int,
    *,
    dtype: Any = None,
    sharding: Optional[Any] = None,
) -> KVCache:
    """Zeroed cache in the model's compute dtype (bf16 on TPU). With
    ``sharding`` (a NamedSharding, applied to both buffers, or a KVCache
    of them) the buffers are created directly on their shards."""
    shape = kv_cache_shape(cfg, batch, max_seq)
    dt = dtype or getattr(cfg, "dtype", jnp.bfloat16)
    k = jnp.zeros(shape, dt)
    v = jnp.zeros(shape, dt)
    if sharding is not None:
        sk, sv = (sharding.k, sharding.v) if isinstance(sharding, KVCache) \
            else (sharding, sharding)
        k = jax.device_put(k, sk)
        v = jax.device_put(v, sv)
    return KVCache(k=k, v=v)


def kv_cache_specs(
    *, tp_axis: Optional[str] = "tp", batch_axis: Optional[str] = None
) -> KVCache:
    """PartitionSpec pair for the cache buffers — the cache-side
    counterpart of ``llama_param_specs``: KV heads over ``tp`` (matching
    the column-parallel k/v projections, so the decode matmuls never
    re-shard), slots optionally over ``batch_axis`` (dp) for throughput
    serving. Layer / sequence / head_dim axes stay unsharded — the
    sequence axis is appended to in place every step.
    """
    spec = P(None, batch_axis, tp_axis, None, None)
    return KVCache(k=spec, v=spec)


def kv_cache_shardings(
    mesh,
    *,
    tp_axis: Optional[str] = "tp",
    batch_axis: Optional[str] = None,
) -> KVCache:
    """NamedShardings over ``mesh`` for the cache pair."""
    specs = kv_cache_specs(tp_axis=tp_axis, batch_axis=batch_axis)
    return KVCache(
        k=NamedSharding(mesh, specs.k), v=NamedSharding(mesh, specs.v)
    )


def init_mla_cache(attn_cfg, batch: int, max_seq: int,
                   *, dtype: Any = None) -> MLACache:
    """Zeroed latent cache for an AttentionConfig with MLA ranks."""
    return MLACache(latent=jnp.zeros(
        (batch, max_seq, attn_cfg.kv_lora_rank), dtype or attn_cfg.dtype
    ))


# ---------------------------------------------------------------------------
# paged layout (ISSUE 10)
# ---------------------------------------------------------------------------
def ceil_div(a: int, b: int) -> int:
    """Page-count rounding, shared by every pages-for-N-tokens site
    (engine admission, decode step shapes, bench sizing)."""
    return -(-a // b)


class PagedKVCache(NamedTuple):
    """Stacked page pools, each [L, n_pages, Hkv, page_size, D].

    The device half of the paged cache: a global pool of fixed-size
    pages shared by every slot. Which slot owns which page lives
    host-side (``PageAllocator`` + the engine's page tables) and reaches
    the device as DATA — page-table contents are ints, never shapes, so
    the jitted steps compile once regardless of admissions, prefix hits,
    quarantine clears, and frees.
    """

    k: jax.Array
    v: jax.Array


def paged_kv_cache_shape(cfg, num_pages: int, page_size: int
                         ) -> Tuple[int, ...]:
    """[L, n_pages, Hkv, page_size, D] for any config ``kv_cache_shape``
    knows (page 0 is the reserved TRASH page — size the pool with it)."""
    l, _, h, _, d = kv_cache_shape(cfg, 1, 1)
    return (l, num_pages, h, page_size, d)


def init_paged_kv_cache(
    cfg,
    num_pages: int,
    page_size: int,
    *,
    dtype: Any = None,
    sharding: Optional[Any] = None,
) -> PagedKVCache:
    """Zeroed page pool in the model's compute dtype; with ``sharding``
    (a NamedSharding applied to both pools, or a PagedKVCache of them)
    the pools are created directly on their shards."""
    shape = paged_kv_cache_shape(cfg, num_pages, page_size)
    dt = dtype or getattr(cfg, "dtype", jnp.bfloat16)
    k = jnp.zeros(shape, dt)
    v = jnp.zeros(shape, dt)
    if sharding is not None:
        sk, sv = (sharding.k, sharding.v) \
            if isinstance(sharding, PagedKVCache) else (sharding, sharding)
        k = jax.device_put(k, sk)
        v = jax.device_put(v, sv)
    return PagedKVCache(k=k, v=v)


def paged_kv_cache_specs(
    *, tp_axis: Optional[str] = "tp"
) -> PagedKVCache:
    """PartitionSpec pair for the page pools — the same TP placement as
    the dense ``kv_cache_specs``: KV heads over ``tp`` (matching the
    column-parallel k/v projections). The page axis stays unsharded —
    pages are the unit of host-side ownership and any page must be
    reachable from any slot's table."""
    spec = P(None, None, tp_axis, None, None)
    return PagedKVCache(k=spec, v=spec)


def paged_kv_cache_shardings(
    mesh, *, tp_axis: Optional[str] = "tp"
) -> PagedKVCache:
    """NamedShardings over ``mesh`` for the page pools."""
    specs = paged_kv_cache_specs(tp_axis=tp_axis)
    return PagedKVCache(
        k=NamedSharding(mesh, specs.k), v=NamedSharding(mesh, specs.v)
    )


class PageAllocator:
    """Host-side page bookkeeping: free list + per-page refcounts.

    Page ids are indices into the device pool; page ``TRASH_PAGE`` (0)
    is reserved at construction and never handed out. A page is either
    FREE (on the free list, refcount 0) or ALLOCATED (refcount >= 1):
    ``alloc`` hands out pages at refcount 1, ``retain`` adds a
    reference (a prefix-sharing slot, the radix tree), ``release``
    drops one and returns the page to the free list at zero. Double
    release and foreign retain raise — the conservation invariant
    (free + allocated == capacity, every allocated page's refcount >= 1)
    is property-tested across randomized admit/retire/quarantine
    schedules.
    """

    def __init__(self, num_pages: int,
                 reserved: Tuple[int, ...] = (TRASH_PAGE,)) -> None:
        if num_pages < len(reserved) + 1:
            raise ValueError(
                f"page pool needs at least {len(reserved) + 1} pages "
                f"({len(reserved)} reserved), got {num_pages}"
            )
        self.num_pages = num_pages
        self.reserved = tuple(reserved)
        self._free: deque[int] = deque(
            p for p in range(num_pages) if p not in reserved)
        self._ref: Dict[int, int] = {}

    @property
    def capacity(self) -> int:
        """Allocatable pages (pool minus reserved)."""
        return self.num_pages - len(self.reserved)

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return len(self._ref)

    def refcount(self, page: int) -> int:
        """0 for free pages."""
        return self._ref.get(page, 0)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` fresh pages at refcount 1, or None (allocation is
        all-or-nothing — a partially admitted request would leak)."""
        if n < 0:
            raise ValueError(f"alloc needs n >= 0, got {n}")
        if n > len(self._free):
            return None
        pages = [self._free.popleft() for _ in range(n)]
        for p in pages:
            self._ref[p] = 1
        return pages

    def retain(self, page: int) -> None:
        if page not in self._ref:
            raise ValueError(f"retain of unallocated page {page}")
        self._ref[page] += 1

    def release(self, page: int) -> None:
        count = self._ref.get(page)
        if count is None:
            raise ValueError(f"double free of page {page}")
        if count == 1:
            del self._ref[page]
            self._free.append(page)
        else:
            self._ref[page] = count - 1

    def check_conservation(self) -> None:
        """Raise unless free + allocated == capacity and every allocated
        page holds a positive refcount (the property tests' oracle)."""
        if len(self._free) + len(self._ref) != self.capacity:
            raise AssertionError(
                f"page leak: {len(self._free)} free + {len(self._ref)} "
                f"allocated != capacity {self.capacity}"
            )
        bad = [p for p, c in self._ref.items() if c < 1]
        if bad:
            raise AssertionError(f"non-positive refcounts: {bad}")
        overlap = set(self._free) & set(self._ref)
        if overlap:
            raise AssertionError(f"pages both free and allocated: {overlap}")


class _RadixNode:
    __slots__ = ("children", "page", "last_used")

    def __init__(self, page: int = TRASH_PAGE) -> None:
        self.children: Dict[Tuple[int, ...], "_RadixNode"] = {}
        self.page = page
        self.last_used = 0


class RadixPrefixCache:
    """Page-granular radix tree over token prefixes.

    Each edge is one full ``page_size`` token chunk; a node owns the pool
    page holding that chunk's K/V. Prefix sharing is copy-on-write *at
    the page boundary*: only FULLY-FROZEN prompt pages (every position
    written at prefill, never written again) are ever registered, so a
    shared page is immutable by construction — a partially-filled
    boundary page is re-prefilled into the new request's own page
    instead of being split.

    The tree holds ONE allocator reference per registered page
    (``retain`` at insert); slots sharing the page add their own. A node
    is evictable only when no slot references its page (allocator
    refcount back down to the tree's single reference) — eviction is
    LRU over leaves, releasing the tree's reference so the page returns
    to the free list at refcount 0.
    """

    def __init__(self, page_size: int,
                 retain: Callable[[int], None],
                 release: Callable[[int], None],
                 refcount: Callable[[int], int]) -> None:
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.page_size = page_size
        self._retain = retain
        self._release = release
        self._refcount = refcount
        self.root = _RadixNode()
        self._clock = 0

    def _chunks(self, tokens) -> List[Tuple[int, ...]]:
        p = self.page_size
        return [tuple(tokens[i:i + p])
                for i in range(0, (len(tokens) // p) * p, p)]

    def __len__(self) -> int:
        count = 0
        stack = [self.root]
        while stack:
            node = stack.pop()
            count += len(node.children)
            stack.extend(node.children.values())
        return count

    def match(self, tokens) -> Tuple[int, List[int]]:
        """Longest page-aligned cached prefix of ``tokens``:
        (matched token count — a multiple of page_size — and the page
        ids, root-first). Touches the matched path's LRU clocks. The
        caller must ``retain`` every returned page before anything else
        can evict."""
        self._clock += 1
        node = self.root
        pages: List[int] = []
        for chunk in self._chunks(tokens):
            child = node.children.get(chunk)
            if child is None:
                break
            child.last_used = self._clock
            pages.append(child.page)
            node = child
        return len(pages) * self.page_size, pages

    def insert(self, tokens, pages: List[int]) -> int:
        """Register ``tokens`` (length a multiple of page_size) held in
        ``pages`` (one per chunk, root-first). Chunks already present
        keep their existing page (first writer wins — concurrent
        admissions of the same prompt each computed identical K/V, the
        duplicate copy stays private to its slot); new nodes take one
        allocator reference on their page. Returns the number of new
        nodes."""
        chunks = self._chunks(tokens)
        if len(chunks) != len(pages) or len(tokens) % self.page_size:
            raise ValueError(
                f"insert needs page-aligned tokens and one page per "
                f"chunk: {len(tokens)} tokens, {len(pages)} pages"
            )
        self._clock += 1
        node = self.root
        created = 0
        for chunk, page in zip(chunks, pages):
            child = node.children.get(chunk)
            if child is None:
                child = _RadixNode(page=page)
                node.children[chunk] = child
                self._retain(page)
                created += 1
            child.last_used = self._clock
            node = child
        return created

    def chains(self) -> List[Tuple[List[int], List[int]]]:
        """Snapshot every root-to-leaf path as ``(tokens, pages)`` —
        the donor half of warm rejoin. Leaf chains subsume their
        ancestors (the recipient re-inserts prefixes for free), so the
        list is the minimal set that reconstructs the tree. Pure read:
        no LRU touch, no refcount change — the caller decides which
        pages to retain for how long."""
        out: List[Tuple[List[int], List[int]]] = []
        stack: List[Tuple[_RadixNode, List[int], List[int]]] = [
            (self.root, [], [])]
        while stack:
            node, tokens, pages = stack.pop()
            if not node.children and pages:
                out.append((tokens, pages))
                continue
            for chunk, child in node.children.items():
                stack.append((child, tokens + list(chunk),
                              pages + [child.page]))
        return out

    def registered_pages(self) -> List[int]:
        """Every page the tree currently holds a reference on (the
        frozen set a donor may stream; anything else is mutable slot
        state and must never leave the process)."""
        pages: List[int] = []
        stack = [self.root]
        while stack:
            node = stack.pop()
            for child in node.children.values():
                pages.append(child.page)
                stack.append(child)
        return pages

    def evict(self, n_pages: int) -> int:
        """Free up to ``n_pages`` pages by pruning LRU leaves whose page
        no live slot references (allocator refcount == 1, the tree's
        own). Returns how many were released. Inner nodes become
        evictable once their children go — the loop re-scans until the
        target is met or nothing more can move."""
        freed = 0
        while freed < n_pages:
            leaves: List[Tuple[int, _RadixNode, Tuple[int, ...],
                               _RadixNode]] = []
            stack = [self.root]
            while stack:
                node = stack.pop()
                for chunk, child in node.children.items():
                    if child.children:
                        stack.append(child)
                    elif self._refcount(child.page) == 1:
                        leaves.append((child.last_used, id(child), chunk,
                                       node))
                    # leaves with live slot references are pinned
            if not leaves:
                break
            leaves.sort()
            for _, _, chunk, parent in leaves:
                child = parent.children.pop(chunk)
                self._release(child.page)
                freed += 1
                if freed >= n_pages:
                    break
        return freed


class PagedKVIO:
    """Paged-cache adapter for the models' cache-aware forwards.

    The dense path writes with ``write_kv_cache`` and attends with
    ``cached_sdpa_attention`` against ``[B, Hkv, S_max, D]`` buffers;
    with a ``kv_io`` the same forwards write/attend through this object
    against the page pool — constructed INSIDE the jitted step from the
    traced page tables, so tables are data and the step compiles once.
    ``seq_limit`` crops the fallback's gathered view to the engine's
    ``max_seq`` (bit-identical operand shapes vs the dense engine);
    ``kernel`` forwards to ``paged_attention``'s dispatcher (None =
    auto: Pallas decode kernel on TPU, lax gather elsewhere).
    """

    def __init__(self, page_tables: jax.Array, page_size: int, *,
                 seq_limit: Optional[int] = None,
                 kernel: Optional[bool] = None,
                 interpret: bool = False) -> None:
        self.page_tables = page_tables
        self.page_size = page_size
        self.seq_limit = seq_limit
        self.kernel = kernel
        self.interpret = interpret

    def write(self, pool: jax.Array, new: jax.Array, positions: jax.Array,
              write_mask: Optional[jax.Array]) -> jax.Array:
        return paged_write_kv(pool, new, positions, self.page_tables,
                              self.page_size, write_mask)

    def attend(self, q: jax.Array, pool_k: jax.Array, pool_v: jax.Array,
               q_positions: jax.Array) -> jax.Array:
        return paged_attention(
            q, pool_k, pool_v, self.page_tables, q_positions,
            page_size=self.page_size, seq_limit=self.seq_limit,
            kernel=self.kernel, interpret=self.interpret,
        )
