"""KV-cache containers in the models' stacked-scan layout.

The decode engine keeps one cache buffer pair per model: keys and values
``[L, B, Hkv, S_max, D]`` with the layer axis leading — the same stacked
layout the training params use, so the cached forward scans layers and
cache slices together (models/llama.py forward_cached) and compile time
stays O(1) in depth.

Sharding reuses the training stack's TP placement: K/V projections are
column-parallel over ``tp`` (tensor_parallel.llama_param_specs), so the
cache shards its KV-head axis over the same ``tp`` mesh axis —
``kv_cache_specs`` is the cache-side counterpart of llama_param_specs.
Slots (the engine's batch axis) can additionally shard over ``dp`` for
throughput serving. Placement is declarative (NamedSharding +
device_put); the jitted steps run GSPMD — no shard_map needed, so the
serving path works on any jax new enough for NamedSharding.

MLA models cache only the low-rank latent (``MLACache``,
[B, S_max, kv_rank]) and re-expand K/V per step — the trade the variant
documents (models/attention/variants.py MultiHeadLatentAttention).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


class KVCache(NamedTuple):
    """Stacked per-layer cache buffers, each [L, B, Hkv, S_max, D].

    A NamedTuple so it is a pytree (jit/donate/scan-friendly) and
    unpacks as the plain ``(k, v)`` pair the models' cache-aware
    forwards consume.
    """

    k: jax.Array
    v: jax.Array


class MLACache(NamedTuple):
    """Latent-only cache [B, S_max, kv_rank] for MLA attention."""

    latent: jax.Array


def kv_cache_shape(cfg, batch: int, max_seq: int) -> Tuple[int, ...]:
    """[L, B, Hkv, S_max, D] for a Llama-family config, or
    [L, B, H, S_max, D] for GPT-MoE (full per-head K/V)."""
    if hasattr(cfg, "num_key_value_heads"):  # Llama / Qwen3 / Qwen3-MoE
        return (cfg.num_hidden_layers, batch, cfg.num_key_value_heads,
                max_seq, cfg.actual_head_dim)
    if hasattr(cfg, "n_layer"):  # GPTMoEConfig
        return (cfg.n_layer, batch, cfg.n_head, max_seq, cfg.head_dim)
    raise TypeError(f"no KV-cache layout known for config {type(cfg).__name__}")


def kv_cache_bytes(cfg, batch: int, max_seq: int, dtype: Any = None) -> int:
    """Total cache footprint (both buffers) — the capacity-planning number
    the engine logs at startup."""
    shape = kv_cache_shape(cfg, batch, max_seq)
    dt = jnp.dtype(dtype or getattr(cfg, "dtype", jnp.bfloat16))
    n = 1
    for d in shape:
        n *= d
    return 2 * n * dt.itemsize


def init_kv_cache(
    cfg,
    batch: int,
    max_seq: int,
    *,
    dtype: Any = None,
    sharding: Optional[Any] = None,
) -> KVCache:
    """Zeroed cache in the model's compute dtype (bf16 on TPU). With
    ``sharding`` (a NamedSharding, applied to both buffers, or a KVCache
    of them) the buffers are created directly on their shards."""
    shape = kv_cache_shape(cfg, batch, max_seq)
    dt = dtype or getattr(cfg, "dtype", jnp.bfloat16)
    k = jnp.zeros(shape, dt)
    v = jnp.zeros(shape, dt)
    if sharding is not None:
        sk, sv = (sharding.k, sharding.v) if isinstance(sharding, KVCache) \
            else (sharding, sharding)
        k = jax.device_put(k, sk)
        v = jax.device_put(v, sv)
    return KVCache(k=k, v=v)


def kv_cache_specs(
    *, tp_axis: Optional[str] = "tp", batch_axis: Optional[str] = None
) -> KVCache:
    """PartitionSpec pair for the cache buffers — the cache-side
    counterpart of ``llama_param_specs``: KV heads over ``tp`` (matching
    the column-parallel k/v projections, so the decode matmuls never
    re-shard), slots optionally over ``batch_axis`` (dp) for throughput
    serving. Layer / sequence / head_dim axes stay unsharded — the
    sequence axis is appended to in place every step.
    """
    spec = P(None, batch_axis, tp_axis, None, None)
    return KVCache(k=spec, v=spec)


def kv_cache_shardings(
    mesh,
    *,
    tp_axis: Optional[str] = "tp",
    batch_axis: Optional[str] = None,
) -> KVCache:
    """NamedShardings over ``mesh`` for the cache pair."""
    specs = kv_cache_specs(tp_axis=tp_axis, batch_axis=batch_axis)
    return KVCache(
        k=NamedSharding(mesh, specs.k), v=NamedSharding(mesh, specs.v)
    )


def init_mla_cache(attn_cfg, batch: int, max_seq: int,
                   *, dtype: Any = None) -> MLACache:
    """Zeroed latent cache for an AttentionConfig with MLA ranks."""
    return MLACache(latent=jnp.zeros(
        (batch, max_seq, attn_cfg.kv_lora_rank), dtype or attn_cfg.dtype
    ))
