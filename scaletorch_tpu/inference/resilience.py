"""Serving resilience: terminal outcomes, fault injection, stall watchdog.

PRs 1-2 made *training* survivable (divergence sentinel, coordinated
multi-host recovery, hang watchdog, exit-code contract); this module is
the serving counterpart for ``InferenceEngine``. At fleet scale faults
are the steady state, not the exception (PAPERS.md: collective
communication at 100k+ GPUs), and a front door for millions of users
cannot let one malformed request, one NaN'd batch slot, or one stalled
decode step take the whole engine down. Three cooperating pieces:

  * a **terminal-outcome taxonomy** — every submitted request ends in
    exactly ONE of ``TERMINAL_OUTCOMES``; the engine maintains the
    conservation invariant ``requests_submitted == sum(outcomes)`` so an
    operator (or a test) can always account for every request:

      - ``ok``          finished normally (eos / length / max_seq)
      - ``timeout``     per-request deadline (TTL) exceeded, queued or
                        mid-decode; partial tokens are attached
      - ``shed``        dropped oldest-first by bounded admission when
                        the queue exceeded ``queue_capacity``
      - ``rejected``    failed validation at submit (over-long prompt,
                        empty prompt, draining engine) under
                        ``strict_submit=False``
      - ``quarantined`` the slot's logits went non-finite (a poison
                        request / bad numerics); the slot is retired,
                        its cache lines mask-cleared, and the engine
                        keeps serving the other slots
      - ``aborted``     the engine gave up externally: ``run(max_steps)``
                        exhausted, or ``drain()`` retired it

  * a ``ServingFaultInjector`` — config/env-driven serving faults
    (NaN logits at a decode step, a slow decode stall, a submit storm,
    a deadline storm) mirroring the training ``FaultInjector`` so the
    recovery paths are exercised by hermetic end-to-end tests.

  * ``make_serving_watchdog`` — the existing ``HangWatchdog`` pointed at
    the engine: a stalled ``step()`` dumps thread stacks plus the engine
    metrics snapshot to a crash report (``write_crash_report``) and
    exits ``SERVING_STALL_EXIT_CODE`` (44), extending the 0/42/43/130
    contract documented in docs/fault_tolerance.md.

Graceful drain lives on the engine itself (``InferenceEngine.drain``),
wired to the training stack's ``PreemptionHandler`` so SIGTERM follows
the same stop-at-the-next-boundary discipline as a training run.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Optional

from scaletorch_tpu.resilience_distributed import (
    SERVING_STALL_EXIT_CODE,
    HangWatchdog,
    write_crash_report,
)
from scaletorch_tpu.utils.logger import get_logger

__all__ = [
    "TERMINAL_OUTCOMES",
    "EngineDraining",
    "ServingFaultInjector",
    "SERVING_STALL_EXIT_CODE",
    "make_serving_watchdog",
]

# Every submitted request ends in exactly one of these (RequestResult
# .outcome); the engine's conservation invariant sums over them.
TERMINAL_OUTCOMES = (
    "ok", "timeout", "shed", "rejected", "quarantined", "aborted",
)


class EngineDraining(RuntimeError):
    """Raised by ``submit()`` (strict mode) once ``drain()`` has stopped
    admissions — the serving loop is shutting down."""


# --------------------------------------------------------------------------
# Fault injection
# --------------------------------------------------------------------------


@dataclass
class ServingFaultInjector:
    """Config/env-driven serving fault hooks. All knobs default to off (0).

    Steps are DECODE steps, 1-based: ``at_step == k`` fires on the tick
    that runs the k-th decode step of the engine's lifetime.

    * ``nan_logits_at_step`` / ``nan_logits_slot`` — before decode step
      k, fill slot ``nan_logits_slot``'s KV-cache lines with NaN so its
      logits go non-finite that step (a poison request), driving the
      quarantine path. The write is a masked device op
      (``make_fill_slots_step``) — data changes, no retrace.
    * ``slow_decode_at_step`` / ``slow_decode_seconds`` — stall the
      engine once before decode step k, simulating a wedged device
      dispatch for the serving watchdog.
    * ``submit_storm_at_step`` / ``submit_storm_count`` — inject a burst
      of n one-token requests at step k, driving bounded admission and
      oldest-first shedding.
    * ``deadline_storm_at_step`` — force every in-flight request's
      deadline (queued and mid-decode) into the past at step k, driving
      the ``timeout`` paths at admission and decode.

    Gateway drills (serving/gateway.py; the counting unit is HTTP
    requests, 1-based, not decode steps — the gateway is upstream of
    the engine's tick clock):

    * ``gw_tenant_storm_at`` / ``gw_tenant_storm_count`` — when the k-th
      generate request arrives, one synthetic tenant (``storm``) floods
      the admission queue with n requests, driving weighted-fair
      queueing (the victim tenants keep their WFQ share) and
      shed-before-latency backpressure (HTTP 429 + Retry-After).
    * ``gw_replica_down_at`` — when the k-th request is DISPATCHED to a
      replica, the router marks that replica dead mid-stream (exit-code
      contract, as if it exited 43/44): its in-flight requests end
      ``aborted``, queued requests re-route to the surviving replicas.
    * ``gw_replica_crash_at`` — when the k-th request is DISPATCHED,
      SIGKILL that replica's child process (``worker.kill()``; an
      in-process worker degrades to thread death). Nothing is
      announced: the gateway must OBSERVE the crash — the reader
      threads synthesize the ``aborted`` terminals, the poller flips
      liveness, the supervisor restarts the child with backoff.
    * ``gw_replica_hang_at`` — when the k-th request is DISPATCHED,
      stall that replica's step loop (``worker.stall()``): no ticks,
      no watchdog beats, so its armed serving watchdog fires exit 44
      and the supervisor treats it as a crash.

    Env overrides (present-wins, the ``env.env_override`` contract
    shared with the training ``FaultInjector``):
    ``SCALETORCH_TPU_FT_SERVE_NAN_STEP``, ``.._SERVE_NAN_SLOT``,
    ``.._SERVE_SLOW_STEP``, ``.._SERVE_SLOW_SECONDS``,
    ``.._SERVE_SUBMIT_STORM_STEP``, ``.._SERVE_SUBMIT_STORM_COUNT``,
    ``.._SERVE_DEADLINE_STORM_STEP``; gateway:
    ``SCALETORCH_TPU_FT_GW_TENANT_STORM_AT``,
    ``.._GW_TENANT_STORM_COUNT``, ``.._GW_REPLICA_DOWN_AT``,
    ``.._GW_REPLICA_CRASH_AT``, ``.._GW_REPLICA_HANG_AT``,
    ``.._GW_WARM_DONOR_CRASH_AT``, ``.._GW_WARM_CORRUPT_CHUNK_AT``.
    """

    nan_logits_at_step: int = 0
    nan_logits_slot: int = 0
    slow_decode_at_step: int = 0
    slow_decode_seconds: float = 30.0
    submit_storm_at_step: int = 0
    submit_storm_count: int = 8
    deadline_storm_at_step: int = 0
    gw_tenant_storm_at: int = 0
    gw_tenant_storm_count: int = 8
    gw_replica_down_at: int = 0
    gw_replica_crash_at: int = 0
    gw_replica_hang_at: int = 0
    gw_warm_donor_crash_at: int = 0
    gw_warm_corrupt_chunk_at: int = 0
    _nan_fired: bool = field(default=False, repr=False)
    _slow_fired: bool = field(default=False, repr=False)
    _storm_fired: bool = field(default=False, repr=False)
    _deadline_fired: bool = field(default=False, repr=False)
    _gw_storm_fired: bool = field(default=False, repr=False)
    _gw_down_fired: bool = field(default=False, repr=False)
    _gw_crash_fired: bool = field(default=False, repr=False)
    _gw_hang_fired: bool = field(default=False, repr=False)
    _gw_warm_crash_fired: bool = field(default=False, repr=False)
    _gw_warm_corrupt_fired: bool = field(default=False, repr=False)

    @classmethod
    def from_config(cls, cfg) -> "ServingFaultInjector":
        from scaletorch_tpu.env import env_override

        def env_or(name: str, cfg_field: str, default):
            return env_override(name, getattr(cfg, cfg_field, default))

        return cls(
            nan_logits_at_step=int(env_or(
                "SCALETORCH_TPU_FT_SERVE_NAN_STEP",
                "ft_serve_nan_at_step", 0)),
            nan_logits_slot=int(env_or(
                "SCALETORCH_TPU_FT_SERVE_NAN_SLOT",
                "ft_serve_nan_slot", 0)),
            slow_decode_at_step=int(env_or(
                "SCALETORCH_TPU_FT_SERVE_SLOW_STEP",
                "ft_serve_slow_at_step", 0)),
            slow_decode_seconds=float(env_or(
                "SCALETORCH_TPU_FT_SERVE_SLOW_SECONDS",
                "ft_serve_slow_seconds", 30.0)),
            submit_storm_at_step=int(env_or(
                "SCALETORCH_TPU_FT_SERVE_SUBMIT_STORM_STEP",
                "ft_serve_submit_storm_at_step", 0)),
            submit_storm_count=int(env_or(
                "SCALETORCH_TPU_FT_SERVE_SUBMIT_STORM_COUNT",
                "ft_serve_submit_storm_count", 8)),
            deadline_storm_at_step=int(env_or(
                "SCALETORCH_TPU_FT_SERVE_DEADLINE_STORM_STEP",
                "ft_serve_deadline_storm_at_step", 0)),
            gw_tenant_storm_at=int(env_or(
                "SCALETORCH_TPU_FT_GW_TENANT_STORM_AT",
                "ft_gw_tenant_storm_at", 0)),
            gw_tenant_storm_count=int(env_or(
                "SCALETORCH_TPU_FT_GW_TENANT_STORM_COUNT",
                "ft_gw_tenant_storm_count", 8)),
            gw_replica_down_at=int(env_or(
                "SCALETORCH_TPU_FT_GW_REPLICA_DOWN_AT",
                "ft_gw_replica_down_at", 0)),
            gw_replica_crash_at=int(env_or(
                "SCALETORCH_TPU_FT_GW_REPLICA_CRASH_AT",
                "ft_gw_replica_crash_at", 0)),
            gw_replica_hang_at=int(env_or(
                "SCALETORCH_TPU_FT_GW_REPLICA_HANG_AT",
                "ft_gw_replica_hang_at", 0)),
            gw_warm_donor_crash_at=int(env_or(
                "SCALETORCH_TPU_FT_GW_WARM_DONOR_CRASH_AT",
                "ft_gw_warm_donor_crash_at", 0)),
            gw_warm_corrupt_chunk_at=int(env_or(
                "SCALETORCH_TPU_FT_GW_WARM_CORRUPT_CHUNK_AT",
                "ft_gw_warm_corrupt_chunk_at", 0)),
        )

    @property
    def active(self) -> bool:
        return bool(self.nan_logits_at_step or self.slow_decode_at_step
                    or self.submit_storm_at_step
                    or self.deadline_storm_at_step
                    or self.gw_tenant_storm_at
                    or self.gw_replica_down_at
                    or self.gw_replica_crash_at
                    or self.gw_replica_hang_at
                    or self.gw_warm_donor_crash_at
                    or self.gw_warm_corrupt_chunk_at)

    def take_nan_logits(self, step: int) -> Optional[int]:
        """Slot index to poison before decode step ``step``, or None."""
        if self.nan_logits_at_step and step == self.nan_logits_at_step \
                and not self._nan_fired:
            self._nan_fired = True
            get_logger().warning(
                f"serving fault injection: NaN logits in slot "
                f"{self.nan_logits_slot} at decode step {step}"
            )
            return max(0, self.nan_logits_slot)
        return None

    def take_slow_decode(self, step: int) -> float:
        """Seconds to stall before decode step ``step`` (0 = no stall)."""
        if self.slow_decode_at_step and step == self.slow_decode_at_step \
                and not self._slow_fired:
            self._slow_fired = True
            get_logger().warning(
                f"serving fault injection: stalling {self.slow_decode_seconds:g}s "
                f"before decode step {step}"
            )
            return self.slow_decode_seconds
        return 0.0

    def take_submit_storm(self, step: int) -> int:
        """Number of storm requests to inject at step ``step``."""
        if self.submit_storm_at_step and step == self.submit_storm_at_step \
                and not self._storm_fired:
            self._storm_fired = True
            get_logger().warning(
                f"serving fault injection: submit storm of "
                f"{self.submit_storm_count} requests at decode step {step}"
            )
            return max(0, self.submit_storm_count)
        return 0

    def take_deadline_storm(self, step: int) -> bool:
        """True when every in-flight deadline must be forced expired."""
        if self.deadline_storm_at_step \
                and step == self.deadline_storm_at_step \
                and not self._deadline_fired:
            self._deadline_fired = True
            get_logger().warning(
                f"serving fault injection: deadline storm at decode "
                f"step {step}"
            )
            return True
        return False

    def take_gw_tenant_storm(self, http_request: int) -> int:
        """Number of storm-tenant requests the gateway must inject when
        the ``http_request``-th (1-based) generate request arrives."""
        if self.gw_tenant_storm_at \
                and http_request == self.gw_tenant_storm_at \
                and not self._gw_storm_fired:
            self._gw_storm_fired = True
            get_logger().warning(
                f"gateway fault injection: tenant storm of "
                f"{self.gw_tenant_storm_count} requests at HTTP request "
                f"{http_request}"
            )
            return max(0, self.gw_tenant_storm_count)
        return 0

    def take_gw_replica_down(self, dispatch: int) -> bool:
        """True when the replica receiving the ``dispatch``-th (1-based)
        routed request must be marked dead mid-stream."""
        if self.gw_replica_down_at \
                and dispatch == self.gw_replica_down_at \
                and not self._gw_down_fired:
            self._gw_down_fired = True
            get_logger().warning(
                f"gateway fault injection: marking the routed replica "
                f"dead at dispatch {dispatch}"
            )
            return True
        return False

    def take_gw_replica_crash(self, dispatch: int) -> bool:
        """True when the replica receiving the ``dispatch``-th (1-based)
        routed request must be SIGKILL'd (process fleet) / thread-killed
        (in-process) — the crash the gateway must survive by observation
        alone."""
        if self.gw_replica_crash_at \
                and dispatch == self.gw_replica_crash_at \
                and not self._gw_crash_fired:
            self._gw_crash_fired = True
            get_logger().warning(
                f"gateway fault injection: killing the routed replica's "
                f"process at dispatch {dispatch}"
            )
            return True
        return False

    def take_gw_replica_hang(self, dispatch: int) -> bool:
        """True when the replica receiving the ``dispatch``-th (1-based)
        routed request must stall its step loop (the serving watchdog
        should fire exit 44)."""
        if self.gw_replica_hang_at \
                and dispatch == self.gw_replica_hang_at \
                and not self._gw_hang_fired:
            self._gw_hang_fired = True
            get_logger().warning(
                f"gateway fault injection: stalling the routed replica's "
                f"step loop at dispatch {dispatch}"
            )
            return True
        return False

    def take_gw_warm_donor_crash(self, chunk: int) -> bool:
        """True when the donor replica must SIGKILL itself after
        streaming the ``chunk``-th (1-based) warm-transfer frame — the
        mid-transfer donor death the recipient must survive by falling
        back to the next peer (or a cold rejoin)."""
        if self.gw_warm_donor_crash_at \
                and chunk == self.gw_warm_donor_crash_at \
                and not self._gw_warm_crash_fired:
            self._gw_warm_crash_fired = True
            get_logger().warning(
                f"gateway fault injection: donor self-SIGKILL after "
                f"warm-transfer chunk {chunk}"
            )
            return True
        return False

    def take_gw_warm_corrupt_chunk(self, chunk: int) -> bool:
        """True when the donor must flip bytes in the ``chunk``-th
        (1-based) warm-transfer frame AFTER checksumming it — the
        recipient must detect the mismatch, drop that chunk, and keep
        the rest of the stream."""
        if self.gw_warm_corrupt_chunk_at \
                and chunk == self.gw_warm_corrupt_chunk_at \
                and not self._gw_warm_corrupt_fired:
            self._gw_warm_corrupt_fired = True
            get_logger().warning(
                f"gateway fault injection: corrupting warm-transfer "
                f"chunk {chunk} in flight"
            )
            return True
        return False


# --------------------------------------------------------------------------
# Serving stall watchdog
# --------------------------------------------------------------------------


def make_serving_watchdog(
    engine,
    timeout: float,
    *,
    crash_report_dir: str = "results",
    exit_fn: Callable[[int], None] = os._exit,
    attach: bool = True,
) -> HangWatchdog:
    """A ``HangWatchdog`` pointed at an ``InferenceEngine``.

    ``engine.step()`` beats the watchdog each tick; a ``step()`` that
    stalls past ``timeout`` seconds (a wedged device dispatch, a dead
    collective on a sharded serving mesh) dumps every thread stack plus
    the engine's metrics snapshot — including the per-outcome counters,
    so the post-mortem shows what the engine had admitted/shed/
    quarantined when it died — to ``crash_report_dir`` and exits
    ``SERVING_STALL_EXIT_CODE`` (44). Same fire-dump-exit discipline,
    crash-report plumbing, and launcher contract as the training
    watchdog; tests inject a recording ``exit_fn``.

    With ``attach`` (default) the watchdog is installed as
    ``engine.watchdog`` so ``step()`` beats it; the caller still owns
    start/stop (``with make_serving_watchdog(...):``).
    """

    def _report(info: dict) -> None:
        monitor = getattr(engine, "monitor", None)
        tracer = getattr(engine, "tracer", None)
        write_crash_report(
            info.get("reason", "serving stall watchdog fired"),
            engine.metrics.decode_steps,
            directory=crash_report_dir,
            counters=engine.metrics.snapshot(),
            monitor_records=(
                list(monitor.records) if monitor is not None else None
            ),
            thread_stacks=info.get("thread_stacks"),
            # the engine's span timeline (tick/admission/prefill/decode)
            # right up to the stall — same enriched layout as training
            # crash reports (docs/fault_tolerance.md)
            span_tail=(tracer.tail() if tracer is not None else None),
            extra={
                "serving": True,
                "exit_code": SERVING_STALL_EXIT_CODE,
                "pending_requests": engine.pending,
            },
        )

    wd = HangWatchdog(
        timeout,
        crash_report=_report,
        exit_fn=exit_fn,
        exit_code=SERVING_STALL_EXIT_CODE,
    )
    if attach:
        engine.watchdog = wd
    return wd
