"""Token sampling: greedy / temperature / top-k / top-p, per-slot keys.

All knobs are STATIC (baked into the jitted decode step at engine build
— changing them is a new engine, not a retrace hazard mid-run); the
per-slot PRNG keys are traced, derived per (request seed, position) so a
slot's stream is deterministic regardless of which physical slot the
request landed in or what its neighbours sample.

Filter order follows the HF convention: temperature first, then top-k,
then top-p on the already-scaled distribution.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

_NEG_INF = jnp.finfo(jnp.float32).min


@dataclass(frozen=True)
class SamplingParams:
    """temperature == 0 -> greedy argmax (top_k/top_p ignored);
    top_k == 0 and top_p == 1.0 disable their filters."""

    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0

    def __post_init__(self) -> None:
        if self.temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {self.temperature}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if not 0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")

    @property
    def greedy(self) -> bool:
        return self.temperature == 0


def _filter_top_k(logits: jax.Array, k: int) -> jax.Array:
    """Keep the k highest logits, mask the rest to -inf. k is static."""
    if k <= 0 or k >= logits.shape[-1]:
        return logits
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, _NEG_INF, logits)


def _filter_top_p(logits: jax.Array, p: float) -> jax.Array:
    """Nucleus filter: keep the smallest prefix of the sorted
    distribution whose cumulative probability reaches ``p`` (the
    highest-probability token always survives)."""
    if p >= 1.0:
        return logits
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits.astype(jnp.float32), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # exclusive cumulative < p keeps the first token unconditionally
    keep = (cum - probs) < p
    cutoff = jnp.min(
        jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits < cutoff, _NEG_INF, logits)


def sample_one(
    logits: jax.Array, key: jax.Array, params: SamplingParams
) -> jax.Array:
    """One token from one slot's [V] logits."""
    if params.greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits.astype(jnp.float32) / params.temperature
    scaled = _filter_top_k(scaled, params.top_k)
    scaled = _filter_top_p(scaled, params.top_p)
    return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)


def sample(
    logits: jax.Array, keys: jax.Array, params: SamplingParams
) -> jax.Array:
    """[B, V] logits + per-slot keys [B, ...] -> [B] tokens."""
    if params.greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.vmap(lambda l, k: sample_one(l, k, params))(logits, keys)


def finite_mask(logits: jax.Array) -> jax.Array:
    """[..., V] logits -> [...] bool: True where every vocab entry is
    finite. The serving counterpart of the train step's in-jit
    ``nonfinite_guard``: both engine steps compute it on the logits they
    sample from, so a slot whose numerics went NaN/Inf (a poison
    request) is flagged INSIDE the compiled step — the engine
    quarantines it without retracing and without a speculative host
    round-trip per token."""
    return jnp.all(jnp.isfinite(logits), axis=-1)


def slot_keys(base_keys: jax.Array, positions: jax.Array) -> jax.Array:
    """Per-step keys: fold each slot's position into its request seed —
    the (seed, position) pair makes every emitted token's randomness
    reproducible independent of slot placement or batch composition."""
    return jax.vmap(jax.random.fold_in)(base_keys, positions)
