"""Model zoo: functional JAX models (params pytree + pure apply).

Parity with reference scaletorch/models/__init__.py:1-9 — Llama, Qwen3,
Qwen3-MoE, GPT(MoE), LeNet, plus the standalone attention-variant library
(MHA/MQA/GQA/MLA) and the attention backend registry.
"""

from scaletorch_tpu.models.registry import (  # noqa: F401
    get_attention_backend,
    register_attention_backend,
    resolve_attention_backend,
)
from scaletorch_tpu.models.llama import Llama, LlamaConfig  # noqa: F401
from scaletorch_tpu.models.qwen3 import Qwen3, Qwen3Config  # noqa: F401
from scaletorch_tpu.models.qwen3_moe import Qwen3MoE, Qwen3MoEConfig  # noqa: F401
from scaletorch_tpu.models.gpt_moe import GPTMoE, GPTMoEConfig  # noqa: F401
from scaletorch_tpu.models.lenet import LeNet, LeNetConfig  # noqa: F401
from scaletorch_tpu.models.resnet import ResNetConfig  # noqa: F401

# Register the non-default attention backends (flash; ring arrives with the
# context-parallel module).
import scaletorch_tpu.ops  # noqa: E402,F401
