"""Standalone attention-variant library: MHA / MQA / GQA / MLA.

Parity with reference scaletorch/models/attention/{base,mha,mqa,gqa,
mla}.py (852 LoC) — a self-contained educational family, not wired into
the production decoders (reference models/__init__.py note). Functional
JAX style: each variant is an ``init(key, cfg) -> params`` +
``apply(params, x, ...) -> y`` pair over a shared config.
"""

from scaletorch_tpu.models.attention.base import AttentionConfig  # noqa: F401
from scaletorch_tpu.models.attention.variants import (  # noqa: F401
    GroupQueryAttention,
    MultiHeadAttention,
    MultiHeadLatentAttention,
    MultiQueryAttention,
)
