"""Shared config + scaffold for the attention-variant library.

Parity with reference scaletorch/models/attention/base.py:12
(``BaseAttention`` ABC: embed_dim/num_heads bookkeeping, dropout knobs,
shape validation). Functional version: the config carries the
bookkeeping; each variant supplies init/apply.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class AttentionConfig:
    embed_dim: int = 256
    num_heads: int = 8
    num_kv_heads: Optional[int] = None  # GQA groups; 1 = MQA; None = MHA
    head_dim: Optional[int] = None
    # MLA latent dims (reference mla.py:60-66: q/kv down-up projections)
    q_lora_rank: Optional[int] = None
    kv_lora_rank: int = 64
    dtype: Any = jnp.float32

    def __post_init__(self):
        if self.embed_dim % self.num_heads != 0:
            raise ValueError(
                f"embed_dim {self.embed_dim} not divisible by num_heads "
                f"{self.num_heads}"
            )
        kv = self.num_kv_heads
        if kv is not None and self.num_heads % kv != 0:
            raise ValueError(
                f"num_heads {self.num_heads} not divisible by num_kv_heads {kv}"
            )

    @property
    def actual_head_dim(self) -> int:
        return self.head_dim or self.embed_dim // self.num_heads

    @property
    def actual_num_kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads


class AttentionVariant:
    """Thin OO veneer shared by all variants (reference BaseAttention)."""

    def __init__(self, cfg: AttentionConfig):
        self.cfg = cfg

    def init(self, key: jax.Array):
        raise NotImplementedError

    def __call__(self, params, x: jax.Array, *, causal: bool = True):
        raise NotImplementedError
