"""MHA / MQA / GQA / MLA attention variants.

Parity with reference scaletorch/models/attention/:
  * ``MultiHeadAttention`` (mha.py:9) — full per-head K/V
  * ``MultiQueryAttention`` (mqa.py:9) — single shared K/V head
  * ``GroupQueryAttention`` (gqa.py:9) — grouped K/V heads
  * ``MultiHeadLatentAttention`` (mla.py:9,60-66) — DeepSeek-style
    low-rank q/kv down-up projections through a latent bottleneck

All four are one parameterised implementation: MHA/MQA are GQA with
kv_heads = heads / 1 (the same collapse the reference's class hierarchy
expresses), MLA adds the latent projections in front.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from scaletorch_tpu.models.attention.base import AttentionConfig, AttentionVariant
from scaletorch_tpu.models.layers import (
    fan_in_uniform,
    repeat_kv,
    sdpa_attention,
)

Params = Dict[str, jax.Array]


def _gqa_init(key: jax.Array, cfg: AttentionConfig, kv_heads: int) -> Params:
    d, dh = cfg.embed_dim, cfg.actual_head_dim
    nh = cfg.num_heads
    ks = jax.random.split(key, 4)
    pd = cfg.dtype
    return {
        "q_proj": fan_in_uniform(ks[0], (d, nh * dh), d, pd),
        "k_proj": fan_in_uniform(ks[1], (d, kv_heads * dh), d, pd),
        "v_proj": fan_in_uniform(ks[2], (d, kv_heads * dh), d, pd),
        "o_proj": fan_in_uniform(ks[3], (nh * dh, d), nh * dh, pd),
    }


def _gqa_apply(
    params: Params, x: jax.Array, cfg: AttentionConfig, kv_heads: int,
    *, causal: bool = True,
) -> jax.Array:
    b, s, _ = x.shape
    nh, dh = cfg.num_heads, cfg.actual_head_dim
    q = (x @ params["q_proj"]).reshape(b, s, nh, dh).transpose(0, 2, 1, 3)
    k = (x @ params["k_proj"]).reshape(b, s, kv_heads, dh).transpose(0, 2, 1, 3)
    v = (x @ params["v_proj"]).reshape(b, s, kv_heads, dh).transpose(0, 2, 1, 3)
    k = repeat_kv(k, nh // kv_heads)
    v = repeat_kv(v, nh // kv_heads)
    o = sdpa_attention(q, k, v, causal=causal)
    return o.transpose(0, 2, 1, 3).reshape(b, s, nh * dh) @ params["o_proj"]


class MultiHeadAttention(AttentionVariant):
    """Per-head K/V (reference mha.py:9)."""

    def init(self, key):
        return _gqa_init(key, self.cfg, self.cfg.num_heads)

    def __call__(self, params, x, *, causal: bool = True):
        return _gqa_apply(params, x, self.cfg, self.cfg.num_heads, causal=causal)


class MultiQueryAttention(AttentionVariant):
    """One shared K/V head (reference mqa.py:9)."""

    def init(self, key):
        return _gqa_init(key, self.cfg, 1)

    def __call__(self, params, x, *, causal: bool = True):
        return _gqa_apply(params, x, self.cfg, 1, causal=causal)


class GroupQueryAttention(AttentionVariant):
    """Grouped K/V heads (reference gqa.py:9)."""

    def init(self, key):
        return _gqa_init(key, self.cfg, self.cfg.actual_num_kv_heads)

    def __call__(self, params, x, *, causal: bool = True):
        return _gqa_apply(
            params, x, self.cfg, self.cfg.actual_num_kv_heads, causal=causal
        )


class MultiHeadLatentAttention(AttentionVariant):
    """Low-rank latent q/kv projections (reference mla.py:9,60-66):
    x -> down-project to a small latent -> up-project to per-head q/k/v.
    The KV cache (in inference) stores ONLY the latent: ``init_cache`` /
    ``prefill`` / ``decode`` keep a [B, S_max, kv_rank] buffer and
    re-expand K/V from it per step — per-token cache cost R floats
    instead of 2·H·D (inference/kv_cache.MLACache wraps the buffer for
    the engine-side bookkeeping)."""

    def init(self, key):
        cfg = self.cfg
        d, dh, nh = cfg.embed_dim, cfg.actual_head_dim, cfg.num_heads
        qr = cfg.q_lora_rank or d
        kr = cfg.kv_lora_rank
        ks = jax.random.split(key, 6)
        pd = cfg.dtype
        params: Params = {
            "kv_down": fan_in_uniform(ks[0], (d, kr), d, pd),
            "k_up": fan_in_uniform(ks[1], (kr, nh * dh), kr, pd),
            "v_up": fan_in_uniform(ks[2], (kr, nh * dh), kr, pd),
            "o_proj": fan_in_uniform(ks[3], (nh * dh, d), nh * dh, pd),
        }
        if cfg.q_lora_rank:
            params["q_down"] = fan_in_uniform(ks[4], (d, qr), d, pd)
            params["q_up"] = fan_in_uniform(ks[5], (qr, nh * dh), qr, pd)
        else:
            params["q_proj"] = fan_in_uniform(ks[4], (d, nh * dh), d, pd)
        return params

    def __call__(self, params, x, *, causal: bool = True):
        cfg = self.cfg
        b, s, _ = x.shape
        nh, dh = cfg.num_heads, cfg.actual_head_dim
        if "q_down" in params:
            q = (x @ params["q_down"]) @ params["q_up"]
        else:
            q = x @ params["q_proj"]
        latent = x @ params["kv_down"]  # [B, S, kv_rank] — the cacheable state
        k = latent @ params["k_up"]
        v = latent @ params["v_up"]
        q = q.reshape(b, s, nh, dh).transpose(0, 2, 1, 3)
        k = k.reshape(b, s, nh, dh).transpose(0, 2, 1, 3)
        v = v.reshape(b, s, nh, dh).transpose(0, 2, 1, 3)
        o = sdpa_attention(q, k, v, causal=causal)
        return o.transpose(0, 2, 1, 3).reshape(b, s, nh * dh) @ params["o_proj"]

    # ---- latent-only KV cache (decode engine hook) -----------------------

    def init_cache(self, batch: int, max_seq: int,
                   dtype=None) -> jax.Array:
        """Zeroed latent cache [B, S_max, kv_rank] — the ONLY decode
        state MLA keeps (K/V re-expand from it through k_up/v_up)."""
        return jnp.zeros((batch, max_seq, self.cfg.kv_lora_rank),
                         dtype or self.cfg.dtype)

    def _query(self, params, x):
        if "q_down" in params:
            return (x @ params["q_down"]) @ params["q_up"]
        return x @ params["q_proj"]

    def _attend_cache(self, params, q, latent_cache, q_positions):
        """q: [B, S, nh·dh] flat; latent_cache: [B, S_max, R];
        q_positions: [B, S]. Up-projects the whole cached latent to K/V
        and attends with the j <= p mask."""
        from scaletorch_tpu.models.layers import cached_sdpa_attention

        cfg = self.cfg
        b, s, _ = q.shape
        nh, dh = cfg.num_heads, cfg.actual_head_dim
        k = (latent_cache @ params["k_up"]).reshape(b, -1, nh, dh)
        v = (latent_cache @ params["v_up"]).reshape(b, -1, nh, dh)
        q = q.reshape(b, s, nh, dh).transpose(0, 2, 1, 3)
        o = cached_sdpa_attention(
            q, k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3), q_positions
        )
        return o.transpose(0, 2, 1, 3).reshape(b, s, nh * dh) @ params["o_proj"]

    def prefill(self, params, x, cache):
        """Full-prompt pass that also fills the latent cache.

        x: [B, P, E]; cache: [B, S_max, R] (zeroed or being reused).
        Returns (out [B, P, E], new_cache) — ``out`` matches
        ``__call__(params, x)`` to float tolerance.
        """
        b, p, _ = x.shape
        latent = x @ params["kv_down"]  # [B, P, R]
        cache = jax.lax.dynamic_update_slice(
            cache, latent.astype(cache.dtype), (0, 0, 0))
        positions = jnp.broadcast_to(jnp.arange(p, dtype=jnp.int32), (b, p))
        return self._attend_cache(
            params, self._query(params, x), cache, positions), cache

    def decode(self, params, x_t, cache, positions):
        """One decode step. x_t: [B, 1, E] (the new token's hidden);
        positions: [B] absolute position per slot. Appends the token's
        latent at ``positions`` and attends the query against the cached
        latents [0, p]. Returns (out [B, 1, E], new_cache)."""
        latent_t = x_t @ params["kv_down"]  # [B, 1, R]

        def write(c, l, p):
            return jax.lax.dynamic_update_slice(c, l, (p, 0))

        cache = jax.vmap(write)(cache, latent_t.astype(cache.dtype),
                                positions.astype(jnp.int32))
        return self._attend_cache(
            params, self._query(params, x_t), cache,
            positions.astype(jnp.int32)[:, None]), cache
