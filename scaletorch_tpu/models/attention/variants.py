"""MHA / MQA / GQA / MLA attention variants.

Parity with reference scaletorch/models/attention/:
  * ``MultiHeadAttention`` (mha.py:9) — full per-head K/V
  * ``MultiQueryAttention`` (mqa.py:9) — single shared K/V head
  * ``GroupQueryAttention`` (gqa.py:9) — grouped K/V heads
  * ``MultiHeadLatentAttention`` (mla.py:9,60-66) — DeepSeek-style
    low-rank q/kv down-up projections through a latent bottleneck

All four are one parameterised implementation: MHA/MQA are GQA with
kv_heads = heads / 1 (the same collapse the reference's class hierarchy
expresses), MLA adds the latent projections in front.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from scaletorch_tpu.models.attention.base import AttentionConfig, AttentionVariant
from scaletorch_tpu.models.layers import (
    fan_in_uniform,
    repeat_kv,
    sdpa_attention,
)

Params = Dict[str, jax.Array]


def _gqa_init(key: jax.Array, cfg: AttentionConfig, kv_heads: int) -> Params:
    d, dh = cfg.embed_dim, cfg.actual_head_dim
    nh = cfg.num_heads
    ks = jax.random.split(key, 4)
    pd = cfg.dtype
    return {
        "q_proj": fan_in_uniform(ks[0], (d, nh * dh), d, pd),
        "k_proj": fan_in_uniform(ks[1], (d, kv_heads * dh), d, pd),
        "v_proj": fan_in_uniform(ks[2], (d, kv_heads * dh), d, pd),
        "o_proj": fan_in_uniform(ks[3], (nh * dh, d), nh * dh, pd),
    }


def _gqa_apply(
    params: Params, x: jax.Array, cfg: AttentionConfig, kv_heads: int,
    *, causal: bool = True,
) -> jax.Array:
    b, s, _ = x.shape
    nh, dh = cfg.num_heads, cfg.actual_head_dim
    q = (x @ params["q_proj"]).reshape(b, s, nh, dh).transpose(0, 2, 1, 3)
    k = (x @ params["k_proj"]).reshape(b, s, kv_heads, dh).transpose(0, 2, 1, 3)
    v = (x @ params["v_proj"]).reshape(b, s, kv_heads, dh).transpose(0, 2, 1, 3)
    k = repeat_kv(k, nh // kv_heads)
    v = repeat_kv(v, nh // kv_heads)
    o = sdpa_attention(q, k, v, causal=causal)
    return o.transpose(0, 2, 1, 3).reshape(b, s, nh * dh) @ params["o_proj"]


class MultiHeadAttention(AttentionVariant):
    """Per-head K/V (reference mha.py:9)."""

    def init(self, key):
        return _gqa_init(key, self.cfg, self.cfg.num_heads)

    def __call__(self, params, x, *, causal: bool = True):
        return _gqa_apply(params, x, self.cfg, self.cfg.num_heads, causal=causal)


class MultiQueryAttention(AttentionVariant):
    """One shared K/V head (reference mqa.py:9)."""

    def init(self, key):
        return _gqa_init(key, self.cfg, 1)

    def __call__(self, params, x, *, causal: bool = True):
        return _gqa_apply(params, x, self.cfg, 1, causal=causal)


class GroupQueryAttention(AttentionVariant):
    """Grouped K/V heads (reference gqa.py:9)."""

    def init(self, key):
        return _gqa_init(key, self.cfg, self.cfg.actual_num_kv_heads)

    def __call__(self, params, x, *, causal: bool = True):
        return _gqa_apply(
            params, x, self.cfg, self.cfg.actual_num_kv_heads, causal=causal
        )


class MultiHeadLatentAttention(AttentionVariant):
    """Low-rank latent q/kv projections (reference mla.py:9,60-66):
    x -> down-project to a small latent -> up-project to per-head q/k/v.
    The KV cache (in inference) would store only the latent."""

    def init(self, key):
        cfg = self.cfg
        d, dh, nh = cfg.embed_dim, cfg.actual_head_dim, cfg.num_heads
        qr = cfg.q_lora_rank or d
        kr = cfg.kv_lora_rank
        ks = jax.random.split(key, 6)
        pd = cfg.dtype
        params: Params = {
            "kv_down": fan_in_uniform(ks[0], (d, kr), d, pd),
            "k_up": fan_in_uniform(ks[1], (kr, nh * dh), kr, pd),
            "v_up": fan_in_uniform(ks[2], (kr, nh * dh), kr, pd),
            "o_proj": fan_in_uniform(ks[3], (nh * dh, d), nh * dh, pd),
        }
        if cfg.q_lora_rank:
            params["q_down"] = fan_in_uniform(ks[4], (d, qr), d, pd)
            params["q_up"] = fan_in_uniform(ks[5], (qr, nh * dh), qr, pd)
        else:
            params["q_proj"] = fan_in_uniform(ks[4], (d, nh * dh), d, pd)
        return params

    def __call__(self, params, x, *, causal: bool = True):
        cfg = self.cfg
        b, s, _ = x.shape
        nh, dh = cfg.num_heads, cfg.actual_head_dim
        if "q_down" in params:
            q = (x @ params["q_down"]) @ params["q_up"]
        else:
            q = x @ params["q_proj"]
        latent = x @ params["kv_down"]  # [B, S, kv_rank] — the cacheable state
        k = latent @ params["k_up"]
        v = latent @ params["v_up"]
        q = q.reshape(b, s, nh, dh).transpose(0, 2, 1, 3)
        k = k.reshape(b, s, nh, dh).transpose(0, 2, 1, 3)
        v = v.reshape(b, s, nh, dh).transpose(0, 2, 1, 3)
        o = sdpa_attention(q, k, v, causal=causal)
        return o.transpose(0, 2, 1, 3).reshape(b, s, nh * dh) @ params["o_proj"]
