"""GPT with capacity-based MoE — the self-contained educational model.

Parity with reference scaletorch/models/moe.py:40-903: ``GPTConfig`` with
the MoE knob surface (:40-133), noisy-top-k ``Router`` with z-loss + aux
loss and capacity-factor dispatch (:350-600), batched ``MLPExperts``
einsum experts (:269-347), einsum aggregation (:603-640), ``GPT`` with
learned positional embeddings, weight tying, ``generate`` and
``estimate_mfu`` (:659-871). Single-device by design in the reference
("Not EP-distributed — used by tests/benchmarks"); here the dispatch path
reuses parallel/expert_parallel, so passing ``ep_axis`` inside a
shard_map distributes it for free.

TPU-first notes: GELU MLP experts as batched einsums (MXU), ``generate``
is a ``lax.scan`` over positions on a fixed-size buffer (static shapes —
one compile, no per-token retrace), noise via explicit PRNG keys.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from scaletorch_tpu.models.layers import (
    cached_sdpa_attention,
    normal_init,
    sdpa_attention,
    write_kv_cache,
)
from scaletorch_tpu.parallel.expert_parallel import (
    combine_routed,
    dispatch_routed,
    expert_capacity,
    resolve_moe_dispatch,
    route_tokens,
)

Params = Dict[str, Any]


@dataclass(frozen=True)
class GPTMoEConfig:
    """Reference GPTConfig (moe.py:40-133) knob surface."""

    block_size: int = 256
    vocab_size: int = 65
    n_layer: int = 4
    n_head: int = 4
    n_embd: int = 128
    # MoE
    use_moe: bool = True
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    z_loss_weight: float = 0.001
    router_noise_std: float = 1.0  # noisy top-k (moe.py noisy routing)
    norm_topk_prob: bool = True
    # einsum | index token movement (see expert_parallel.route_tokens);
    # auto picks index at every E, like Qwen3MoEConfig
    # (AOT_DISPATCH_CROSSOVER.json: the one-hot cost never wins)
    moe_dispatch: str = "auto"
    dtype: Any = jnp.float32

    def resolved_moe_dispatch(self) -> str:
        # single source of truth for the auto crossover:
        # expert_parallel.resolve_moe_dispatch
        return resolve_moe_dispatch(self.moe_dispatch, self.num_experts)

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head


def init_params(key: jax.Array, cfg: GPTMoEConfig) -> Params:
    l, d, v = cfg.n_layer, cfg.n_embd, cfg.vocab_size
    e, i = cfg.num_experts, 4 * cfg.n_embd
    ks = jax.random.split(key, 12)
    pd = jnp.float32

    def stack(k, shape, std=0.02):
        return normal_init(k, (l,) + shape, std, pd)

    layers: Params = {
        "ln1": jnp.ones((l, d), pd),
        "attn_qkv": stack(ks[0], (d, 3 * d)),
        "attn_proj": stack(ks[1], (d, d), 0.02 / jnp.sqrt(2 * l)),
        "ln2": jnp.ones((l, d), pd),
    }
    if cfg.use_moe:
        layers["router"] = stack(ks[2], (d, e))
        layers["router_noise"] = stack(ks[3], (d, e))
        layers["expert_fc"] = normal_init(ks[4], (l, e, d, i), 0.02, pd)
        layers["expert_proj"] = normal_init(
            ks[5], (l, e, i, d), 0.02 / jnp.sqrt(2 * l), pd
        )
    else:
        layers["mlp_fc"] = stack(ks[6], (d, i))
        layers["mlp_proj"] = stack(ks[7], (i, d), 0.02 / jnp.sqrt(2 * l))
    return {
        "wte": normal_init(ks[8], (v, d), 0.02, pd),  # tied head (moe.py:659+)
        "wpe": normal_init(ks[9], (cfg.block_size, d), 0.02, pd),
        "layers": layers,
        "ln_f": jnp.ones((d,), pd),
    }


def _layer_norm(x: jax.Array, w: jax.Array) -> jax.Array:
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    return ((x32 - mu) / jnp.sqrt(var + 1e-5) * w).astype(x.dtype)


def _moe_ffn(
    h: jax.Array,
    layer: Params,
    cfg: GPTMoEConfig,
    noise_key: Optional[jax.Array],
    ep_axis: Optional[str],
) -> Tuple[jax.Array, jax.Array]:
    """Noisy-top-k routed GELU experts; returns (y, aux_loss_scalar)."""
    g, s, d = h.shape
    logits = jnp.einsum("gsh,he->gse", h, layer["router"])
    if noise_key is not None and cfg.router_noise_std > 0:
        # noisy top-k (reference Router noise head): learned per-token
        # noise scale, softplus'd, scaled standard-normal
        noise_scale = jax.nn.softplus(
            jnp.einsum("gsh,he->gse", h, layer["router_noise"])
        )
        noise = jax.random.normal(noise_key, logits.shape)
        logits = logits + cfg.router_noise_std * noise_scale * noise
    cap = expert_capacity(s, cfg.num_experts, cfg.top_k, cfg.capacity_factor)
    mode = cfg.resolved_moe_dispatch()
    state, aux = jax.vmap(
        lambda lg: route_tokens(
            lg, cfg.top_k, cap, mode=mode,
            normalize_weights=cfg.norm_topk_prob,
        )
    )(logits)
    slots = dispatch_routed(h, state, mode=mode,
                            num_experts=cfg.num_experts, capacity=cap,
                            axis=ep_axis)
    act = jax.nn.gelu(
        jnp.einsum("eth,ehi->eti", slots, layer["expert_fc"].astype(h.dtype))
    )
    out = jnp.einsum("eti,eih->eth", act,
                     layer["expert_proj"].astype(h.dtype))
    y = combine_routed(out, state, mode=mode,
                       num_experts=cfg.num_experts, capacity=cap,
                       axis=ep_axis)
    aux_loss = (
        cfg.aux_loss_weight * jnp.mean(aux["aux_loss"])
        + cfg.z_loss_weight * jnp.mean(aux["z_loss"])
    )
    return y, aux_loss


def forward(
    params: Params,
    input_ids: jax.Array,
    cfg: GPTMoEConfig,
    *,
    noise_key: Optional[jax.Array] = None,
    ep_axis: Optional[str] = None,
    return_aux: bool = False,
):
    """[B, S] -> logits [B, S, V] (and total aux loss with return_aux).

    ``noise_key`` enables noisy routing (training); omit for deterministic
    eval (the reference disables noise at eval, moe.py:350-600).
    """
    b, s = input_ids.shape
    cdt = cfg.dtype
    x = (params["wte"][input_ids] + params["wpe"][:s]).astype(cdt)

    def layer_body(carry, inp):
        h, key = carry
        layer = inp
        a = _layer_norm(h, layer["ln1"])
        qkv = a @ layer["attn_qkv"].astype(cdt)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(b, s, cfg.n_head, cfg.head_dim).transpose(0, 2, 1, 3)

        o = sdpa_attention(heads(q), heads(k), heads(v), causal=True)
        o = o.transpose(0, 2, 1, 3).reshape(b, s, cfg.n_embd)
        h = h + o @ layer["attn_proj"].astype(cdt)

        m = _layer_norm(h, layer["ln2"])
        if cfg.use_moe:
            if key is not None:
                key, sub = jax.random.split(key)
            else:
                sub = None
            y, aux = _moe_ffn(m, layer, cfg, sub, ep_axis)
        else:
            y = jax.nn.gelu(m @ layer["mlp_fc"].astype(cdt))
            y = y @ layer["mlp_proj"].astype(cdt)
            aux = jnp.float32(0.0)
        return (h + y.astype(cdt), key), aux

    (x, _), aux_per_layer = jax.lax.scan(
        layer_body, (x, noise_key), params["layers"]
    )
    x = _layer_norm(x, params["ln_f"])
    logits = x @ params["wte"].astype(cdt).T  # weight tying
    if return_aux:
        return logits, jnp.sum(aux_per_layer)
    return logits


def init_cache(
    cfg: GPTMoEConfig, batch: int, dtype: Any = None
) -> Tuple[jax.Array, jax.Array]:
    """Zeroed per-layer KV cache in the scan layout
    [L, B, n_head, block_size, head_dim] (GPT attends with full per-head
    K/V — no GQA grouping)."""
    shape = (cfg.n_layer, batch, cfg.n_head, cfg.block_size, cfg.head_dim)
    dt = dtype or cfg.dtype
    return jnp.zeros(shape, dt), jnp.zeros(shape, dt)


def forward_cached(
    params: Params,
    input_ids: jax.Array,
    cfg: GPTMoEConfig,
    cache: Tuple[jax.Array, jax.Array],
    *,
    positions: jax.Array,
    write_mask: Optional[jax.Array] = None,
    kv_io: Optional[Any] = None,
):
    """KV-cached forward: [B, S] tokens at absolute ``positions`` [B, S]
    -> (logits [B, S, V], new cache). Positional signal is the learned
    ``wpe`` table looked up at the absolute positions (no RoPE). Routing
    is deterministic (no noise) — matching ``generate``'s eval-mode
    forward. ``kv_io`` swaps the cache layout (paged pool) exactly as in
    ``llama.attention_block_cached``.
    """
    cache_k, cache_v = cache
    b, s = input_ids.shape
    cdt = cfg.dtype
    x = (params["wte"][input_ids] + params["wpe"][positions]).astype(cdt)

    def layer_body(h, xs):
        layer, ck, cv = xs
        a = _layer_norm(h, layer["ln1"])
        qkv = a @ layer["attn_qkv"].astype(cdt)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(b, s, cfg.n_head, cfg.head_dim).transpose(0, 2, 1, 3)

        if kv_io is None:
            ck = write_kv_cache(ck, heads(k), positions[:, 0], write_mask)
            cv = write_kv_cache(cv, heads(v), positions[:, 0], write_mask)
            o = cached_sdpa_attention(heads(q), ck, cv, positions)
        else:
            ck = kv_io.write(ck, heads(k), positions, write_mask)
            cv = kv_io.write(cv, heads(v), positions, write_mask)
            o = kv_io.attend(heads(q), ck, cv, positions)
        o = o.transpose(0, 2, 1, 3).reshape(b, s, cfg.n_embd)
        h = h + o @ layer["attn_proj"].astype(cdt)

        m = _layer_norm(h, layer["ln2"])
        if cfg.use_moe:
            y, _ = _moe_ffn(m, layer, cfg, None, None)
        else:
            y = jax.nn.gelu(m @ layer["mlp_fc"].astype(cdt))
            y = y @ layer["mlp_proj"].astype(cdt)
        return h + y.astype(cdt), (ck, cv)

    x, (k_new, v_new) = jax.lax.scan(
        layer_body, x, (params["layers"], cache_k, cache_v)
    )
    x = _layer_norm(x, params["ln_f"])
    return x @ params["wte"].astype(cdt).T, (k_new, v_new)


def generate(
    params: Params,
    prompt: jax.Array,
    cfg: GPTMoEConfig,
    *,
    max_new_tokens: int = 32,
    temperature: float = 1.0,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """Autoregressive sampling (reference GPT.generate, moe.py:659-871),
    KV-cached: one full prefill over the prompt, then a ``lax.scan`` of
    single-token decode steps against the cache — O(S·S_max) attention
    per emitted token instead of the old recompute path's O(S_max²·L)
    full forward per token (retained as ``generate_recompute`` for the
    tools/bench_decode.py A/B). Static shapes throughout — prefill + one
    decode-scan compile. prompt: [B, P]. Greedy when temperature == 0.

    Sampled continuations draw per-step keys from ``key`` exactly like
    before, but the stream is indexed from the prompt boundary — numeric
    parity with the recompute path holds for greedy decoding (same math,
    float-tolerance logits), not for the sampled RNG stream.
    """
    b, p = prompt.shape
    total = min(cfg.block_size, p + max_new_tokens)
    key = key if key is not None else jax.random.PRNGKey(0)
    cache = init_cache(cfg, b)

    buf = jnp.zeros((b, cfg.block_size), jnp.int32)
    buf = jax.lax.dynamic_update_slice(buf, prompt.astype(jnp.int32), (0, 0))

    def pick(logits_t, sub):
        if temperature == 0:
            return jnp.argmax(logits_t, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            sub, logits_t / temperature, axis=-1
        ).astype(jnp.int32)

    # Prefill: one causal pass over the prompt writes cache [0, p) and
    # yields the logits that sample token p.
    positions = jnp.broadcast_to(jnp.arange(p, dtype=jnp.int32), (b, p))
    logits, cache = forward_cached(params, prompt.astype(jnp.int32), cfg,
                                   cache, positions=positions)
    key, sub = jax.random.split(key)
    tok = pick(logits[:, -1, :], sub)
    if p < total:
        buf = jax.lax.dynamic_update_slice_in_dim(buf, tok[:, None], p, axis=1)

    def step(carry, t):
        buf, cache, key, tok = carry
        # feed the token at position t; its logits sample position t+1
        logits_t, cache = forward_cached(
            params, tok[:, None], cfg, cache,
            positions=jnp.broadcast_to(t, (b, 1)).astype(jnp.int32),
        )
        key, sub = jax.random.split(key)
        nxt = pick(logits_t[:, 0, :], sub)
        write = t + 1 < total
        col = jnp.where(write, nxt, buf[:, t + 1])
        buf = jax.lax.dynamic_update_slice_in_dim(buf, col[:, None], t + 1,
                                                  axis=1)
        return (buf, cache, key, jnp.where(write, nxt, tok)), None

    # total is a static Python int, so the scan length is exactly the
    # requested generation — no decode steps are spent on positions the
    # caller never asked for.
    if p < total - 1:
        (buf, _, _, _), _ = jax.lax.scan(
            step, (buf, cache, key, tok),
            jnp.arange(p, total - 1, dtype=jnp.int32),
        )
    return buf[:, :total]


def generate_recompute(
    params: Params,
    prompt: jax.Array,
    cfg: GPTMoEConfig,
    *,
    max_new_tokens: int = 32,
    temperature: float = 1.0,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """The original cache-less sampler: reruns the full O(S²·L) forward
    over the whole block buffer for every emitted token. Kept ONLY as the
    baseline arm of ``tools/bench_decode.py`` — use ``generate``.
    """
    b, p = prompt.shape
    total = min(cfg.block_size, p + max_new_tokens)
    buf = jnp.zeros((b, cfg.block_size), jnp.int32)
    buf = jax.lax.dynamic_update_slice(buf, prompt.astype(jnp.int32), (0, 0))
    key = key if key is not None else jax.random.PRNGKey(0)

    def step(carry, t):
        buf, key = carry
        logits = forward(params, buf, cfg)  # [B, block, V]
        next_logits = jnp.take_along_axis(
            logits, (t - 1)[None, None, None].repeat(b, 0), axis=1
        )[:, 0, :]
        key, sub = jax.random.split(key)
        if temperature == 0:
            nxt = jnp.argmax(next_logits, axis=-1)
        else:
            nxt = jax.random.categorical(sub, next_logits / temperature, axis=-1)
        # only write positions >= p (keep the prompt intact)
        write = (t >= p) & (t < total)
        col = jnp.where(write, nxt.astype(jnp.int32), buf[:, t])
        buf = jax.lax.dynamic_update_slice_in_dim(
            buf, col[:, None], t, axis=1
        )
        return (buf, key), None

    (buf, _), _ = jax.lax.scan(
        step, (buf, key), jnp.arange(1, cfg.block_size)
    )
    return buf[:, :total]


def estimate_mfu(
    cfg: GPTMoEConfig, params: Params, tokens_per_second: float,
    peak_flops: float,
) -> float:
    """Model FLOPs utilisation (reference GPT.estimate_mfu, moe.py:826-871):
    active params only for MoE (top_k of num_experts)."""
    n = sum(x.size for x in jax.tree.leaves(params))
    if cfg.use_moe:
        expert_params = (
            params["layers"]["expert_fc"].size
            + params["layers"]["expert_proj"].size
        )
        n = n - expert_params + expert_params * cfg.top_k // cfg.num_experts
    l, h, q, t = cfg.n_layer, cfg.n_head, cfg.head_dim, cfg.block_size
    flops_per_token = 6 * n + 12 * l * h * q * t
    return flops_per_token * tokens_per_second / peak_flops


class GPTMoE:
    config_cls = GPTMoEConfig

    def __init__(self, config: GPTMoEConfig):
        self.config = config

    def init(self, key: jax.Array) -> Params:
        return init_params(key, self.config)

    def __call__(self, params: Params, input_ids: jax.Array, **kw):
        return forward(params, input_ids, self.config, **kw)
