"""Shared model building blocks: RMSNorm, RoPE, SDPA attention, initializers.

Functional counterparts of reference scaletorch/models/attention_utils.py:
RMSNorm computed internally in fp32 (:247-271), RoPE ``get_cos_sin`` /
``apply_rotary_pos_emb`` (:170-239), fan-in uniform ``_init_weights``
(:160-167). All functions are pure and jit/scan-friendly (static shapes,
no Python control flow on traced values).
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


# ---- initialisation ---------------------------------------------------------
def fan_in_uniform(key: jax.Array, shape: Tuple[int, ...], fan_in: int,
                   dtype=jnp.float32) -> jax.Array:
    """U(-1/sqrt(fan_in), 1/sqrt(fan_in)) — the reference's Linear init
    (attention_utils.py:160-167)."""
    bound = 1.0 / math.sqrt(fan_in)
    return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)


def normal_init(key: jax.Array, shape: Tuple[int, ...], std: float = 0.02,
                dtype=jnp.float32) -> jax.Array:
    return std * jax.random.normal(key, shape, dtype)


# ---- RMSNorm ----------------------------------------------------------------
def _rms_norm_fwd_math(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    variance = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(variance + eps)
    return (x32 * inv * weight.astype(jnp.float32)).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _rms_norm_p(eps: float, x: jax.Array, weight: jax.Array) -> jax.Array:
    return _rms_norm_fwd_math(x, weight, eps)


def _rms_norm_fwd(eps, x, weight):
    return _rms_norm_fwd_math(x, weight, eps), (x, weight)


def _rms_norm_bwd(eps, res, g):
    x, weight = res
    x32 = x.astype(jnp.float32)
    variance = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(variance + eps)
    xhat = x32 * inv
    g32 = g.astype(jnp.float32)
    w32 = weight.astype(jnp.float32)
    gw = g32 * w32
    # d/dx of xhat·w: (1/rms)·(g·w − xhat·mean(g·w·xhat)) over the norm axis.
    dx = inv * (gw - xhat * jnp.mean(gw * xhat, axis=-1, keepdims=True))
    # weight broadcasts over all leading axes of x (per-head q/k norms use
    # a [Dh] weight against [B, S, H, Dh] activations).
    reduce_axes = tuple(range(x.ndim - weight.ndim))
    dw = jnp.sum(g32 * xhat, axis=reduce_axes)
    return dx.astype(x.dtype), dw.astype(weight.dtype)


_rms_norm_p.defvjp(_rms_norm_fwd, _rms_norm_bwd)


# ---- SwiGLU -----------------------------------------------------------------
@jax.custom_vjp
def swiglu(gate: jax.Array, up: jax.Array) -> jax.Array:
    """``silu(gate) * up`` with a memory-lean VJP.

    Plain autodiff stashes silu(gate) and the product alongside gate/up —
    four FFN-wide buffers per layer where two suffice (measured 6x672 MB
    of SwiGLU residuals at 0.6B/seq2048/bs2 no-remat, tools/aot_memory.py).
    This VJP saves only (gate, up) and recomputes the cheap elementwise
    pieces in backward, exactly like fused SwiGLU kernels do.
    """
    return jax.nn.silu(gate) * up


def _swiglu_fwd(gate, up):
    return jax.nn.silu(gate) * up, (gate, up)


def _swiglu_bwd(res, ct):
    gate, up = res
    g32 = gate.astype(jnp.float32)
    s = jax.nn.sigmoid(g32)
    silu = g32 * s
    dsilu = s + silu * (1.0 - s)  # d/dg [g·sigmoid(g)]
    ct32 = ct.astype(jnp.float32)
    dgate = (ct32 * up.astype(jnp.float32) * dsilu).astype(gate.dtype)
    dup = (ct32 * silu).astype(up.dtype)
    return dgate, dup


swiglu.defvjp(_swiglu_fwd, _swiglu_bwd)


def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm with fp32 internal math (parity: attention_utils.py:247-271).

    Memory-lean custom VJP: plain autodiff would stash the fp32 upcast
    and the normalised fp32 product as residuals — for a no-remat
    (gradient_checkpointing=False) train step those fp32 copies of every
    norm input dominate HBM (measured 4.4 GB of the 13.4 GB activation
    arena at 0.6B/seq2048/bs2, tools/aot_memory.py). The VJP saves only
    the ORIGINAL-dtype ``x`` and ``weight`` and recomputes the fp32
    internals in the backward — the same trade every fused RMSNorm kernel
    (e.g. the reference's NPU fused norm) makes.

    Under shard_map, ``x`` (activation) and ``weight`` (replicated param,
    pvaried over every mesh axis) may carry different varying-axis sets; a
    custom VJP must return cotangents typed exactly like its primal
    inputs, so both are aligned to their vma union here, OUTSIDE the VJP
    — the pvary's psum transpose is then autodiff's job, not ours.

    On jax builds without the VMA machinery (``jax.typeof``/``pvary``
    absent), the alignment is a no-op — single-device and GSPMD-jit
    semantics are unchanged.
    """
    try:
        vma_x = frozenset(getattr(jax.typeof(x), "vma", frozenset()))
        vma_w = frozenset(getattr(jax.typeof(weight), "vma", frozenset()))
    except AttributeError:  # jax without typeof/vma (pre-0.6)
        return _rms_norm_p(float(eps), x, weight)
    if vma_x != vma_w:
        x = jax.lax.pvary(x, tuple(vma_w - vma_x))
        weight = jax.lax.pvary(weight, tuple(vma_x - vma_w))
    return _rms_norm_p(float(eps), x, weight)


# ---- RoPE -------------------------------------------------------------------
def get_cos_sin(
    seq_len: int,
    head_dim: int,
    rope_theta: float = 10000.0,
    dtype=jnp.float32,
    positions: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Precompute rotary cos/sin tables ``[seq, head_dim]``.

    Matches the HF/reference convention (attention_utils.py:170-210): inverse
    frequencies over even dims, angles duplicated across the two halves.
    ``positions`` overrides 0..seq_len-1 (used by CP to slice this rank's
    sequence shard, reference context_parallel.py:427-473). A 2-D
    ``positions`` [B, S] yields per-batch tables ``[B, S, head_dim]`` —
    the decode path's per-slot absolute positions (inference/decode.py).
    """
    inv_freq = 1.0 / (
        rope_theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    if positions is None:
        positions = jnp.arange(seq_len, dtype=jnp.float32)
    else:
        positions = positions.astype(jnp.float32)
    freqs = positions[..., None] * inv_freq  # [..., S, Dh/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)  # [..., S, Dh]
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def rotate_half(x: jax.Array) -> jax.Array:
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rotary_pos_emb(
    q: jax.Array, k: jax.Array, cos: jax.Array, sin: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """Apply RoPE. q/k: [B, H, S, Dh]; cos/sin: [S, Dh] (broadcast over
    B, H) or per-batch [B, S, Dh] (decode's per-slot positions; broadcast
    over H only)."""
    if cos.ndim == 3:
        cos = cos[:, None, :, :].astype(q.dtype)
        sin = sin[:, None, :, :].astype(q.dtype)
    else:
        cos = cos[None, None, :, :].astype(q.dtype)
        sin = sin[None, None, :, :].astype(q.dtype)
    q_rot = q * cos + rotate_half(q) * sin
    k_rot = k * cos + rotate_half(k) * sin
    return q_rot, k_rot


# ---- attention --------------------------------------------------------------
def repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """GQA KV head expansion [B, Hkv, S, D] -> [B, Hkv*n_rep, S, D].

    The reference uses a zero-copy ``expand`` (llama.py:176-192); under XLA
    the broadcast is fused away, so an explicit broadcast is equally free.
    """
    if n_rep == 1:
        return k
    b, h_kv, s, d = k.shape
    k = jnp.broadcast_to(k[:, :, None, :, :], (b, h_kv, n_rep, s, d))
    return k.reshape(b, h_kv * n_rep, s, d)


def sdpa_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    bias: Optional[jax.Array] = None,
) -> jax.Array:
    """Plain XLA scaled-dot-product attention with fp32 softmax.

    q: [B, Hq, S, D]; k/v: [B, Hkv, Skv, D] (GQA expanded here).
    The default/portable backend (reference 'sdpa', attention_utils.py:130-152).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    n_rep = q.shape[1] // k.shape[1]
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
        scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def sdpa_attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
) -> Tuple[jax.Array, jax.Array]:
    """SDPA that also returns the log-sum-exp ``[B, H, S]`` (fp32).

    Building block for ring attention's blockwise LSE merge (reference
    ring_attention_forward, context_parallel.py:266-330).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    n_rep = q.shape[1] // k.shape[1]
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
        scores = jnp.where(mask, scores, -jnp.inf)
    lse = jax.nn.logsumexp(scores, axis=-1)  # [B, H, S]
    # Rows with no visible keys (fully masked) have lse = -inf; their output
    # is defined as 0 so the ring merge can rescale them safely.
    probs = jnp.exp(scores - jnp.where(jnp.isfinite(lse), lse, 0.0)[..., None])
    probs = jnp.where(jnp.isfinite(lse)[..., None], probs, 0.0).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    return out, lse


def cached_sdpa_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    q_positions: jax.Array,
    *,
    scale: Optional[float] = None,
) -> jax.Array:
    """SDPA against a fixed-size KV cache with absolute-position masking.

    q: [B, Hq, S, D] (S = prompt length at prefill, 1 at decode);
    k_cache/v_cache: [B, Hkv, S_max, D]; q_positions: [B, S] absolute
    token positions. Query at position p attends cache entries j <= p —
    causal over the cache, independent of how much of it is stale, which
    is exactly right under the engine invariant that positions [0, p] of
    a live slot have always been written (prefill fills [0, len), decode
    overwrites position p before reading it).

    Same fp32-softmax math as ``sdpa_attention``, so prefill logits match
    the full-sequence training forward to float tolerance.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    n_rep = q.shape[1] // k_cache.shape[1]
    k = repeat_kv(k_cache, n_rep)
    v = repeat_kv(v_cache, n_rep)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    key_idx = jnp.arange(k_cache.shape[2], dtype=jnp.int32)
    mask = key_idx[None, None, :] <= q_positions[:, :, None]  # [B, S, S_max]
    scores = jnp.where(mask[:, None], scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def write_kv_cache(
    cache: jax.Array,
    new: jax.Array,
    starts: jax.Array,
    write_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Append ``new`` [B, H, S, D] into ``cache`` [B, H, S_max, D] at
    per-slot sequence offsets ``starts`` [B] (``lax.dynamic_update_slice``
    vmapped over the slot axis — XLA lowers the batched variant to an
    in-place scatter under buffer donation). ``write_mask`` [B] bool
    keeps unlisted slots' cache bytes untouched (continuous batching
    admits new requests without perturbing live ones)."""

    def one(c, n, st):
        return jax.lax.dynamic_update_slice(c, n, (0, st, 0))

    updated = jax.vmap(one)(cache, new.astype(cache.dtype),
                            starts.astype(jnp.int32))
    if write_mask is not None:
        updated = jnp.where(write_mask[:, None, None, None], updated, cache)
    return updated


# ---- losses -----------------------------------------------------------------
def cross_entropy_loss(
    logits: jax.Array,
    targets: jax.Array,
    ignore_index: int = -100,
) -> jax.Array:
    """Token-mean cross entropy with ignore_index masking (fp32 internally).

    logits: [..., V]; targets: [...] int32. Matches the reference's
    F.cross_entropy(ignore_index=-100) semantics (train_step.py:98-103).
    """
    logits = logits.astype(jnp.float32)
    mask = targets != ignore_index
    safe_targets = jnp.where(mask, targets, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, safe_targets[..., None], axis=-1)[..., 0]
    nll = (logz - gold) * mask
    denom = jnp.maximum(mask.sum(), 1)
    return nll.sum() / denom
