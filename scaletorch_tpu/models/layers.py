"""Shared model building blocks: RMSNorm, RoPE, SDPA attention, initializers.

Functional counterparts of reference scaletorch/models/attention_utils.py:
RMSNorm computed internally in fp32 (:247-271), RoPE ``get_cos_sin`` /
``apply_rotary_pos_emb`` (:170-239), fan-in uniform ``_init_weights``
(:160-167). All functions are pure and jit/scan-friendly (static shapes,
no Python control flow on traced values).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


# ---- initialisation ---------------------------------------------------------
def fan_in_uniform(key: jax.Array, shape: Tuple[int, ...], fan_in: int,
                   dtype=jnp.float32) -> jax.Array:
    """U(-1/sqrt(fan_in), 1/sqrt(fan_in)) — the reference's Linear init
    (attention_utils.py:160-167)."""
    bound = 1.0 / math.sqrt(fan_in)
    return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)


def normal_init(key: jax.Array, shape: Tuple[int, ...], std: float = 0.02,
                dtype=jnp.float32) -> jax.Array:
    return std * jax.random.normal(key, shape, dtype)


# ---- RMSNorm ----------------------------------------------------------------
def rms_norm(x: jax.Array, weight: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm with fp32 internal math (parity: attention_utils.py:247-271)."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    variance = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    x32 = x32 * jax.lax.rsqrt(variance + eps)
    return (x32 * weight.astype(jnp.float32)).astype(dtype)


# ---- RoPE -------------------------------------------------------------------
def get_cos_sin(
    seq_len: int,
    head_dim: int,
    rope_theta: float = 10000.0,
    dtype=jnp.float32,
    positions: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Precompute rotary cos/sin tables ``[seq, head_dim]``.

    Matches the HF/reference convention (attention_utils.py:170-210): inverse
    frequencies over even dims, angles duplicated across the two halves.
    ``positions`` overrides 0..seq_len-1 (used by CP to slice this rank's
    sequence shard, reference context_parallel.py:427-473).
    """
    inv_freq = 1.0 / (
        rope_theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    if positions is None:
        positions = jnp.arange(seq_len, dtype=jnp.float32)
    else:
        positions = positions.astype(jnp.float32)
    freqs = jnp.outer(positions, inv_freq)  # [S, Dh/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)  # [S, Dh]
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def rotate_half(x: jax.Array) -> jax.Array:
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rotary_pos_emb(
    q: jax.Array, k: jax.Array, cos: jax.Array, sin: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """Apply RoPE. q/k: [B, H, S, Dh]; cos/sin: [S, Dh] (broadcast over B, H)."""
    cos = cos[None, None, :, :].astype(q.dtype)
    sin = sin[None, None, :, :].astype(q.dtype)
    q_rot = q * cos + rotate_half(q) * sin
    k_rot = k * cos + rotate_half(k) * sin
    return q_rot, k_rot


# ---- attention --------------------------------------------------------------
def repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """GQA KV head expansion [B, Hkv, S, D] -> [B, Hkv*n_rep, S, D].

    The reference uses a zero-copy ``expand`` (llama.py:176-192); under XLA
    the broadcast is fused away, so an explicit broadcast is equally free.
    """
    if n_rep == 1:
        return k
    b, h_kv, s, d = k.shape
    k = jnp.broadcast_to(k[:, :, None, :, :], (b, h_kv, n_rep, s, d))
    return k.reshape(b, h_kv * n_rep, s, d)


def sdpa_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    bias: Optional[jax.Array] = None,
) -> jax.Array:
    """Plain XLA scaled-dot-product attention with fp32 softmax.

    q: [B, Hq, S, D]; k/v: [B, Hkv, Skv, D] (GQA expanded here).
    The default/portable backend (reference 'sdpa', attention_utils.py:130-152).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    n_rep = q.shape[1] // k.shape[1]
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
        scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def sdpa_attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
) -> Tuple[jax.Array, jax.Array]:
    """SDPA that also returns the log-sum-exp ``[B, H, S]`` (fp32).

    Building block for ring attention's blockwise LSE merge (reference
    ring_attention_forward, context_parallel.py:266-330).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    n_rep = q.shape[1] // k.shape[1]
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
        scores = jnp.where(mask, scores, -jnp.inf)
    lse = jax.nn.logsumexp(scores, axis=-1)  # [B, H, S]
    # Rows with no visible keys (fully masked) have lse = -inf; their output
    # is defined as 0 so the ring merge can rescale them safely.
    probs = jnp.exp(scores - jnp.where(jnp.isfinite(lse), lse, 0.0)[..., None])
    probs = jnp.where(jnp.isfinite(lse)[..., None], probs, 0.0).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    return out, lse


# ---- losses -----------------------------------------------------------------
def cross_entropy_loss(
    logits: jax.Array,
    targets: jax.Array,
    ignore_index: int = -100,
) -> jax.Array:
    """Token-mean cross entropy with ignore_index masking (fp32 internally).

    logits: [..., V]; targets: [...] int32. Matches the reference's
    F.cross_entropy(ignore_index=-100) semantics (train_step.py:98-103).
    """
    logits = logits.astype(jnp.float32)
    mask = targets != ignore_index
    safe_targets = jnp.where(mask, targets, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, safe_targets[..., None], axis=-1)[..., 0]
    nll = (logz - gold) * mask
    denom = jnp.maximum(mask.sum(), 1)
    return nll.sum() / denom
