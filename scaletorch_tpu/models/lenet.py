"""LeNet-5 MNIST CNN — the minimal-example model.

Parity with reference scaletorch/models/lenet.py:10-38 (two conv+pool
blocks, three FC layers), functional JAX: convs via
``lax.conv_general_dilated`` in NHWC (TPU-native layout; torch uses NCHW).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

import jax
import jax.numpy as jnp

Params = Dict[str, jax.Array]


@dataclass(frozen=True)
class LeNetConfig:
    num_classes: int = 10
    in_channels: int = 1


def init_params(key: jax.Array, cfg: LeNetConfig = LeNetConfig()) -> Params:
    ks = jax.random.split(key, 5)

    def conv_init(k, shape):  # HWIO
        fan_in = shape[0] * shape[1] * shape[2]
        bound = 1.0 / jnp.sqrt(fan_in)
        return jax.random.uniform(k, shape, minval=-bound, maxval=bound)

    def fc_init(k, shape):
        bound = 1.0 / jnp.sqrt(shape[0])
        return jax.random.uniform(k, shape, minval=-bound, maxval=bound)

    return {
        "conv1": conv_init(ks[0], (5, 5, cfg.in_channels, 6)),
        "conv2": conv_init(ks[1], (5, 5, 6, 16)),
        "fc1": fc_init(ks[2], (16 * 4 * 4, 120)),
        "fc2": fc_init(ks[3], (120, 84)),
        "fc3": fc_init(ks[4], (84, cfg.num_classes)),
    }


def _conv(x: jax.Array, w: jax.Array) -> jax.Array:
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _max_pool(x: jax.Array) -> jax.Array:
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
    )


def forward(params: Params, x: jax.Array) -> jax.Array:
    """x: [B, 28, 28, C] -> logits [B, num_classes]."""
    x = _max_pool(jax.nn.relu(_conv(x, params["conv1"])))  # [B,12,12,6]
    x = _max_pool(jax.nn.relu(_conv(x, params["conv2"])))  # [B,4,4,16]
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["fc1"])
    x = jax.nn.relu(x @ params["fc2"])
    return x @ params["fc3"]


class LeNet:
    config_cls = LeNetConfig

    def __init__(self, config: LeNetConfig = LeNetConfig()):
        self.config = config

    def init(self, key: jax.Array) -> Params:
        return init_params(key, self.config)

    def __call__(self, params: Params, x: jax.Array) -> jax.Array:
        return forward(params, x)
