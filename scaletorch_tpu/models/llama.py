"""Llama-family decoder LM — functional JAX implementation.

Capability parity with reference scaletorch/models/llama.py:65-556
(LlamaAttention with GQA, SwiGLU MLP, RMSNorm decoder layers, shared RoPE
tables computed once and CP-slicable, gradient checkpointing), re-designed
TPU-first:

  * parameters are a pytree with **layers stacked along axis 0** and the
    decoder loop is a ``lax.scan`` — compile time is O(1) in depth and XLA
    sees one fused layer body instead of L copies;
  * gradient checkpointing is ``jax.checkpoint`` around the scan body
    (reference uses torch.utils.checkpoint per layer, llama.py:534-545);
  * attention dispatches through the backend registry (sdpa / flash /
    ring), resolved statically before jit;
  * mixed precision: parameters live in fp32 (optimizer master copy),
    compute runs in ``cfg.dtype`` (bf16 on TPU) — norm/softmax internals
    stay fp32.

The same ``forward`` also serves Qwen3 (per-head q/k RMSNorm before RoPE,
tied embeddings, explicit head_dim — reference model_qwen3.py:139-350) via
config flags, so there is a single decoder implementation to optimise.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from scaletorch_tpu.models.layers import (
    apply_rotary_pos_emb,
    fan_in_uniform,
    get_cos_sin,
    rms_norm,
    sdpa_attention,
)
from scaletorch_tpu.models.registry import (
    get_attention_backend,
    register_attention_backend,
)

Params = Dict[str, Any]


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 2048
    intermediate_size: int = 5632
    num_hidden_layers: int = 22
    num_attention_heads: int = 16
    num_key_value_heads: int = 4
    head_dim: Optional[int] = None  # defaults to hidden // heads
    max_position_embeddings: int = 32768
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-6
    tie_word_embeddings: bool = False
    qk_norm: bool = False  # Qwen3-style per-head q/k RMSNorm before RoPE
    dtype: Any = jnp.bfloat16  # compute dtype
    param_dtype: Any = jnp.float32

    @property
    def actual_head_dim(self) -> int:
        return self.head_dim or self.hidden_size // self.num_attention_heads

    @property
    def q_size(self) -> int:
        return self.num_attention_heads * self.actual_head_dim

    @property
    def kv_size(self) -> int:
        return self.num_key_value_heads * self.actual_head_dim

    @classmethod
    def from_hf(cls, hf_config, **overrides) -> "LlamaConfig":
        """Build from a transformers AutoConfig (reference
        ModelArguments auto-fill, config.py:102-119)."""
        kw = dict(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_hidden_layers=hf_config.num_hidden_layers,
            num_attention_heads=hf_config.num_attention_heads,
            num_key_value_heads=getattr(
                hf_config, "num_key_value_heads", hf_config.num_attention_heads
            ),
            head_dim=getattr(hf_config, "head_dim", None),
            max_position_embeddings=hf_config.max_position_embeddings,
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            rms_norm_eps=getattr(hf_config, "rms_norm_eps", 1e-6),
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings", False),
        )
        kw.update(overrides)
        return cls(**kw)

    def num_params(self) -> int:
        """Analytic parameter count (for MFU; matches get_num_params on an
        actual init)."""
        h, i, l, v = (
            self.hidden_size,
            self.intermediate_size,
            self.num_hidden_layers,
            self.vocab_size,
        )
        attn = h * self.q_size + 2 * h * self.kv_size + self.q_size * h
        mlp = 3 * h * i
        norms = 2 * h + (2 * self.actual_head_dim if self.qk_norm else 0)
        per_layer = attn + mlp + norms
        embed = v * h
        head = 0 if self.tie_word_embeddings else v * h
        return l * per_layer + embed + h + head


def init_params(key: jax.Array, cfg: LlamaConfig) -> Params:
    """Random init: fan-in uniform for projections (reference
    attention_utils.py:160-167), ones for norms, normal(0.02) embeddings."""
    l = cfg.num_hidden_layers
    h, i, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    dh = cfg.actual_head_dim
    keys = jax.random.split(key, 9)
    pd = cfg.param_dtype

    def stack_init(k, shape, fan_in):
        # one independent fan-in-uniform slab per layer, stacked on axis 0
        ks = jax.random.split(k, l)
        return jnp.stack([fan_in_uniform(kk, shape, fan_in, pd) for kk in ks])

    layers: Params = {
        "input_layernorm": jnp.ones((l, h), pd),
        "q_proj": stack_init(keys[0], (h, cfg.q_size), h),
        "k_proj": stack_init(keys[1], (h, cfg.kv_size), h),
        "v_proj": stack_init(keys[2], (h, cfg.kv_size), h),
        "o_proj": stack_init(keys[3], (cfg.q_size, h), cfg.q_size),
        "post_attention_layernorm": jnp.ones((l, h), pd),
        "gate_proj": stack_init(keys[4], (h, i), h),
        "up_proj": stack_init(keys[5], (h, i), h),
        "down_proj": stack_init(keys[6], (i, h), i),
    }
    if cfg.qk_norm:
        layers["q_norm"] = jnp.ones((l, dh), pd)
        layers["k_norm"] = jnp.ones((l, dh), pd)

    params: Params = {
        "embed_tokens": 0.02 * jax.random.normal(keys[7], (v, h), pd),
        "layers": layers,
        "norm": jnp.ones((h,), pd),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = fan_in_uniform(keys[8], (h, v), h, pd)
    return params


def _decoder_layer(
    x: jax.Array,
    layer: Params,
    cos: jax.Array,
    sin: jax.Array,
    cfg: LlamaConfig,
    attn_fn: Callable,
) -> jax.Array:
    """One pre-norm decoder block. x: [B, S, H] in compute dtype."""
    b, s, _ = x.shape
    nh, nkv, dh = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.actual_head_dim
    cdt = cfg.dtype

    # ---- attention ----------------------------------------------------------
    h = rms_norm(x, layer["input_layernorm"], cfg.rms_norm_eps)
    q = (h @ layer["q_proj"].astype(cdt)).reshape(b, s, nh, dh)
    k = (h @ layer["k_proj"].astype(cdt)).reshape(b, s, nkv, dh)
    v = (h @ layer["v_proj"].astype(cdt)).reshape(b, s, nkv, dh)
    if cfg.qk_norm:
        # Qwen3: RMSNorm over head_dim, per head, before RoPE
        # (reference model_qwen3.py:179-180,209-210).
        q = rms_norm(q, layer["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, layer["k_norm"], cfg.rms_norm_eps)
    q = q.transpose(0, 2, 1, 3)  # [B, H, S, D]
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    q, k = apply_rotary_pos_emb(q, k, cos, sin)
    attn = attn_fn(q, k, v, causal=True)
    attn = attn.transpose(0, 2, 1, 3).reshape(b, s, nh * dh)
    x = x + attn @ layer["o_proj"].astype(cdt)

    # ---- SwiGLU MLP (reference llama.py:207-249) ----------------------------
    h = rms_norm(x, layer["post_attention_layernorm"], cfg.rms_norm_eps)
    gate = jax.nn.silu(h @ layer["gate_proj"].astype(cdt))
    up = h @ layer["up_proj"].astype(cdt)
    x = x + (gate * up) @ layer["down_proj"].astype(cdt)
    return x


def forward(
    params: Params,
    input_ids: jax.Array,
    cfg: LlamaConfig,
    *,
    positions: Optional[jax.Array] = None,
    attention_backend: str = "sdpa",
    gradient_checkpointing: bool = False,
) -> jax.Array:
    """Full decoder forward: [B, S] int tokens -> [B, S, V] logits.

    ``positions`` (shape [S]) overrides absolute positions for the RoPE
    table — CP passes this rank's sequence-shard positions (reference
    update_rope_for_context_parallel, context_parallel.py:427-473).
    """
    cdt = cfg.dtype
    x = params["embed_tokens"][input_ids].astype(cdt)  # [B, S, H]
    s = x.shape[1]

    # RoPE tables computed once and shared across layers (reference
    # llama.py:476-491), fp32 then cast at application.
    cos, sin = get_cos_sin(s, cfg.actual_head_dim, cfg.rope_theta,
                           positions=positions)

    attn_fn = get_attention_backend(attention_backend)

    def layer_body(h, layer_params):
        h = _decoder_layer(h, layer_params, cos, sin, cfg, attn_fn)
        return h, None

    if gradient_checkpointing:
        layer_body = jax.checkpoint(
            layer_body, policy=jax.checkpoint_policies.nothing_saveable
        )

    x, _ = jax.lax.scan(layer_body, x, params["layers"])

    x = rms_norm(x, params["norm"], cfg.rms_norm_eps)
    if cfg.tie_word_embeddings:
        logits = x @ params["embed_tokens"].astype(cdt).T
    else:
        logits = x @ params["lm_head"].astype(cdt)
    return logits


class Llama:
    """Thin OO veneer matching the reference's ``Llama`` class API
    (llama.py:476+) over the functional init/forward pair."""

    config_cls = LlamaConfig

    def __init__(self, config: LlamaConfig):
        self.config = config

    def init(self, key: jax.Array) -> Params:
        return init_params(key, self.config)

    def __call__(self, params: Params, input_ids: jax.Array, **kw) -> jax.Array:
        return forward(params, input_ids, self.config, **kw)


# Default backends registered at import, like the reference registers
# ring/flash/sdpa at llama.py:38-57. ops.flash_attention and
# ops.ring_attention re-register 'flash'/'ring' with the real kernels when
# imported (scaletorch_tpu.ops does so eagerly).
register_attention_backend("sdpa", sdpa_attention)
