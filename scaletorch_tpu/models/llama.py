"""Llama-family decoder LM — functional JAX implementation.

Capability parity with reference scaletorch/models/llama.py:65-556
(LlamaAttention with GQA, SwiGLU MLP, RMSNorm decoder layers, shared RoPE
tables computed once and CP-slicable, gradient checkpointing), re-designed
TPU-first:

  * parameters are a pytree with **layers stacked along axis 0** and the
    decoder loop is a ``lax.scan`` — compile time is O(1) in depth and XLA
    sees one fused layer body instead of L copies;
  * gradient checkpointing is ``jax.checkpoint`` around the scan body
    (reference uses torch.utils.checkpoint per layer, llama.py:534-545);
  * attention dispatches through the backend registry (sdpa / flash /
    ring), resolved statically before jit;
  * mixed precision: parameters live in fp32 (optimizer master copy),
    compute runs in ``cfg.dtype`` (bf16 on TPU) — norm/softmax internals
    stay fp32.

The same ``forward`` also serves Qwen3 (per-head q/k RMSNorm before RoPE,
tied embeddings, explicit head_dim — reference model_qwen3.py:139-350) via
config flags, so there is a single decoder implementation to optimise.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from scaletorch_tpu.models.layers import (
    apply_rotary_pos_emb,
    cached_sdpa_attention,
    fan_in_uniform,
    get_cos_sin,
    rms_norm,
    sdpa_attention,
    swiglu,
    write_kv_cache,
)
from scaletorch_tpu.models.registry import (
    get_attention_backend,
    register_attention_backend,
)
from scaletorch_tpu.parallel.tensor_parallel import pvary_missing

Params = Dict[str, Any]


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 2048
    intermediate_size: int = 5632
    num_hidden_layers: int = 22
    num_attention_heads: int = 16
    num_key_value_heads: int = 4
    head_dim: Optional[int] = None  # defaults to hidden // heads
    max_position_embeddings: int = 32768
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-6
    tie_word_embeddings: bool = False
    qk_norm: bool = False  # Qwen3-style per-head q/k RMSNorm before RoPE
    dtype: Any = jnp.bfloat16  # compute dtype
    param_dtype: Any = jnp.float32

    @property
    def actual_head_dim(self) -> int:
        return self.head_dim or self.hidden_size // self.num_attention_heads

    @property
    def q_size(self) -> int:
        return self.num_attention_heads * self.actual_head_dim

    @property
    def kv_size(self) -> int:
        return self.num_key_value_heads * self.actual_head_dim

    @classmethod
    def from_hf(cls, hf_config, **overrides) -> "LlamaConfig":
        """Build from a transformers AutoConfig (reference
        ModelArguments auto-fill, config.py:102-119)."""
        kw = dict(
            vocab_size=hf_config.vocab_size,
            hidden_size=hf_config.hidden_size,
            intermediate_size=hf_config.intermediate_size,
            num_hidden_layers=hf_config.num_hidden_layers,
            num_attention_heads=hf_config.num_attention_heads,
            num_key_value_heads=getattr(
                hf_config, "num_key_value_heads", hf_config.num_attention_heads
            ),
            head_dim=getattr(hf_config, "head_dim", None),
            max_position_embeddings=hf_config.max_position_embeddings,
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            rms_norm_eps=getattr(hf_config, "rms_norm_eps", 1e-6),
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings", False),
        )
        kw.update(overrides)
        return cls(**kw)

    def num_params(self) -> int:
        """Analytic parameter count (for MFU; matches get_num_params on an
        actual init)."""
        h, i, l, v = (
            self.hidden_size,
            self.intermediate_size,
            self.num_hidden_layers,
            self.vocab_size,
        )
        attn = h * self.q_size + 2 * h * self.kv_size + self.q_size * h
        mlp = 3 * h * i
        norms = 2 * h + (2 * self.actual_head_dim if self.qk_norm else 0)
        per_layer = attn + mlp + norms
        embed = v * h
        head = 0 if self.tie_word_embeddings else v * h
        return l * per_layer + embed + h + head


def init_params(key: jax.Array, cfg: LlamaConfig, *, mlp: bool = True) -> Params:
    """Random init: fan-in uniform for projections (reference
    attention_utils.py:160-167), ones for norms, normal(0.02) embeddings.

    ``mlp=False`` skips the dense MLP stacks (MoE models replace them with
    expert weights — no point materialising weights that are discarded).
    """
    l = cfg.num_hidden_layers
    h, i, v = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    dh = cfg.actual_head_dim
    keys = jax.random.split(key, 9)
    pd = cfg.param_dtype

    def stack_init(k, shape, fan_in):
        # one batched draw for all layers: fan-in-uniform bounds depend
        # only on fan_in, so [L, ...] in a single RNG call is
        # distributionally identical to per-layer slabs
        return fan_in_uniform(k, (l,) + shape, fan_in, pd)

    layers: Params = {
        "input_layernorm": jnp.ones((l, h), pd),
        "q_proj": stack_init(keys[0], (h, cfg.q_size), h),
        "k_proj": stack_init(keys[1], (h, cfg.kv_size), h),
        "v_proj": stack_init(keys[2], (h, cfg.kv_size), h),
        "o_proj": stack_init(keys[3], (cfg.q_size, h), cfg.q_size),
        "post_attention_layernorm": jnp.ones((l, h), pd),
    }
    if mlp:
        layers["gate_proj"] = stack_init(keys[4], (h, i), h)
        layers["up_proj"] = stack_init(keys[5], (h, i), h)
        layers["down_proj"] = stack_init(keys[6], (i, h), i)
    if cfg.qk_norm:
        layers["q_norm"] = jnp.ones((l, dh), pd)
        layers["k_norm"] = jnp.ones((l, dh), pd)

    params: Params = {
        "embed_tokens": 0.02 * jax.random.normal(keys[7], (v, h), pd),
        "layers": layers,
        "norm": jnp.ones((h,), pd),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = fan_in_uniform(keys[8], (h, v), h, pd)
    return params


def tp_region_helpers(
    cfg: LlamaConfig,
    tp_axis: Optional[str],
    sequence_parallel: bool,
) -> Tuple[Callable, Callable, Callable, Callable]:
    """(pv, enter_full_seq, col, row) — the four region functions that
    parameterise a decoder block over its TP/SP mode. Shared by the dense
    decoder layer and the MoE decoder layer."""
    cdt = cfg.dtype
    tp = tp_axis

    if tp:
        from scaletorch_tpu.parallel.sequence_parallel import all_gather_sequence
        from scaletorch_tpu.parallel.tensor_parallel import (
            column_parallel_linear,
            row_parallel_linear,
        )

        def pv(t):
            return pvary_missing(t, tp)

        def enter_full_seq(h):
            # norm-region shard -> full sequence for attention/MLP
            return all_gather_sequence(h, tp) if sequence_parallel else pv(h)

        def col(h, w):
            return column_parallel_linear(h, w.astype(cdt), axis=tp)

        def row(h, w):
            return row_parallel_linear(
                h, w.astype(cdt), axis=tp, sequence_parallel=sequence_parallel
            )

    else:

        def pv(t):
            return t

        def enter_full_seq(h):
            return h

        def col(h, w):
            return h @ w.astype(cdt)

        def row(h, w):
            return h @ w.astype(cdt)

    return pv, enter_full_seq, col, row


def attention_block(
    x: jax.Array,
    layer: Params,
    cos: jax.Array,
    sin: jax.Array,
    cfg: LlamaConfig,
    attn_fn: Callable,
    helpers: Tuple[Callable, Callable, Callable, Callable],
) -> jax.Array:
    """Pre-norm attention sub-block with residual (reference
    LlamaAttention, llama.py:132-198). Shared by dense and MoE layers."""
    pv, enter_full_seq, col, row = helpers
    nh_l = layer["q_proj"].shape[-1]  # local q width (already tp-sliced)
    nkv_l = layer["k_proj"].shape[-1]
    dh = cfg.actual_head_dim

    h = rms_norm(x, pv(layer["input_layernorm"]), cfg.rms_norm_eps)
    h = enter_full_seq(h)
    b, s, _ = h.shape
    q = col(h, layer["q_proj"]).reshape(b, s, nh_l // dh, dh)
    k = col(h, layer["k_proj"]).reshape(b, s, nkv_l // dh, dh)
    v = col(h, layer["v_proj"]).reshape(b, s, nkv_l // dh, dh)
    if cfg.qk_norm:
        # Qwen3: RMSNorm over head_dim, per head, before RoPE
        # (reference model_qwen3.py:179-180,209-210).
        q = rms_norm(q, pv(layer["q_norm"]), cfg.rms_norm_eps)
        k = rms_norm(k, pv(layer["k_norm"]), cfg.rms_norm_eps)
    q = q.transpose(0, 2, 1, 3)  # [B, H_local, S, D]
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    q, k = apply_rotary_pos_emb(q, k, pv(cos), pv(sin))
    attn = attn_fn(q, k, v, causal=True)
    # Offer the attention output to the remat policy (the 'save_attn'
    # policy keeps it instead of recomputing the whole block in backward).
    attn = checkpoint_name(attn, "attn_out")
    attn = attn.transpose(0, 2, 1, 3).reshape(b, s, nh_l)
    return x + row(attn, layer["o_proj"])


def _decoder_layer(
    x: jax.Array,
    layer: Params,
    cos: jax.Array,
    sin: jax.Array,
    cfg: LlamaConfig,
    attn_fn: Callable,
    tp_axis: Optional[str] = None,
    sequence_parallel: bool = False,
) -> jax.Array:
    """One pre-norm decoder block. x: [B, S, H] in compute dtype.

    With ``tp_axis`` set (inside shard_map, weights arriving pre-sharded
    per llama_param_specs): q/k/v/gate/up are column-parallel, o/down are
    row-parallel (reference apply_tensor_parallel mapping,
    tensor_parallel.py:107-143). With ``sequence_parallel``, x is
    seq-sharded over tp; norm regions run on the shard, attention/MLP on
    the gathered sequence, and the row-parallel all-reduce becomes a
    reduce-scatter (reference llama.py:314-377, sp_comms.py:31-94).
    """
    helpers = tp_region_helpers(cfg, tp_axis, sequence_parallel)
    pv, enter_full_seq, col, row = helpers

    x = attention_block(x, layer, cos, sin, cfg, attn_fn, helpers)

    # ---- SwiGLU MLP (reference llama.py:207-249) ----------------------------
    h = rms_norm(x, pv(layer["post_attention_layernorm"]), cfg.rms_norm_eps)
    h = enter_full_seq(h)
    gate = col(h, layer["gate_proj"])
    up = col(h, layer["up_proj"])
    x = x + row(swiglu(gate, up), layer["down_proj"])
    return x


def embed(
    params: Params,
    input_ids: jax.Array,
    cfg: LlamaConfig,
    *,
    tp_axis: Optional[str] = None,
    sequence_parallel: bool = False,
) -> jax.Array:
    """Token embedding: [B, S] -> [B, S(/tp under SP), H] in compute dtype.

    Factored out of ``forward`` so pipeline parallelism can run it on the
    first stage only (reference PipelineParallel keeps the embedding on
    stage 0, pipeline_parallel.py:135-178).
    """
    cdt = cfg.dtype
    if sequence_parallel and tp_axis is None:
        raise ValueError("sequence_parallel requires tp_axis (run inside shard_map)")
    if tp_axis is None:
        return params["embed_tokens"][input_ids].astype(cdt)  # [B, S, H]
    from scaletorch_tpu.parallel.sequence_parallel import reduce_scatter_sequence
    from scaletorch_tpu.parallel.tensor_parallel import vocab_parallel_embedding

    if sequence_parallel:
        # Fused all-reduce + seq-scatter: the embedding's partial sums
        # are completed by the reduce-scatter that enters the SP region
        # (reference skips the embedding all-reduce under SP the same
        # way, tensor_parallel.py:238-240 + llama.py:530-552).
        partial = vocab_parallel_embedding(
            input_ids, params["embed_tokens"], axis=tp_axis, reduce="none"
        )
        return reduce_scatter_sequence(partial.astype(cdt), tp_axis)
    return vocab_parallel_embedding(
        input_ids, params["embed_tokens"], axis=tp_axis
    ).astype(cdt)


def final_hidden(
    params: Params,
    x: jax.Array,
    cfg: LlamaConfig,
    *,
    tp_axis: Optional[str] = None,
    sequence_parallel: bool = False,
) -> jax.Array:
    """Final RMSNorm (+ SP sequence all-gather): the last-stage epilogue
    before the LM head (reference keeps final_norm/final_proj on the last
    PP stage, pipeline_parallel.py:135-178)."""
    x = rms_norm(
        x,
        pvary_missing(params["norm"], tp_axis) if tp_axis else params["norm"],
        cfg.rms_norm_eps,
    )
    if sequence_parallel:
        from scaletorch_tpu.parallel.sequence_parallel import all_gather_sequence

        x = all_gather_sequence(x, tp_axis)
    return x


def resolve_remat_policy(name: str):
    """Map a config-level policy name to a jax.checkpoint policy.

    The reference's gradient checkpointing has exactly one mode — recompute
    the whole layer (torch.utils.checkpoint, llama.py:534-545). On TPU the
    policy is the main GC perf lever (VERDICT r1 #10): what gets saved
    decides how much of the flash/ring attention is recomputed in backward.
    """
    cp = jax.checkpoint_policies
    policies = {
        "nothing_saveable": cp.nothing_saveable,
        "dots_saveable": cp.dots_saveable,
        "dots_with_no_batch_dims_saveable": cp.dots_with_no_batch_dims_saveable,
        # Keeps the flash kernel's (out, lse) residuals (named in
        # ops/pallas/flash.py _flash_fwd) plus the layer-level attn output,
        # so backward under GC skips the flash-forward recompute and runs
        # the dq/dkv kernels directly off the saved statistics.
        "save_attn": cp.save_only_these_names("attn_out", "attn_lse"),
    }
    if name not in policies:
        raise ValueError(
            f"unknown remat_policy {name!r}; have {sorted(policies)}"
        )
    return policies[name]


def decoder_stack(
    x: jax.Array,
    layers: Params,
    cos: jax.Array,
    sin: jax.Array,
    cfg: LlamaConfig,
    attn_fn: Callable,
    *,
    tp_axis: Optional[str] = None,
    sequence_parallel: bool = False,
    gradient_checkpointing: bool = False,
    remat_policy: str = "nothing_saveable",
    active_layers: Optional[jax.Array] = None,
) -> jax.Array:
    """Scan ``_decoder_layer`` over a stack of layer params (leading axis =
    layer index). Used by ``forward`` for the whole model and by pipeline
    parallelism for one stage's layer subset.

    ``active_layers`` (scalar) marks the first k stacked slots as real;
    later slots are identity padding (uneven pipeline stages — reference
    PipelineParallel supports ragged layer counts, pipeline_parallel.py:
    83-133 — pad the stacked axis and mask here). Masked slots forward
    ``h`` unchanged, so their (zero-initialised) params get exactly zero
    gradient through the ``where``.
    """

    def layer_body(h, xs):
        layer_params, idx = xs
        out = _decoder_layer(
            h, layer_params, cos, sin, cfg, attn_fn,
            tp_axis=tp_axis, sequence_parallel=sequence_parallel,
        )
        if active_layers is not None:
            out = jnp.where(idx < active_layers, out, h)
        return out, None

    if gradient_checkpointing:
        layer_body = jax.checkpoint(
            layer_body, policy=resolve_remat_policy(remat_policy)
        )
    x, _ = jax.lax.scan(
        layer_body, x, (layers, scan_slot_indices(layers, active_layers))
    )
    return x


def scan_slot_indices(layers: Params, active_layers) -> jax.Array:
    """Per-slot indices [0..n_slots) for a stacked-layer scan. When an
    ``active_layers`` mask scalar is in play, the indices are broadcast
    onto its varying-mesh-axes (the ``+ 0 *`` trick) so the in-scan
    ``jnp.where`` compares vma-consistent operands under shard_map."""
    n_slots = jax.tree_util.tree_leaves(layers)[0].shape[0]
    idx = jnp.arange(n_slots, dtype=jnp.int32)
    if active_layers is not None:
        idx = idx + 0 * active_layers.astype(jnp.int32)
    return idx


def forward(
    params: Params,
    input_ids: jax.Array,
    cfg: LlamaConfig,
    *,
    positions: Optional[jax.Array] = None,
    attention_backend: str = "sdpa",
    gradient_checkpointing: bool = False,
    remat_policy: str = "nothing_saveable",
    tp_axis: Optional[str] = None,
    sequence_parallel: bool = False,
    return_hidden: bool = False,
) -> jax.Array:
    """Full decoder forward: [B, S] int tokens -> logits.

    Pure single-device semantics by default. With ``tp_axis`` (must run
    inside a shard_map over that mesh axis, params sharded per
    llama_param_specs) the decoder runs Megatron-style tensor parallel and
    the returned logits are **vocab-sharded** [B, S, V/tp] — pair with
    vocab_parallel_cross_entropy, or all-gather for dense logits.

    ``positions`` (shape [S]) overrides absolute positions for the RoPE
    table — CP passes this rank's sequence-shard positions (reference
    update_rope_for_context_parallel, context_parallel.py:427-473).
    """
    s = input_ids.shape[1]
    x = embed(params, input_ids, cfg, tp_axis=tp_axis,
              sequence_parallel=sequence_parallel)

    # RoPE tables computed once and shared across layers (reference
    # llama.py:476-491), fp32 then cast at application.
    cos, sin = get_cos_sin(s, cfg.actual_head_dim, cfg.rope_theta,
                           positions=positions)

    attn_fn = get_attention_backend(attention_backend)
    x = decoder_stack(
        x, params["layers"], cos, sin, cfg, attn_fn,
        tp_axis=tp_axis, sequence_parallel=sequence_parallel,
        gradient_checkpointing=gradient_checkpointing,
        remat_policy=remat_policy,
    )
    x = final_hidden(params, x, cfg, tp_axis=tp_axis,
                     sequence_parallel=sequence_parallel)
    if return_hidden:
        # Caller applies the LM head via lm_head_weight() (e.g. the fused
        # chunked CE in parallel/spmd.py).
        return x
    return x @ lm_head_weight(params, cfg, tp_axis)


def lm_head_weight(
    params: Params, cfg: LlamaConfig, tp_axis: Optional[str] = None
) -> jax.Array:
    """[H, V(/tp)] head weight in compute dtype (tied-embedding aware)."""
    head = (
        params["embed_tokens"].astype(cfg.dtype).T
        if cfg.tie_word_embeddings
        else params["lm_head"].astype(cfg.dtype)
    )
    return pvary_missing(head, tp_axis) if tp_axis else head


# ---- KV-cache inference path (scaletorch_tpu/inference) ---------------------
#
# The decode engine's two jitted steps (prefill / single-token decode,
# inference/decode.py) both lower onto ``forward_cached``: a full-sequence
# call with positions [B, 0..P) is prefill, a one-token call with positions
# [B, 1] = p is decode. TP runs via GSPMD — params and cache arrive as
# NamedSharding-placed global arrays (llama_param_specs + kv_cache_specs)
# and XLA partitions the plain einsums; no shard_map/tp_axis threading.


def attention_block_cached(
    x: jax.Array,
    layer: Params,
    cache_k: jax.Array,
    cache_v: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    positions: jax.Array,
    cfg: LlamaConfig,
    *,
    write_mask: Optional[jax.Array] = None,
    kv_io: Optional[Any] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Cache-aware pre-norm attention sub-block with residual.

    x: [B, S, H]; cache_k/cache_v: [B, Hkv, S_max, D]; cos/sin:
    [B, S, Dh] per-slot RoPE tables; positions: [B, S] absolute token
    positions (contiguous per slot — prefill passes [0..S), decode a
    single column p). K/V are computed with RoPE at the absolute
    positions, appended into the cache at ``positions[:, 0]`` (see
    ``write_kv_cache``; ``write_mask`` [B] protects live slots during a
    mixed admit-prefill), and attention runs q-against-cache with the
    j <= p mask. Returns (out, new_cache_k, new_cache_v).

    ``kv_io`` swaps the cache layout: an adapter with
    ``write(cache, kv, positions, write_mask)`` and
    ``attend(q, cache_k, cache_v, positions)`` (e.g. the paged pool's
    ``inference.kv_cache.PagedKVIO``) replaces the dense
    ``write_kv_cache`` + ``cached_sdpa_attention`` pair; the cache
    arrays then carry the adapter's layout instead of
    [B, Hkv, S_max, D].
    """
    cdt = cfg.dtype
    dh = cfg.actual_head_dim
    h = rms_norm(x, layer["input_layernorm"], cfg.rms_norm_eps)
    b, s, _ = h.shape
    q = (h @ layer["q_proj"].astype(cdt)).reshape(b, s, -1, dh)
    k = (h @ layer["k_proj"].astype(cdt)).reshape(b, s, -1, dh)
    v = (h @ layer["v_proj"].astype(cdt)).reshape(b, s, -1, dh)
    if cfg.qk_norm:
        q = rms_norm(q, layer["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, layer["k_norm"], cfg.rms_norm_eps)
    q = q.transpose(0, 2, 1, 3)  # [B, Hq, S, D]
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    q, k = apply_rotary_pos_emb(q, k, cos, sin)
    if kv_io is None:
        cache_k = write_kv_cache(cache_k, k, positions[:, 0], write_mask)
        cache_v = write_kv_cache(cache_v, v, positions[:, 0], write_mask)
        attn = cached_sdpa_attention(q, cache_k, cache_v, positions)
    else:
        cache_k = kv_io.write(cache_k, k, positions, write_mask)
        cache_v = kv_io.write(cache_v, v, positions, write_mask)
        attn = kv_io.attend(q, cache_k, cache_v, positions)
    attn = attn.transpose(0, 2, 1, 3).reshape(b, s, -1)
    return x + attn @ layer["o_proj"].astype(cdt), cache_k, cache_v


def _mlp_block(x: jax.Array, layer: Params, cfg: LlamaConfig) -> jax.Array:
    """Dense SwiGLU MLP sub-block with residual (single-device form; the
    TP/SP training path stays in ``_decoder_layer``)."""
    cdt = cfg.dtype
    h = rms_norm(x, layer["post_attention_layernorm"], cfg.rms_norm_eps)
    gate = h @ layer["gate_proj"].astype(cdt)
    up = h @ layer["up_proj"].astype(cdt)
    return x + swiglu(gate, up) @ layer["down_proj"].astype(cdt)


def forward_cached(
    params: Params,
    input_ids: jax.Array,
    cfg: LlamaConfig,
    cache: Tuple[jax.Array, jax.Array],
    *,
    positions: jax.Array,
    write_mask: Optional[jax.Array] = None,
    kv_io: Optional[Any] = None,
) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
    """KV-cached decoder forward: [B, S] tokens at absolute ``positions``
    [B, S] -> (logits [B, S, V], new (cache_k, cache_v)).

    ``cache`` is a pair of [L, B, Hkv, S_max, D] stacked per-layer
    buffers in the models' scan layout (inference/kv_cache.py builds and
    shards them). One trace serves both engine steps: prefill (S = P,
    positions [0..P), ``write_mask`` selecting the admitted slots) and
    decode (S = 1, positions = current length per slot). The layer loop
    is the same ``lax.scan`` shape as the training forward — the cache
    rides the scan as per-layer xs/ys — so compile time stays O(1) in
    depth. With ``kv_io`` the cache pair is the adapter's layout instead
    (the paged pool's [L, n_pages, Hkv, page_size, D]); the scan slices
    its leading layer axis the same way.
    """
    cache_k, cache_v = cache
    x = embed(params, input_ids, cfg)
    cos, sin = get_cos_sin(
        input_ids.shape[1], cfg.actual_head_dim, cfg.rope_theta,
        positions=positions,
    )

    def layer_body(h, xs):
        layer, ck, cv = xs
        h, ck, cv = attention_block_cached(
            h, layer, ck, cv, cos, sin, positions, cfg,
            write_mask=write_mask, kv_io=kv_io,
        )
        h = _mlp_block(h, layer, cfg)
        return h, (ck, cv)

    x, (k_new, v_new) = jax.lax.scan(
        layer_body, x, (params["layers"], cache_k, cache_v)
    )
    x = rms_norm(x, params["norm"], cfg.rms_norm_eps)
    logits = x @ lm_head_weight(params, cfg)
    return logits, (k_new, v_new)


class Llama:
    """Thin OO veneer matching the reference's ``Llama`` class API
    (llama.py:476+) over the functional init/forward pair."""

    config_cls = LlamaConfig

    def __init__(self, config: LlamaConfig):
        self.config = config

    def init(self, key: jax.Array) -> Params:
        return init_params(key, self.config)

    def __call__(self, params: Params, input_ids: jax.Array, **kw) -> jax.Array:
        return forward(params, input_ids, self.config, **kw)


# Default backends registered at import, like the reference registers
# ring/flash/sdpa at llama.py:38-57. ops.flash_attention and
# ops.ring_attention re-register 'flash'/'ring' with the real kernels when
# imported (scaletorch_tpu.ops does so eagerly).
register_attention_backend("sdpa", sdpa_attention)
