"""Named architecture presets for benchmarks and tools.

The reference benchmarks against local HF checkout dirs
(scripts/benchmark_comprehensive.py:24 MODEL_ROOT + Qwen3-* names); the
TPU build runs hermetic synthetic-data benchmarks, so the architectures
are declared here directly (field values match the published HF configs
for Qwen/Qwen3-*; MoE matches Qwen/Qwen3-30B-A3B).

Each preset is a kwargs dict for ``ScaleTorchTPUArguments`` — pass
``**preset("qwen3-0.6b")`` plus run-shape fields.
"""

from __future__ import annotations

from typing import Any, Dict

_QWEN3_COMMON = dict(
    model_type="qwen3",
    vocab_size=151936,
    num_key_value_heads=8,
    head_dim=128,
    rope_theta=1e6,
    rms_norm_eps=1e-6,
    max_position_embeddings=40960,
)

MODEL_PRESETS: Dict[str, Dict[str, Any]] = {
    "qwen3-0.6b": dict(
        _QWEN3_COMMON,
        hidden_size=1024,
        intermediate_size=3072,
        num_hidden_layers=28,
        num_attention_heads=16,
        tie_word_embeddings=True,
    ),
    "qwen3-1.7b": dict(
        _QWEN3_COMMON,
        hidden_size=2048,
        intermediate_size=6144,
        num_hidden_layers=28,
        num_attention_heads=16,
        tie_word_embeddings=True,
    ),
    "qwen3-4b": dict(
        _QWEN3_COMMON,
        hidden_size=2560,
        intermediate_size=9728,
        num_hidden_layers=36,
        num_attention_heads=32,
        tie_word_embeddings=True,
    ),
    "qwen3-8b": dict(
        _QWEN3_COMMON,
        hidden_size=4096,
        intermediate_size=12288,
        num_hidden_layers=36,
        num_attention_heads=32,
        tie_word_embeddings=False,
    ),
    "qwen3-14b": dict(
        _QWEN3_COMMON,
        hidden_size=5120,
        intermediate_size=17408,
        num_hidden_layers=40,
        num_attention_heads=40,
        tie_word_embeddings=False,
    ),
    "qwen3-32b": dict(
        _QWEN3_COMMON,
        hidden_size=5120,
        intermediate_size=25600,
        num_hidden_layers=64,
        num_attention_heads=64,
        tie_word_embeddings=False,
    ),
    # Qwen3-30B-A3B: 128 experts, top-8, 3.3B active of 30.5B total.
    "qwen3-30b-a3b": dict(
        model_type="qwen3_moe",
        vocab_size=151936,
        hidden_size=2048,
        intermediate_size=6144,
        moe_intermediate_size=768,
        num_hidden_layers=48,
        num_attention_heads=32,
        num_key_value_heads=4,
        head_dim=128,
        rope_theta=1e6,
        rms_norm_eps=1e-6,
        max_position_embeddings=40960,
        tie_word_embeddings=False,
        num_experts=128,
        num_experts_per_tok=8,
    ),
    # Single-v5e-chip MoE (same shape family as qwen3-30b-a3b, scaled to
    # fit 16 GB with bf16 master weights): E=64/top-8 keeps the
    # large-expert-count dispatch regime where the index form wins
    # (tools/bench_moe_dispatch.py measures it on-chip).
    "moe-mid": dict(
        model_type="qwen3_moe",
        vocab_size=32768,
        hidden_size=1024,
        intermediate_size=3072,
        moe_intermediate_size=384,
        num_hidden_layers=12,
        num_attention_heads=16,
        num_key_value_heads=4,
        head_dim=64,
        rope_theta=1e6,
        rms_norm_eps=1e-6,
        max_position_embeddings=40960,
        tie_word_embeddings=False,
        num_experts=64,
        num_experts_per_tok=8,
    ),
    # Downscaled MoE for 8-chip correctness/system sweeps (same shape
    # family as qwen3-30b-a3b; fits a CPU-device mesh).
    "moe-tiny": dict(
        model_type="qwen3_moe",
        vocab_size=4096,
        hidden_size=256,
        intermediate_size=512,
        moe_intermediate_size=192,
        num_hidden_layers=4,
        num_attention_heads=8,
        num_key_value_heads=4,
        head_dim=32,
        rope_theta=1e6,
        max_position_embeddings=8192,
        tie_word_embeddings=True,
        num_experts=8,
        num_experts_per_tok=2,
    ),
    # Downscaled dense model for 8-chip correctness/system sweeps.
    "dense-tiny": dict(
        model_type="qwen3",
        vocab_size=4096,
        hidden_size=256,
        intermediate_size=512,
        num_hidden_layers=4,
        num_attention_heads=8,
        num_key_value_heads=4,
        head_dim=32,
        rope_theta=1e6,
        max_position_embeddings=8192,
        tie_word_embeddings=True,
    ),
}


def preset(name: str) -> Dict[str, Any]:
    try:
        return dict(MODEL_PRESETS[name.lower()])
    except KeyError:
        raise KeyError(
            f"unknown model preset {name!r}; available: "
            f"{', '.join(sorted(MODEL_PRESETS))}"
        ) from None
