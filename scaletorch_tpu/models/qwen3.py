"""Qwen3 dense model — Llama variant with QK-norm and tied embeddings.

Parity with reference scaletorch/models/model_qwen3.py:139-350: explicit
``head_dim`` from config (:148), per-head q/k RMSNorm before RoPE
(:179-180, 209-210), ``tie_word_embeddings`` (:297-298), rope_theta
default 1e6-class values. The decoder body is shared with Llama
(models/llama.py) via the ``qk_norm`` config flag — one implementation to
optimise, two model identities for API/checkpoint parity.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax

from scaletorch_tpu.models import llama as _llama
from scaletorch_tpu.models.llama import LlamaConfig, Params


@dataclass(frozen=True)
class Qwen3Config(LlamaConfig):
    # Qwen3-0.6B-ish defaults; override from HF config in practice.
    vocab_size: int = 151936
    hidden_size: int = 1024
    intermediate_size: int = 3072
    num_hidden_layers: int = 28
    num_attention_heads: int = 16
    num_key_value_heads: int = 8
    head_dim: int = 128  # explicit, != hidden // heads (model_qwen3.py:148)
    rope_theta: float = 1000000.0
    tie_word_embeddings: bool = True
    qk_norm: bool = True


def init_params(key: jax.Array, cfg: Qwen3Config) -> Params:
    return _llama.init_params(key, cfg)


def forward(params: Params, input_ids: jax.Array, cfg: Qwen3Config, **kw):
    return _llama.forward(params, input_ids, cfg, **kw)


def forward_cached(params: Params, input_ids: jax.Array, cfg: Qwen3Config,
                   cache, **kw):
    """KV-cached forward (llama.forward_cached; qk_norm rides the config
    flag) — the decode-engine entry point for the Qwen3 family."""
    return _llama.forward_cached(params, input_ids, cfg, cache, **kw)


class Qwen3(_llama.Llama):
    config_cls = Qwen3Config
