"""Qwen3-MoE — sparse-MLP decoder with expert parallelism.

Capability parity with reference scaletorch/models/model_qwen3_moe.py:
30-409 (MoERouter top-k gate + Switch aux loss :30-92, MoEExperts per-
expert SwiGLU :98-171, MoELayer EP dispatch path :244-288, decoder-layer
aux-loss stashing :309-322, model-level aggregation :375-381), re-designed
TPU-first:

  * experts live as stacked tensors [L, E, H, I] and run as one batched
    einsum (parallel/expert_parallel.moe_mlp) — the grouped-matmul role of
    ``npu_grouped_matmul`` (reference models/npu_patch.py:94-131) without
    a custom kernel, because XLA maps batched einsums onto the MXU;
  * token movement is capacity-based dispatch + ``lax.all_to_all`` over
    the ep mesh axis (static shapes — XLA-compatible), instead of the
    reference's ragged sort-based exchange (ep_comms.py:41-133);
  * aux losses (Switch load-balance + router z-loss) accumulate through
    the layer scan and return alongside the hidden states — the
    functional version of per-layer ``_aux_loss`` stashes + get_aux_loss.

Attention/embedding/norm are shared with Llama/Qwen3 (models/llama.py),
so TP/SP/CP compose identically; EP adds the ep axis for expert shards
and token exchange.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from scaletorch_tpu.models import llama as _llama
from scaletorch_tpu.models.layers import fan_in_uniform, get_cos_sin, rms_norm
from scaletorch_tpu.models.llama import Params
from scaletorch_tpu.models.qwen3 import Qwen3Config
from scaletorch_tpu.models.registry import get_attention_backend
from scaletorch_tpu.parallel.expert_parallel import (
    dispatch_tokens,
    expert_capacity,
    gather_tokens,
    moe_mlp,
    top_k_routing,
)
from scaletorch_tpu.parallel.tensor_parallel import pvary_missing


@dataclass(frozen=True)
class Qwen3MoEConfig(Qwen3Config):
    # Qwen3-30B-A3B-style knobs (reference model_qwen3_moe.py + HF config)
    num_experts: int = 8
    num_experts_per_tok: int = 2
    moe_intermediate_size: int = 768
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.001  # router_aux_loss_coef
    z_loss_coef: float = 0.0
    norm_topk_prob: bool = True
    tie_word_embeddings: bool = False

    @classmethod
    def from_hf(cls, hf_config, **overrides) -> "Qwen3MoEConfig":
        # This build is all-MoE (every layer sparse); reject HF configs
        # with interleaved dense layers rather than silently building a
        # different architecture.
        if getattr(hf_config, "mlp_only_layers", None):
            raise NotImplementedError(
                "mlp_only_layers (interleaved dense layers) is not supported"
            )
        if getattr(hf_config, "decoder_sparse_step", 1) not in (0, 1):
            raise NotImplementedError(
                "decoder_sparse_step > 1 (interleaved dense layers) is not "
                "supported"
            )
        kw = dict(
            num_experts=getattr(hf_config, "num_experts", 8),
            num_experts_per_tok=getattr(hf_config, "num_experts_per_tok", 2),
            moe_intermediate_size=getattr(hf_config, "moe_intermediate_size", 768),
            norm_topk_prob=getattr(hf_config, "norm_topk_prob", True),
        )
        kw.update(overrides)
        return super().from_hf(hf_config, **kw)

    def num_params(self) -> int:
        h, l, v = self.hidden_size, self.num_hidden_layers, self.vocab_size
        attn = h * self.q_size + 2 * h * self.kv_size + self.q_size * h
        moe = self.num_experts * 3 * h * self.moe_intermediate_size
        router = h * self.num_experts
        norms = 2 * h + (2 * self.actual_head_dim if self.qk_norm else 0)
        per_layer = attn + moe + router + norms
        head = 0 if self.tie_word_embeddings else v * h
        return l * per_layer + v * h + h + head

    def num_active_params(self) -> int:
        """Active parameters per token (top-k experts) — the MFU
        denominator the reference uses for MoE tables (README.md:131)."""
        h, l, v = self.hidden_size, self.num_hidden_layers, self.vocab_size
        attn = h * self.q_size + 2 * h * self.kv_size + self.q_size * h
        moe = self.num_experts_per_tok * 3 * h * self.moe_intermediate_size
        router = h * self.num_experts
        norms = 2 * h + (2 * self.actual_head_dim if self.qk_norm else 0)
        head = 0 if self.tie_word_embeddings else v * h
        return l * (attn + moe + router + norms) + v * h + h + head


def init_params(key: jax.Array, cfg: Qwen3MoEConfig) -> Params:
    """Dense attention params from the Llama initializer (mlp=False); MoE
    params take the dense MLP keys' place (stacked [L, E, ...])."""
    l, h, e = cfg.num_hidden_layers, cfg.hidden_size, cfg.num_experts
    i = cfg.moe_intermediate_size
    pd = cfg.param_dtype
    base = _llama.init_params(key, cfg, mlp=False)
    layers = base["layers"]
    keys = jax.random.split(jax.random.fold_in(key, 7), 4)

    def expert_stack(k, shape, fan_in):
        # one batched draw: fan-in-uniform bounds depend only on fan_in,
        # so [L, E, ...] in a single RNG call is distributionally identical
        return fan_in_uniform(k, (l, e) + shape, fan_in, pd)

    layers["router"] = 0.02 * jax.random.normal(keys[0], (l, h, e), pd)
    layers["expert_gate_proj"] = expert_stack(keys[1], (h, i), h)
    layers["expert_up_proj"] = expert_stack(keys[2], (h, i), h)
    layers["expert_down_proj"] = expert_stack(keys[3], (i, h), i)
    return base


def moe_block(
    x: jax.Array,
    layer: Params,
    cfg: Qwen3MoEConfig,
    helpers: Tuple[Callable, Callable, Callable, Callable],
    *,
    ep_axis: Optional[str] = None,
    tp_axis: Optional[str] = None,
    sequence_parallel: bool = False,
) -> Tuple[jax.Array, jax.Array, dict]:
    """Post-attention MoE sub-block with residual.
    Returns (x, aux_loss, stats) — stats carries the per-step routing
    health scalars the operator must see (VERDICT r1 weak #5):
    ``dropped_fraction`` (tokens beyond capacity) and ``load_cv``
    (coefficient of variation of expert load; 0 = perfectly balanced).

    Reference MoELayer.forward (model_qwen3_moe.py:210-288): router ->
    dispatch -> experts -> gather -> top-k sum, with the EP path active
    when ep_axis is set.
    """
    pv, enter_full_seq, _, _ = helpers
    h_norm = rms_norm(x, pv(layer["post_attention_layernorm"]), cfg.rms_norm_eps)
    h_full = enter_full_seq(h_norm)  # [B, S, H]
    b, s, hid = h_full.shape

    # Router in fp32 (reference router runs in fp32 for gate stability).
    # Each batch row routes as its own group (GShard-style grouping): the
    # [G, S, E, C] dispatch/combine tensors stay O(tokens·S·k) instead of
    # the O(tokens²·k) a flat [N, E, C] would cost.
    logits = jnp.einsum(
        "gsh,he->gse",
        h_full.astype(jnp.float32),
        pv(layer["router"]).astype(jnp.float32),
    )
    cap = expert_capacity(
        s, cfg.num_experts, cfg.num_experts_per_tok, cfg.capacity_factor
    )
    dispatch, combine, aux = jax.vmap(
        lambda lg: top_k_routing(
            lg, cfg.num_experts_per_tok, cap,
            normalize_weights=cfg.norm_topk_prob,
        )
    )(logits)
    aux = {k: jnp.mean(v, axis=0) for k, v in aux.items()}  # mean over groups
    slots = dispatch_tokens(h_full, dispatch, axis=ep_axis)
    kernel_extra = {}
    from scaletorch_tpu.env import get_env

    if get_env("SCALETORCH_TPU_GROUPED_MLP_KERNEL"):
        # slot-skipping expert kernel: per-(expert, group) fill counts
        # ride the same exchange layout as the slots
        from scaletorch_tpu.ops.pallas.grouped_mlp import slot_fill_counts
        from scaletorch_tpu.parallel.expert_parallel import (
            exchange_slot_counts,
        )

        kernel_extra = dict(
            slot_counts=exchange_slot_counts(
                slot_fill_counts(dispatch), ep_axis),
            capacity=cap,
        )
    out = moe_mlp(
        slots,
        layer["expert_gate_proj"],
        layer["expert_up_proj"],
        layer["expert_down_proj"],
        tp_axis=tp_axis,
        compute_dtype=cfg.dtype,
        reduce="none" if sequence_parallel else "sum",
        **kernel_extra,
    )
    y = gather_tokens(out, combine, axis=ep_axis)  # [B, S, H]
    if sequence_parallel:
        # Expert outputs are still tp-partial (reduce='none'); complete the
        # sum with the reduce-scatter that re-enters the SP region — the
        # same fusion the dense row-parallel path uses (sp_comms.py:64-94).
        from scaletorch_tpu.parallel.sequence_parallel import reduce_scatter_sequence

        y = reduce_scatter_sequence(y, tp_axis)
    aux_total = (
        cfg.aux_loss_coef * aux["aux_loss"] + cfg.z_loss_coef * aux["z_loss"]
    )
    load = aux["expert_load"]  # [E], sums to top_k
    stats = {
        "moe_dropped_fraction": aux["dropped_fraction"],
        "moe_load_cv": jnp.std(load) / jnp.maximum(jnp.mean(load), 1e-9),
    }
    return x + y.astype(x.dtype), aux_total, stats


def moe_decoder_stack(
    x: jax.Array,
    layers: Params,
    cos: jax.Array,
    sin: jax.Array,
    cfg: Qwen3MoEConfig,
    attn_fn: Callable,
    helpers,
    *,
    tp_axis: Optional[str] = None,
    ep_axis: Optional[str] = None,
    sequence_parallel: bool = False,
    gradient_checkpointing: bool = False,
    remat_policy: str = "nothing_saveable",
    active_layers: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, dict]:
    """Scan attention+MoE layers over a stacked layer block; returns
    (hidden, aux_loss_sum, stats_layer_mean). The MoE counterpart of
    llama.decoder_stack, shared by the full forward and by one pipeline
    stage's compute (where ``layers`` is the pp-sharded [L/pp, ...] block).
    ``active_layers`` masks identity padding slots exactly like
    llama.decoder_stack (uneven pipeline stages): padded slots forward
    ``h`` and contribute zero aux/stats."""
    extra = tuple(a for a in (tp_axis, ep_axis) if a)
    x = pvary_missing(x, extra) if extra else x

    def layer_body(h, xs):
        layer_params, idx = xs
        out = _llama.attention_block(h, layer_params, cos, sin, cfg, attn_fn,
                                     helpers)
        out, aux, stats = moe_block(
            out, layer_params, cfg, helpers,
            ep_axis=ep_axis, tp_axis=tp_axis,
            sequence_parallel=sequence_parallel,
        )
        if active_layers is not None:
            live = idx < active_layers
            out = jnp.where(live, out, h)
            aux = jnp.where(live, aux, 0.0)
            stats = jax.tree.map(lambda v: jnp.where(live, v, 0.0), stats)
        if extra:
            out, aux = pvary_missing(out, extra), pvary_missing(aux, extra)
            stats = jax.tree.map(lambda v: pvary_missing(v, extra), stats)
        return out, (aux, stats)

    if gradient_checkpointing:
        layer_body = jax.checkpoint(
            layer_body, policy=_llama.resolve_remat_policy(remat_policy)
        )

    x, (aux_per_layer, stats_per_layer) = jax.lax.scan(
        layer_body, x,
        (layers, _llama.scan_slot_indices(layers, active_layers)))
    aux_loss = jnp.sum(aux_per_layer)
    if active_layers is None:
        moe_stats = jax.tree.map(lambda v: jnp.mean(v, axis=0), stats_per_layer)
    else:
        # mean over REAL layers only — padded slots contributed zeros
        denom = jnp.maximum(active_layers.astype(jnp.float32), 1.0)
        moe_stats = jax.tree.map(
            lambda v: jnp.sum(v, axis=0) / denom, stats_per_layer)
    return x, aux_loss, moe_stats


def forward(
    params: Params,
    input_ids: jax.Array,
    cfg: Qwen3MoEConfig,
    *,
    positions: Optional[jax.Array] = None,
    attention_backend: str = "sdpa",
    gradient_checkpointing: bool = False,
    remat_policy: str = "nothing_saveable",
    tp_axis: Optional[str] = None,
    ep_axis: Optional[str] = None,
    sequence_parallel: bool = False,
    return_hidden: bool = False,
    return_moe_stats: bool = False,
) -> Any:
    """[B, S] tokens -> logits (or (hidden, aux_loss) with return_hidden;
    (hidden, aux_loss, stats) with return_moe_stats too — stats holds the
    layer-mean routing scalars from ``moe_block``).

    The scalar aux loss is already coefficient-scaled and summed over
    layers (reference get_aux_loss, model_qwen3_moe.py:375-381); add it to
    the CE loss.
    """
    s = input_ids.shape[1]
    x = _llama.embed(params, input_ids, cfg, tp_axis=tp_axis,
                     sequence_parallel=sequence_parallel)
    cos, sin = get_cos_sin(s, cfg.actual_head_dim, cfg.rope_theta,
                           positions=positions)
    attn_fn = get_attention_backend(attention_backend)
    helpers = _llama.tp_region_helpers(cfg, tp_axis, sequence_parallel)

    # moe_decoder_stack keeps the scan carry's varying-axis set stable:
    # the MoE combine einsum re-marks the residual as varying over tp (the
    # combine weights come from the tp-varied router), so it pins both the
    # initial carry and the per-layer outputs to the same vma.
    x, aux_loss, moe_stats = moe_decoder_stack(
        x, params["layers"], cos, sin, cfg, attn_fn, helpers,
        tp_axis=tp_axis, ep_axis=ep_axis,
        sequence_parallel=sequence_parallel,
        gradient_checkpointing=gradient_checkpointing,
        remat_policy=remat_policy,
    )

    x = _llama.final_hidden(params, x, cfg, tp_axis=tp_axis,
                            sequence_parallel=sequence_parallel)
    if return_hidden:
        if return_moe_stats:
            return x, aux_loss, moe_stats
        return x, aux_loss
    logits = x @ _llama.lm_head_weight(params, cfg, tp_axis)
    if return_moe_stats:
        return logits, aux_loss, moe_stats
    return logits


def lm_head_weight(params: Params, cfg: Qwen3MoEConfig,
                   tp_axis: Optional[str] = None) -> jax.Array:
    return _llama.lm_head_weight(params, cfg, tp_axis)


def qwen3_moe_param_specs(
    cfg: Qwen3MoEConfig,
    *,
    tp_axis: Optional[str] = "tp",
    ep_axis: Optional[str] = "ep",
    pp_axis: Optional[str] = None,
) -> Dict[str, Any]:
    """Sharding rules: attention/embed/norm from llama_param_specs;
    experts sharded over ep on the expert dim and over tp on the
    intermediate dim (reference EP×TP composition,
    model_qwen3_moe.py:192-207); the router replicated (reference
    :192-207 keeps the gate replicated)."""
    from scaletorch_tpu.parallel.tensor_parallel import llama_param_specs

    t, ep, pstg = tp_axis, ep_axis, pp_axis
    specs = llama_param_specs(cfg, tp_axis=t, pp_axis=pstg)
    layers = specs["layers"]
    for k in ("gate_proj", "up_proj", "down_proj"):
        del layers[k]
    layers["router"] = P(pstg, None, None)
    layers["expert_gate_proj"] = P(pstg, ep, None, t)
    layers["expert_up_proj"] = P(pstg, ep, None, t)
    layers["expert_down_proj"] = P(pstg, ep, t, None)
    return specs


class Qwen3MoE:
    """OO veneer matching the reference ``Qwen3MoE`` class API."""

    config_cls = Qwen3MoEConfig

    def __init__(self, config: Qwen3MoEConfig):
        self.config = config

    def init(self, key: jax.Array) -> Params:
        return init_params(key, self.config)

    def __call__(self, params: Params, input_ids: jax.Array, **kw):
        return forward(params, input_ids, self.config, **kw)
