"""Qwen3-MoE — sparse-MLP decoder with expert parallelism.

Capability parity with reference scaletorch/models/model_qwen3_moe.py:
30-409 (MoERouter top-k gate + Switch aux loss :30-92, MoEExperts per-
expert SwiGLU :98-171, MoELayer EP dispatch path :244-288, decoder-layer
aux-loss stashing :309-322, model-level aggregation :375-381), re-designed
TPU-first:

  * experts live as stacked tensors [L, E, H, I] and run as one batched
    einsum (parallel/expert_parallel.moe_mlp) — the grouped-matmul role of
    ``npu_grouped_matmul`` (reference models/npu_patch.py:94-131) without
    a custom kernel, because XLA maps batched einsums onto the MXU;
  * token movement is capacity-based dispatch + ``lax.all_to_all`` over
    the ep mesh axis (static shapes — XLA-compatible), instead of the
    reference's ragged sort-based exchange (ep_comms.py:41-133);
  * aux losses (Switch load-balance + router z-loss) accumulate through
    the layer scan and return alongside the hidden states — the
    functional version of per-layer ``_aux_loss`` stashes + get_aux_loss.

Attention/embedding/norm are shared with Llama/Qwen3 (models/llama.py),
so TP/SP/CP compose identically; EP adds the ep axis for expert shards
and token exchange.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from scaletorch_tpu.models import llama as _llama
from scaletorch_tpu.models.layers import fan_in_uniform, get_cos_sin, rms_norm
from scaletorch_tpu.models.llama import Params
from scaletorch_tpu.models.qwen3 import Qwen3Config
from scaletorch_tpu.models.registry import get_attention_backend
from scaletorch_tpu.parallel.expert_parallel import (
    combine_routed,
    dispatch_routed,
    expert_capacity,
    moe_mlp,
    resolve_moe_dispatch,
    route_tokens,
    routed_fill_counts,
)
from scaletorch_tpu.parallel.tensor_parallel import pvary_missing


def _grouped_mlp_env_default() -> bool:
    from scaletorch_tpu.env import get_env

    return bool(get_env("SCALETORCH_TPU_GROUPED_MLP_KERNEL"))


@dataclass(frozen=True)
class Qwen3MoEConfig(Qwen3Config):
    # Qwen3-30B-A3B-style knobs (reference model_qwen3_moe.py + HF config)
    num_experts: int = 8
    num_experts_per_tok: int = 2
    moe_intermediate_size: int = 768
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.001  # router_aux_loss_coef
    z_loss_coef: float = 0.0
    norm_topk_prob: bool = True
    tie_word_embeddings: bool = False
    # Interleaved dense/sparse architecture knobs (HF Qwen3MoeConfig):
    # layer i runs a dense SwiGLU MLP (intermediate_size) instead of the
    # MoE block when i is in mlp_only_layers OR (i+1) % decoder_sparse_step
    # != 0 — the exact HF predicate (modeling_qwen3_moe.Qwen3MoeDecoderLayer).
    mlp_only_layers: Tuple[int, ...] = ()
    decoder_sparse_step: int = 1
    # Token-movement implementation for the capacity dispatch. 'einsum' =
    # GShard one-hot einsums (dense MXU work, O(N·E·C·H) MACs — fine at
    # small E); 'index' = scatter/gather of exactly the O(N·k·H) moving
    # rows (at Qwen3-30B-A3B scale, E=128/top-8, the one-hot einsums cost
    # ~4.5x the expert matmuls themselves). 'auto' picks 'index' at every
    # expert count — the one-hot cost is E-independent (E*C = N*k*cf) and
    # always the larger compile (AOT_DISPATCH_CROSSOVER.json). Both
    # compute identical math (same drops, same weights).
    moe_dispatch: str = "auto"
    # Slot-skipping Pallas expert kernel (ops/pallas/grouped_mlp.py). The
    # env toggle is read ONCE, at config construction (host side) — never
    # at trace time inside the jitted model, so two models with different
    # settings coexist in one process and post-compile env flips are
    # (correctly) inert. Pass the field explicitly to override the env.
    use_grouped_mlp_kernel: bool = field(
        default_factory=lambda: _grouped_mlp_env_default())

    def __post_init__(self) -> None:
        # frozen dataclass: coerce a list argument to a hashable tuple
        object.__setattr__(self, "mlp_only_layers",
                           tuple(self.mlp_only_layers))
        if self.moe_dispatch not in ("auto", "einsum", "index"):
            raise ValueError(
                f"moe_dispatch must be 'auto', 'einsum' or 'index', got "
                f"{self.moe_dispatch!r}"
            )
        if self.decoder_sparse_step < 1:
            raise ValueError(
                f"decoder_sparse_step must be >= 1, got "
                f"{self.decoder_sparse_step}"
            )
        bad = [i for i in self.mlp_only_layers
               if not 0 <= i < self.num_hidden_layers]
        if bad:
            raise ValueError(
                f"mlp_only_layers indices {bad} out of range for "
                f"{self.num_hidden_layers} layers"
            )
        if not any(self.sparse_layout()):
            raise ValueError(
                "no layer is sparse under mlp_only_layers="
                f"{self.mlp_only_layers} / decoder_sparse_step="
                f"{self.decoder_sparse_step}; use the dense Qwen3 family "
                "instead"
            )

    # ---- interleaved dense/sparse layout helpers -------------------------

    def layer_is_sparse(self, layer_idx: int) -> bool:
        """HF parity predicate (modeling_qwen3_moe.Qwen3MoeDecoderLayer):
        sparse iff not an mlp-only layer AND (idx+1) divisible by
        decoder_sparse_step."""
        return (
            layer_idx not in self.mlp_only_layers
            and self.num_experts > 0
            and (layer_idx + 1) % self.decoder_sparse_step == 0
        )

    def sparse_layout(self) -> Tuple[bool, ...]:
        return tuple(
            self.layer_is_sparse(i) for i in range(self.num_hidden_layers)
        )

    @property
    def is_uniform_sparse(self) -> bool:
        return all(self.sparse_layout())

    def resolved_moe_dispatch(self) -> str:
        # single source of truth for the auto crossover:
        # expert_parallel.resolve_moe_dispatch
        return resolve_moe_dispatch(self.moe_dispatch, self.num_experts)

    def sparse_layer_ids(self) -> Tuple[int, ...]:
        return tuple(i for i, s in enumerate(self.sparse_layout()) if s)

    def dense_layer_ids(self) -> Tuple[int, ...]:
        return tuple(i for i, s in enumerate(self.sparse_layout()) if not s)

    def moe_segments(self) -> Tuple[Tuple[bool, int, int], ...]:
        """Contiguous (is_sparse, lo, hi) runs of same-kind layers — the
        scan segments of the interleaved forward (each segment is one
        ``lax.scan`` over its sliced layer stack)."""
        layout = self.sparse_layout()
        segs = []
        lo = 0
        for i in range(1, len(layout) + 1):
            if i == len(layout) or layout[i] != layout[lo]:
                segs.append((layout[lo], lo, i))
                lo = i
        return tuple(segs)

    @classmethod
    def from_hf(cls, hf_config, **overrides) -> "Qwen3MoEConfig":
        kw = dict(
            num_experts=getattr(hf_config, "num_experts", 8),
            num_experts_per_tok=getattr(hf_config, "num_experts_per_tok", 2),
            moe_intermediate_size=getattr(hf_config, "moe_intermediate_size", 768),
            norm_topk_prob=getattr(hf_config, "norm_topk_prob", True),
            mlp_only_layers=tuple(
                getattr(hf_config, "mlp_only_layers", None) or ()),
            decoder_sparse_step=getattr(hf_config, "decoder_sparse_step", 1)
            or 1,
        )
        kw.update(overrides)
        return super().from_hf(hf_config, **kw)

    def num_params(self) -> int:
        h, v = self.hidden_size, self.vocab_size
        n_sparse = sum(self.sparse_layout())
        n_dense = self.num_hidden_layers - n_sparse
        attn = h * self.q_size + 2 * h * self.kv_size + self.q_size * h
        moe = self.num_experts * 3 * h * self.moe_intermediate_size
        dense_mlp = 3 * h * self.intermediate_size
        router = h * self.num_experts
        norms = 2 * h + (2 * self.actual_head_dim if self.qk_norm else 0)
        per_common = attn + norms
        head = 0 if self.tie_word_embeddings else v * h
        return (
            self.num_hidden_layers * per_common
            + n_sparse * (moe + router)
            + n_dense * dense_mlp
            + v * h + h + head
        )

    def num_active_params(self) -> int:
        """Active parameters per token (top-k experts on sparse layers,
        the full MLP on dense layers) — the MFU denominator the reference
        uses for MoE tables (README.md:131)."""
        h, v = self.hidden_size, self.vocab_size
        n_sparse = sum(self.sparse_layout())
        n_dense = self.num_hidden_layers - n_sparse
        attn = h * self.q_size + 2 * h * self.kv_size + self.q_size * h
        moe = self.num_experts_per_tok * 3 * h * self.moe_intermediate_size
        dense_mlp = 3 * h * self.intermediate_size
        router = h * self.num_experts
        norms = 2 * h + (2 * self.actual_head_dim if self.qk_norm else 0)
        head = 0 if self.tie_word_embeddings else v * h
        return (
            self.num_hidden_layers * (attn + norms)
            + n_sparse * (moe + router)
            + n_dense * dense_mlp
            + v * h + h + head
        )


def init_params(key: jax.Array, cfg: Qwen3MoEConfig) -> Params:
    """Dense attention params from the Llama initializer (mlp=False); MoE
    params take the dense MLP keys' place.

    Stacked layout: attention/norm keys span ALL layers [L, ...]; the MoE
    keys are stacked over the SPARSE layer subset [L_sparse, ...] and —
    for interleaved dense/sparse configs (mlp_only_layers /
    decoder_sparse_step, HF Qwen3MoeConfig) — the dense SwiGLU keys over
    the DENSE subset [L_dense, H, intermediate_size]. All-sparse configs
    (L_sparse == L, no dense keys) keep the round-1 layout unchanged.
    """
    h, e = cfg.hidden_size, cfg.num_experts
    i = cfg.moe_intermediate_size
    ls = len(cfg.sparse_layer_ids())
    ld = cfg.num_hidden_layers - ls
    pd = cfg.param_dtype
    base = _llama.init_params(key, cfg, mlp=False)
    layers = base["layers"]
    keys = jax.random.split(jax.random.fold_in(key, 7), 7)

    def expert_stack(k, shape, fan_in):
        # one batched draw: fan-in-uniform bounds depend only on fan_in,
        # so [L, E, ...] in a single RNG call is distributionally identical
        return fan_in_uniform(k, (ls, e) + shape, fan_in, pd)

    layers["router"] = 0.02 * jax.random.normal(keys[0], (ls, h, e), pd)
    layers["expert_gate_proj"] = expert_stack(keys[1], (h, i), h)
    layers["expert_up_proj"] = expert_stack(keys[2], (h, i), h)
    layers["expert_down_proj"] = expert_stack(keys[3], (i, h), i)
    if ld:
        di = cfg.intermediate_size
        layers["gate_proj"] = fan_in_uniform(keys[4], (ld, h, di), h, pd)
        layers["up_proj"] = fan_in_uniform(keys[5], (ld, h, di), h, pd)
        layers["down_proj"] = fan_in_uniform(keys[6], (ld, di, h), di, pd)
    return base


def moe_block(
    x: jax.Array,
    layer: Params,
    cfg: Qwen3MoEConfig,
    helpers: Tuple[Callable, Callable, Callable, Callable],
    *,
    ep_axis: Optional[str] = None,
    tp_axis: Optional[str] = None,
    sequence_parallel: bool = False,
) -> Tuple[jax.Array, jax.Array, dict]:
    """Post-attention MoE sub-block with residual.
    Returns (x, aux_loss, stats) — stats carries the per-step routing
    health scalars the operator must see (VERDICT r1 weak #5):
    ``dropped_fraction`` (tokens beyond capacity) and ``load_cv``
    (coefficient of variation of expert load; 0 = perfectly balanced).

    Reference MoELayer.forward (model_qwen3_moe.py:210-288): router ->
    dispatch -> experts -> gather -> top-k sum, with the EP path active
    when ep_axis is set.
    """
    pv, enter_full_seq, _, _ = helpers
    h_norm = rms_norm(x, pv(layer["post_attention_layernorm"]), cfg.rms_norm_eps)
    h_full = enter_full_seq(h_norm)  # [B, S, H]
    b, s, hid = h_full.shape

    # Router in fp32 (reference router runs in fp32 for gate stability).
    # Each batch row routes as its own group (GShard-style grouping): the
    # [G, S, E, C] dispatch/combine tensors stay O(tokens·S·k) instead of
    # the O(tokens²·k) a flat [N, E, C] would cost.
    logits = jnp.einsum(
        "gsh,he->gse",
        h_full.astype(jnp.float32),
        pv(layer["router"]).astype(jnp.float32),
    )
    cap = expert_capacity(
        s, cfg.num_experts, cfg.num_experts_per_tok, cfg.capacity_factor
    )
    # Mode-aware movement API (expert_parallel.route_tokens & co):
    # 'einsum' = GShard one-hot, 'index' = O(N·k·H) scatter/gather —
    # identical math; 'auto' resolves to index at every expert count
    # (the one-hot cost is E-independent and always the larger compile —
    # AOT_DISPATCH_CROSSOVER.json, resolve_moe_dispatch).
    mode = cfg.resolved_moe_dispatch()
    state, aux = jax.vmap(
        lambda lg: route_tokens(
            lg, cfg.num_experts_per_tok, cap, mode=mode,
            normalize_weights=cfg.norm_topk_prob,
        )
    )(logits)
    slots = dispatch_routed(
        h_full, state, mode=mode, num_experts=cfg.num_experts,
        capacity=cap, axis=ep_axis)
    aux = {k: jnp.mean(v, axis=0) for k, v in aux.items()}  # mean over groups
    kernel_extra = {}
    if cfg.use_grouped_mlp_kernel:
        # slot-skipping expert kernel: per-(expert, group) fill counts
        # ride the same exchange layout as the slots
        from scaletorch_tpu.parallel.expert_parallel import (
            exchange_slot_counts,
        )

        kernel_extra = dict(
            slot_counts=exchange_slot_counts(
                routed_fill_counts(state, mode=mode,
                                   num_experts=cfg.num_experts,
                                   capacity=cap),
                ep_axis),
            capacity=cap,
        )
    out = moe_mlp(
        slots,
        layer["expert_gate_proj"],
        layer["expert_up_proj"],
        layer["expert_down_proj"],
        tp_axis=tp_axis,
        compute_dtype=cfg.dtype,
        reduce="none" if sequence_parallel else "sum",
        **kernel_extra,
    )
    y = combine_routed(
        out, state, mode=mode, num_experts=cfg.num_experts,
        capacity=cap, axis=ep_axis)  # [B, S, H]
    if sequence_parallel:
        # Expert outputs are still tp-partial (reduce='none'); complete the
        # sum with the reduce-scatter that re-enters the SP region — the
        # same fusion the dense row-parallel path uses (sp_comms.py:64-94).
        from scaletorch_tpu.parallel.sequence_parallel import reduce_scatter_sequence

        y = reduce_scatter_sequence(y, tp_axis)
    aux_total = (
        cfg.aux_loss_coef * aux["aux_loss"] + cfg.z_loss_coef * aux["z_loss"]
    )
    load = aux["expert_load"]  # [E], sums to top_k
    stats = {
        "moe_dropped_fraction": aux["dropped_fraction"],
        "moe_load_cv": jnp.std(load) / jnp.maximum(jnp.mean(load), 1e-9),
    }
    return x + y.astype(x.dtype), aux_total, stats


def moe_decoder_stack(
    x: jax.Array,
    layers: Params,
    cos: jax.Array,
    sin: jax.Array,
    cfg: Qwen3MoEConfig,
    attn_fn: Callable,
    helpers,
    *,
    tp_axis: Optional[str] = None,
    ep_axis: Optional[str] = None,
    sequence_parallel: bool = False,
    gradient_checkpointing: bool = False,
    remat_policy: str = "nothing_saveable",
    active_layers: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, dict]:
    """Scan attention+MoE layers over a stacked layer block; returns
    (hidden, aux_loss_sum, stats_layer_mean). The MoE counterpart of
    llama.decoder_stack, shared by the full forward and by one pipeline
    stage's compute (where ``layers`` is the pp-sharded [L/pp, ...] block).
    ``active_layers`` masks identity padding slots exactly like
    llama.decoder_stack (uneven pipeline stages): padded slots forward
    ``h`` and contribute zero aux/stats."""
    extra = tuple(a for a in (tp_axis, ep_axis) if a)
    x = pvary_missing(x, extra) if extra else x

    def layer_body(h, xs):
        layer_params, idx = xs
        out = _llama.attention_block(h, layer_params, cos, sin, cfg, attn_fn,
                                     helpers)
        out, aux, stats = moe_block(
            out, layer_params, cfg, helpers,
            ep_axis=ep_axis, tp_axis=tp_axis,
            sequence_parallel=sequence_parallel,
        )
        if active_layers is not None:
            live = idx < active_layers
            out = jnp.where(live, out, h)
            aux = jnp.where(live, aux, 0.0)
            stats = jax.tree.map(lambda v: jnp.where(live, v, 0.0), stats)
        if extra:
            out, aux = pvary_missing(out, extra), pvary_missing(aux, extra)
            stats = jax.tree.map(lambda v: pvary_missing(v, extra), stats)
        return out, (aux, stats)

    if gradient_checkpointing:
        layer_body = jax.checkpoint(
            layer_body, policy=_llama.resolve_remat_policy(remat_policy)
        )

    x, (aux_per_layer, stats_per_layer) = jax.lax.scan(
        layer_body, x,
        (layers, _llama.scan_slot_indices(layers, active_layers)))
    aux_loss = jnp.sum(aux_per_layer)
    if active_layers is None:
        moe_stats = jax.tree.map(lambda v: jnp.mean(v, axis=0), stats_per_layer)
    else:
        # mean over REAL layers only — padded slots contributed zeros
        denom = jnp.maximum(active_layers.astype(jnp.float32), 1.0)
        moe_stats = jax.tree.map(
            lambda v: jnp.sum(v, axis=0) / denom, stats_per_layer)
    return x, aux_loss, moe_stats


_ATTN_KEYS = (
    "input_layernorm", "q_proj", "k_proj", "v_proj", "o_proj",
    "post_attention_layernorm", "q_norm", "k_norm",
)
_MOE_KEYS = ("router", "expert_gate_proj", "expert_up_proj",
             "expert_down_proj")
_DENSE_KEYS = ("gate_proj", "up_proj", "down_proj")


def interleaved_decoder_stack(
    x: jax.Array,
    layers: Params,
    cos: jax.Array,
    sin: jax.Array,
    cfg: Qwen3MoEConfig,
    attn_fn: Callable,
    helpers,
    *,
    tp_axis: Optional[str] = None,
    ep_axis: Optional[str] = None,
    sequence_parallel: bool = False,
    gradient_checkpointing: bool = False,
    remat_policy: str = "nothing_saveable",
) -> Tuple[jax.Array, jax.Array, dict]:
    """Mixed dense/sparse decoder (HF ``mlp_only_layers`` /
    ``decoder_sparse_step`` architectures, modeling_qwen3_moe
    Qwen3MoeDecoderLayer; reference checkpoint mapping is generic over
    these configs, utils/checkpoint.py:425-464).

    TPU-first shape: the layer sequence is cut into contiguous same-kind
    segments (``cfg.moe_segments()``) and each segment runs as ONE
    ``lax.scan`` over its sliced parameter stack — compile time stays
    O(#segments), not O(L), and each segment body is the already-optimised
    uniform scan (``moe_decoder_stack`` / ``llama.decoder_stack``). Slices
    are static (config-derived), so XLA sees plain constant-offset views
    of the stacked weights. A dense segment is exactly the Llama SwiGLU
    body, so TP/SP compose identically; sparse segments add EP.

    Returns (hidden, aux_loss_sum, stats) with stats averaged over SPARSE
    layers only (dense layers have no routing health to report).
    """
    aux_total = jnp.float32(0.0)
    stats_sum: dict = {}
    n_sparse = 0
    d_off = s_off = 0
    for is_sparse, lo, hi in cfg.moe_segments():
        n = hi - lo
        attn_slice = {
            k: layers[k][lo:hi] for k in _ATTN_KEYS if k in layers
        }
        if is_sparse:
            seg = dict(attn_slice, **{
                k: layers[k][s_off:s_off + n] for k in _MOE_KEYS})
            x, aux, stats = moe_decoder_stack(
                x, seg, cos, sin, cfg, attn_fn, helpers,
                tp_axis=tp_axis, ep_axis=ep_axis,
                sequence_parallel=sequence_parallel,
                gradient_checkpointing=gradient_checkpointing,
                remat_policy=remat_policy,
            )
            aux_total = aux_total + aux
            # moe_decoder_stack returns per-segment layer means; recombine
            # weighted by segment length for the model-level mean
            for k, v in stats.items():
                stats_sum[k] = stats_sum.get(k, 0.0) + n * v
            n_sparse += n
            s_off += n
        else:
            seg = dict(attn_slice, **{
                k: layers[k][d_off:d_off + n] for k in _DENSE_KEYS})
            x = _llama.decoder_stack(
                x, seg, cos, sin, cfg, attn_fn,
                tp_axis=tp_axis, sequence_parallel=sequence_parallel,
                gradient_checkpointing=gradient_checkpointing,
                remat_policy=remat_policy,
            )
            extra = tuple(a for a in (tp_axis, ep_axis) if a)
            if extra:
                # keep the carry's varying-axis set stable across segment
                # kinds (the sparse segments pin (tp, ep))
                x = pvary_missing(x, extra)
            d_off += n
    stats = {k: v / n_sparse for k, v in stats_sum.items()}
    return x, aux_total, stats


def forward(
    params: Params,
    input_ids: jax.Array,
    cfg: Qwen3MoEConfig,
    *,
    positions: Optional[jax.Array] = None,
    attention_backend: str = "sdpa",
    gradient_checkpointing: bool = False,
    remat_policy: str = "nothing_saveable",
    tp_axis: Optional[str] = None,
    ep_axis: Optional[str] = None,
    sequence_parallel: bool = False,
    return_hidden: bool = False,
    return_moe_stats: bool = False,
) -> Any:
    """[B, S] tokens -> logits (or (hidden, aux_loss) with return_hidden;
    (hidden, aux_loss, stats) with return_moe_stats too — stats holds the
    layer-mean routing scalars from ``moe_block``).

    The scalar aux loss is already coefficient-scaled and summed over
    layers (reference get_aux_loss, model_qwen3_moe.py:375-381); add it to
    the CE loss.
    """
    s = input_ids.shape[1]
    x = _llama.embed(params, input_ids, cfg, tp_axis=tp_axis,
                     sequence_parallel=sequence_parallel)
    cos, sin = get_cos_sin(s, cfg.actual_head_dim, cfg.rope_theta,
                           positions=positions)
    attn_fn = get_attention_backend(attention_backend)
    helpers = _llama.tp_region_helpers(cfg, tp_axis, sequence_parallel)

    # moe_decoder_stack keeps the scan carry's varying-axis set stable:
    # the MoE combine einsum re-marks the residual as varying over tp (the
    # combine weights come from the tp-varied router), so it pins both the
    # initial carry and the per-layer outputs to the same vma.
    stack = (moe_decoder_stack if cfg.is_uniform_sparse
             else interleaved_decoder_stack)
    x, aux_loss, moe_stats = stack(
        x, params["layers"], cos, sin, cfg, attn_fn, helpers,
        tp_axis=tp_axis, ep_axis=ep_axis,
        sequence_parallel=sequence_parallel,
        gradient_checkpointing=gradient_checkpointing,
        remat_policy=remat_policy,
    )

    x = _llama.final_hidden(params, x, cfg, tp_axis=tp_axis,
                            sequence_parallel=sequence_parallel)
    if return_hidden:
        if return_moe_stats:
            return x, aux_loss, moe_stats
        return x, aux_loss
    logits = x @ _llama.lm_head_weight(params, cfg, tp_axis)
    if return_moe_stats:
        return logits, aux_loss, moe_stats
    return logits


def lm_head_weight(params: Params, cfg: Qwen3MoEConfig,
                   tp_axis: Optional[str] = None) -> jax.Array:
    return _llama.lm_head_weight(params, cfg, tp_axis)


def forward_cached(
    params: Params,
    input_ids: jax.Array,
    cfg: Qwen3MoEConfig,
    cache,
    *,
    positions: jax.Array,
    write_mask: Optional[jax.Array] = None,
    kv_io: Optional[Any] = None,
):
    """KV-cached MoE decoder forward for the decode engine
    (inference/decode.py): [B, S] tokens at absolute ``positions`` [B, S]
    -> (logits [B, S, V], new (cache_k, cache_v)).

    Attention is the shared cache-aware Llama block; the MoE FFN is
    stateless, so it runs the standard capacity-based dispatch per call
    (a decode step routes one token per slot — capacity 1, never
    dropped). Routing at decode considers each token alone, so configs
    that DROP tokens in full-sequence routing (capacity < S·k/E worst
    case) can emit slightly different logits at decode than teacher
    forcing; with a dropless capacity_factor (>= E/top_k) prefill and
    decode match the training forward exactly. Uniform-sparse layouts
    only — interleaved dense/sparse configs have per-kind layer stacks
    that do not align with one scanned cache.
    """
    if not cfg.is_uniform_sparse:
        raise NotImplementedError(
            "forward_cached supports uniform-sparse Qwen3-MoE configs; "
            f"this one interleaves dense layers {cfg.dense_layer_ids()} "
            "(mlp_only_layers/decoder_sparse_step) — serve it with the "
            "dense Qwen3 family or extend the cache to per-kind stacks"
        )
    cache_k, cache_v = cache
    x = _llama.embed(params, input_ids, cfg)
    cos, sin = get_cos_sin(
        input_ids.shape[1], cfg.actual_head_dim, cfg.rope_theta,
        positions=positions,
    )
    helpers = _llama.tp_region_helpers(cfg, None, False)

    def layer_body(h, xs):
        layer, ck, cv = xs
        h, ck, cv = _llama.attention_block_cached(
            h, layer, ck, cv, cos, sin, positions, cfg,
            write_mask=write_mask, kv_io=kv_io,
        )
        h, _aux, _stats = moe_block(h, layer, cfg, helpers)
        return h, (ck, cv)

    x, (k_new, v_new) = jax.lax.scan(
        layer_body, x, (params["layers"], cache_k, cache_v)
    )
    x = rms_norm(x, params["norm"], cfg.rms_norm_eps)
    logits = x @ _llama.lm_head_weight(params, cfg)
    return logits, (k_new, v_new)


def qwen3_moe_param_specs(
    cfg: Qwen3MoEConfig,
    *,
    tp_axis: Optional[str] = "tp",
    ep_axis: Optional[str] = "ep",
    pp_axis: Optional[str] = None,
) -> Dict[str, Any]:
    """Sharding rules: attention/embed/norm from llama_param_specs;
    experts sharded over ep on the expert dim and over tp on the
    intermediate dim (reference EP×TP composition,
    model_qwen3_moe.py:192-207); the router replicated (reference
    :192-207 keeps the gate replicated).

    Interleaved dense/sparse configs keep the dense SwiGLU specs from
    llama_param_specs for their [L_dense, ...] stacks; PP is not
    composable there (the MoE/dense stacks' leading axes are layer
    SUBSETS, which do not align with a pp-sharded attention stack)."""
    from scaletorch_tpu.parallel.tensor_parallel import llama_param_specs

    t, ep, pstg = tp_axis, ep_axis, pp_axis
    if not cfg.is_uniform_sparse and pstg is not None:
        raise NotImplementedError(
            "pipeline parallelism over an interleaved dense/sparse "
            "Qwen3-MoE is not supported: the per-kind layer stacks "
            f"(sparse {cfg.sparse_layer_ids()}, dense "
            f"{cfg.dense_layer_ids()}) do not align with a pp-sharded "
            "stacked layer axis — run this architecture with pp=1"
        )
    specs = llama_param_specs(cfg, tp_axis=t, pp_axis=pstg)
    layers = specs["layers"]
    if cfg.is_uniform_sparse:
        for k in ("gate_proj", "up_proj", "down_proj"):
            del layers[k]
    layers["router"] = P(pstg, None, None)
    layers["expert_gate_proj"] = P(pstg, ep, None, t)
    layers["expert_up_proj"] = P(pstg, ep, None, t)
    layers["expert_down_proj"] = P(pstg, ep, t, None)
    return specs


class Qwen3MoE:
    """OO veneer matching the reference ``Qwen3MoE`` class API."""

    config_cls = Qwen3MoEConfig

    def __init__(self, config: Qwen3MoEConfig):
        self.config = config

    def init(self, key: jax.Array) -> Params:
        return init_params(key, self.config)

    def __call__(self, params: Params, input_ids: jax.Array, **kw):
        return forward(params, input_ids, self.config, **kw)
