"""Attention backend registry.

Parity with reference scaletorch/models/attention_utils.py:33-64:
``register_attention_backend``/``get_attention_backend`` plus the same
resolution order — context parallel forces ``ring``, the FLASH_ATTEN env
toggle selects ``flash``, otherwise ``sdpa`` (attention_utils.py:56-64).

A backend is a callable ``fn(q, k, v, *, causal, scale, **kw) -> out`` with
q/k/v shaped ``[batch, heads, seq, head_dim]`` (kv heads may differ from q
heads; backends handle GQA expansion themselves or expect pre-expanded kv).
"""

from __future__ import annotations

from typing import Callable, Dict

from scaletorch_tpu.env import get_env

_BACKENDS: Dict[str, Callable] = {}


def register_attention_backend(name: str, fn: Callable = None):
    """Register an attention implementation. Usable as a decorator."""

    def _register(f: Callable) -> Callable:
        _BACKENDS[name] = f
        return f

    if fn is not None:
        return _register(fn)
    return _register


def get_attention_backend(name: str) -> Callable:
    if name not in _BACKENDS:
        raise KeyError(
            f"attention backend {name!r} not registered; have {sorted(_BACKENDS)}"
        )
    return _BACKENDS[name]


def resolve_attention_backend(
    requested: str = "auto", context_parallel: bool = False
) -> str:
    """Resolution order parity: CP -> ring, FLASH_ATTEN -> flash, else sdpa."""
    if requested != "auto":
        return requested
    if context_parallel or get_env("CONTEXT_PARALLEL"):
        return "ring"
    if get_env("FLASH_ATTEN") and not get_env("SCALETORCH_TPU_DISABLE_PALLAS"):
        return "flash"
    return "sdpa"


def registered_backends() -> list[str]:
    return sorted(_BACKENDS)
