"""Functional ResNet (18/34, basic blocks) — the reference imagenet
example's model family (examples/torch_examples/imagenet/dist_train.py:24-44
``torchvision.models`` resnet18 default), TPU-native: NHWC layout,
``lax.conv_general_dilated`` (channels-last is the MXU-friendly layout),
BatchNorm as explicit functional state threaded through ``forward`` —
train mode computes batch statistics over the WHOLE (possibly
mesh-sharded) batch and returns updated running stats; eval mode
consumes the running stats. Under GSPMD with the batch sharded over a
data axis, the stat reductions become cross-device all-reduces — i.e.
sync-BN (torch's SyncBatchNorm), not DDP's default local-BN: stats are
batch-size-exact regardless of the device count.

Static Python loops over blocks (8 for r18, 16 for r34) — shapes differ
per stage, so a ``lax.scan`` over stacked layers (the LLM trick) does not
apply; XLA unrolls and fuses the short chain.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]

_DEPTHS = {18: (2, 2, 2, 2), 34: (3, 4, 6, 3)}


@dataclass
class ResNetConfig:
    depth: int = 18
    num_classes: int = 1000
    width: int = 64          # stem channels; stages use width * (1,2,4,8)
    image_size: int = 224
    bn_momentum: float = 0.1
    bn_eps: float = 1e-5
    dtype: Any = jnp.float32

    @property
    def stage_blocks(self) -> Tuple[int, ...]:
        if self.depth not in _DEPTHS:
            raise ValueError(f"depth must be one of {sorted(_DEPTHS)}")
        return _DEPTHS[self.depth]

    def num_params(self) -> int:
        return sum(p.size for p in jax.tree.leaves(
            init_params(jax.random.key(0), self)[0]))


def _conv_init(key, kh, kw, cin, cout):
    # Kaiming-normal fan_out (torchvision resnet init)
    std = (2.0 / (kh * kw * cout)) ** 0.5
    return std * jax.random.normal(key, (kh, kw, cin, cout), jnp.float32)


def _bn_params(c):
    return {"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))}


def _bn_state(c):
    return {"mean": jnp.zeros((c,)), "var": jnp.ones((c,))}


def init_params(key: jax.Array, cfg: ResNetConfig) -> Tuple[Params, Params]:
    """(params, bn_state) for the functional forward."""
    keys = iter(jax.random.split(key, 128))
    w = cfg.width
    params: Params = {"stem": {"conv": _conv_init(next(keys), 7, 7, 3, w),
                               "bn": _bn_params(w)}}
    state: Params = {"stem": _bn_state(w)}
    cin = w
    for si, nblocks in enumerate(cfg.stage_blocks):
        cout = w * (2 ** si)
        blocks: List[Params] = []
        bstates: List[Params] = []
        for bi in range(nblocks):
            stride = 2 if (si > 0 and bi == 0) else 1
            blk = {
                "conv1": _conv_init(next(keys), 3, 3, cin, cout),
                "bn1": _bn_params(cout),
                "conv2": _conv_init(next(keys), 3, 3, cout, cout),
                "bn2": _bn_params(cout),
            }
            bst = {"bn1": _bn_state(cout), "bn2": _bn_state(cout)}
            if stride != 1 or cin != cout:
                blk["down_conv"] = _conv_init(next(keys), 1, 1, cin, cout)
                blk["down_bn"] = _bn_params(cout)
                bst["down_bn"] = _bn_state(cout)
            blocks.append(blk)
            bstates.append(bst)
            cin = cout
        params[f"stage{si}"] = blocks
        state[f"stage{si}"] = bstates
    fc_in = w * 8
    bound = 1.0 / fc_in ** 0.5
    params["fc"] = {
        "kernel": jax.random.uniform(next(keys), (fc_in, cfg.num_classes),
                                     jnp.float32, -bound, bound),
        "bias": jnp.zeros((cfg.num_classes,)),
    }
    return params, state


def _conv(x, w, stride=1, dtype=jnp.float32):
    return jax.lax.conv_general_dilated(
        x.astype(dtype), w.astype(dtype),
        window_strides=(stride, stride),
        padding="SAME" if w.shape[0] > 1 else "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _bn(x, p, s, cfg, train: bool):
    """Returns (y, new_state). Train: batch stats over the full (global)
    batch — sync-BN under a sharded mesh — + fp32 EMA of running stats."""
    x32 = x.astype(jnp.float32)
    if train:
        mean = jnp.mean(x32, axis=(0, 1, 2))
        var = jnp.var(x32, axis=(0, 1, 2))
        m = cfg.bn_momentum
        new_s = {"mean": (1 - m) * s["mean"] + m * mean,
                 "var": (1 - m) * s["var"] + m * var}
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    y = (x32 - mean) * jax.lax.rsqrt(var + cfg.bn_eps)
    y = y * p["scale"] + p["bias"]
    return y.astype(x.dtype), new_s


def forward(
    params: Params,
    state: Params,
    images: jax.Array,
    cfg: ResNetConfig,
    *,
    train: bool = True,
) -> Tuple[jax.Array, Params]:
    """images [N, H, W, 3] -> (logits [N, classes], new_bn_state)."""
    x = images.astype(cfg.dtype)
    new_state: Params = {}
    x = _conv(x, params["stem"]["conv"], stride=2, dtype=cfg.dtype)
    x, new_state["stem"] = _bn(x, params["stem"]["bn"], state["stem"],
                               cfg, train)
    x = jax.nn.relu(x)
    # 3x3 stride-2 max pool
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )
    for si in range(len(cfg.stage_blocks)):
        blocks = params[f"stage{si}"]
        bstates = state[f"stage{si}"]
        new_bstates = []
        for bi, (blk, bst) in enumerate(zip(blocks, bstates)):
            stride = 2 if (si > 0 and bi == 0) else 1
            nst = {}
            out = _conv(x, blk["conv1"], stride=stride, dtype=cfg.dtype)
            out, nst["bn1"] = _bn(out, blk["bn1"], bst["bn1"], cfg, train)
            out = jax.nn.relu(out)
            out = _conv(out, blk["conv2"], stride=1, dtype=cfg.dtype)
            out, nst["bn2"] = _bn(out, blk["bn2"], bst["bn2"], cfg, train)
            if "down_conv" in blk:
                identity = _conv(x, blk["down_conv"], stride=stride,
                                 dtype=cfg.dtype)
                identity, nst["down_bn"] = _bn(
                    identity, blk["down_bn"], bst["down_bn"], cfg, train)
            else:
                identity = x
            x = jax.nn.relu(out + identity)
            new_bstates.append(nst)
        new_state[f"stage{si}"] = new_bstates
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))  # global avg pool
    logits = x @ params["fc"]["kernel"] + params["fc"]["bias"]
    return logits, new_state
