"""Compute kernels: flash attention (Pallas), ring attention, grouped matmul.

Importing this package registers the 'flash' and (once built) 'ring'
attention backends, mirroring the reference registering its backends at
model import (reference models/llama.py:38-57).
"""

from scaletorch_tpu.ops.flash_attention import flash_attention  # noqa: F401
from scaletorch_tpu.ops.pallas.grouped_mlp import grouped_swiglu_mlp  # noqa: F401
from scaletorch_tpu.ops.quantized_collectives import (  # noqa: F401
    dequantize_blockwise,
    quantize_blockwise,
    quantized_pmean,
    quantized_pmean_tree,
)
from scaletorch_tpu.ops.ring_attention import ring_attention  # noqa: F401
from scaletorch_tpu.ops.ulysses import ulysses_attention  # noqa: F401
