"""Flash attention backend — Pallas blockwise kernel on TPU.

The role the reference fills with flash-attn 2 / Ascend's
``npu_flash_attn_func`` (reference models/attention_utils.py:72-122) is on
TPU a Pallas blockwise-softmax kernel: QK^T tiles stream through VMEM with
running-max/sum accumulation, so the O(S^2) score matrix never
materialises in HBM, and the custom VJP recomputes tiles in the backward.
The kernel lives in scaletorch_tpu/ops/pallas/flash.py (GQA-aware — KV
heads are read unexpanded via index maps); this module is the dispatch
surface, with an XLA softmax fallback on CPU (tests) or when
``SCALETORCH_TPU_DISABLE_PALLAS=1``.
"""

from __future__ import annotations

import math
from typing import Optional

import jax

from scaletorch_tpu.env import get_env
from scaletorch_tpu.models.layers import sdpa_attention
from scaletorch_tpu.models.registry import register_attention_backend


def _pallas_available() -> bool:
    if get_env("SCALETORCH_TPU_DISABLE_PALLAS"):
        return False
    if get_env("SCALETORCH_TPU_FORCE_PALLAS"):
        return True
    # is_tpu() recognises chips behind remote-execution PJRT plugins too —
    # a bare ``platform == "tpu"`` check would silently drop REAL TPU
    # hardware to the score-materialising SDPA fallback (34.6 GB of
    # [L,B,H,S,S] scores at 0.6B/seq2048/bs2 per tools/aot_memory.py).
    from scaletorch_tpu.utils.device import is_tpu

    try:
        return is_tpu()
    except Exception:  # AOT compile-only session: no local devices
        return False


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """[B, Hq, S, D] x [B, Hkv, S, D]^2 -> [B, Hq, S, D]."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if _pallas_available():
        from scaletorch_tpu.ops.pallas.flash import pallas_flash_attention

        # tile sizes resolve from SCALETORCH_TPU_FLASH_BLOCK_Q/KV inside
        # the kernel entry (pallas/flash.py _resolve_blocks), shared with
        # the ring-attention composition path
        return pallas_flash_attention(q, k, v, causal=causal, scale=scale)
    return sdpa_attention(q, k, v, causal=causal, scale=scale)


register_attention_backend("flash", flash_attention)


def flash_attention_jax(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """JAX's reference TPU flash kernel as an alternative backend.

    ``jax.experimental.pallas.ops.tpu.flash_attention`` is the
    public, heavily-tuned Mosaic implementation — registering it as
    ``flash_jax`` gives the benchmark an on-chip A/B partner for the
    in-repo kernel (ops/pallas/flash.py), the same role the reference's
    backend registry plays between its sdpa / flash-attn / npu paths
    (reference models/attention_utils.py:56-70). It predates GQA index
    maps, so grouped K/V heads (layout ``[B, Hkv, S, D]``) are expanded
    to ``[B, Hq, S, D]`` here — post-expansion K/V memory and DMA
    traffic scale with Hq, not Hkv (n_rep x larger: ~0.5 GB at
    0.6B/seq8192 with Hq=14/Hkv=2 bf16). Acceptable for an A/B probe;
    the in-repo kernel's unexpanded Hkv reads stay the default.

    Off-TPU (CPU tests, AOT-less sessions) falls back to SDPA like the
    ``flash`` backend does.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if q.shape[1] % k.shape[1]:
        # mirror the explicit guard the in-repo Pallas entry points raise
        # (pallas/flash.py) — a silent floor-division here would surface
        # as an obscure head-count mismatch inside jax's kernel
        raise ValueError(
            f"flash_attention_jax: query heads {q.shape[1]} must be a "
            f"multiple of key/value heads {k.shape[1]}"
        )
    if _pallas_available():
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as _jax_flash,
        )

        from scaletorch_tpu.models.layers import repeat_kv

        n_rep = q.shape[1] // k.shape[1]
        return _jax_flash(q, repeat_kv(k, n_rep), repeat_kv(v, n_rep),
                          causal=causal, sm_scale=scale)
    return sdpa_attention(q, k, v, causal=causal, scale=scale)


register_attention_backend("flash_jax", flash_attention_jax)
