"""Flash attention backend.

The role the reference fills with flash-attn 2 / Ascend's
``npu_flash_attn_func`` (reference models/attention_utils.py:72-122) is on
TPU a Pallas blockwise-softmax kernel. Until the custom kernel lands
(ops/pallas/flash.py), this module provides the dispatch surface and an
XLA fallback: XLA already fuses QK^T -> softmax -> PV reasonably well on
TPU, so the fallback is correct and fast-ish; the Pallas kernel removes
the O(S^2) score materialisation in HBM.

Selection: 'flash' backend -> pallas kernel on TPU unless
SCALETORCH_TPU_DISABLE_PALLAS=1 or the platform is CPU (tests), in which
case the XLA fallback runs.
"""

from __future__ import annotations

from typing import Optional

import jax

from scaletorch_tpu.env import get_env
from scaletorch_tpu.models.layers import sdpa_attention
from scaletorch_tpu.models.registry import register_attention_backend


def _pallas_available() -> bool:
    if get_env("SCALETORCH_TPU_DISABLE_PALLAS"):
        return False
    return jax.devices()[0].platform == "tpu"


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
) -> jax.Array:
    """[B, Hq, S, D] x [B, Hkv, S, D]^2 -> [B, Hq, S, D]."""
    if _pallas_available():
        try:
            from scaletorch_tpu.ops.pallas.flash import pallas_flash_attention

            return pallas_flash_attention(q, k, v, causal=causal, scale=scale)
        except ImportError:
            pass  # kernel not built yet; fall through to XLA
    return sdpa_attention(q, k, v, causal=causal, scale=scale)


register_attention_backend("flash", flash_attention)
