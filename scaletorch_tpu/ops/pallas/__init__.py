"""Pallas TPU kernels: flash attention (ops/pallas/flash.py)."""
