"""Pallas TPU kernels: flash attention (ops/pallas/flash.py), paged
decode attention over the paged KV cache (ops/pallas/paged_attention.py),
grouped expert MLP (ops/pallas/grouped_mlp.py)."""
