"""Pallas TPU flash attention — blockwise softmax with custom VJP.

The TPU-native equivalent of the flash-attn-2 / npu_flash_attn_func path
the reference dispatches to (reference models/attention_utils.py:72-152):
QK^T tiles stream through VMEM with running-max/sum accumulation so the
O(S^2) score matrix never reaches HBM, and the backward recomputes score
tiles from the saved log-sum-exp instead of storing probabilities.

Design points:
  * **GQA without expansion** — the K/V block index maps divide the query
    head by ``n_rep``, so grouped K/V heads are read directly from their
    unexpanded [B, Hkv, S, D] layout (the reference expands via zero-copy
    ``expand``, llama.py:176-192; here the "expansion" is pure indexing).
  * **Causal block skip** — for query block i, key blocks j > i are
    skipped: their compute is predicated off with ``pl.when`` and their
    index maps are clamped to an already-resident block so no DMA is
    issued for them. This is the reference ring-attention causal-skip
    idea (context_parallel.py:154-171) applied at tile granularity.
  * **vma-aware** — output ShapeDtypeStructs carry the varying-mesh-axes
    of their inputs, so the kernel composes with ``jax.shard_map``'s
    vma checking (the spmd train step runs everything inside shard_map).
  * fp32 accumulators and LSE; bf16 MXU feeds.

Backward follows FlashAttention-2: delta = rowsum(dO * O) precomputed in
XLA, then a dq kernel (grid over query blocks, reducing key blocks) and a
dkv kernel (grid over key blocks, reducing query blocks AND the n_rep
grouped query heads).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

def _resolve_blocks(block_q, block_kv):
    """None -> the SCALETORCH_TPU_FLASH_BLOCK_Q/KV env registry values
    (tools/optimize_mfu.py --flash-blocks sweeps these on the real chip).
    Resolved HERE so every entry point — the attention backend, the ring
    attention's forward/backward composition — honours the tuned tiles."""
    if block_q is None or block_kv is None:
        from scaletorch_tpu.env import get_env

        block_q = block_q or get_env("SCALETORCH_TPU_FLASH_BLOCK_Q")
        block_kv = block_kv or get_env("SCALETORCH_TPU_FLASH_BLOCK_KV")
    return block_q, block_kv


_NEG_INF = -1e30  # large-negative instead of -inf: keeps masked rows NaN-free


def _struct(shape, dtype, like):
    """ShapeDtypeStruct carrying ``like``'s varying-mesh-axes (vma) when
    traced inside shard_map; plain struct otherwise."""
    vma = getattr(jax.typeof(like), "vma", None)
    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def _pick_block(seq: int, preferred: int) -> int:
    block = min(preferred, seq)
    while seq % block:
        block //= 2
    return max(block, 1)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_sc, m_sc, l_sc,
                *, scale, causal, bq, bkv):
    i = pl.program_id(2)  # query block
    j = pl.program_id(3)  # key block
    nj = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    # query block i attends key block j iff j*bkv <= i*bq + bq - 1
    needed = (j * bkv <= i * bq + bq - 1) if causal else (j >= 0)

    @pl.when(needed)
    def _block():
        q = q_ref[0, 0]  # [bq, D]
        k = k_ref[0, 0]  # [bkv, D]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bkv]
        if causal:
            # only the blocks straddling the diagonal need the triangle mask
            row = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 0)
            col = j * bkv + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 1)
            s = jnp.where(row >= col, s, _NEG_INF)
        m_prev, l_prev = m_sc[:], l_sc[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_sc[:] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        m_sc[:] = m_new
        acc_sc[:] = acc_sc[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == nj - 1)
    def _finalize():
        l = jnp.maximum(l_sc[:], 1e-30)
        o_ref[0, 0] = (acc_sc[:] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_sc[:, 0] + jnp.log(l[:, 0]))[None, :]


def _semantics(*dims):
    """Mosaic grid dimension semantics: 'p' = parallel (no cross-iteration
    carry — megacore-partitionable on 2-core chips), 'a' = arbitrary (the
    sequential reduction dims that carry scratch accumulators). Declaring
    them lets Mosaic schedule DMAs/compute across iterations instead of
    assuming every dim may carry state."""
    from scaletorch_tpu.compat import pallas_tpu_compiler_params

    m = {"p": pltpu.PARALLEL, "a": pltpu.ARBITRARY}
    return pallas_tpu_compiler_params(
        pltpu, dimension_semantics=tuple(m[d] for d in dims))


def _flash_forward(q, k, v, causal, scale, bq, bkv, interpret):
    b, hq, sq, d = q.shape
    _, hkv, skv, _ = k.shape
    n_rep = hq // hkv
    nq, nkv = sq // bq, skv // bkv

    def clamp_j(i, j):
        # causal: key blocks beyond the last one visible to query block i
        # are skipped; point their DMA at the last visible block (already
        # resident) so no bandwidth is spent on them. The bound is in KEY
        # block units: last visible key row is i*bq + bq - 1.
        return jnp.minimum(j, (i * bq + bq - 1) // bkv) if causal else j

    grid = (b, hq, nq, nkv)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal, bq=bq, bkv=bkv),
        grid=grid,
        compiler_params=_semantics("p", "p", "p", "a"),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h, i, j: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, bkv, d),
                         lambda b_, h, i, j: (b_, h // n_rep, clamp_j(i, j), 0)),
            pl.BlockSpec((1, 1, bkv, d),
                         lambda b_, h, i, j: (b_, h // n_rep, clamp_j(i, j), 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h, i, j: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, 1, bq), lambda b_, h, i, j: (b_, h, 0, i)),
        ],
        out_shape=[
            _struct((b, hq, sq, d), q.dtype, q),
            _struct((b, hq, 1, sq), jnp.float32, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse[:, :, 0, :]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------
def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_sc,
               *, scale, causal, bq, bkv):
    i = pl.program_id(2)
    j = pl.program_id(3)
    nj = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    needed = (j * bkv <= i * bq + bq - 1) if causal else (j >= 0)

    @pl.when(needed)
    def _block():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]      # [1, bq]
        delta = delta_ref[0, 0]  # [1, bq]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            row = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 0)
            col = j * bkv + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 1)
            s = jnp.where(row >= col, s, _NEG_INF)
        p = jnp.exp(s - lse[0][:, None])  # [bq, bkv]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[0][:, None]) * scale
        dq_sc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == nj - 1)
    def _finalize():
        dq_ref[0, 0] = dq_sc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_sc, dv_sc, *, scale, causal, bq, bkv):
    jj = pl.program_id(2)  # key block
    r = pl.program_id(3)   # grouped query head within this kv head
    i = pl.program_id(4)   # query block
    nr = pl.num_programs(3)
    ni = pl.num_programs(4)

    @pl.when((r == 0) & (i == 0))
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    # key block jj receives gradient from query blocks i >= jj
    needed = (i * bq + bq - 1 >= jj * bkv) if causal else (i >= 0)

    @pl.when(needed)
    def _block():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            row = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 0)
            col = jj * bkv + jax.lax.broadcasted_iota(jnp.int32, (bq, bkv), 1)
            s = jnp.where(row >= col, s, _NEG_INF)
        p = jnp.exp(s - lse[0][:, None])
        dv_sc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[0][:, None]) * scale
        dk_sc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when((r == nr - 1) & (i == ni - 1))
    def _finalize():
        dk_ref[0, 0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_sc[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal, scale, bq, bkv, interpret):
    b, hq, sq, d = q.shape
    _, hkv, skv, _ = k.shape
    n_rep = hq // hkv
    nq, nkv = sq // bq, skv // bkv

    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    lse4 = lse[:, :, None, :]      # [B, Hq, 1, S]
    delta4 = delta[:, :, None, :]

    def clamp_j(i, j):
        # same key-block-unit bound as the forward
        return jnp.minimum(j, (i * bq + bq - 1) // bkv) if causal else j

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, bq=bq, bkv=bkv),
        grid=(b, hq, nq, nkv),
        compiler_params=_semantics("p", "p", "p", "a"),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h, i, j: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, bkv, d),
                         lambda b_, h, i, j: (b_, h // n_rep, clamp_j(i, j), 0)),
            pl.BlockSpec((1, 1, bkv, d),
                         lambda b_, h, i, j: (b_, h // n_rep, clamp_j(i, j), 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, h, i, j: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, 1, bq), lambda b_, h, i, j: (b_, h, 0, i)),
            pl.BlockSpec((1, 1, 1, bq), lambda b_, h, i, j: (b_, h, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d), lambda b_, h, i, j: (b_, h, i, 0)),
        out_shape=_struct((b, hq, sq, d), q.dtype, q),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse4, delta4)

    def clamp_i(jj, i):
        # key block jj only receives gradient from query blocks whose last
        # row reaches its first key row jj*bkv — bound in QUERY block units
        return jnp.maximum(i, (jj * bkv) // bq) if causal else i

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal, bq=bq, bkv=bkv),
        grid=(b, hkv, nkv, n_rep, nq),
        compiler_params=_semantics("p", "p", "p", "a", "a"),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d),
                         lambda b_, hk, jj, r, i: (b_, hk * n_rep + r,
                                                   clamp_i(jj, i), 0)),
            pl.BlockSpec((1, 1, bkv, d), lambda b_, hk, jj, r, i: (b_, hk, jj, 0)),
            pl.BlockSpec((1, 1, bkv, d), lambda b_, hk, jj, r, i: (b_, hk, jj, 0)),
            pl.BlockSpec((1, 1, bq, d),
                         lambda b_, hk, jj, r, i: (b_, hk * n_rep + r,
                                                   clamp_i(jj, i), 0)),
            pl.BlockSpec((1, 1, 1, bq),
                         lambda b_, hk, jj, r, i: (b_, hk * n_rep + r, 0,
                                                   clamp_i(jj, i))),
            pl.BlockSpec((1, 1, 1, bq),
                         lambda b_, hk, jj, r, i: (b_, hk * n_rep + r, 0,
                                                   clamp_i(jj, i))),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bkv, d), lambda b_, hk, jj, r, i: (b_, hk, jj, 0)),
            pl.BlockSpec((1, 1, bkv, d), lambda b_, hk, jj, r, i: (b_, hk, jj, 0)),
        ],
        out_shape=[
            _struct((b, hkv, skv, d), k.dtype, k),
            _struct((b, hkv, skv, d), v.dtype, v),
        ],
        scratch_shapes=[
            pltpu.VMEM((bkv, d), jnp.float32),
            pltpu.VMEM((bkv, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, g, lse4, delta4)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public op with custom VJP
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, bq, bkv, interpret):
    out, _ = _flash_forward(q, k, v, causal, scale, bq, bkv, interpret)
    return out


def _flash_fwd(q, k, v, causal, scale, bq, bkv, interpret):
    out, lse = _flash_forward(q, k, v, causal, scale, bq, bkv, interpret)
    # Under jax.checkpoint the 'save_attn' policy keeps these two named
    # residuals, so the backward kernels run off the SAVED (out, lse)
    # instead of recomputing the whole flash forward inside the layer
    # remat (models/llama.py resolve_remat_policy).
    from jax.ad_checkpoint import checkpoint_name

    out_r = checkpoint_name(out, "attn_out")
    lse_r = checkpoint_name(lse, "attn_lse")
    return out, (q, k, v, out_r, lse_r)


def _flash_bwd(causal, scale, bq, bkv, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_backward(q, k, v, out, lse, g, causal, scale, bq, bkv, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def pallas_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int | None = None,
    block_kv: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """q: [B, Hq, S, D]; k/v: [B, Hkv, Skv, D]; Hq % Hkv == 0 (GQA)."""
    b, hq, sq, d = q.shape
    _, hkv, skv, _ = k.shape
    if hq % hkv:
        raise ValueError(f"query heads {hq} not a multiple of kv heads {hkv}")
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    block_q, block_kv = _resolve_blocks(block_q, block_kv)
    bq = _pick_block(sq, block_q)
    bkv = _pick_block(skv, block_kv)
    return _flash(q, k, v, causal, scale, bq, bkv, interpret)


# ---------------------------------------------------------------------------
# raw entries for composition into outer custom-VJP ops (ring attention)
# ---------------------------------------------------------------------------
def flash_forward_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool,
    scale: Optional[float] = None,
    block_q: int | None = None,
    block_kv: int | None = None,
    interpret: bool = False,
):
    """Raw kernel forward returning ``(out, lse)``.

    NOT differentiable — the caller owns the VJP (ring attention merges
    per-block (out, lse) partials across ``ppermute`` steps and drives the
    block backward itself, the role of the reference's blockwise fwd inside
    RingAttentionFunc, context_parallel.py:367-424).
    """
    if q.shape[1] % k.shape[1]:
        raise ValueError(
            f"query heads {q.shape[1]} not a multiple of kv heads {k.shape[1]}"
        )
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    block_q, block_kv = _resolve_blocks(block_q, block_kv)
    bq = _pick_block(q.shape[2], block_q)
    bkv = _pick_block(k.shape[2], block_kv)
    return _flash_forward(q, k, v, causal, scale, bq, bkv, interpret)


def flash_block_backward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    out: jax.Array,
    lse: jax.Array,
    dout: jax.Array,
    *,
    causal: bool,
    scale: Optional[float] = None,
    block_q: int | None = None,
    block_kv: int | None = None,
    interpret: bool = False,
):
    """Gradients of one K/V block against a GLOBAL softmax statistic.

    ``out``/``lse`` are the final merged attention output and log-sum-exp
    over ALL blocks (not just this one); the returned (dq, dk, dv) are then
    exactly this block's additive contribution to the full gradients —
    the identity the reference's dual-ring backward exploits
    (context_parallel.py:184-263). dk/dv come back in the unexpanded
    [B, Hkv, S, D] layout.
    """
    if q.shape[1] % k.shape[1]:
        raise ValueError(
            f"query heads {q.shape[1]} not a multiple of kv heads {k.shape[1]}"
        )
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    block_q, block_kv = _resolve_blocks(block_q, block_kv)
    bq = _pick_block(q.shape[2], block_q)
    bkv = _pick_block(k.shape[2], block_kv)
    return _flash_backward(q, k, v, out, lse, dout, causal, scale, bq, bkv,
                           interpret)
