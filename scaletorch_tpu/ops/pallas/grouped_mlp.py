"""Grouped per-expert SwiGLU MLP — Pallas TPU kernel with slot skipping.

The role of the reference's ``npu_grouped_matmul`` fused expert compute
(reference models/npu_patch.py:94-131): each expert applies its own
gate/up/down projection to its dispatched token slots. The XLA path
(parallel/expert_parallel.moe_mlp) runs one batched einsum over ALL
[E, G, C] capacity slots — MXU-dense but paying full price for padding:
capacity dispatch fills each (expert, group) block's slots as a PREFIX
(position-in-expert is a running count, expert_parallel.top_k_routing),
so slots beyond the fill count are zeros that still burn FLOPs.

This kernel walks (expert, group, slot-tile, intermediate-tile) and
**predicates whole slot-tiles off when the (e, g) fill count ends before
them** — the flash kernel's causal-skip idea applied to expert load. At
capacity factor c and balanced routing ~1 - 1/c of slot FLOPs are
padding (20% at c=1.25); under imbalance the skip grows to whatever the
cold experts leave empty.

The backward is two kernels with the same slot skip — a dx kernel
(reduction over I innermost) and a dW kernel (reduction over (group,
slot-tile) innermost), mirroring flash attention's dq/dkv split: every
output's reduction axes must be the innermost grid dims so its scratch
accumulator survives the sweep. Numerics: fp32 accumulation, bf16 MXU
feeds; ``masked_grouped_mlp`` is the dense XLA reference (and the
off-TPU execution path).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# Guarded so `import scaletorch_tpu.ops` (and through it the inference
# package, whose kv_cache pulls the paged-cache primitives) works on jax
# builds whose pallas-TPU import fails; `masked_grouped_mlp` is the
# non-TPU path and needs no pallas.
try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover - exercised on pallas-less builds
    pl = pltpu = None

from scaletorch_tpu.models.layers import swiglu


def _struct(shape, dtype, like):
    vma = getattr(jax.typeof(like), "vma", None)
    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def _pick_block(n: int, preferred: int) -> int:
    b = min(preferred, n)
    while n % b:
        b //= 2
    return max(b, 1)


def _semantics(*dims):
    """'p' = parallel grid dim, 'a' = arbitrary (sequential reduction dim
    carrying a scratch accumulator) — see ops/pallas/flash.py."""
    from scaletorch_tpu.compat import pallas_tpu_compiler_params

    m = {"p": pltpu.PARALLEL, "a": pltpu.ARBITRARY}
    return pallas_tpu_compiler_params(
        pltpu, dimension_semantics=tuple(m[d] for d in dims))


def _kernel(count_ref, x_ref, wg_ref, wu_ref, wd_ref, o_ref, acc_sc,
            *, bc, bi, ni):
    c_t = pl.program_id(2)  # slot tile within the (e, g) block
    i_t = pl.program_id(3)  # intermediate tile (reduction over I)

    @pl.when(i_t == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)

    # this (e, g) block's fill count arrives as its own [1,1,1,1] block
    # (static indexing — dynamic SMEM-table lookups trip shard_map's
    # varying-axes checker in interpret mode)
    count = count_ref[0, 0, 0, 0]
    # whole slot-tile beyond this (expert, group)'s filled prefix -> skip
    @pl.when(c_t * bc < count)
    def _block():
        x = x_ref[0, 0]        # [bc, H]
        g = jax.lax.dot_general(
            x, wg_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bc, bi]
        u = jax.lax.dot_general(
            x, wu_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        h = swiglu(g, u).astype(x.dtype)
        acc_sc[:] += jax.lax.dot_general(
            h, wd_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bc, H]

    @pl.when(i_t == ni - 1)
    def _finalize():
        # zero the partial tile's rows past the fill count (their inputs
        # are zeros anyway, but swiglu(0,0) @ wd is exactly 0 only in
        # exact arithmetic — make it structural)
        row = c_t * bc + jax.lax.broadcasted_iota(
            jnp.int32, acc_sc.shape, 0)
        o_ref[0, 0] = jnp.where(row < count, acc_sc[:], 0.0).astype(o_ref.dtype)


def _block_grads(x, wg, wu, wd, do):
    """Shared per-tile backward math: recompute gate/up/silu in fp32 and
    return (s, dg, du) for the dx and dW kernels.

    s  = silu(g)·u (the down-projection input)
    dS = dO · Wd^T;  du = dS·silu(g);  dg = dS·u·silu'(g)
    with silu'(g) = σ(g)·(1 + g·(1 − σ(g))).
    """
    g = jax.lax.dot_general(
        x, wg, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    u = jax.lax.dot_general(
        x, wu, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    sig = jax.nn.sigmoid(g)
    silu = g * sig
    s = silu * u
    ds = jax.lax.dot_general(
        do, wd, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    du = ds * silu
    dg = ds * u * (sig * (1.0 + g * (1.0 - sig)))
    return s, dg, du


def _dx_kernel(count_ref, x_ref, wg_ref, wu_ref, wd_ref, do_ref, dx_ref,
               acc_sc, *, bc, bi, ni):
    c_t = pl.program_id(2)
    i_t = pl.program_id(3)

    @pl.when(i_t == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)

    count = count_ref[0, 0, 0, 0]

    @pl.when(c_t * bc < count)
    def _block():
        x = x_ref[0, 0]
        _, dg, du = _block_grads(x, wg_ref[0], wu_ref[0], wd_ref[0],
                                 do_ref[0, 0])
        acc_sc[:] += jax.lax.dot_general(
            dg.astype(x.dtype), wg_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_sc[:] += jax.lax.dot_general(
            du.astype(x.dtype), wu_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i_t == ni - 1)
    def _finalize():
        row = c_t * bc + jax.lax.broadcasted_iota(jnp.int32, acc_sc.shape, 0)
        dx_ref[0, 0] = jnp.where(row < count, acc_sc[:], 0.0).astype(
            dx_ref.dtype)


def _dw_kernel(counts_ref, x_ref, wg_ref, wu_ref, wd_ref, do_ref,
               dwg_ref, dwu_ref, dwd_ref, dwg_sc, dwu_sc, dwd_sc,
               *, bc, bi, ng, nc):
    g_t = pl.program_id(2)
    c_t = pl.program_id(3)

    @pl.when((g_t == 0) & (c_t == 0))
    def _init():
        dwg_sc[:] = jnp.zeros_like(dwg_sc)
        dwu_sc[:] = jnp.zeros_like(dwu_sc)
        dwd_sc[:] = jnp.zeros_like(dwd_sc)

    count = counts_ref[0, 0, 0, 0]

    @pl.when(c_t * bc < count)
    def _block():
        x = x_ref[0, 0]
        do = do_ref[0, 0]
        # mask the covering tile's rows past the fill count: upstream
        # cotangents of structurally-zero outputs must not train weights
        # (parity with masked_grouped_mlp's where-mask VJP)
        row = c_t * bc + jax.lax.broadcasted_iota(jnp.int32, do.shape, 0)
        do = jnp.where(row < count, do, 0.0)
        s, dg, du = _block_grads(x, wg_ref[0], wu_ref[0], wd_ref[0], do)
        dwg_sc[:] += jax.lax.dot_general(
            x, dg.astype(x.dtype), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dwu_sc[:] += jax.lax.dot_general(
            x, du.astype(x.dtype), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dwd_sc[:] += jax.lax.dot_general(
            s.astype(x.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when((g_t == ng - 1) & (c_t == nc - 1))
    def _finalize():
        dwg_ref[0] = dwg_sc[:].astype(dwg_ref.dtype)
        dwu_ref[0] = dwu_sc[:].astype(dwu_ref.dtype)
        dwd_ref[0] = dwd_sc[:].astype(dwd_ref.dtype)


def _backward(x, counts, wg, wu, wd, do, bc, bi, interpret):
    """Slot-skipping backward: a dx kernel (reduction over I innermost)
    and a dW kernel (reduction over (group, slot-tile) innermost) — the
    same two-kernel split flash attention's backward uses, because each
    output's reduction axes must be the innermost grid dims."""
    e, g, c, h = x.shape
    i_dim = wg.shape[-1]
    nc, ni = c // bc, i_dim // bi
    counts4 = counts.reshape(e, g, 1, 1)

    dx = pl.pallas_call(
        functools.partial(_dx_kernel, bc=bc, bi=bi, ni=ni),
        grid=(e, g, nc, ni),
        compiler_params=_semantics("p", "p", "p", "a"),
        in_specs=[
            pl.BlockSpec((1, 1, 1, 1), lambda e_, g_, c_, i_: (e_, g_, 0, 0)),
            pl.BlockSpec((1, 1, bc, h), lambda e_, g_, c_, i_: (e_, g_, c_, 0)),
            pl.BlockSpec((1, h, bi), lambda e_, g_, c_, i_: (e_, 0, i_)),
            pl.BlockSpec((1, h, bi), lambda e_, g_, c_, i_: (e_, 0, i_)),
            pl.BlockSpec((1, bi, h), lambda e_, g_, c_, i_: (e_, i_, 0)),
            pl.BlockSpec((1, 1, bc, h), lambda e_, g_, c_, i_: (e_, g_, c_, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bc, h),
                               lambda e_, g_, c_, i_: (e_, g_, c_, 0)),
        out_shape=_struct((e, g, c, h), x.dtype, x),
        scratch_shapes=[pltpu.VMEM((bc, h), jnp.float32)],
        interpret=interpret,
    )(counts4, x, wg, wu, wd, do)

    dwg, dwu, dwd = pl.pallas_call(
        functools.partial(_dw_kernel, bc=bc, bi=bi, ng=g, nc=nc),
        grid=(e, i_dim // bi, g, nc),
        compiler_params=_semantics("p", "p", "a", "a"),
        in_specs=[
            pl.BlockSpec((1, 1, 1, 1), lambda e_, i_, g_, c_: (e_, g_, 0, 0)),
            pl.BlockSpec((1, 1, bc, h), lambda e_, i_, g_, c_: (e_, g_, c_, 0)),
            pl.BlockSpec((1, h, bi), lambda e_, i_, g_, c_: (e_, 0, i_)),
            pl.BlockSpec((1, h, bi), lambda e_, i_, g_, c_: (e_, 0, i_)),
            pl.BlockSpec((1, bi, h), lambda e_, i_, g_, c_: (e_, i_, 0)),
            pl.BlockSpec((1, 1, bc, h), lambda e_, i_, g_, c_: (e_, g_, c_, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, h, bi), lambda e_, i_, g_, c_: (e_, 0, i_)),
            pl.BlockSpec((1, h, bi), lambda e_, i_, g_, c_: (e_, 0, i_)),
            pl.BlockSpec((1, bi, h), lambda e_, i_, g_, c_: (e_, i_, 0)),
        ],
        out_shape=[
            _struct(wg.shape, wg.dtype, wg),
            _struct(wu.shape, wu.dtype, wu),
            _struct(wd.shape, wd.dtype, wd),
        ],
        scratch_shapes=[
            pltpu.VMEM((h, bi), jnp.float32),
            pltpu.VMEM((h, bi), jnp.float32),
            pltpu.VMEM((bi, h), jnp.float32),
        ],
        interpret=interpret,
    )(counts4, x, wg, wu, wd, do)
    return dx, dwg, dwu, dwd


def _forward(x, counts, wg, wu, wd, bc, bi, interpret):
    e, g, c, h = x.shape
    i_dim = wg.shape[-1]
    nc, ni = c // bc, i_dim // bi
    grid = (e, g, nc, ni)
    counts4 = counts.reshape(e, g, 1, 1)
    return pl.pallas_call(
        functools.partial(_kernel, bc=bc, bi=bi, ni=ni),
        grid=grid,
        compiler_params=_semantics("p", "p", "p", "a"),
        in_specs=[
            pl.BlockSpec((1, 1, 1, 1), lambda e_, g_, c_, i_: (e_, g_, 0, 0)),
            pl.BlockSpec((1, 1, bc, h), lambda e_, g_, c_, i_: (e_, g_, c_, 0)),
            pl.BlockSpec((1, h, bi), lambda e_, g_, c_, i_: (e_, 0, i_)),
            pl.BlockSpec((1, h, bi), lambda e_, g_, c_, i_: (e_, 0, i_)),
            pl.BlockSpec((1, bi, h), lambda e_, g_, c_, i_: (e_, i_, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bc, h),
                               lambda e_, g_, c_, i_: (e_, g_, c_, 0)),
        out_shape=_struct((e, g, c, h), x.dtype, x),
        scratch_shapes=[pltpu.VMEM((bc, h), jnp.float32)],
        interpret=interpret,
    )(counts4, x, wg, wu, wd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def grouped_swiglu_mlp(x, counts, wg, wu, wd, bc=256, bi=512,
                       interpret=False):
    """x: [E, G, C, H] capacity slots (prefix-filled per (e, g));
    counts: [E, G] int32 fill counts; wg/wu: [E, H, I]; wd: [E, I, H].
    Returns [E, G, C, H]; rows at or past the fill count are zero."""
    if pl is None:
        raise RuntimeError(
            "the grouped-MLP kernel needs jax.experimental.pallas; this "
            "jax build lacks it — use masked_grouped_mlp"
        )
    bc = _pick_block(x.shape[2], bc)
    bi = _pick_block(wg.shape[-1], bi)
    return _forward(x, counts, wg, wu, wd, bc, bi, interpret)


def masked_grouped_mlp(x, counts, wg, wu, wd):
    """The dense numeric reference AND the non-TPU execution path:
    einsum with the past-count rows structurally zeroed (exactly the
    kernel's output; its autodiff is what the Pallas backward kernels
    are parity-tested against). Interpret-mode pallas inside a
    large sharded program trips a JAX closed_call lowering-cache bug, so
    off-TPU callers take this path while the kernel itself is validated
    by interpret-mode parity tests and Mosaic AOT compilation."""
    e, g, c, h = x.shape
    mask = (jnp.arange(c)[None, None, :] < counts[..., None])[..., None]
    x = jnp.where(mask, x, 0)
    gate = jnp.einsum("egch,ehi->egci", x, wg)
    up = jnp.einsum("egch,ehi->egci", x, wu)
    out = jnp.einsum("egci,eih->egch", swiglu(gate, up), wd)
    return jnp.where(mask, out, 0)


def _fwd(x, counts, wg, wu, wd, bc, bi, interpret):
    out = grouped_swiglu_mlp(x, counts, wg, wu, wd, bc, bi, interpret)
    return out, (x, counts, wg, wu, wd)


def _bwd(bc, bi, interpret, res, g_out):
    x, counts, wg, wu, wd = res
    bc = _pick_block(x.shape[2], bc)
    bi = _pick_block(wg.shape[-1], bi)
    dx, dwg, dwu, dwd = _backward(x, counts, wg, wu, wd, g_out, bc, bi,
                                  interpret)
    return dx, None, dwg, dwu, dwd


grouped_swiglu_mlp.defvjp(_fwd, _bwd)


def slot_fill_counts(dispatch: jax.Array) -> jax.Array:
    """[G, N, E, C] (or [N, E, C]) dispatch one-hots -> [E, G] int32 fill
    counts (capacity dispatch fills slots as a prefix, so the count IS
    the number of occupied slots)."""
    if dispatch.ndim == 3:
        dispatch = dispatch[None]
    return jnp.sum(dispatch, axis=(1, 3)).astype(jnp.int32).T  # [E, G]
