"""Grouped per-expert SwiGLU MLP — Pallas TPU kernel with slot skipping.

The role of the reference's ``npu_grouped_matmul`` fused expert compute
(reference models/npu_patch.py:94-131): each expert applies its own
gate/up/down projection to its dispatched token slots. The XLA path
(parallel/expert_parallel.moe_mlp) runs one batched einsum over ALL
[E, G, C] capacity slots — MXU-dense but paying full price for padding:
capacity dispatch fills each (expert, group) block's slots as a PREFIX
(position-in-expert is a running count, expert_parallel.top_k_routing),
so slots beyond the fill count are zeros that still burn FLOPs.

This kernel walks (expert, group, slot-tile, intermediate-tile) and
**predicates whole slot-tiles off when the (e, g) fill count ends before
them** — the flash kernel's causal-skip idea applied to expert load. At
capacity factor c and balanced routing ~1 - 1/c of slot FLOPs are
padding (20% at c=1.25); under imbalance the skip grows to whatever the
cold experts leave empty.

Forward-only by design: the VJP recomputes through the masked XLA path
(the backward's matmuls run dense — a backward kernel is a follow-up).
Numerics: fp32 accumulation over intermediate tiles, bf16 MXU feeds.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from scaletorch_tpu.models.layers import swiglu


def _struct(shape, dtype, like):
    vma = getattr(jax.typeof(like), "vma", None)
    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def _pick_block(n: int, preferred: int) -> int:
    b = min(preferred, n)
    while n % b:
        b //= 2
    return max(b, 1)


def _kernel(count_ref, x_ref, wg_ref, wu_ref, wd_ref, o_ref, acc_sc,
            *, bc, bi, ni):
    c_t = pl.program_id(2)  # slot tile within the (e, g) block
    i_t = pl.program_id(3)  # intermediate tile (reduction over I)

    @pl.when(i_t == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)

    # this (e, g) block's fill count arrives as its own [1,1,1,1] block
    # (static indexing — dynamic SMEM-table lookups trip shard_map's
    # varying-axes checker in interpret mode)
    count = count_ref[0, 0, 0, 0]
    # whole slot-tile beyond this (expert, group)'s filled prefix -> skip
    @pl.when(c_t * bc < count)
    def _block():
        x = x_ref[0, 0]        # [bc, H]
        g = jax.lax.dot_general(
            x, wg_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bc, bi]
        u = jax.lax.dot_general(
            x, wu_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        h = swiglu(g, u).astype(x.dtype)
        acc_sc[:] += jax.lax.dot_general(
            h, wd_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [bc, H]

    @pl.when(i_t == ni - 1)
    def _finalize():
        # zero the partial tile's rows past the fill count (their inputs
        # are zeros anyway, but swiglu(0,0) @ wd is exactly 0 only in
        # exact arithmetic — make it structural)
        row = c_t * bc + jax.lax.broadcasted_iota(
            jnp.int32, acc_sc.shape, 0)
        o_ref[0, 0] = jnp.where(row < count, acc_sc[:], 0.0).astype(o_ref.dtype)


def _forward(x, counts, wg, wu, wd, bc, bi, interpret):
    e, g, c, h = x.shape
    i_dim = wg.shape[-1]
    nc, ni = c // bc, i_dim // bi
    grid = (e, g, nc, ni)
    counts4 = counts.reshape(e, g, 1, 1)
    return pl.pallas_call(
        functools.partial(_kernel, bc=bc, bi=bi, ni=ni),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, 1, 1), lambda e_, g_, c_, i_: (e_, g_, 0, 0)),
            pl.BlockSpec((1, 1, bc, h), lambda e_, g_, c_, i_: (e_, g_, c_, 0)),
            pl.BlockSpec((1, h, bi), lambda e_, g_, c_, i_: (e_, 0, i_)),
            pl.BlockSpec((1, h, bi), lambda e_, g_, c_, i_: (e_, 0, i_)),
            pl.BlockSpec((1, bi, h), lambda e_, g_, c_, i_: (e_, i_, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bc, h),
                               lambda e_, g_, c_, i_: (e_, g_, c_, 0)),
        out_shape=_struct((e, g, c, h), x.dtype, x),
        scratch_shapes=[pltpu.VMEM((bc, h), jnp.float32)],
        interpret=interpret,
    )(counts4, x, wg, wu, wd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def grouped_swiglu_mlp(x, counts, wg, wu, wd, bc=256, bi=512,
                       interpret=False):
    """x: [E, G, C, H] capacity slots (prefix-filled per (e, g));
    counts: [E, G] int32 fill counts; wg/wu: [E, H, I]; wd: [E, I, H].
    Returns [E, G, C, H]; rows at or past the fill count are zero."""
    bc = _pick_block(x.shape[2], bc)
    bi = _pick_block(wg.shape[-1], bi)
    return _forward(x, counts, wg, wu, wd, bc, bi, interpret)


def masked_grouped_mlp(x, counts, wg, wu, wd):
    """Reference semantics for the VJP recompute AND the non-TPU
    execution path: dense einsum with the past-count rows structurally
    zeroed (exactly the kernel's output). Interpret-mode pallas inside a
    large sharded program trips a JAX closed_call lowering-cache bug, so
    off-TPU callers take this path while the kernel itself is validated
    by interpret-mode parity tests and Mosaic AOT compilation."""
    e, g, c, h = x.shape
    mask = (jnp.arange(c)[None, None, :] < counts[..., None])[..., None]
    x = jnp.where(mask, x, 0)
    gate = jnp.einsum("egch,ehi->egci", x, wg)
    up = jnp.einsum("egch,ehi->egci", x, wu)
    out = jnp.einsum("egci,eih->egch", swiglu(gate, up), wd)
    return jnp.where(mask, out, 0)


def _fwd(x, counts, wg, wu, wd, bc, bi, interpret):
    out = grouped_swiglu_mlp(x, counts, wg, wu, wd, bc, bi, interpret)
    return out, (x, counts, wg, wu, wd)


def _bwd(bc, bi, interpret, res, g_out):
    x, counts, wg, wu, wd = res
    # Dense masked-XLA backward (kernel is forward-only for now): grads
    # of padded rows vanish through the mask, matching the kernel output.
    _, vjp = jax.vjp(
        lambda x_, wg_, wu_, wd_: masked_grouped_mlp(x_, counts, wg_, wu_, wd_),
        x, wg, wu, wd,
    )
    dx, dwg, dwu, dwd = vjp(g_out)
    return dx, None, dwg, dwu, dwd


grouped_swiglu_mlp.defvjp(_fwd, _bwd)


def slot_fill_counts(dispatch: jax.Array) -> jax.Array:
    """[G, N, E, C] (or [N, E, C]) dispatch one-hots -> [E, G] int32 fill
    counts (capacity dispatch fills slots as a prefix, so the count IS
    the number of occupied slots)."""
    if dispatch.ndim == 3:
        dispatch = dispatch[None]
    return jnp.sum(dispatch, axis=(1, 3)).astype(jnp.int32).T  # [E, G]
