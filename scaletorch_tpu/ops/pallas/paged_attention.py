"""Pallas TPU paged-decode attention + the paged-cache KV primitives.

The paged KV cache (inference/kv_cache.py ``PagedKVCache``) keeps one
global pool of fixed-size pages ``[n_pages, Hkv, page_size, D]`` per
layer; each decode slot owns a per-slot *page table* ``[max_pages]``
int32 mapping logical page ``t // page_size`` to a physical pool page.
Attention reads therefore become gathers over the page table. Two
implementations live here:

  * **Pallas decode kernel** (``pallas_paged_decode_attention``): one
    query token per slot against its paged cache. The grid is
    ``(B, Hkv, max_pages)`` and the page table + positions ride the
    TPU scalar-prefetch path (``pltpu.PrefetchScalarGridSpec``), so the
    K/V *index maps themselves* chase the page table: page ``j``'s
    physical block is DMA'd HBM→VMEM directly — the gathered reads stay
    in VMEM and the dense ``[B, Hkv, S_max, D]`` view is never
    materialised in HBM. Pages past the slot's live length are skipped
    flash-style: compute predicated off with ``pl.when`` and the index
    map clamped to an already-resident page so no DMA is issued
    (the causal block-skip idiom from ops/pallas/flash.py). GQA reads
    grouped K/V unexpanded — the ``n_rep`` query heads of one KV head
    are the rows of a single ``[n_rep, page_size]`` score tile.
  * **Pure-lax fallback** (``paged_gather_kv`` + the models' shared
    ``cached_sdpa_attention``): a whole-table gather that reconstructs
    the dense cache view. This is the CPU / interpret-mode / old-jax
    path (``compat.py`` backfills the pallas CompilerParams naming) and
    the *bit-parity oracle* for the kernel — it performs the identical
    reduction the dense engine's attention performs, which is what makes
    the paged engine's greedy outputs bit-identical to the dense
    engine's.

``paged_attention`` dispatches between them: the kernel serves
single-token decode on a real TPU backend (toggle:
``SCALETORCH_TPU_PAGED_KERNEL``); prefill (S > 1) and non-TPU backends
take the gather fallback.

Writes (``paged_write_kv``) are a batched scatter: token at absolute
position ``t`` lands at ``(table[b, t // page_size], t % page_size)``.
Masked-off slots and positions beyond the table are redirected to the
reserved TRASH page (page 0 — never allocated, read only through masked
attention lanes), which keeps the write unconditional — data changes,
shapes never do, so the engine's one-compile discipline survives
admissions, prefix hits, and frees.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

# The cache primitives (TRASH_PAGE, paged_write_kv, paged_gather_kv) are
# pure lax and imported at module level by inference/kv_cache.py — only
# the decode kernel itself needs pallas, so a jax build whose pallas-TPU
# import fails still serves the gather-fallback (and dense) paths.
try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover - exercised on pallas-less builds
    pl = pltpu = None

# Page 0 is reserved: never allocated, present in page tables only as
# the sentinel for "no page here" (table padding, masked-off writes).
# Reads of it only ever flow through attention lanes the j <= p mask has
# already zeroed.
TRASH_PAGE = 0

_NEG_INF = -1e30  # large-negative, not -inf: keeps masked rows NaN-free


def _semantics(*dims):
    """Mosaic grid dimension semantics ('p' parallel / 'a' arbitrary),
    via the compat CompilerParams naming guard (same helper shape as
    ops/pallas/flash.py)."""
    from scaletorch_tpu.compat import pallas_tpu_compiler_params

    m = {"p": pltpu.PARALLEL, "a": pltpu.ARBITRARY}
    return pallas_tpu_compiler_params(
        pltpu, dimension_semantics=tuple(m[d] for d in dims))


# ---------------------------------------------------------------------------
# paged cache primitives (pure lax — shared by fallback and engine steps)
# ---------------------------------------------------------------------------
def paged_gather_kv(pool: jax.Array, page_tables: jax.Array) -> jax.Array:
    """Reconstruct the dense cache view from the page pool.

    pool: [n_pages, Hkv, page_size, D]; page_tables: [B, max_pages]
    -> [B, Hkv, max_pages * page_size, D], logical position ``t`` of slot
    ``b`` at sequence index ``t`` exactly as the dense layout stores it.
    """
    view = pool[page_tables]  # [B, max_pages, Hkv, page_size, D]
    b, mp, h, p, d = view.shape
    return view.transpose(0, 2, 1, 3, 4).reshape(b, h, mp * p, d)


def paged_write_kv(
    pool: jax.Array,
    new: jax.Array,
    positions: jax.Array,
    page_tables: jax.Array,
    page_size: int,
    write_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Scatter ``new`` [B, H, S, D] into ``pool`` [n_pages, H, page_size,
    D] at per-token absolute ``positions`` [B, S] through ``page_tables``
    [B, max_pages]. ``write_mask`` [B] bool redirects unlisted slots'
    writes to the TRASH page (their own pages stay byte-identical —
    continuous batching admits new requests without perturbing live
    ones); positions past the table's reach go to TRASH too.
    """
    max_pages = page_tables.shape[1]
    logical = positions // page_size                       # [B, S]
    offsets = positions % page_size
    valid = logical < max_pages
    pages = jnp.take_along_axis(
        page_tables, jnp.minimum(logical, max_pages - 1), axis=1)
    if write_mask is not None:
        valid = valid & write_mask[:, None]
    pages = jnp.where(valid, pages, TRASH_PAGE)
    vals = new.astype(pool.dtype).transpose(0, 2, 1, 3)    # [B, S, H, D]
    return pool.at[pages, :, offsets, :].set(vals)


# ---------------------------------------------------------------------------
# the decode kernel
# ---------------------------------------------------------------------------
def _paged_decode_kernel(pt_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                         acc_sc, m_sc, l_sc, *, scale, page_size):
    b = pl.program_id(0)   # slot
    j = pl.program_id(2)   # logical page
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc)
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    # pages past the slot's live length carry no visible keys: skip their
    # compute; their DMA was already clamped to a resident page.
    n_live = pos_ref[b] // page_size + 1

    @pl.when(j < n_live)
    def _page():
        q = q_ref[0, 0]   # [n_rep, D]
        k = k_ref[0, 0]   # [page_size, D]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [n_rep, page_size]
        # causal-over-the-cache mask at logical positions: key o of
        # logical page j sits at absolute position j*page_size + o
        nrep = q.shape[0]
        key_pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (nrep, page_size), 1)
        s = jnp.where(key_pos <= pos_ref[b], s, _NEG_INF)
        m_prev, l_prev = m_sc[:], l_sc[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_sc[:] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        m_sc[:] = m_new
        acc_sc[:] = acc_sc[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == nj - 1)
    def _finalize():
        l = jnp.maximum(l_sc[:], 1e-30)
        o_ref[0, 0] = (acc_sc[:] / l).astype(o_ref.dtype)


def pallas_paged_decode_attention(
    q: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    page_tables: jax.Array,
    positions: jax.Array,
    *,
    scale: Optional[float] = None,
    interpret: bool = False,
) -> jax.Array:
    """One-token paged attention: q [B, Hq, D] against the page pool.

    pool_k/pool_v: [n_pages, Hkv, page_size, D]; page_tables:
    [B, max_pages] int32; positions: [B] int32 absolute position of the
    query token (attends keys j <= position). Returns [B, Hq, D].

    The page table and positions are scalar-prefetched so the K/V block
    index maps resolve physical pages before each grid step's DMA; only
    live pages are fetched, and the per-page flash accumulation keeps
    everything after the HBM page read in VMEM.
    """
    if pl is None:
        raise RuntimeError(
            "the Pallas paged-decode kernel needs jax.experimental.pallas; "
            "this jax build lacks it — use the gather fallback "
            "(paged_attention with kernel=False)"
        )
    b, hq, d = q.shape
    n_pages, hkv, page_size, _ = pool_k.shape
    if hq % hkv:
        raise ValueError(f"query heads {hq} not a multiple of kv heads {hkv}")
    n_rep = hq // hkv
    max_pages = page_tables.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)

    q_r = q.reshape(b, hkv, n_rep, d)

    def q_idx(b_, h, j, pt_ref, pos_ref):
        return (b_, h, 0, 0)

    def kv_idx(b_, h, j, pt_ref, pos_ref):
        # clamp dead pages to the last live one (already resident — no
        # DMA is spent on pages the mask would zero anyway)
        n_live = pos_ref[b_] // page_size + 1
        return (pt_ref[b_, jnp.minimum(j, n_live - 1)], h, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv, max_pages),
        in_specs=[
            pl.BlockSpec((1, 1, n_rep, d), q_idx),
            pl.BlockSpec((1, 1, page_size, d), kv_idx),
            pl.BlockSpec((1, 1, page_size, d), kv_idx),
        ],
        out_specs=pl.BlockSpec((1, 1, n_rep, d), q_idx),
        scratch_shapes=[
            pltpu.VMEM((n_rep, d), jnp.float32),
            pltpu.VMEM((n_rep, 1), jnp.float32),
            pltpu.VMEM((n_rep, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, scale=scale,
                          page_size=page_size),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, n_rep, d), q.dtype),
        compiler_params=_semantics("p", "p", "a"),
        interpret=interpret,
    )(page_tables.astype(jnp.int32), positions.astype(jnp.int32),
      q_r, pool_k, pool_v)
    return out.reshape(b, hq, d)


# ---------------------------------------------------------------------------
# dispatcher
# ---------------------------------------------------------------------------
def paged_attention(
    q: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    page_tables: jax.Array,
    q_positions: jax.Array,
    *,
    page_size: int,
    seq_limit: Optional[int] = None,
    scale: Optional[float] = None,
    kernel: Optional[bool] = None,
    interpret: bool = False,
) -> jax.Array:
    """Attention against the paged cache, kernel or fallback.

    q: [B, Hq, S, D] (S = tail length at prefill, 1 at decode);
    q_positions: [B, S] absolute positions. ``kernel=None`` auto-selects:
    the Pallas kernel for single-token decode on the TPU backend
    (``SCALETORCH_TPU_PAGED_KERNEL`` gates it), the lax gather +
    ``cached_sdpa_attention`` everywhere else — CPU, interpret mode,
    prefill, and jax builds without working Mosaic. ``seq_limit`` crops
    the gathered view to the engine's ``max_seq`` so the fallback's
    reduction has *exactly* the dense layout's operand shapes — the
    bit-identity contract with the dense engine.
    """
    from scaletorch_tpu.models.layers import cached_sdpa_attention

    s = q.shape[2]
    use_kernel = kernel
    if use_kernel is None:
        from scaletorch_tpu.env import get_env

        use_kernel = (
            s == 1
            and jax.default_backend() == "tpu"
            and bool(get_env("SCALETORCH_TPU_PAGED_KERNEL"))
        )
    if use_kernel:
        if s != 1:
            raise ValueError(
                f"the paged-decode kernel serves single-token queries; "
                f"got S={s} (prefill goes through the gather fallback)"
            )
        out = pallas_paged_decode_attention(
            q[:, :, 0, :], pool_k, pool_v, page_tables, q_positions[:, 0],
            scale=scale, interpret=interpret,
        )
        return out[:, :, None, :]
    k = paged_gather_kv(pool_k, page_tables)
    v = paged_gather_kv(pool_v, page_tables)
    if seq_limit is not None and k.shape[2] > seq_limit:
        k = k[:, :, :seq_limit, :]
        v = v[:, :, :seq_limit, :]
    return cached_sdpa_attention(q, k, v, q_positions, scale=scale)
