"""Int8 block-scaled gradient all-reduce — the quantized DCN edge.

Training gradients are the one tensor stream that crosses the slow
(DCN, inter-host) edge of the mesh every step, and they tolerate
aggressive quantization: following EQuARX (PAPERS.md — quantized
all-reduce inside XLA at a block granularity) and "The Big Send-off"
(bandwidth-optimal DCN collectives), this module implements the
all-reduce itself in int8 wire format with fp32 accumulation:

    quantize (per-block absmax scales)
      -> reduce-scatter as int8 + scales (one tiled all_to_all)
      -> dequantize + SUM IN FP32 (each rank reduces its owned chunk)
      -> re-quantize the reduced chunk
      -> all-gather as int8 + scales
      -> dequantize

Wire bytes per rank for N fp32 gradient elements over an n-rank axis:
plain fp32 all-reduce moves 2·N·(n-1)/n·4 bytes; this path moves
2·N·(n-1)/n·1 + 2·(N/block)·4 — a 4x reduction at the default
block=256 (scale overhead 1.6%). Accuracy: absmax int8 per block bounds
the element error by absmax/254 per quantization, applied twice
(scatter + gather legs); measured grad cosine similarity vs the fp32
path is >= 0.999 on real train steps (tests/ops/test_quantized_collectives.py).

The reduction itself is deterministic: chunk boundaries depend only on
(axis size, block size) and the fp32 accumulation sums source ranks in
index order (a single ``jnp.sum`` over the rank dim), so results are
bit-identical across runs and across host/process layouts of the same
logical mesh.

Everything is built from ``shard_map``-level collectives
(``all_to_all``/``all_gather``) available on every jax this repo
supports (the 0.4.37 compat surface — scaletorch_tpu/compat.py); the
per-axis selectability lives one level up: parallel/spmd.py keeps the
ICI-cheap axes (cp/ep/tp) in fp32 and routes only the configured
bandwidth-bound axis (default ``dp``) through here.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

DEFAULT_BLOCK_SIZE = 256
_QMAX = 127.0  # symmetric int8

GRAD_ALLREDUCE_DTYPES = ("fp32", "bf16", "int8")


def quantize_blockwise(
    x: jax.Array, block_size: int = DEFAULT_BLOCK_SIZE
) -> Tuple[jax.Array, jax.Array]:
    """[M] fp32 (M % block_size == 0) -> (int8 [M/B, B], fp32 scales [M/B]).

    Symmetric per-block absmax: scale = absmax/127, q = round(x/scale).
    An all-zero block gets scale 1.0 (not 0) so dequantization never
    divides/multiplies by zero-derived garbage.
    """
    if x.ndim != 1 or x.shape[0] % block_size:
        raise ValueError(
            f"quantize_blockwise wants 1-D input padded to a multiple of "
            f"block_size={block_size}, got shape {x.shape}"
        )
    blocks = x.astype(jnp.float32).reshape(-1, block_size)
    absmax = jnp.max(jnp.abs(blocks), axis=-1)
    scales = jnp.where(absmax > 0, absmax / _QMAX, 1.0)
    q = jnp.clip(jnp.round(blocks / scales[:, None]), -_QMAX, _QMAX)
    return q.astype(jnp.int8), scales


def dequantize_blockwise(q: jax.Array, scales: jax.Array) -> jax.Array:
    """(int8 [..., nB, B], fp32 [..., nB]) -> fp32 [..., nB*B]."""
    deq = q.astype(jnp.float32) * scales[..., None]
    return deq.reshape(*q.shape[:-2], q.shape[-2] * q.shape[-1])


def _padded_len(n: int, ranks: int, block_size: int) -> int:
    unit = ranks * block_size
    return -(-n // unit) * unit


def quantized_pmean(
    x: jax.Array,
    axis: str,
    *,
    block_size: int = DEFAULT_BLOCK_SIZE,
    mean: bool = True,
) -> jax.Array:
    """Block-scaled int8 all-reduce(-mean) of ``x`` over mesh axis
    ``axis``. Call inside ``shard_map``; any shape/dtype in, fp32 out
    (same shape). The wire format is int8 everywhere; accumulation is
    fp32 (module docstring).
    """
    n = jax.lax.axis_size(axis)
    orig_shape = x.shape
    flat = x.astype(jnp.float32).ravel()
    padded = _padded_len(flat.shape[0], n, block_size)
    if padded != flat.shape[0]:
        pad = jnp.zeros(padded - flat.shape[0], jnp.float32)
        # On VMA builds fresh zeros are axis-invariant while ``x`` varies
        # over the mesh — align them or the concatenate is ill-typed.
        vma = getattr(jax.typeof(flat), "vma", ())
        if vma:
            pad = jax.lax.pvary(pad, tuple(vma))
        flat = jnp.concatenate([flat, pad])
    chunk = padded // n  # per-rank owned chunk, a multiple of block_size

    # leg 1 — reduce-scatter in int8: quantize all n chunks, tiled
    # all_to_all hands rank r every rank's chunk r.
    q, s = quantize_blockwise(flat, block_size)      # [padded/B, B], [padded/B]
    q = q.reshape(n, chunk // block_size, block_size)
    s = s.reshape(n, chunk // block_size)
    q = jax.lax.all_to_all(q, axis, split_axis=0, concat_axis=0, tiled=True)
    s = jax.lax.all_to_all(s, axis, split_axis=0, concat_axis=0, tiled=True)

    # fp32 accumulation of the owned chunk, source ranks in index order
    # (deterministic); mean divides here, while still in fp32.
    owned = jnp.sum(dequantize_blockwise(q, s), axis=0)  # [chunk]
    if mean:
        owned = owned / n

    # leg 2 — all-gather in int8: requantize the reduced chunk once,
    # circulate, dequantize. all_gather's output is replicated over
    # ``axis`` (identical on every member), which is exactly what the
    # surrounding step's out_specs expect of a reduced gradient.
    q2, s2 = quantize_blockwise(owned, block_size)
    q2 = jax.lax.all_gather(q2, axis, axis=0, tiled=True)
    s2 = jax.lax.all_gather(s2, axis, axis=0, tiled=True)
    out = dequantize_blockwise(q2, s2)
    return out[: _size(orig_shape)].reshape(orig_shape)


def _size(shape) -> int:
    size = 1
    for d in shape:
        size *= int(d)
    return size


def reduced_pmean(x: jax.Array, axis: str, dtype: str,
                  *, block_size: int = DEFAULT_BLOCK_SIZE) -> jax.Array:
    """The per-dtype mean-reduction over one mesh axis: 'fp32' is a plain
    ``pmean``, 'bf16' halves the wire bytes by casting around the pmean,
    'int8' is the block-scaled path above. fp32 result either way."""
    if dtype == "fp32":
        return jax.lax.pmean(x.astype(jnp.float32), axis)
    if dtype == "bf16":
        return jax.lax.pmean(
            x.astype(jnp.bfloat16), axis).astype(jnp.float32)
    if dtype == "int8":
        return quantized_pmean(x, axis, block_size=block_size)
    raise ValueError(
        f"grad_allreduce_dtype must be one of {GRAD_ALLREDUCE_DTYPES}, "
        f"got {dtype!r}"
    )


# The HLO collective parser grew up here as this module's attestation
# backend but is analysis infrastructure shared by the byte-attestation
# test, tools/aot_cp_crossover.py and the deep-tier comm-budget gate;
# it now lives in analysis/hlo.py and is re-exported for back-compat.
from scaletorch_tpu.analysis.hlo import (  # noqa: E402,F401
    collective_wire_bytes,
)


def quantized_pmean_tree(
    grads: Any,
    axis: str,
    *,
    dtype: str = "int8",
    block_size: int = DEFAULT_BLOCK_SIZE,
) -> Any:
    """Mean-reduce a whole gradient tree over ``axis`` with ONE fused
    collective pair: leaves are raveled into a single fp32 vector (the
    bucketed-all-reduce layout, flattened to exactly one bucket — XLA
    pays per-collective latency once, not per leaf), reduced, and split
    back. fp32/bf16 fall back to per-leaf pmeans (XLA already fuses
    same-dtype pmeans; concatenation would only add copies)."""
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if not leaves:
        return grads
    if dtype != "int8":
        return jax.tree_util.tree_unflatten(
            treedef,
            [reduced_pmean(g, axis, dtype, block_size=block_size)
             for g in leaves],
        )
    def _pad_to_block(v: jax.Array) -> jax.Array:
        # Per-leaf padding to a block boundary: without it a
        # small-magnitude leaf (norm scales, ~1e-4) sharing an absmax
        # block with a large-magnitude neighbor's tail (~1e-1) would
        # quantize to all-zeros — invisible in aggregate cosine metrics,
        # fatal for that parameter. Costs < block_size elements per leaf.
        rem = -v.shape[0] % block_size
        if not rem:
            return v
        pad = jnp.zeros(rem, jnp.float32)
        vma = getattr(jax.typeof(v), "vma", ())
        if vma:
            pad = jax.lax.pvary(pad, tuple(vma))
        return jnp.concatenate([v, pad])

    segs = [_pad_to_block(g.astype(jnp.float32).ravel()) for g in leaves]
    red = quantized_pmean(
        jnp.concatenate(segs), axis, block_size=block_size)
    out, off = [], 0
    for g, seg in zip(leaves, segs):
        size = _size(g.shape)
        out.append(red[off: off + size].reshape(g.shape))
        off += seg.shape[0]
    return jax.tree_util.tree_unflatten(treedef, out)
