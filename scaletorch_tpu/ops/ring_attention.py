"""Ring attention — context parallelism over the ``cp`` mesh axis.

Capability parity with reference scaletorch/parallel/context_parallel/
context_parallel.py:83-515 (RingAttentionFunc + blockwise math from
zhuzilin/ring-flash-attention), re-designed for TPU SPMD:

  * the K/V blocks circulate the cp ring with ``lax.ppermute`` (the
    reference queues isend/irecv pairs per step, cp_comms.py:117-176);
  * each ring step computes one blockwise attention piece and merges it
    into a running ``(out, lse)`` pair — the reference's
    sigmoid/logsigmoid LSE merge (context_parallel.py:367-424) is the
    same recurrence;
  * the per-block compute has two implementations: ``impl='pallas'``
    runs the flash kernel (ops/pallas/flash.py) so the [S/cp, S/cp]
    score tile never reaches HBM — the reference's whole point of
    flash-inside-the-ring — and ``impl='xla'`` is the plain-softmax
    fallback used on CPU;
  * the **causal skip** halves compute: with contiguous sequence shards,
    a query shard r never attends key shards j > r, so those steps run a
    ``lax.cond`` no-op branch (reference skips step>rank blocks,
    :154-171);
  * the backward is a ``jax.custom_vjp`` that re-circulates K/V together
    with the dK/dV accumulators — after cp rotations each accumulator is
    home with every rank's contribution (the reference's dual kv/dkv
    ring, :184-263). Without the custom vjp, autodiff through the
    forward ring would checkpoint every rotated K/V block and the memory
    saving of CP would be lost. The pallas block backward exploits the
    flash identity: gradients of one block against the GLOBAL lse are
    exactly that block's additive contribution.

Inputs are the rank-local sequence shards [B, H, S/cp, D] (the loader
ships contiguous shards; positions arrive via the sharded position_ids).
GQA: K/V circulate **unexpanded** (fewer bytes on the ring); the pallas
kernel reads them unexpanded via index maps, the xla path expands per
block.

Two sequence layouts:

  * ``layout='contiguous'`` — rank r holds tokens [r·S/cp, (r+1)·S/cp).
    The causal skip halves total FLOPs but leaves the ring
    load-imbalanced: rank r computes r+1 blocks while all ranks tick in
    lockstep, so wall-clock is rank cp-1's (the reference has the same
    skew, context_parallel.py:154-171).
  * ``layout='zigzag'`` — the sequence is split into 2·cp stripes and
    rank r holds stripes r and 2cp-1-r concatenated (the
    zhuzilin/ring-flash-attention zigzag scheme). Every ring step then
    costs exactly two stripe-pair attention blocks on EVERY rank —
    perfectly balanced causal work, no idle ranks. The host permutes
    the token order (parallel/zigzag.py) so the mesh's contiguous cp
    slices are exactly these stripe pairs; absolute position_ids ride
    along, so RoPE and the loss are layout-transparent.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from scaletorch_tpu.models.layers import repeat_kv
from scaletorch_tpu.models.registry import register_attention_backend


def _ring_perm(axis: str):
    n = jax.lax.axis_size(axis)
    return [(i, (i + 1) % n) for i in range(n)]


def _block_scores(q, k, scale):
    # q: [B, Hq, Sq, D]; k: [B, Hq, Sk, D] (pre-expanded) -> fp32 scores
    return jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale


def _causal_mask(sq: int, sk: int):
    return jnp.tril(jnp.ones((sq, sk), dtype=bool))


def _fwd_block(q, k, v, *, scale, causal_diag, impl, interpret, n_rep):
    """One blockwise attention piece -> (normalised out fp32, lse fp32)."""
    if impl == "pallas":
        from scaletorch_tpu.ops.pallas.flash import flash_forward_with_lse

        o, lse = flash_forward_with_lse(
            q, k, v, causal=causal_diag, scale=scale, interpret=interpret
        )
        return o.astype(jnp.float32), lse
    from scaletorch_tpu.models.layers import sdpa_attention_with_lse

    o, lse = sdpa_attention_with_lse(q, k, v, causal=causal_diag, scale=scale)
    return o.astype(jnp.float32), lse


def _merge(o1, lse1, o2, lse2):
    """Merge two normalised flash-style partial results (fp32)."""
    m = jnp.maximum(lse1, lse2)
    w1 = jnp.exp(lse1 - m)
    w2 = jnp.exp(lse2 - m)
    lsum = w1 + w2
    o = (o1 * w1[..., None] + o2 * w2[..., None]) / lsum[..., None]
    return o, m + jnp.log(lsum)


def _ring_forward(q, k, v, axis: str, scale: float, impl: str, interpret: bool):
    """Returns (out [B,H,S,D] in q.dtype, lse fp32 [B,H,S])."""
    cp = jax.lax.axis_size(axis)
    r = jax.lax.axis_index(axis)
    n_rep = q.shape[1] // k.shape[1]
    perm = _ring_perm(axis)
    blk = partial(_fwd_block, scale=scale, impl=impl, interpret=interpret,
                  n_rep=n_rep)

    # step 0: the diagonal (own) block, causal-masked — every query row sees
    # at least itself, so accumulators start finite.
    o, lse = blk(q, k, v, causal_diag=True)

    k_blk, v_blk = k, v
    for t in range(1, cp):
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        j = (r - t) % cp  # origin rank of the block now held

        def attend(o=o, lse=lse, k_blk=k_blk, v_blk=v_blk):
            o2, lse2 = blk(q, k_blk, v_blk, causal_diag=False)
            return _merge(o, lse, o2, lse2)

        def skip(o=o, lse=lse):
            return o, lse

        # causal skip: key shard j holds positions AFTER ours when j > r
        o, lse = jax.lax.cond(j < r, attend, skip)

    return o.astype(q.dtype), lse


def _ring_forward_zigzag(q, k, v, axis: str, scale: float, impl: str,
                         interpret: bool):
    """Load-balanced forward: local shards are [low stripe; high stripe].

    With low_r = r and high_r = 2cp-1-r, the causal structure against the
    block from origin j is total (two stripe-pairs of work) at EVERY step:

      j == r: low×low causal + high×high causal + high×low full
      j <  r: both query stripes attend j's LOW stripe fully (high_j is
              above even our high stripe);
      j >  r: only our HIGH stripe attends, but to BOTH of j's stripes.
    """
    cp = jax.lax.axis_size(axis)
    r = jax.lax.axis_index(axis)
    n_rep = q.shape[1] // k.shape[1]
    perm = _ring_perm(axis)
    blk = partial(_fwd_block, scale=scale, impl=impl, interpret=interpret,
                  n_rep=n_rep)
    sh = q.shape[2] // 2
    ql, qh = q[:, :, :sh], q[:, :, sh:]

    # diagonal step
    kl, kh = k[:, :, :sh], k[:, :, sh:]
    vl, vh = v[:, :, :sh], v[:, :, sh:]
    o_l, lse_l = blk(ql, kl, vl, causal_diag=True)
    o_hh, lse_hh = blk(qh, kh, vh, causal_diag=True)
    o_hl, lse_hl = blk(qh, kl, vl, causal_diag=False)
    o_h, lse_h = _merge(o_hh, lse_hh, o_hl, lse_hl)
    o = jnp.concatenate([o_l, o_h], axis=2)
    lse = jnp.concatenate([lse_l, lse_h], axis=2)

    k_blk, v_blk = k, v
    for t in range(1, cp):
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        j = (r - t) % cp  # origin rank of the block now held

        def older(o=o, lse=lse, k_blk=k_blk, v_blk=v_blk):
            # j < r: full attention of [ql; qh] onto j's low stripe
            o2, lse2 = blk(q, k_blk[:, :, :sh], v_blk[:, :, :sh],
                           causal_diag=False)
            return _merge(o, lse, o2, lse2)

        def newer(o=o, lse=lse, k_blk=k_blk, v_blk=v_blk):
            # j > r: our high stripe attends both of j's stripes; the low
            # query stripe gets a -inf lse pad (a no-op in the merge)
            o2h, lse2h = blk(qh, k_blk, v_blk, causal_diag=False)
            o2 = jnp.concatenate([jnp.zeros_like(o2h), o2h], axis=2)
            lse2 = jnp.concatenate(
                [jnp.full_like(lse2h, -jnp.inf), lse2h], axis=2)
            return _merge(o, lse, o2, lse2)

        # equal-cost branches: half the ranks take each at every step
        o, lse = jax.lax.cond(j < r, older, newer)

    return o.astype(q.dtype), lse


def _bwd_block_xla(q, k, v, dout, lse, delta, scale, causal_diag: bool):
    """Gradients of one pre-expanded block: (dq, dk, dv) in fp32.

    Standard flash backward: p = exp(s - lse); dv = p^T dout;
    ds = p * (dout v^T - delta) * scale; dq = ds k; dk = ds^T q.
    """
    s = _block_scores(q, k, scale)
    if causal_diag:
        s = jnp.where(_causal_mask(s.shape[-2], s.shape[-1]), s, -jnp.inf)
    p = jnp.exp(s - lse[..., None])              # [B,H,Sq,Sk] fp32
    dout32 = dout.astype(jnp.float32)
    v32 = v.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, dout32)
    dp = jnp.einsum("bhqd,bhkd->bhqk", dout32, v32)
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k.astype(jnp.float32))
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32))
    return dq, dk, dv


def _sum_heads(d_expanded, n_rep):
    """Fold gradients of GQA-expanded heads back onto kv heads."""
    if n_rep == 1:
        return d_expanded
    b, h, s, d = d_expanded.shape
    return d_expanded.reshape(b, h // n_rep, n_rep, s, d).sum(axis=2)


def _bwd_block(q, k_blk, v_blk, out, lse, dout, delta, *,
               scale, causal_diag, impl, interpret, n_rep):
    """Per-ring-step block backward -> (dq, dk, dv) fp32, dk/dv unexpanded."""
    if impl == "pallas":
        from scaletorch_tpu.ops.pallas.flash import flash_block_backward

        dq, dk, dv = flash_block_backward(
            q, k_blk, v_blk, out, lse, dout,
            causal=causal_diag, scale=scale, interpret=interpret,
        )
        return (dq.astype(jnp.float32), dk.astype(jnp.float32),
                dv.astype(jnp.float32))
    dq, dk, dv = _bwd_block_xla(
        q, repeat_kv(k_blk, n_rep), repeat_kv(v_blk, n_rep),
        dout, lse, delta, scale, causal_diag,
    )
    return dq, _sum_heads(dk, n_rep), _sum_heads(dv, n_rep)


def _ring_backward_zigzag(q, k, v, out, lse, dout, axis, scale, impl,
                          interpret):
    """Backward mirror of the zigzag schedule: the dk/dv accumulator
    circulates with the K/V block in the ORIGIN rank's [low; high] stripe
    layout, receiving each step's contribution into the stripes that
    step actually attended."""
    cp = jax.lax.axis_size(axis)
    r = jax.lax.axis_index(axis)
    n_rep = q.shape[1] // k.shape[1]
    perm = _ring_perm(axis)
    blk = partial(_bwd_block, scale=scale, impl=impl, interpret=interpret,
                  n_rep=n_rep)
    sh = q.shape[2] // 2

    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    ql, qh = q[:, :, :sh], q[:, :, sh:]
    kl, kh = k[:, :, :sh], k[:, :, sh:]
    vl, vh = v[:, :, :sh], v[:, :, sh:]
    out_l, out_h = out[:, :, :sh], out[:, :, sh:]
    do_l, do_h = dout[:, :, :sh], dout[:, :, sh:]
    lse_l, lse_h = lse[:, :, :sh], lse[:, :, sh:]
    dta_l, dta_h = delta[:, :, :sh], delta[:, :, sh:]

    # diagonal step: the same three blocks as the forward
    dql, dkl, dvl = blk(ql, kl, vl, out_l, lse_l, do_l, dta_l,
                        causal_diag=True)
    dqh, dkh, dvh = blk(qh, kh, vh, out_h, lse_h, do_h, dta_h,
                        causal_diag=True)
    dqh2, dkl2, dvl2 = blk(qh, kl, vl, out_h, lse_h, do_h, dta_h,
                           causal_diag=False)
    dq = jnp.concatenate([dql, dqh + dqh2], axis=2)
    dk_acc = jnp.concatenate([dkl + dkl2, dkh], axis=2)
    dv_acc = jnp.concatenate([dvl + dvl2, dvh], axis=2)

    k_blk, v_blk = k, v
    for t in range(1, cp):
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        dk_acc = jax.lax.ppermute(dk_acc, axis, perm)
        dv_acc = jax.lax.ppermute(dv_acc, axis, perm)
        j = (r - t) % cp

        def older(dq=dq, dk_acc=dk_acc, dv_acc=dv_acc,
                  k_blk=k_blk, v_blk=v_blk):
            # j < r: the forward attended [ql; qh] x j's low stripe
            dq_c, dk_c, dv_c = blk(
                q, k_blk[:, :, :sh], v_blk[:, :, :sh],
                out, lse, dout, delta, causal_diag=False)
            zeros_k = jnp.zeros_like(dk_c)
            return (dq + dq_c,
                    dk_acc + jnp.concatenate([dk_c, zeros_k], axis=2),
                    dv_acc + jnp.concatenate([dv_c, zeros_k], axis=2))

        def newer(dq=dq, dk_acc=dk_acc, dv_acc=dv_acc,
                  k_blk=k_blk, v_blk=v_blk):
            # j > r: the forward attended qh x both of j's stripes
            dq_c, dk_c, dv_c = blk(
                qh, k_blk, v_blk, out_h, lse_h, do_h, dta_h,
                causal_diag=False)
            return (dq + jnp.concatenate([jnp.zeros_like(dq_c), dq_c], axis=2),
                    dk_acc + dk_c, dv_acc + dv_c)

        dq, dk_acc, dv_acc = jax.lax.cond(j < r, older, newer)

    # one final rotation brings every accumulator home
    dk_acc = jax.lax.ppermute(dk_acc, axis, perm)
    dv_acc = jax.lax.ppermute(dv_acc, axis, perm)

    return dq.astype(q.dtype), dk_acc.astype(k.dtype), dv_acc.astype(v.dtype)


def _check_layout(layout: str, causal: bool, seq_local: int) -> None:
    if not causal:
        raise NotImplementedError("ring attention is causal-only")
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown cp layout {layout!r}")
    if layout == "zigzag" and seq_local % 2:
        raise ValueError(
            f"zigzag layout needs an even local sequence, got {seq_local}"
        )


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def ring_attention(q, k, v, axis: str = "cp", causal: bool = True,
                   scale: Optional[float] = None, impl: str = "xla",
                   interpret: bool = False, layout: str = "contiguous"):
    """Ring attention over mesh axis ``axis``; call inside shard_map.

    q: [B, Hq, S/cp, D]; k/v: [B, Hkv, S/cp, D] (local shards).
    Only causal=True is supported (parity: the reference ring attention
    is causal-only, context_parallel.py:154-171).

    ``impl='pallas'`` computes each ring block with the flash kernel so
    per-step memory is O(S/cp · D), not O((S/cp)^2); ``impl='xla'`` is
    the plain-softmax fallback (CPU tests). ``layout`` selects the
    sequence-shard scheme (module docstring): 'zigzag' balances causal
    work across ranks and needs the host-side zigzag token order
    (parallel/zigzag.py).
    """
    _check_layout(layout, causal, q.shape[2])
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    fwd = _ring_forward_zigzag if layout == "zigzag" else _ring_forward
    out, _ = fwd(q, k, v, axis, scale, impl, interpret)
    return out


def _ring_fwd(q, k, v, axis, causal, scale, impl, interpret, layout):
    # guard repeated here: under differentiation custom_vjp traces this
    # function instead of the primal body above
    _check_layout(layout, causal, q.shape[2])
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    fwd = _ring_forward_zigzag if layout == "zigzag" else _ring_forward
    out, lse = fwd(q, k, v, axis, scale, impl, interpret)
    return out, (q, k, v, out, lse)


def _ring_bwd(axis, causal, scale, impl, interpret, layout, res, dout):
    q, k, v, out, lse = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if layout == "zigzag":
        return _ring_backward_zigzag(q, k, v, out, lse, dout, axis, scale,
                                     impl, interpret)
    cp = jax.lax.axis_size(axis)
    r = jax.lax.axis_index(axis)
    n_rep = q.shape[1] // k.shape[1]
    perm = _ring_perm(axis)
    blk = partial(_bwd_block, scale=scale, impl=impl, interpret=interpret,
                  n_rep=n_rep)

    # delta = rowsum(dout * out) — the softmax-jacobian diagonal term
    # (the pallas path recomputes it inside flash_block_backward)
    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)

    # own (diagonal) block
    dq, dk_acc, dv_acc = blk(q, k, v, out, lse, dout, delta, causal_diag=True)

    # Rotate (k, v, dk, dv) together: after the remaining cp-1 rotations
    # plus one final rotation, each dk/dv accumulator is back at its origin
    # with every rank's contribution (reference dual-ring, :184-263).
    k_blk, v_blk = k, v
    for t in range(1, cp):
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        dk_acc = jax.lax.ppermute(dk_acc, axis, perm)
        dv_acc = jax.lax.ppermute(dv_acc, axis, perm)
        j = (r - t) % cp

        def contribute(dq=dq, dk_acc=dk_acc, dv_acc=dv_acc,
                       k_blk=k_blk, v_blk=v_blk):
            dq_c, dk_c, dv_c = blk(
                q, k_blk, v_blk, out, lse, dout, delta, causal_diag=False
            )
            return dq + dq_c, dk_acc + dk_c, dv_acc + dv_c

        def skip(dq=dq, dk_acc=dk_acc, dv_acc=dv_acc):
            return dq, dk_acc, dv_acc

        dq, dk_acc, dv_acc = jax.lax.cond(j < r, contribute, skip)

    # one final rotation brings every accumulator home
    dk_acc = jax.lax.ppermute(dk_acc, axis, perm)
    dv_acc = jax.lax.ppermute(dv_acc, axis, perm)

    return dq.astype(q.dtype), dk_acc.astype(k.dtype), dv_acc.astype(v.dtype)


ring_attention.defvjp(_ring_fwd, _ring_bwd)


def ring_attention_backend(q, k, v, *, causal: bool = True,
                           scale: Optional[float] = None, axis: str = "cp",
                           impl: Optional[str] = None,
                           interpret: bool = False,
                           layout: Optional[str] = None):
    """Registry-compatible wrapper (backend name 'ring').

    Picks the flash-kernel block implementation on TPU, the XLA softmax
    fallback elsewhere (same policy as the 'flash' backend dispatch,
    ops/flash_attention.py). The sequence layout defaults to the
    ``SCALETORCH_TPU_CP_LAYOUT`` env toggle (set by the trainer from
    ``cp_layout``) because model code calls backends as plain
    ``fn(q, k, v, causal=, scale=)``.
    """
    if impl is None:
        from scaletorch_tpu.ops.flash_attention import _pallas_available

        impl = "pallas" if _pallas_available() else "xla"
    if layout is None:
        from scaletorch_tpu.env import get_env

        layout = get_env("SCALETORCH_TPU_CP_LAYOUT")
    return ring_attention(q, k, v, axis, causal, scale, impl, interpret, layout)


register_attention_backend("ring", ring_attention_backend)
# Explicit-layout variants: let the spmd step pin cp_layout from config at
# trace time for BOTH layouts — the bare 'ring' name reads the
# SCALETORCH_TPU_CP_LAYOUT env at trace time, which is process-global and
# therefore unsafe when steps with different layouts trace in one process.
register_attention_backend(
    "ring_zigzag", partial(ring_attention_backend, layout="zigzag")
)
register_attention_backend(
    "ring_contiguous", partial(ring_attention_backend, layout="contiguous")
)
