"""Ring attention — context parallelism over the ``cp`` mesh axis.

Capability parity with reference scaletorch/parallel/context_parallel/
context_parallel.py:83-515 (RingAttentionFunc + blockwise math from
zhuzilin/ring-flash-attention), re-designed for TPU SPMD:

  * the K/V blocks circulate the cp ring with ``lax.ppermute`` (the
    reference queues isend/irecv pairs per step, cp_comms.py:117-176);
  * blockwise softmax uses flash-style running-max/sum accumulation in
    fp32 (the reference's sigmoid/logsigmoid LSE merge,
    context_parallel.py:367-424, is the same recurrence);
  * the **causal skip** halves compute: with contiguous sequence shards,
    a query shard r never attends key shards j > r, so those steps run a
    ``lax.cond`` no-op branch (reference skips step>rank blocks,
    :154-171);
  * the backward is a ``jax.custom_vjp`` that re-circulates K/V together
    with the dK/dV accumulators — after cp rotations each accumulator is
    home with every rank's contribution (the reference's dual kv/dkv
    ring, :184-263). Without the custom vjp, autodiff through the
    forward ring would checkpoint every rotated K/V block and the memory
    saving of CP would be lost.

Inputs are the rank-local sequence shards [B, H, S/cp, D] (the loader
ships contiguous shards; positions arrive via the sharded position_ids).
GQA: K/V circulate **unexpanded** (fewer bytes on the ring) and are
expanded per block.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from scaletorch_tpu.models.layers import repeat_kv
from scaletorch_tpu.models.registry import register_attention_backend


def _ring_perm(axis: str):
    n = jax.lax.axis_size(axis)
    return [(i, (i + 1) % n) for i in range(n)]


def _block_scores(q, k, scale):
    # q: [B, Hq, Sq, D]; k: [B, Hq, Sk, D] (pre-expanded) -> fp32 scores
    return jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale


def _causal_mask(sq: int, sk: int):
    return jnp.tril(jnp.ones((sq, sk), dtype=bool))


def _fwd_block(q, k, v, scale, causal_diag: bool):
    """One blockwise attention piece -> (unnormalised acc, rowmax m, rowsum l)."""
    s = _block_scores(q, k, scale)
    if causal_diag:
        s = jnp.where(_causal_mask(s.shape[-2], s.shape[-1]), s, -jnp.inf)
    m = jnp.max(s, axis=-1)                      # [B, H, Sq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v).astype(jnp.float32)
    return acc, m, l


def _merge(acc, m, l, acc2, m2, l2):
    """Merge two flash-style partial results (fp32)."""
    m_new = jnp.maximum(m, m2)
    w1 = jnp.exp(m - m_new)
    w2 = jnp.exp(m2 - m_new)
    return (
        acc * w1[..., None] + acc2 * w2[..., None],
        m_new,
        l * w1 + l2 * w2,
    )


def _ring_forward(q, k, v, axis: str, scale: float):
    """Returns (out [B,H,S,D] in q.dtype, lse fp32 [B,H,S])."""
    cp = jax.lax.axis_size(axis)
    r = jax.lax.axis_index(axis)
    n_rep = q.shape[1] // k.shape[1]
    perm = _ring_perm(axis)

    # step 0: the diagonal (own) block, causal-masked — every query row sees
    # at least itself, so accumulators start finite.
    acc, m, l = _fwd_block(q, repeat_kv(k, n_rep), repeat_kv(v, n_rep), scale, True)

    k_blk, v_blk = k, v
    for t in range(1, cp):
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        j = (r - t) % cp  # origin rank of the block now held

        def attend(acc=acc, m=m, l=l, k_blk=k_blk, v_blk=v_blk):
            a2, m2, l2 = _fwd_block(
                q, repeat_kv(k_blk, n_rep), repeat_kv(v_blk, n_rep), scale, False
            )
            return _merge(acc, m, l, a2, m2, l2)

        def skip(acc=acc, m=m, l=l):
            return acc, m, l

        # causal skip: key shard j holds positions AFTER ours when j > r
        acc, m, l = jax.lax.cond(j < r, attend, skip)

    out = (acc / l[..., None]).astype(q.dtype)
    lse = m + jnp.log(l)
    return out, lse


def _bwd_block(q, k, v, dout, lse, delta, scale, causal_diag: bool):
    """Gradients of one block: (dq, dk, dv) in fp32.

    Standard flash backward: p = exp(s - lse); dv = p^T dout;
    ds = p * (dout v^T - delta) * scale; dq = ds k; dk = ds^T q.
    """
    s = _block_scores(q, k, scale)
    if causal_diag:
        s = jnp.where(_causal_mask(s.shape[-2], s.shape[-1]), s, -jnp.inf)
    p = jnp.exp(s - lse[..., None])              # [B,H,Sq,Sk] fp32
    dout32 = dout.astype(jnp.float32)
    v32 = v.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, dout32)
    dp = jnp.einsum("bhqd,bhkd->bhqk", dout32, v32)
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k.astype(jnp.float32))
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32))
    return dq, dk, dv


def _sum_heads(d_expanded, n_rep):
    """Fold gradients of GQA-expanded heads back onto kv heads."""
    if n_rep == 1:
        return d_expanded
    b, h, s, d = d_expanded.shape
    return d_expanded.reshape(b, h // n_rep, n_rep, s, d).sum(axis=2)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_attention(q, k, v, axis: str = "cp", causal: bool = True,
                   scale: Optional[float] = None):
    """Ring attention over mesh axis ``axis``; call inside shard_map.

    q: [B, Hq, S/cp, D]; k/v: [B, Hkv, S/cp, D] (local shards).
    Only causal=True is supported (parity: the reference ring attention
    is causal-only, context_parallel.py:154-171).
    """
    if not causal:
        raise NotImplementedError("ring attention is causal-only")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    out, _ = _ring_forward(q, k, v, axis, scale)
    return out


def _ring_fwd(q, k, v, axis, causal, scale):
    # guard repeated here: under differentiation custom_vjp traces this
    # function instead of the primal body above
    if not causal:
        raise NotImplementedError("ring attention is causal-only")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    out, lse = _ring_forward(q, k, v, axis, scale)
    return out, (q, k, v, out, lse)


def _ring_bwd(axis, causal, scale, res, dout):
    q, k, v, out, lse = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    cp = jax.lax.axis_size(axis)
    r = jax.lax.axis_index(axis)
    n_rep = q.shape[1] // k.shape[1]
    perm = _ring_perm(axis)

    # delta = rowsum(dout * out) — the softmax-jacobian diagonal term
    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)

    # own (diagonal) block
    dq, dk_own, dv_own = _bwd_block(
        q, repeat_kv(k, n_rep), repeat_kv(v, n_rep), dout, lse, delta, scale, True
    )
    dk_acc = _sum_heads(dk_own, n_rep)
    dv_acc = _sum_heads(dv_own, n_rep)

    # Rotate (k, v, dk, dv) together: after the remaining cp-1 rotations
    # plus one final rotation, each dk/dv accumulator is back at its origin
    # with every rank's contribution (reference dual-ring, :184-263).
    k_blk, v_blk = k, v
    for t in range(1, cp):
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        dk_acc = jax.lax.ppermute(dk_acc, axis, perm)
        dv_acc = jax.lax.ppermute(dv_acc, axis, perm)
        j = (r - t) % cp

        def contribute(dq=dq, dk_acc=dk_acc, dv_acc=dv_acc,
                       k_blk=k_blk, v_blk=v_blk):
            dq_c, dk_c, dv_c = _bwd_block(
                q, repeat_kv(k_blk, n_rep), repeat_kv(v_blk, n_rep),
                dout, lse, delta, scale, False,
            )
            return (dq + dq_c,
                    dk_acc + _sum_heads(dk_c, n_rep),
                    dv_acc + _sum_heads(dv_c, n_rep))

        def skip(dq=dq, dk_acc=dk_acc, dv_acc=dv_acc):
            return dq, dk_acc, dv_acc

        dq, dk_acc, dv_acc = jax.lax.cond(j < r, contribute, skip)

    # one final rotation brings every accumulator home
    dk_acc = jax.lax.ppermute(dk_acc, axis, perm)
    dv_acc = jax.lax.ppermute(dv_acc, axis, perm)

    return dq.astype(q.dtype), dk_acc.astype(k.dtype), dv_acc.astype(v.dtype)


ring_attention.defvjp(_ring_fwd, _ring_bwd)


def ring_attention_backend(q, k, v, *, causal: bool = True,
                           scale: Optional[float] = None, axis: str = "cp"):
    """Registry-compatible wrapper (backend name 'ring')."""
    return ring_attention(q, k, v, axis, causal, scale)


register_attention_backend("ring", ring_attention_backend)
