"""Ulysses-style context parallelism — all-to-all head scatter.

The reference ships only ring attention for CP (SURVEY.md §5 notes "no
Ulysses (head-scatter all-to-all)"); on TPU the Ulysses layout (the
DeepSpeed-Ulysses scheme) is a natural second strategy and often the
better one at moderate sequence lengths:

  * two ``lax.all_to_all``s swap the sharding axis — sequence-sharded
    [B, H, S/cp, D] becomes head-sharded [B, H/cp, S, D] — and each rank
    runs ONE full-sequence flash attention over its head subset;
  * causal work is inherently balanced (every rank owns whole heads), so
    no zigzag striping or per-step `lax.cond` schedule is needed;
  * comm volume is 2 all-to-alls of the activations vs the ring's cp-1
    K/V rotations — cheaper whenever 2·S·D < (cp-1)·2·S/cp·D·(Hkv/Hq)
    ... in practice: fewer, larger transfers that XLA overlaps better;
  * the trade-off is parallelism degree: cp must divide the KV head
    count (GQA models cap cp at Hkv), where the ring scales cp
    arbitrarily — the registry keeps 'ring' the CP default and 'ulysses'
    an opt-in (``--attention_backend ulysses``).

Differentiability is free: ``all_to_all`` transposes to itself and the
inner attention is the already-VJP'd flash/SDPA path, so no custom VJP.

Inputs are post-RoPE q/k/v sequence shards in the CONTIGUOUS layout
(head ownership makes zigzag pointless; the Trainer skips the zigzag
host permutation for this backend).
"""

from __future__ import annotations

import math
from typing import Optional

import jax

from scaletorch_tpu.models.registry import register_attention_backend
from scaletorch_tpu.parallel.tensor_parallel import pvary_missing


def _scatter_heads(x: jax.Array, axis: str) -> jax.Array:
    """[B, H, S/cp, D] -> [B, H/cp, S, D]: split heads, gather sequence."""
    return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)


def _gather_heads(x: jax.Array, axis: str) -> jax.Array:
    """[B, H/cp, S, D] -> [B, H, S/cp, D]: the inverse exchange."""
    return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    axis: str = "cp",
    impl: Optional[str] = None,
    interpret: bool = False,
) -> jax.Array:
    """q: [B, Hq, S/cp, D]; k/v: [B, Hkv, S/cp, D] local sequence shards
    (contiguous layout). Requires Hq % cp == 0 and Hkv % cp == 0."""
    cp = jax.lax.axis_size(axis)
    hq, hkv = q.shape[1], k.shape[1]
    if hq % cp or hkv % cp:
        raise ValueError(
            f"ulysses needs cp ({cp}) to divide both query heads ({hq}) and "
            f"kv heads ({hkv}); use the 'ring' backend for higher cp"
        )
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if cp == 1:
        # degenerate: no exchange; still honour an explicit impl override
        if impl == "xla":
            from scaletorch_tpu.models.layers import sdpa_attention

            return sdpa_attention(q, k, v, causal=causal, scale=scale)
        if impl == "pallas":
            from scaletorch_tpu.ops.pallas.flash import pallas_flash_attention

            return pallas_flash_attention(q, k, v, causal=causal,
                                          scale=scale, interpret=interpret)
        from scaletorch_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, scale=scale)

    q, k, v = (pvary_missing(t, axis) for t in (q, k, v))
    qh = _scatter_heads(q, axis)   # [B, Hq/cp, S, D]
    kh = _scatter_heads(k, axis)
    vh = _scatter_heads(v, axis)

    if impl is None:
        from scaletorch_tpu.ops.flash_attention import _pallas_available

        impl = "pallas" if _pallas_available() else "xla"
    if impl == "pallas":
        from scaletorch_tpu.ops.pallas.flash import pallas_flash_attention

        o = pallas_flash_attention(qh, kh, vh, causal=causal, scale=scale,
                                   interpret=interpret)
    else:
        from scaletorch_tpu.models.layers import sdpa_attention

        o = sdpa_attention(qh, kh, vh, causal=causal, scale=scale)
    return _gather_heads(pvary_missing(o, axis), axis)


register_attention_backend("ulysses", ulysses_attention)
