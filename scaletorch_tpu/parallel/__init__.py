"""Parallelism strategies over the 5D device mesh ``(dp, pp, cp, ep, tp)``."""

from scaletorch_tpu.parallel.mesh import (  # noqa: F401
    MESH_AXES,
    MeshManager,
    mesh_manager,
    setup_mesh_manager,
    reset_mesh_manager,
)
from scaletorch_tpu.parallel.pipeline_parallel import (  # noqa: F401
    make_llama_pipeline_loss,
    pipeline_spmd_loss,
    stage_layer_partition,
    validate_pp_divisibility,
)
from scaletorch_tpu.parallel.fsdp import (  # noqa: F401
    fsdp_param_specs,
    make_fsdp_train_step,
    setup_fsdp,
    shard_params_fsdp,
)
