"""Parallelism strategies over the 5D device mesh ``(dp, pp, cp, ep, tp)``."""

from scaletorch_tpu.parallel.mesh import (  # noqa: F401
    MESH_AXES,
    MeshManager,
    mesh_manager,
    setup_mesh_manager,
    reset_mesh_manager,
)
from scaletorch_tpu.parallel.pipeline_parallel import (  # noqa: F401
    deinterleave_stacked_params,
    interleave_stacked_params,
    interleaved_tick_schedule,
    make_llama_pipeline_loss,
    pad_stacked_params,
    padded_stage_counts,
    pipeline_interleaved_loss,
    pipeline_spmd_loss,
    stage_layer_partition,
    suggest_virtual_stages,
    unpad_stacked_params,
    validate_interleaved_divisibility,
    validate_pp_divisibility,
)
from scaletorch_tpu.parallel.fsdp import (  # noqa: F401
    fsdp_param_specs,
    make_fsdp_train_step,
    setup_fsdp,
    shard_params_fsdp,
)
from scaletorch_tpu.parallel.cp_select import (  # noqa: F401
    CPChoice,
    cp_cross_host_hops,
    resolve_cp_backend,
    ring_wire_bytes,
    ulysses_wire_bytes,
)
from scaletorch_tpu.parallel.expert_parallel import (  # noqa: F401
    combine_routed,
    dispatch_routed,
    resolve_moe_dispatch,
    route_tokens,
    routed_fill_counts,
    sort_dispatch_tokens,
    sort_gather_tokens,
    sorted_moe_forward,
    top_k_routing_indexed,
)
from scaletorch_tpu.parallel.zigzag import (  # noqa: F401
    zigzag_batch,
    zigzag_order,
    zigzag_restore,
)
