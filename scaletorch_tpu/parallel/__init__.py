"""Parallelism strategies over the 5D device mesh ``(dp, pp, cp, ep, tp)``."""

from scaletorch_tpu.parallel.mesh import (  # noqa: F401
    MESH_AXES,
    MeshManager,
    mesh_manager,
    setup_mesh_manager,
    reset_mesh_manager,
)
