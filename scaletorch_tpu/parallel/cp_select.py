"""Topology-aware CP backend selection — the hand-tuned table, computed.

docs/long_context.md §4 used to be a table the operator applied by hand:
ring+zigzag by default, ulysses across hosts or at head-heavy
geometries, ring again at extreme sequence lengths. Following TASP
(topology-aware sequence parallelism, PAPERS.md) and the established
``resolve_moe_dispatch`` pattern (guess -> compiled evidence), this
module computes that choice from what it actually depends on:

  * mesh topology — does the cp ring cross a host boundary (DCN)?
    Counted from ``process_index`` transitions along the cp axis of the
    real device mesh, the same signal a human reads off the slice
    topology;
  * model geometry — ulysses is only admissible when cp divides both
    head counts, and its wire bytes scale with (Hq+Hkv)/cp where the
    ring's scale with Hkv·(cp-1)/cp (un-expanded GQA K/V);
  * sequence length — ulysses ranks run FULL-sequence attention over
    their head subset, so extreme S prefers the ring's (S/cp)² tiles.

The decision is attested, not guessed: ``tools/aot_cp_crossover.py``
compiles the REAL spmd train step both ways per topology and records
XLA's collective wire bytes into AOT_CP_CROSSOVER.json; its ``--check``
mode (run in CI) verifies this resolver reproduces the recorded
winners and the docs-table scenarios.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

# Ring hops overlap with per-hop attention compute; ulysses' all-to-alls
# are exposed on the critical path. On ICI we therefore keep the ring
# unless ulysses moves at least this factor fewer bytes (the byte model
# alone would flip to ulysses at ~1x, which wall-clock does not support —
# the same compiled-cost-vs-silicon caveat as resolve_moe_dispatch).
ICI_ULYSSES_BYTE_MARGIN = 2.0

# Past this sequence length ulysses' full-S rows (and its S x S/heads
# score tiles on non-flash paths) dominate the memory budget; the ring's
# (S/cp)^2 locality wins regardless of wire bytes.
EXTREME_SEQ_THRESHOLD = 32768


@dataclasses.dataclass(frozen=True)
class CPChoice:
    backend: str  # 'ring' | 'ulysses'
    layout: str   # 'zigzag' | 'contiguous' (ring's causal balance; ulysses
                  # owns whole heads and is balanced in contiguous layout)
    reason: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def ring_wire_bytes(cp: int, seq: int, num_kv_heads: int, head_dim: int,
                    bytes_per_el: int = 2) -> float:
    """Per-device forward wire bytes of ring attention: K and V shards
    (UN-expanded GQA heads — ops/ring_attention.py) circulate cp-1 hops."""
    return 2.0 * (cp - 1) * (seq / cp) * num_kv_heads * head_dim * bytes_per_el


def ulysses_wire_bytes(cp: int, seq: int, num_q_heads: int,
                       num_kv_heads: int, head_dim: int,
                       bytes_per_el: int = 2) -> float:
    """Per-device forward wire bytes of ulysses: four tiled all-to-alls
    (q, k, v scatter + output gather), each moving (cp-1)/cp of its local
    [B, H, S/cp, D] array (ops/ulysses.py)."""
    per_el = (cp - 1) / cp * (seq / cp) * head_dim * bytes_per_el
    return per_el * (2 * num_q_heads + 2 * num_kv_heads)


def cp_cross_host_hops(mesh, cp_axis: str = "cp") -> int:
    """How many host (process) boundaries the cp ring crosses — the
    DCN-hop count. 0 means the whole ring rides ICI. Computed as the max
    over all non-cp mesh coordinates of the number of process_index
    transitions around that coordinate's cp cycle."""
    import numpy as np

    axes = list(mesh.axis_names)
    if cp_axis not in axes:
        return 0
    devs = np.asarray(mesh.devices)
    cp_dim = axes.index(cp_axis)
    if devs.shape[cp_dim] == 1:
        return 0
    # bring cp to the last dim; iterate rings
    devs = np.moveaxis(devs, cp_dim, -1)
    worst = 0
    for ring in devs.reshape(-1, devs.shape[-1]):
        procs = [getattr(d, "process_index", 0) for d in ring]
        hops = sum(
            1 for i in range(len(procs))
            if procs[i] != procs[(i + 1) % len(procs)]
        )
        worst = max(worst, hops)
    return worst


def resolve_cp_backend(
    requested: str,
    mesh=None,
    *,
    cp: int,
    num_q_heads: int,
    num_kv_heads: Optional[int],
    seq_len: int,
    cross_host_hops: Optional[int] = None,
    layout: str = "zigzag",
) -> CPChoice:
    """'auto' -> the CP attention backend the topology and geometry favor.

    ``mesh`` supplies the DCN-hop signal (``cp_cross_host_hops``); pass
    ``cross_host_hops`` directly instead for mesh-free resolution (tests,
    the ``--check`` CI smoke, capacity planning for a not-yet-provisioned
    slice). An explicit ``requested`` backend is always honored —
    auto-selection must never override an operator's measured choice.
    """
    num_kv_heads = num_kv_heads or num_q_heads
    if requested != "auto":
        lay = layout if requested == "ring" else "contiguous"
        return CPChoice(requested, lay, "explicitly requested")
    if cp <= 1:
        return CPChoice("ring", layout, "cp=1: degenerate (no CP exchange)")
    if num_q_heads % cp or num_kv_heads % cp:
        return CPChoice(
            "ring", layout,
            f"ulysses needs cp ({cp}) to divide heads "
            f"(Hq={num_q_heads}, Hkv={num_kv_heads}); ring scales to any cp",
        )
    if cross_host_hops is None:
        cross_host_hops = cp_cross_host_hops(mesh) if mesh is not None else 0
    if cross_host_hops > 0:
        return CPChoice(
            "ulysses", "contiguous",
            f"cp ring crosses {cross_host_hops} host boundaries (DCN): "
            "2 fused all-to-alls beat cp-1 serialized DCN ring hops",
        )
    if seq_len > EXTREME_SEQ_THRESHOLD:
        return CPChoice(
            "ring", layout,
            f"extreme sequence ({seq_len} > {EXTREME_SEQ_THRESHOLD}): "
            "ring keeps (S/cp)^2 attention tiles; ulysses ranks would run "
            "full-sequence rows",
        )
    head_dim = 1  # ratio is head_dim-independent
    ratio = (
        ring_wire_bytes(cp, seq_len, num_kv_heads, head_dim)
        / max(ulysses_wire_bytes(cp, seq_len, num_q_heads, num_kv_heads,
                                 head_dim), 1e-9)
    )
    if ratio >= ICI_ULYSSES_BYTE_MARGIN:
        return CPChoice(
            "ulysses", "contiguous",
            f"head-heavy geometry: ring would move {ratio:.2f}x the wire "
            f"bytes (cp·Hkv/(Hq+Hkv) = {ratio:.2f} >= "
            f"{ICI_ULYSSES_BYTE_MARGIN})",
        )
    return CPChoice(
        "ring", layout,
        f"ICI ring with overlapped hops (ulysses byte advantage "
        f"{ratio:.2f}x < {ICI_ULYSSES_BYTE_MARGIN}x margin): the "
        "long-context default",
    )
